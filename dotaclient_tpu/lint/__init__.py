"""graftlint: multi-pass static analysis for the repo's disciplines.

``python -m dotaclient_tpu.lint`` runs every pass; see ``core.py`` for
the framework and docs/ARCHITECTURE.md "Static analysis" for the rule
catalog, the ``# lint-ok: <rule>(<why>)`` waiver, and when to baseline.

Import-light by design (stdlib only — no jax/numpy): the tier-1 wrapper
(tests/test_lint.py) runs the full lint in-process on every test run.
"""

from dotaclient_tpu.lint.alert_drift import AlertDriftRule
from dotaclient_tpu.lint.config_drift import ConfigCliDriftRule
from dotaclient_tpu.lint.core import (
    DEFAULT_BASELINE,
    Diagnostic,
    FileCtx,
    LintResult,
    Rule,
    fingerprint,
    load_baseline,
    run_rules,
)
from dotaclient_tpu.lint.donation import UseAfterDonateRule
from dotaclient_tpu.lint.host_sync import HostSyncRule
from dotaclient_tpu.lint.ownership import ThreadOwnershipRule
from dotaclient_tpu.lint.telemetry_drift import TelemetryDriftRule

# registration order = report order: cheap/precise first
ALL_RULES = (
    HostSyncRule,
    UseAfterDonateRule,
    ThreadOwnershipRule,
    TelemetryDriftRule,
    ConfigCliDriftRule,
    AlertDriftRule,
)

__all__ = [
    "ALL_RULES",
    "DEFAULT_BASELINE",
    "Diagnostic",
    "FileCtx",
    "LintResult",
    "Rule",
    "fingerprint",
    "load_baseline",
    "run_rules",
]
