"""thread-ownership pass: declarative per-thread state ownership, checked.

The learner stack is a small fleet of threads — the train thread, the
snapshot engine thread, the transport's accept/reader/writer threads, an
optional in-process actor thread — and the last three PRs each needed a
post-review fix for a shared-state race between them (``_pending_best``
swap, ``_last_verdict_m`` re-fold after rollback, the sync-gate fold
ordering). Locks were the fix each time; what was missing was a *declared*
ownership model a machine can re-check on every commit.

``OWNERSHIP`` below is that declaration. For each mapped class:

* ``default_thread`` + ``methods`` assign every method (and named nested
  def — closures resolve to the innermost declared name) to the thread it
  runs on;
* ``attrs`` maps each guarded attribute to its discipline:

  - ``"<thread>"`` — only methods on that thread may touch it;
  - ``"lock:<attr>"`` — any thread, but the access must be lexically
    inside ``with self.<attr>:`` (or in a method listed in ``holds`` as
    called-with-the-lock-held, the ``*_locked`` helper convention);
  - ``"any"`` — explicitly unguarded (documented free-for-all, e.g. a
    latched bool the readers tolerate stale).

Unmapped attributes are unchecked: the map is a statement of the
disciplines that matter, not an inventory. ``__init__`` is exempt — the
object is not shared until construction returns. Deliberate exceptions
(handoff-after-barrier reads, monotonic-value races) are waived at the
line with ``# lint-ok: thread-ownership(<why>)`` so the reasoning is in
the diff, not the reviewer's head.

The three PR 5–6 race shapes are pinned as fixtures in
``tests/test_lint.py`` — this pass flags each of them.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Mapping, Tuple

from dotaclient_tpu.lint.core import Diagnostic, FileCtx, Rule, dotted_name


@dataclasses.dataclass(frozen=True)
class ClassMap:
    default_thread: str
    methods: Mapping[str, str] = dataclasses.field(default_factory=dict)
    attrs: Mapping[str, str] = dataclasses.field(default_factory=dict)
    # method name → lock attrs the CALLER is contractually holding (the
    # `_locked`-suffix helper convention: the lock is acquired upstream)
    holds: Mapping[str, Tuple[str, ...]] = dataclasses.field(
        default_factory=dict
    )


# The shipped ownership model. Thread names are labels, not OS identities:
# "train" = the thread running Learner.train(), "engine" = the snapshot
# thread, "reader"/"writer"/"accept" = the transport's per-connection and
# accept threads, "learner" = the single consuming/publishing side of a
# transport object.
OWNERSHIP: Dict[str, Dict[str, ClassMap]] = {
    "dotaclient_tpu/train/learner.py": {
        "Learner": ClassMap(
            default_thread="train",
            methods={
                # the async log-boundary continuation runs ON the snapshot
                # thread (submitted via submit_metrics)
                "_finish_metrics": "engine",
                # overlap mode's in-process actor pool thread
                "actor_loop": "actor",
                # signal-handler entry: one latched flag write
                "request_stop": "any",
            },
            attrs={
                # THE donation hazard: in-flight dispatches donate the
                # TrainState's buffers, so only the train thread — which
                # ordered those dispatches — may ever touch it.
                "state": "train",
                # deferred best-model candidate: written by the snapshot
                # thread's metrics continuation, consumed on the train
                # thread — the PR 5 race fix made the swap lock-protected.
                "_pending_best": "lock:_pending_best_lock",
                # sync-gate fold state (PR 6 race fix): cleared by rollback
                # and folded by sync boundaries, all on the train thread.
                "_last_verdict_m": "train",
                "_prefetched": "train",
                "_prefetch_ticket": "train",
                "_mb_rng": "train",
                "_mb_draws": "train",
                "_host_step": "train",
                "_host_version": "train",
                "_dispatch_inflight": "train",
                "_stall_s": "train",
                "_published_version": "train",
                "_rollback_count": "train",
                "_best_win": "train",
                "_last_metrics": "train",
                # utilization accountant (ISSUE 16): phase intervals and
                # folds all happen on the train thread's loop
                "_util": "train",
                # latched stop flag: written by the signal handler, read by
                # every loop — single bool write, stale reads are the design
                "_stop_requested": "any",
            },
        ),
    },
    "dotaclient_tpu/train/snapshot.py": {
        "SnapshotEngine": ClassMap(
            default_thread="train",   # submit/drain/stop: caller side
            methods={
                "_run": "engine",
                "_fetch": "engine",
                "_do_publish": "engine",
                "_do_checkpoint": "engine",
                "_do_metrics": "engine",
            },
            attrs={
                "_jobs": "lock:_cond",
                "_stats_jobs": "lock:_cond",
                "_busy": "lock:_cond",
                "_stopped": "lock:_cond",
                # engine-private monotonic floor; the train thread reads it
                # only after drain() (waived at the property)
                "_last_published": "engine",
            },
            holds={"_pending_locked": ("_cond",)},
        ),
    },
    "dotaclient_tpu/train/health.py": {
        "HealthMonitor": ClassMap(
            default_thread="any",   # called from train AND engine threads
            attrs={
                "_pending": "lock:_lock",
                "_gen": "lock:_lock",
                "_ema_grad": "lock:_lock",
                "_healthy_folds": "lock:_lock",
                "_unhealthy": "lock:_lock",
            },
        ),
    },
    "dotaclient_tpu/transport/socket_transport.py": {
        "TransportServer": ClassMap(
            default_thread="learner",
            methods={
                "_accept_loop": "accept",
                "_reader_loop": "reader",
                "_poison": "reader",
                "_enqueue_rollouts": "reader",
                "_writer_loop": "writer",
                # torn down from readers, writers, publish, and close alike;
                # it touches only lock-guarded state and the conn's own cond
                "_drop": "any",
                "close": "any",
            },
            attrs={
                "_rollouts": "lock:_roll_cond",
                "_conns": "lock:_conns_lock",
                "_latest_weights": "lock:_weights_lock",
                "_latest_payload": "lock:_weights_lock",
                "_latest_crc": "lock:_weights_lock",
                "_publish_seq": "lock:_weights_lock",
                "dropped": "lock:_roll_cond",
                "bad_payloads": "learner",
                "_rollout_totals": "learner",
            },
        ),
    },
    "dotaclient_tpu/serve/engine.py": {
        # Serving plane (ISSUE 11): reader threads submit, ONE batcher
        # thread owns every carry/staging/params mutation (weight swaps
        # and slot zeroes are marshalled to it through latest-wins/pending
        # sets), the weight-swap thread only parks host trees. The PR 5-6
        # race shapes are exactly what this map machine-checks from day
        # one: a reader touching the carry store, a swap landing
        # mid-dispatch, a reply raced past its connection's death.
        "ServeEngine": ClassMap(
            default_thread="client",   # submit/release/stop: caller side
            methods={
                "_batch_loop": "batcher",
                "_apply_pending_weights": "batcher",
                "_collect_window": "batcher",
                "_dispatch_window": "batcher",
                # the parity probe replays the batcher's compiled dispatch
                # on the batcher's data — valid only with the server
                # quiesced, so it is held to the batcher's discipline
                "reference_step": "batcher",
            },
            attrs={
                "_pending": "lock:_cond",
                "_reset_slots": "lock:_cond",
                "_stopped": "lock:_cond",
                "_pending_weights": "lock:_weights_lock",
                # THE carry-residency hazard: dispatches donate the store's
                # buffers, so only the batcher — which ordered those
                # dispatches — may ever touch it (slot zeroes marshal
                # through _reset_slots, never direct writes).
                "_carries": "batcher",
                "_params": "batcher",
                "_lanes": "batcher",
                "_slots_np": "batcher",
                "_reset_np": "batcher",
                "_dispatch_idx": "batcher",
                # latched int: written by the batcher at swap commit, read
                # by attach frames — one-dispatch-stale reads are the design
                "_version": "any",
                # utilization accountant (ISSUE 16): window_wait/dispatch/
                # reply intervals and folds all happen on the batcher
                "_util": "batcher",
            },
        ),
    },
    "dotaclient_tpu/serve/server.py": {
        "PolicyServer": ClassMap(
            default_thread="learner",   # construct/attach/close: owner side
            methods={
                "_accept_loop": "accept",
                "_reader_loop": "reader",
                "_poison": "reader",
                "_make_reply": "reader",
                "_writer_loop": "writer",
                # the weights-subscription poller (attach_weights_source)
                "loop": "weights",
                # torn down from readers, writers, and close alike; touches
                # only lock-guarded state and the conn's own cond
                "_drop": "any",
                "_publish_conn_gauges": "any",
            },
            attrs={
                "_conns": "lock:_conns_lock",
                "_free_slots": "lock:_conns_lock",
                "_weights_thread": "learner",
            },
        ),
    },
    "dotaclient_tpu/utils/tracing.py": {
        # Trace writer (ISSUE 12): the SnapshotEngine division of labor
        # applied to trace events — any pipeline thread enqueues
        # (lock-free, GIL-atomic deque append), ONE writer thread owns
        # the file. The map pins that: the first future "quick fix" that
        # writes the file from a producer thread trips this pass, not a
        # reviewer (regression fixture in tests/test_lint.py).
        "TraceWriter": ClassMap(
            default_thread="producer",
            methods={
                "_run": "writer",
                # close() joins the writer before touching the file —
                # the post-join access is waived at the line
                "close": "any",
            },
            attrs={
                # the file handle is the writer's alone
                "_f": "writer",
                # bounded deque: append (producers) and popleft (writer)
                # are each GIL-atomic; no lock by design
                "_queue": "any",
                # latched stop flag: single bool write, stale reads fine
                "_stopped": "any",
            },
        ),
    },
    "dotaclient_tpu/utils/fleet.py": {
        # Fleet health plane (ISSUE 13): the three-way split the module
        # docstring declares, machine-checked. INGEST runs on transport
        # reader threads (socket) or the learner's consume thread (shm
        # drain) and may only park decoded snapshots in the locked inbox;
        # the MERGE/ROLLUP/ALERT state — per-peer tables and the alert
        # engine's rule state — belongs to the aggregator's own thread
        # alone (an unlocked cross-thread rule-state touch is the pinned
        # regression fixture in tests/test_lint.py); everything else
        # reads through the thread-safe telemetry registry.
        "FleetAggregator": ClassMap(
            default_thread="learner",   # construct/start/stop: owner side
            methods={
                "ingest": "reader",
                "_run": "agg",
                "tick": "agg",
                "_merge": "agg",
                "_rollup": "agg",
                "_peer_counter": "agg",
                "_peer_gauge": "agg",
                "_peer_metric": "agg",
            },
            attrs={
                "_inbox": "lock:_lock",
                "_peers": "agg",
                "_engine": "agg",
                "_thread": "learner",
                # registered by the learner BEFORE start() and only read
                # by the aggregator thread afterwards (handoff-by-start
                # contract documented at the attribute)
                "_tick_hooks": "any",
            },
        ),
    },
    "dotaclient_tpu/outcome/aggregator.py": {
        # Outcome attribution plane (ISSUE 15): tick() has MODAL callers —
        # the fleet aggregator's thread in external-transport modes, the
        # train thread at log boundaries in the in-process modes — so the
        # window state is lock-guarded rather than thread-owned; every
        # other consumer reads the published gauges through the
        # thread-safe telemetry registry.
        "OutcomeAggregator": ClassMap(
            default_thread="any",
            attrs={
                "_samples": "lock:_lock",
                "_armed": "lock:_lock",
                "_last_total_eps": "lock:_lock",
                "_last_episode_t": "lock:_lock",
            },
            holds={"_publish": ("_lock",), "_total_eps": ("_lock",)},
        ),
    },
    "dotaclient_tpu/utils/utilization.py": {
        # Pipeline utilization plane (ISSUE 16): an accountant is owned
        # by exactly the thread that constructed it — train thread
        # (LearnerUtilization), an actor pool's step loop, or serve's
        # batcher (PoolUtilization). No locks by design: the map pins
        # that the first cross-thread "quick fix" (folding a pool's
        # accountant from another thread) trips this pass, not a review.
        "PhaseAccountant": ClassMap(
            default_thread="owner",
            attrs={
                "_acc": "owner",
                "_window_start": "owner",
            },
        ),
        "LearnerUtilization": ClassMap(
            default_thread="owner",
            attrs={
                "_last_step": "owner",
                "_ema_v": "owner",
                "_baseline_v": "owner",
                "_windows": "owner",
            },
        ),
        "PoolUtilization": ClassMap(
            default_thread="owner",
            attrs={"_last_fold": "owner"},
        ),
    },
    "dotaclient_tpu/transport/shm_transport.py": {
        # Single-consumer by design: every method runs on the learner
        # thread (no background threads in the shm server — liveness is
        # the pid beacon, not a thread). The map pins that: the first
        # future thread added here trips the pass instead of a review.
        "ShmTransportServer": ClassMap(
            default_thread="learner",
            attrs={
                "_consumed": "learner",
                "_next_ring": "learner",
                "_last_telemetry": "learner",
                "_bad_streak": "learner",
                "_quarantined": "learner",
                "_rollout_totals": "learner",
                "_closed": "learner",
            },
        ),
    },
}


def _with_lock_stack(
    node: ast.With, lock_prefix: str = "self."
) -> List[str]:
    """Lock attr names a With statement acquires (``with self._lock:`` →
    ["_lock"])."""
    out = []
    for item in node.items:
        name = dotted_name(item.context_expr)
        if name and name.startswith(lock_prefix):
            out.append(name[len(lock_prefix):])
    return out


class _ClassScanner:
    def __init__(
        self, rel: str, cls: ast.ClassDef, cmap: ClassMap, rule_id: str
    ) -> None:
        self.rel = rel
        self.cls = cls
        self.cmap = cmap
        self.rule_id = rule_id
        self.out: List[Diagnostic] = []

    def scan(self) -> List[Diagnostic]:
        for stmt in self.cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if stmt.name == "__init__":
                    continue  # construction: the object is not shared yet
                self._scan_def(stmt, def_stack=[stmt.name], locks=[])
        return self.out

    def _thread_of(self, def_stack: List[str]) -> str:
        for name in reversed(def_stack):
            if name in self.cmap.methods:
                return self.cmap.methods[name]
        return self.cmap.default_thread

    def _scan_def(
        self,
        node: ast.AST,
        def_stack: List[str],
        locks: List[str],
    ) -> None:
        held = list(locks)
        for outer_name in def_stack:
            held.extend(self.cmap.holds.get(outer_name, ()))
        for child in ast.iter_child_nodes(node):
            self._visit(child, def_stack, held)

    def _visit(
        self, node: ast.AST, def_stack: List[str], locks: List[str]
    ) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._scan_def(node, def_stack + [node.name], locks)
            return
        if isinstance(node, ast.With):
            inner = locks + _with_lock_stack(node)
            for child in ast.iter_child_nodes(node):
                self._visit(child, def_stack, inner)
            return
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            self._check_access(node, def_stack, locks)
        for child in ast.iter_child_nodes(node):
            self._visit(child, def_stack, locks)

    def _check_access(
        self, node: ast.Attribute, def_stack: List[str], locks: List[str]
    ) -> None:
        spec = self.cmap.attrs.get(node.attr)
        if spec is None or spec == "any":
            return
        method = def_stack[-1]
        if spec.startswith("lock:"):
            lock = spec[5:]
            if lock in locks:
                return
            self.out.append(
                Diagnostic(
                    self.rel,
                    node.lineno,
                    self.rule_id,
                    f"'{self.cls.name}.{node.attr}' accessed in "
                    f"{method}() outside 'with self.{lock}:' — the "
                    f"ownership map (lint/ownership.py) declares it "
                    f"lock-guarded; acquire the lock, list the method "
                    f"under holds=, or waive with a why",
                    context=f"{self.cls.name}.{method}.{node.attr}",
                )
            )
            return
        thread = self._thread_of(def_stack)
        if thread == spec:
            return
        self.out.append(
            Diagnostic(
                self.rel,
                node.lineno,
                self.rule_id,
                f"'{self.cls.name}.{node.attr}' is owned by the "
                f"{spec} thread but {method}() runs on the "
                f"{thread} thread (ownership map, lint/ownership.py) — "
                f"marshal through the owner, add a lock, or waive with "
                f"a why",
                context=f"{self.cls.name}.{method}.{node.attr}",
            )
        )


def scan_source_with_map(
    rel: str, source: str, class_maps: Dict[str, ClassMap],
    rule_id: str = "thread-ownership",
) -> List[Diagnostic]:
    """Scan one module against an explicit map (the unit-test surface —
    fixtures inject race-shaped snippets with a matching map)."""
    tree = ast.parse(source, rel)
    out: List[Diagnostic] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name in class_maps:
            out.extend(
                _ClassScanner(
                    rel, node, class_maps[node.name], rule_id
                ).scan()
            )
    return out


class ThreadOwnershipRule(Rule):
    id = "thread-ownership"
    summary = (
        "shared attributes are touched only by their owning thread or "
        "under their declared lock"
    )

    def paths(self) -> Iterable[str]:
        return sorted(OWNERSHIP)

    def check(self, files: Dict[str, FileCtx]) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for rel in sorted(OWNERSHIP):
            ctx = files.get(rel)
            if ctx is None:
                continue
            out.extend(
                scan_source_with_map(rel, ctx.source, OWNERSHIP[rel], self.id)
            )
        return out
