"""graftlint core: shared file walker, diagnostics, waivers, baseline.

The framework behind ``python -m dotaclient_tpu.lint`` (ISSUE 9). The
disciplines the learner's performance and correctness rest on — the
dispatch-only hot path, never-read-after-donate buffers, per-thread state
ownership, the documented telemetry/config contracts — regress silently:
nothing crashes when they break, things just get slow, corrupt, or
undocumented. Each discipline is therefore a *pass* (a :class:`Rule`) over
a shared single-parse AST walk, and every finding is either fixed,
consciously waived at the line, or grandfathered in the committed baseline.

Vocabulary:

* **Diagnostic** — one finding: ``file:line rule-id message``.
* **Waiver** — ``# lint-ok: <rule>(<why>)`` on the finding's line or the
  line above. The why is mandatory: a waiver is a reviewed decision, not a
  mute button. (The host-sync pass additionally honors its historical
  ``# host-sync-ok: <why>`` spelling — see
  :mod:`dotaclient_tpu.lint.host_sync`.)
* **Baseline** — ``dotaclient_tpu/lint/baseline.txt``: fingerprints of
  grandfathered findings (each with a tracking comment). Non-strict runs
  suppress them; ``--strict`` does not. Fingerprints hash the *stripped
  source line text* (plus rule id and context), not the line number, so
  unrelated edits above a finding do not invalidate the baseline.
* **Rule** — a pass. It declares the repo-relative ``paths`` it wants;
  the runner parses each file once into a :class:`FileCtx` and hands every
  rule the same map (the shared walker), so N rules cost one parse per
  file.

Rules live in their own modules and register in ``ALL_RULES``
(``__init__``). They must stay import-light — no jax, no numpy — because
the tier-1 wrapper runs the full lint in-process on every test run.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# the conscious-override escape hatch: rule-scoped, why mandatory (the
# lookahead requires the why to start on the marker line; it may continue
# onto following comment lines — waived() walks contiguous comment blocks)
LINT_OK_RE = re.compile(r"#\s*lint-ok:\s*([a-z0-9-]+)\s*\((?=[^)\s])")

DEFAULT_BASELINE = "dotaclient_tpu/lint/baseline.txt"


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding. ``context`` disambiguates the fingerprint when two
    findings share a source line (e.g. a function name or telemetry key);
    it is part of the baseline identity, never of the display."""

    path: str          # repo-relative, forward slashes
    line: int          # 1-based; 0 for whole-file/doc-level findings
    rule: str          # rule id (kebab-case)
    message: str
    context: str = ""

    def format(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"


class FileCtx:
    """One parsed file, shared by every pass: source, lines, AST (``None``
    for non-Python files), and the ``# lint-ok`` waiver map."""

    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        if path.endswith(".py"):
            self.tree = ast.parse(source, path)
        self.lint_ok: Dict[int, Set[str]] = {}
        for i, text in enumerate(self.lines, 1):
            for m in LINT_OK_RE.finditer(text):
                self.lint_ok.setdefault(i, set()).add(m.group(1))

    def waived(self, line: int, rule: str) -> bool:
        """True when ``line`` carries a ``# lint-ok: <rule>(<why>)``
        waiver, or the contiguous comment block directly above it does
        (multi-line whys are encouraged — the why is the point)."""
        if rule in self.lint_ok.get(line, ()):
            return True
        k = line - 1
        while k >= 1 and self.line_text(k).lstrip().startswith("#"):
            if rule in self.lint_ok.get(k, ()):
                return True
            k -= 1
        return False

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


class Rule:
    """Base class for a pass. Subclasses set ``id``/``summary``, list the
    repo-relative files they scan in :meth:`paths`, and emit diagnostics
    from :meth:`check`. The runner handles waivers and the baseline."""

    id: str = ""
    summary: str = ""

    def paths(self) -> Iterable[str]:
        raise NotImplementedError

    def check(self, files: Dict[str, FileCtx]) -> List[Diagnostic]:
        raise NotImplementedError


def package_py_files(
    root: str = REPO_ROOT, package: str = "dotaclient_tpu"
) -> List[str]:
    """Every .py file of the package, repo-relative, sorted — the default
    scan set for package-wide passes. Generated code is excluded (protos)."""
    out: List[str] = []
    for dirpath, dirnames, filenames in os.walk(os.path.join(root, package)):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if not f.endswith(".py") or f.endswith("_pb2.py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, f), root)
            out.append(rel.replace(os.sep, "/"))
    return sorted(out)


def fingerprint(diag: Diagnostic, ctx: Optional[FileCtx]) -> str:
    """Baseline identity of a finding: path | rule | hash of (rule, the
    stripped source line, context). Line-number-free, so edits elsewhere
    in the file do not churn the baseline."""
    basis = diag.message
    if ctx is not None and diag.line:
        text = ctx.line_text(diag.line).strip()
        if text:
            basis = text
    h = hashlib.sha1(
        f"{diag.rule}|{basis}|{diag.context}".encode()
    ).hexdigest()[:12]
    return f"{diag.path}|{diag.rule}|{h}"


def load_baseline(path: str) -> List[str]:
    """Fingerprint lines (comments/blanks skipped); [] for a missing file."""
    return [fp for _comments, fp in load_baseline_blocks(path)]


def load_baseline_blocks(path: str) -> List[Tuple[List[str], str]]:
    """The baseline as (comment-lines, fingerprint) blocks, preserving
    each entry's tracking comment — the unit ``--update-baseline`` must
    keep intact for entries whose rule did not run."""
    if not os.path.exists(path):
        return []
    blocks: List[Tuple[List[str], str]] = []
    pending: List[str] = []
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line:
                pending = []
                continue
            if line.startswith("#"):
                pending.append(line)
                continue
            blocks.append((pending, line))
            pending = []
    return blocks


def baseline_rule(fp: str) -> str:
    """Rule id a fingerprint belongs to ('' for malformed lines)."""
    parts = fp.split("|")
    return parts[1] if len(parts) == 3 else ""


def write_baseline(
    path: str,
    entries: Sequence[Tuple[str, Diagnostic]],
    preserved: Sequence[Tuple[List[str], str]] = (),
) -> None:
    """Rewrite the baseline: one tracking comment + fingerprint per
    grandfathered finding (``--update-baseline``). ``preserved`` blocks
    (entries of rules that did not run, with their original comments)
    are kept verbatim ahead of the regenerated entries."""
    with open(path, "w") as f:
        f.write(
            "# graftlint baseline — grandfathered findings "
            "(python -m dotaclient_tpu.lint --update-baseline).\n"
            "# Each entry is a fingerprint (path|rule|hash of the source "
            "line) preceded by a\n"
            "# tracking comment; fix the finding and drop its entry. "
            "--strict ignores this file.\n"
        )
        for comments, fp in preserved:
            f.write("\n")
            for c in comments:
                f.write(c + "\n")
            f.write(fp + "\n")
        for fp, diag in sorted(entries, key=lambda e: e[0]):
            f.write(f"\n# TRACKING: {diag.format()}\n{fp}\n")


@dataclasses.dataclass
class LintResult:
    new: List[Tuple[Diagnostic, str]]          # (diag, fingerprint)
    suppressed: List[Tuple[Diagnostic, str]]   # baseline-matched
    stale_baseline: List[str]                  # baselined but no longer found
    per_rule: Dict[str, int]                   # new findings per rule id

    @property
    def failed(self) -> bool:
        return bool(self.new)


def run_rules(
    rules: Sequence[Rule],
    root: str = REPO_ROOT,
    baseline: Optional[Sequence[str]] = None,
    strict: bool = False,
) -> LintResult:
    """The shared walker + runner: parse each requested file once, run
    every rule, apply waivers, then split findings against the baseline.
    ``strict`` disables baseline suppression (waivers still apply — they
    are in-code, reviewed decisions; the baseline is the debt list)."""
    files: Dict[str, FileCtx] = {}
    for rule in rules:
        for rel in rule.paths():
            if rel in files:
                continue
            path = os.path.join(root, rel)
            if not os.path.exists(path):
                continue  # a rule's target may not exist in a pruned tree
            with open(path) as f:
                files[rel] = FileCtx(rel, f.read())
    baseline_set = set(baseline or ())
    matched: Set[str] = set()
    new: List[Tuple[Diagnostic, str]] = []
    suppressed: List[Tuple[Diagnostic, str]] = []
    per_rule: Dict[str, int] = {r.id: 0 for r in rules}
    for rule in rules:
        for diag in rule.check(files):
            ctx = files.get(diag.path)
            if ctx is not None and diag.line and ctx.waived(diag.line, rule.id):
                continue
            fp = fingerprint(diag, ctx)
            if not strict and fp in baseline_set:
                matched.add(fp)
                suppressed.append((diag, fp))
                continue
            per_rule[rule.id] += 1
            new.append((diag, fp))
    # an entry is stale only when its OWN rule ran and no longer produces
    # it — a --rule subset run must not report other rules' entries
    ran = {r.id for r in rules}
    stale = (
        sorted(
            fp
            for fp in baseline_set - matched
            if fp.split("|")[1:2] and fp.split("|")[1] in ran
        )
        if not strict
        else []
    )
    return LintResult(
        new=new, suppressed=suppressed, stale_baseline=stale, per_rule=per_rule
    )


# -- shared AST helpers (used by several passes) ---------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``self.state.params`` → "self.state.params"; None for anything that
    is not a plain Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def assign_targets(stmt: ast.stmt) -> List[str]:
    """Dotted names a statement (re)binds, tuple targets flattened."""
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    out: List[str] = []

    def _flatten(t: ast.expr) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                _flatten(e)
        else:
            name = dotted_name(t)
            if name:
                out.append(name)

    for t in targets:
        _flatten(t)
    return out
