"""telemetry-drift pass: code, schema tiers, and docs agree on every key.

Three places claim to know the telemetry key set: the code that emits it
(``.counter("...")`` / ``.gauge("...")`` / ``.span("...")`` /
``.timer("span/...")`` sites), the hand-maintained tier lists in
``scripts/check_telemetry_schema.py`` (the CI contract), and the
docs/ARCHITECTURE.md "Observability" tables (the operator contract). They
drift independently: a renamed counter silently orphans its runbook row, a
documented key that was never wired ships a false promise, and the schema
checker only notices keys it already knows about.

This pass extracts all three sets statically and fails on:

* **documented-but-never-emitted** — a key in a schema tier list (or in
  ARCHITECTURE.md) with no emission site in the package;
* **emitted-but-undocumented** — an emission site whose key appears
  nowhere in ARCHITECTURE.md (span stages may be documented bare, e.g.
  ``actor/collect``, or rooted, ``span/actor/collect``);
* **unresolvable emission** — a key built from an expression the
  extractor cannot expand (see below), which would silently escape both
  checks.

Extraction handles the idioms the codebase actually uses: literal
strings; ``for key in ("a", "b"): ....gauge(key)`` eager-creation loops
(the loop literals are expanded); and f-string keys whose (prefix,
suffix) pair is declared in ``DYNAMIC_KEY_EXPANSIONS`` (e.g.
``f"snapshot/{kind}_coalesced"``). Anything else flags — add the
expansion or use a literal. Doc keys support ``{a,b,c}`` brace expansion
and ``*``/``<var>`` wildcards (wildcards document families and satisfy
emitted-key lookups; they are not themselves required to be emitted).

``utils/telemetry.py`` (the registry mechanism — its internal key
composition is not an emission) is excluded from extraction.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from dotaclient_tpu.lint.core import (
    Diagnostic,
    FileCtx,
    Rule,
    package_py_files,
)

ARCHITECTURE_MD = "docs/ARCHITECTURE.md"
SCHEMA_SCRIPT = "scripts/check_telemetry_schema.py"

# The registry mechanism itself: composes keys generically, emits nothing.
EXCLUDED_FILES = ("dotaclient_tpu/utils/telemetry.py",)

_EMIT_METHODS = ("counter", "gauge", "timer", "span")

# Declared expansions for f-string keys: (constant prefix, constant
# suffix) → the values the formatted hole takes. Keep in sync with the
# emitting site's comment.
_INSTRUMENTED_PROGRAMS = (
    # every instrument_jit(..., name) site in the package (ISSUE 12):
    # learner, buffer, and serve jit entry points. A NEW instrumented
    # program must be added here (its per-program compile keys are
    # f-strings in utils/tracing.py) and is covered by the
    # `compile/<program>/...` wildcard rows in ARCHITECTURE.md.
    "train_step", "epoch_step", "fused_step", "minibatch_gather",
    "snap_copy", "buffer_scatter", "buffer_scatter_dev", "buffer_gather",
    "serve_dispatch", "advantage_pass",
)

DYNAMIC_KEY_EXPANSIONS: Dict[Tuple[str, str], Tuple[str, ...]] = {
    # train/snapshot.py: one coalesce counter per job slot kind (_KINDS)
    ("snapshot/", "_coalesced"): ("publish", "checkpoint", "metrics"),
    # utils/tracing.py InstrumentedJit: per-program compile accounting
    ("compile/", "/compiles_total"): _INSTRUMENTED_PROGRAMS,
    ("compile/", "/retraces_total"): _INSTRUMENTED_PROGRAMS,
    ("compile/", "/last_compile_s"): _INSTRUMENTED_PROGRAMS,
    # utils/fleet.py FleetAggregator rollups: fleet/agg/<metric>/<stat>
    # gauges across live peers — keep in sync with fleet.AGG_SOURCES ×
    # AGG_STATS and the FLEET_KEYS schema tier
    ("fleet/agg/", ""): (
        "weight_staleness/min", "weight_staleness/max",
        "weight_staleness/mean",
        "env_fps/min", "env_fps/max", "env_fps/mean",
        "reconnects/min", "reconnects/max", "reconnects/mean",
        "corrupt_frames/min", "corrupt_frames/max", "corrupt_frames/mean",
        "ship_wait/min", "ship_wait/max", "ship_wait/mean",
    ),
    # utils/fleet.py per-peer mirror keys: fleet/<peer>/<shipped metric>
    # (peer labels are runtime values — representative members here; the
    # family is documented as the `fleet/<peer>/*` wildcard row)
    ("fleet/", ""): (
        "a0/actor/env_steps", "a0/env_fps",
    ),
    # serve/router.py per-backend session gauges (ISSUE 19): backend
    # indices are runtime values — representative members; documented as
    # the `router/backend/<i>/sessions` wildcard row
    ("router/backend/", "/sessions"): ("0", "1"),
    # Outcome attribution plane (ISSUE 15; dotaclient_tpu/outcome/).
    # Keep the value tuples in sync with outcome.records BUCKETS / SIDES
    # / REWARD_TERMS / N_LEN_BUCKETS and the OUTCOME_KEYS schema tier.
    ("outcome/episodes/", ""): (
        "vs_scripted", "vs_league", "vs_selfplay",
    ),
    ("outcome/wins/", ""): (
        "vs_scripted", "vs_league", "vs_selfplay",
    ),
    ("outcome/win_rate/", ""): (
        "vs_scripted", "vs_league", "overall",
    ),
    ("outcome/episodes_side/", ""): ("radiant", "dire"),
    ("outcome/ep_len_hist/", ""): (
        "00", "01", "02", "03", "04", "05",
        "06", "07", "08", "09", "10", "11",
    ),
    ("outcome/reward_sum/", ""): (
        "xp", "gold", "hp", "enemy_hp", "last_hits", "denies", "kills",
        "deaths", "tower_damage", "own_tower", "win",
    ),
    ("outcome/reward/", ""): (
        "xp", "gold", "hp", "enemy_hp", "last_hits", "denies", "kills",
        "deaths", "tower_damage", "own_tower", "win",
    ),
}

# Token shape of a telemetry key in backticked doc text: slash-separated
# lowercase segments, optional {a,b}/<var>/* holes; no dots (dots mean a
# file path or config field, not a key).
_DOC_KEY_RE = re.compile(
    r"^[a-z][a-z0-9_]*(?:/[a-z0-9_{},<>*]+)+$"
)

# Namespaces telemetry keys live in. Doc tokens outside these are
# key-shaped but not keys (rollout leaf names like `obs/hero_id`,
# `carry0/*`) — never treated as documented-telemetry claims. A NEW
# namespace must be added here when its first key is minted.
KEY_PREFIXES = (
    "actor/", "advantage/", "alerts/", "buffer/", "checkpoint/",
    "compile/", "faults/", "fleet/", "fused/", "health/", "league/",
    "learner/", "mem/", "mesh/", "outcome/", "router/", "serve/",
    "shm/", "snapshot/", "span/", "trace/", "transport/", "util/",
)
# single-line inline code only: multi-line matches would mispair across
# ``` fence lines (odd backtick count flips pairing for the whole doc)
_BACKTICK_RE = re.compile(r"`([^`\n]+)`")


# -- emitted-key extraction -------------------------------------------------


def _loop_literal_bindings(func: ast.AST) -> Dict[int, Dict[str, List[str]]]:
    """For every ``for NAME in (<str literals>):`` in ``func``, map the
    loop body's line span to {NAME: literals} so a ``.gauge(NAME)`` call
    inside resolves."""
    out: Dict[int, Dict[str, List[str]]] = {}
    for node in ast.walk(func):
        if not isinstance(node, ast.For):
            continue
        if not isinstance(node.target, ast.Name):
            continue
        lits = _str_literals(node.iter)
        if lits is None:
            continue
        for line in range(node.lineno, (node.end_lineno or node.lineno) + 1):
            out.setdefault(line, {})[node.target.id] = lits
    return out


def _str_literals(node: ast.AST) -> Optional[List[str]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return out
    return None


def extract_emitted(
    files: Dict[str, FileCtx],
) -> Tuple[Set[str], List[Tuple[str, int, str]], List[Diagnostic]]:
    """→ (emitted keys, [(key, line, path)] sites, unresolvable-site
    diagnostics). Span/timer keys are normalized to ``span/<stage>``."""
    keys: Set[str] = set()
    sites: List[Tuple[str, int, str]] = []
    problems: List[Diagnostic] = []
    for rel in sorted(files):
        ctx = files[rel]
        if ctx.tree is None or rel in EXCLUDED_FILES:
            continue
        if not rel.startswith("dotaclient_tpu/"):
            continue
        loop_bindings = _loop_literal_bindings(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute) or fn.attr not in _EMIT_METHODS:
                continue
            if not node.args:
                continue
            arg = node.args[0]
            resolved = _resolve_key_arg(arg, node.lineno, loop_bindings)
            if resolved is None:
                problems.append(
                    Diagnostic(
                        rel,
                        node.lineno,
                        "telemetry-drift",
                        f".{fn.attr}(...) key is not statically "
                        f"resolvable — use a literal, the "
                        f"for-over-literals idiom, or declare the "
                        f"f-string in DYNAMIC_KEY_EXPANSIONS "
                        f"(lint/telemetry_drift.py)",
                    )
                )
                continue
            for key in resolved:
                if fn.attr == "span":
                    key = f"span/{key}"
                keys.add(key)
                sites.append((key, node.lineno, rel))
    return keys, sites, problems


def _resolve_key_arg(
    arg: ast.AST, line: int, loop_bindings: Dict[int, Dict[str, List[str]]]
) -> Optional[List[str]]:
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return [arg.value]
    if isinstance(arg, ast.Name):
        lits = loop_bindings.get(line, {}).get(arg.id)
        if lits is not None:
            return lits
        return None
    if isinstance(arg, ast.JoinedStr):
        prefix = suffix = ""
        holes = 0
        for part in arg.values:
            if isinstance(part, ast.Constant):
                if holes == 0:
                    prefix += str(part.value)
                else:
                    suffix += str(part.value)
            else:
                holes += 1
        if holes == 1:
            values = DYNAMIC_KEY_EXPANSIONS.get((prefix, suffix))
            if values is not None:
                return [f"{prefix}{v}{suffix}" for v in values]
        return None
    return None


# -- documented-key extraction ----------------------------------------------


def extract_doc_keys(doc_text: str) -> Tuple[Set[str], List[re.Pattern]]:
    """Backticked key tokens in doc text → (exact keys, wildcard
    patterns). ``{a,b}`` expands; ``*`` and ``<var>`` become wildcards."""
    exact: Set[str] = set()
    patterns: List[re.Pattern] = []
    for m in _BACKTICK_RE.finditer(doc_text):
        token = m.group(1).strip()
        if not _DOC_KEY_RE.match(token):
            continue
        if not token.startswith(KEY_PREFIXES):
            continue
        for expanded in _expand_braces(token):
            if "*" in expanded or "<" in expanded:
                rx = re.escape(expanded)
                rx = rx.replace(r"\*", r"[a-z0-9_/]+")
                rx = re.sub(r"<[a-z0-9_\\]+>", r"[a-z0-9_]+", rx)
                patterns.append(re.compile(f"^{rx}$"))
            else:
                exact.add(expanded)
    return exact, patterns


def _expand_braces(token: str) -> List[str]:
    m = re.search(r"\{([^{}]*)\}", token)
    if not m:
        return [token]
    head, tail = token[: m.start()], token[m.end():]
    out: List[str] = []
    for alt in m.group(1).split(","):
        out.extend(_expand_braces(head + alt.strip() + tail))
    return out


def _documented(
    key: str, exact: Set[str], patterns: List[re.Pattern]
) -> bool:
    candidates = [key]
    if key.startswith("span/"):
        candidates.append(key[len("span/"):])  # stages documented bare
    for c in candidates:
        if c in exact or any(p.match(c) for p in patterns):
            return True
    return False


# -- schema tier lists ------------------------------------------------------


def extract_schema_tiers(script_source: str) -> Dict[str, List[str]]:
    """Module-level ``*_KEYS``/``REQUIRED_KEYS`` tuple assignments of the
    schema checker, literal-evaluated (no import)."""
    tree = ast.parse(script_source)
    tiers: Dict[str, List[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        if not (target.id.endswith("_KEYS") or target.id == "REQUIRED_KEYS"):
            continue
        try:
            value = ast.literal_eval(node.value)
        except ValueError:
            continue
        if isinstance(value, (tuple, list)):
            tiers[target.id] = [v for v in value if isinstance(v, str)]
    return tiers


_TIMER_LEAVES = ("count", "total_s", "last_s", "mean_s", "ema_s", "p95_s")


def _tier_key_emitted(key: str, emitted: Set[str]) -> bool:
    if key in emitted:
        return True
    # span-leaf form: span/<stage>/<leaf> is emitted iff its root span is
    parts = key.split("/")
    if parts[0] == "span" and parts[-1] in _TIMER_LEAVES:
        return "/".join(parts[:-1]) in emitted
    return False


# -- the pass ---------------------------------------------------------------


def drift_findings(
    emitted: Set[str],
    sites: List[Tuple[str, int, str]],
    doc_text: str,
    tiers: Dict[str, List[str]],
    rule_id: str = "telemetry-drift",
    doc_path: str = ARCHITECTURE_MD,
    schema_path: str = SCHEMA_SCRIPT,
) -> List[Diagnostic]:
    """Pure cross-check (unit-testable: feed synthetic inputs)."""
    out: List[Diagnostic] = []
    exact, patterns = extract_doc_keys(doc_text)
    # 1. schema tiers: documented-but-never-emitted (the CI contract
    #    promises presence the code cannot deliver)
    for tier, keys in sorted(tiers.items()):
        for key in keys:
            if not _tier_key_emitted(key, emitted):
                out.append(
                    Diagnostic(
                        schema_path,
                        0,
                        rule_id,
                        f"{key!r} is required by schema tier {tier} but "
                        f"no emission site exists in the package — the "
                        f"tier would fail every run; fix the emitter or "
                        f"the tier list",
                        context=key,
                    )
                )
    # 2. ARCHITECTURE.md: documented-but-never-emitted
    for key in sorted(exact):
        if not (key in emitted or f"span/{key}" in emitted):
            out.append(
                Diagnostic(
                    doc_path,
                    0,
                    rule_id,
                    f"{key!r} is documented in ARCHITECTURE.md but no "
                    f"emission site exists in the package — stale docs "
                    f"or a renamed key",
                    context=key,
                )
            )
    # 3. emitted-but-undocumented (one finding per key, at its first site)
    first_site: Dict[str, Tuple[int, str]] = {}
    for key, line, rel in sites:
        first_site.setdefault(key, (line, rel))
    for key in sorted(emitted):
        if _documented(key, exact, patterns):
            continue
        line, rel = first_site.get(key, (0, doc_path))
        out.append(
            Diagnostic(
                rel,
                line,
                rule_id,
                f"telemetry key {key!r} is emitted here but absent from "
                f"the docs/ARCHITECTURE.md 'Observability' tables — "
                f"document it (operators grep those tables during "
                f"incidents) or rename/remove the emission",
                context=key,
            )
        )
    return out


class TelemetryDriftRule(Rule):
    id = "telemetry-drift"
    summary = (
        "emitted telemetry keys, schema tier lists, and ARCHITECTURE.md "
        "tables agree"
    )

    def paths(self) -> Iterable[str]:
        return package_py_files() + [ARCHITECTURE_MD, SCHEMA_SCRIPT]

    def check(self, files: Dict[str, FileCtx]) -> List[Diagnostic]:
        emitted, sites, problems = extract_emitted(files)
        doc = files.get(ARCHITECTURE_MD)
        schema = files.get(SCHEMA_SCRIPT)
        tiers = (
            extract_schema_tiers(schema.source) if schema is not None else {}
        )
        out = list(problems)
        out.extend(
            drift_findings(
                emitted,
                sites,
                doc.source if doc is not None else "",
                tiers,
                self.id,
            )
        )
        return out
