"""use-after-donate pass: donated XLA buffers are dead after dispatch.

``jax.jit(..., donate_argnums=...)`` hands the argument's device buffers
to XLA for in-place reuse: after the call dispatches, the Python reference
still exists but the buffers are garbage-in-waiting. On TPU a read is
silent corruption; on the CPU sandbox (no real donation) it *works*, which
is exactly why no test catches it — the classic "passed CI, corrupted the
pod" class. The fused epoch step, the plain train step, and the buffer's
ingest scatter all donate (``train/ppo.py``, ``buffer/trajectory_buffer.
py``), so the learner is one careless ``state.params`` read away.

The pass is a two-phase AST analysis over the whole package:

1. **Registry build.** Every module is scanned for donating callables:
   direct ``jax.jit(...)/pjit(...)`` calls carrying ``donate_argnums``
   (literal positions), and *factories* — module-level functions that
   return such a jit (``make_train_step`` → donates arg 0). Assignments
   ``self.step = jax.jit(..., donate_argnums=(0,))`` or
   ``self.step = make_train_step(...)`` then mark the dotted target as a
   donating callable within that module.
2. **Call-site scan.** For each call to a donating callable, the argument
   at each donated position (when it is a plain ``name``/``self.x.y``
   chain) is *tainted* from the end of the enclosing statement until the
   first statement that rebinds it (or a prefix of it). Any load of the
   tainted name — or a longer chain rooted at it, like ``self.state.
   params`` after ``self.state`` was donated — inside that window flags.
   The idiomatic rebind-in-the-same-statement
   (``self.state, m = self.step(self.state, batch)``) is recognized and
   never flags.

Known limits (this is a tripwire, not a prover): the window is textual
within one function, so a loop that donates without rebinding only flags
reads *after* the call line, and aliasing through a second variable is
invisible. Both are fine — the discipline the pass enforces is "rebind or
copy, visibly", and every violation of *that* is caught. Waive a
deliberate read with ``# lint-ok: use-after-donate(<why>)``.

Donation specs the pass cannot position-track — ``donate_argnames``, or a
``donate_argnums`` that is not a literal int/tuple (``donate_argnums=
DONATE``) — are reported once at the definition site: the pass would
otherwise be silently blind to every use of that callable, which is worse
than the friction of a literal tuple or a waived definition.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from dotaclient_tpu.lint.core import (
    Diagnostic,
    FileCtx,
    Rule,
    assign_targets,
    dotted_name,
    package_py_files,
)


# sentinel: the call donates, but the positions are not statically known
# (donate_argnames, or a non-literal donate_argnums expression) — such a
# definition gets its own diagnostic instead of silent taint blindness
UNTRACKABLE = "untrackable"


def _unwrap_instrumented(node: ast.AST) -> ast.AST:
    """See through ``tracing.instrument_jit(<jit-or-factory-call>, ...)``
    (ISSUE 12): the wrapper is call-transparent, so the donation spec of
    its first argument IS the spec of the wrapped callable. Without this,
    instrumenting a donating jit would silently drop its taint tracking —
    the exact blindness this pass exists to prevent."""
    if isinstance(node, ast.Call) and node.args:
        callee = dotted_name(node.func)
        if callee and callee.rsplit(".", 1)[-1] == "instrument_jit":
            return node.args[0]
    return node


def _donated_positions(call: ast.Call):
    """Literal donate_argnums of a jit/pjit call; () for a jit without
    donation; :data:`UNTRACKABLE` when it donates but the positions are
    not literal; None when the node is not a jit/pjit call at all."""
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None
    )
    if name not in ("jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, int):
                        out.append(e.value)
                    else:
                        return UNTRACKABLE  # mixed/non-literal element
                return tuple(out)
            return UNTRACKABLE  # name/expression spec
        if kw.arg == "donate_argnames":
            return UNTRACKABLE
    return ()


def _donating_call_spec(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """Donated positions when ``node`` is a jit/pjit call WITH literal,
    trackable donation (UNTRACKABLE specs report separately). An
    ``instrument_jit(...)`` wrapper is transparent."""
    node = _unwrap_instrumented(node)
    if not isinstance(node, ast.Call):
        return None
    pos = _donated_positions(node)
    if pos and pos is not UNTRACKABLE:
        return pos
    return None


def _untrackable_donation(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and _donated_positions(node) is UNTRACKABLE
    )


def build_factory_registry(
    files: Dict[str, FileCtx]
) -> Dict[str, Tuple[int, ...]]:
    """Module-level functions (by bare name, package-wide) that return a
    donating jit — directly or via a local variable. Conservative: a
    factory with ANY donating return donates."""
    registry: Dict[str, Tuple[int, ...]] = {}
    for ctx in files.values():
        if ctx.tree is None:
            continue
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            local_donating: Dict[str, Tuple[int, ...]] = {}
            returns_spec: Optional[Tuple[int, ...]] = None
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    spec = _donating_call_spec(sub.value)
                    if spec:
                        for t in assign_targets(sub):
                            local_donating[t] = spec
                elif isinstance(sub, ast.Return) and sub.value is not None:
                    spec = _donating_call_spec(sub.value)
                    if spec is None:
                        name = dotted_name(sub.value)
                        spec = local_donating.get(name) if name else None
                    if spec:
                        returns_spec = spec
            if returns_spec:
                registry[node.name] = returns_spec
    return registry


class _FuncAnalysis:
    """Taint analysis for one function body."""

    def __init__(
        self,
        func: ast.AST,
        donating: Dict[str, Tuple[int, ...]],
        rel: str,
        rule_id: str,
    ) -> None:
        self.func = func
        self.donating = donating
        self.rel = rel
        self.rule_id = rule_id

    def run(self) -> List[Diagnostic]:
        # statement list in source order, with each statement's bound names
        stmts: List[ast.stmt] = [
            n for n in ast.walk(self.func) if isinstance(n, ast.stmt)
        ]
        stmts.sort(key=lambda s: (s.lineno, s.col_offset))
        out: List[Diagnostic] = []
        for stmt in stmts:
            for call in ast.walk(stmt):
                if not isinstance(call, ast.Call):
                    continue
                callee = dotted_name(call.func)
                spec = self.donating.get(callee) if callee else None
                if not spec:
                    continue
                rebound = set(assign_targets(stmt))
                for pos in spec:
                    if pos >= len(call.args):
                        continue
                    donated = dotted_name(call.args[pos])
                    if donated is None or donated in rebound:
                        continue  # rebind-in-statement: the idiom, safe
                    out.extend(
                        self._taint_window(stmt, stmts, callee, donated)
                    )
        return out

    def _taint_window(
        self,
        call_stmt: ast.stmt,
        stmts: List[ast.stmt],
        callee: str,
        donated: str,
    ) -> List[Diagnostic]:
        start = getattr(call_stmt, "end_lineno", call_stmt.lineno)
        # first later statement that rebinds the donated name or a prefix
        # of it (rebinding `self.state` revives `self.state.params`)
        end = None
        prefixes = _prefixes(donated)
        for stmt in stmts:
            if stmt.lineno <= start:
                continue
            if any(t in prefixes for t in assign_targets(stmt)):
                end = stmt.lineno
                break
        out: List[Diagnostic] = []
        seen_lines: Set[int] = set()
        for node in ast.walk(self.func):
            if not isinstance(node, (ast.Name, ast.Attribute)):
                continue
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            name = dotted_name(node)
            if name is None:
                continue
            if name != donated and not name.startswith(donated + "."):
                continue
            line = node.lineno
            if line <= start or (end is not None and line >= end):
                continue
            if line in seen_lines:
                continue
            seen_lines.add(line)
            out.append(
                Diagnostic(
                    self.rel,
                    line,
                    self.rule_id,
                    f"read of {name!r} after it was donated to "
                    f"{callee!r} (line {call_stmt.lineno}) — donated "
                    f"buffers are invalid once the call dispatches "
                    f"(silent corruption on TPU, invisible on CPU); "
                    f"rebind the result, reorder the read, or copy "
                    f"before donating",
                    context=donated,
                )
            )
        return out


def _prefixes(name: str) -> Set[str]:
    """{"self", "self.state"} for "self.state" — rebinding any of these
    revives the donated name."""
    parts = name.split(".")
    return {".".join(parts[: i + 1]) for i in range(len(parts))}


def analyze_module(
    ctx: FileCtx,
    factories: Dict[str, Tuple[int, ...]],
    rule_id: str = "use-after-donate",
) -> List[Diagnostic]:
    """All use-after-donate findings for one module."""
    if ctx.tree is None:
        return []
    out: List[Diagnostic] = []
    # module-wide donating callables: self.X / X assigned from a donating
    # jit or a registered factory anywhere in the module
    donating: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            inner = _unwrap_instrumented(node.value)
            spec = _donating_call_spec(inner)
            if spec is None and isinstance(inner, ast.Call):
                callee = dotted_name(inner.func)
                if callee:
                    spec = factories.get(callee.rsplit(".", 1)[-1])
            if spec:
                for t in assign_targets(node):
                    donating[t] = spec
        if _untrackable_donation(node):
            out.append(
                Diagnostic(
                    ctx.path,
                    node.lineno,
                    rule_id,
                    "this jit donates but the positions are not "
                    "statically trackable (donate_argnames, or a "
                    "non-literal donate_argnums expression) — the pass "
                    "would be blind to every use-after-donate through "
                    "this callable; use a literal donate_argnums tuple, "
                    "or waive this definition with a why",
                )
            )
    if not donating:
        return out
    # every def is analyzed (closures reading a donated name must flag),
    # and a nested def's findings also surface through its parent's walk —
    # dedupe on (line, donated name) so each read reports once
    seen: set = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in _FuncAnalysis(node, donating, ctx.path, rule_id).run():
                key = (d.line, d.context)
                if key not in seen:
                    seen.add(key)
                    out.append(d)
    return out


class UseAfterDonateRule(Rule):
    id = "use-after-donate"
    summary = "no reads of a variable after its buffers were donated to XLA"

    def paths(self) -> Iterable[str]:
        return package_py_files()

    def check(self, files: Dict[str, FileCtx]) -> List[Diagnostic]:
        factories = build_factory_registry(files)
        out: List[Diagnostic] = []
        for rel in sorted(files):
            if not rel.startswith("dotaclient_tpu/"):
                continue
            out.extend(analyze_module(files[rel], factories, self.id))
        return out
