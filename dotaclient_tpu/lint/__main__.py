"""graftlint CLI: ``python -m dotaclient_tpu.lint [--strict] [--rule ...]``.

Exit 0 when clean (baseline-suppressed findings are reported as a count),
1 when any new finding exists. ``--strict`` ignores the baseline — every
grandfathered finding fails too (CI escalation: ``LINT_STRICT=1`` in the
tier-1 wrapper, the TIER1_DURATION_STRICT pattern). ``--update-baseline``
rewrites the baseline to exactly the current findings (each with a
tracking comment) — run it after triaging a new rule's first findings,
never to silence a regression.

Usage:
    python -m dotaclient_tpu.lint                 # all passes, baseline on
    python -m dotaclient_tpu.lint --strict        # baseline off
    python -m dotaclient_tpu.lint --rule host-sync --rule config-drift
    python -m dotaclient_tpu.lint --list-rules
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from dotaclient_tpu.lint import ALL_RULES
from dotaclient_tpu.lint.core import (
    DEFAULT_BASELINE,
    REPO_ROOT,
    baseline_rule,
    load_baseline,
    load_baseline_blocks,
    run_rules,
    write_baseline,
)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dotaclient_tpu.lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument(
        "--strict", action="store_true",
        help="ignore the baseline: grandfathered findings fail too",
    )
    p.add_argument(
        "--rule", action="append", default=None, metavar="ID",
        help="run only this rule (repeatable); default: all rules",
    )
    p.add_argument(
        "--baseline", type=str, default=None, metavar="PATH",
        help=f"baseline file (default {DEFAULT_BASELINE})",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to the current findings and exit 0",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    args = p.parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.id:20s} {cls.summary}")
        return 0

    by_id = {cls.id: cls for cls in ALL_RULES}
    if args.rule:
        unknown = [r for r in args.rule if r not in by_id]
        if unknown:
            p.error(
                f"unknown rule(s) {unknown} — one of {sorted(by_id)}"
            )
        rules = [by_id[r]() for r in args.rule]
    else:
        rules = [cls() for cls in ALL_RULES]

    baseline_path = args.baseline or os.path.join(
        REPO_ROOT, DEFAULT_BASELINE
    )
    baseline = load_baseline(baseline_path)
    result = run_rules(
        rules, REPO_ROOT, baseline=baseline, strict=args.strict
    )

    if args.update_baseline:
        entries = [(fp, d) for d, fp in result.new] + [
            (fp, d) for d, fp in result.suppressed
        ]
        # a --rule subset regenerates ONLY its own rules' entries: blocks
        # belonging to rules that did not run are preserved verbatim,
        # tracking comments included — a partial update must never wipe
        # another rule's grandfathered debt
        ran = {r.id for r in rules}
        preserved = [
            (comments, fp)
            for comments, fp in load_baseline_blocks(baseline_path)
            if baseline_rule(fp) not in ran
        ]
        write_baseline(baseline_path, entries, preserved=preserved)
        print(
            f"graftlint: baseline rewritten with {len(entries)} "
            f"finding(s) ({len(preserved)} entr"
            f"{'y' if len(preserved) == 1 else 'ies'} of non-run rules "
            f"preserved) → {os.path.relpath(baseline_path, REPO_ROOT)}"
        )
        return 0

    for diag, _fp in result.new:
        print(diag.format(), file=sys.stderr)
    if result.stale_baseline:
        # informational: fixed findings should leave the baseline too,
        # but a stale entry must not fail CI (line drift, deleted code)
        print(
            f"graftlint: note — {len(result.stale_baseline)} stale "
            f"baseline entr{'y' if len(result.stale_baseline) == 1 else 'ies'} "
            f"(fixed or moved); run --update-baseline to prune",
        )
    ran = ", ".join(r.id for r in rules)
    if result.new:
        counts = ", ".join(
            f"{rid}: {n}" for rid, n in sorted(result.per_rule.items()) if n
        )
        print(
            f"graftlint FAILED ({len(result.new)} finding(s) — {counts}; "
            f"{len(result.suppressed)} baseline-suppressed) "
            f"[rules: {ran}]",
            file=sys.stderr,
        )
        return 1
    print(
        f"graftlint OK: {len(rules)} passes clean "
        f"({len(result.suppressed)} baseline-suppressed) [rules: {ran}]"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
