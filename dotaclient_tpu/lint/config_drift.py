"""config-drift pass: CLI flags and override knobs match the operator docs.

Two operator contracts drift the same way telemetry keys do:

* **Override knobs.** Every dataclass field reachable through the
  ``--ppo/--reward/--league/--buffer/--health/--learner K=V`` override
  flags (``utils/overrides.py``) is a public tuning surface. The
  docs/OPERATIONS.md "Config override knobs" tables must list every such
  field, and every field the tables list must exist — a renamed field
  silently orphans its row; an undocumented field is a knob operators
  cannot find during an incident.
* **CLI flags.** Every ``--flag`` OPERATIONS.md mentions must exist in
  some entrypoint (a doc'd flag that argparse rejects is a broken
  runbook), and every flag the learner/actor CLIs define must appear in
  OPERATIONS.md (those two are the operator-facing surfaces; bench and
  one-off scripts document themselves).

Everything is extracted statically: ``config.py`` dataclass fields via
AST, ``add_argument("--x", ...)`` calls via AST, documented flags via a
regex that rejects ``--xla_...``-style env-var fragments, knob tables via
the ``### --flag (ClassName)`` heading + first-column-backtick convention.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from dotaclient_tpu.lint.core import Diagnostic, FileCtx, Rule

CONFIG_PY = "dotaclient_tpu/config.py"
OPERATIONS_MD = "docs/OPERATIONS.md"

# override flag → the dataclass it reaches (train/learner.py main();
# scripts/train_demo.py shares --ppo/--reward/--league via the same parser)
OVERRIDE_FLAGS: Dict[str, str] = {
    "--ppo": "PPOConfig",
    "--reward": "RewardConfig",
    "--league": "LeagueConfig",
    "--buffer": "BufferConfig",
    "--health": "HealthConfig",
    "--learner": "LearnerConfig",
    "--mesh": "MeshConfig",
    "--serve": "ServeConfig",
}

# CLIs whose full flag surface must be documented in OPERATIONS.md
OPERATOR_CLIS = (
    "dotaclient_tpu/train/learner.py",
    "dotaclient_tpu/actor/__main__.py",
)

# every entrypoint a documented flag may legitimately belong to
ALL_CLIS = OPERATOR_CLIS + (
    "dotaclient_tpu/league/__main__.py",
    "dotaclient_tpu/lint/__main__.py",
    "dotaclient_tpu/serve/__main__.py",
    "dotaclient_tpu/serve/router.py",
    "scripts/serve_loadgen.py",
    "scripts/chaos_run.py",
    "scripts/fleet_status.py",
    "scripts/run_multichip.py",
    "scripts/train_demo.py",
    "scripts/curriculum_5v5.py",
    "scripts/bench_configs.py",
    "scripts/bench_transport_producer.py",
    "scripts/check_telemetry_schema.py",
    "scripts/check_host_sync.py",
    "scripts/bench_trajectory.py",
    "bench.py",
)

# `--flag` mention: lowercase-dashed word; a trailing [_a-z0-9] after the
# match would mean we clipped a longer token (e.g. --xla_force_...), and
# a leading '-' would mean we are inside a '---' rule line.
_DOC_FLAG_RE = re.compile(r"(?<!-)--([a-z][a-z0-9]*(?:-[a-z0-9]+)*)(?![a-z0-9_-])")

_KNOB_HEADING_RE = re.compile(r"^###\s+`?(--[a-z-]+)`?\s+\((\w+)\)\s*$")
_KNOB_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|")


def dataclass_fields(config_source: str) -> Dict[str, List[str]]:
    """class name → annotated field names, via AST (no import)."""
    tree = ast.parse(config_source)
    out: Dict[str, List[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        fields = [
            stmt.target.id
            for stmt in node.body
            if isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
        ]
        out[node.name] = fields
    return out


def cli_flags(py_source: str) -> Set[str]:
    """Every literal ``--flag`` passed to an add_argument call."""
    tree = ast.parse(py_source)
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr == "add_argument"):
            continue
        for arg in node.args:
            if (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and arg.value.startswith("--")
            ):
                out.add(arg.value)
    return out


def documented_flags(doc_text: str) -> Dict[str, int]:
    """--flag mentions in the doc → first line number."""
    out: Dict[str, int] = {}
    for i, line in enumerate(doc_text.splitlines(), 1):
        for m in _DOC_FLAG_RE.finditer(line):
            out.setdefault(f"--{m.group(1)}", i)
    return out


def knob_tables(doc_text: str) -> Dict[str, Tuple[str, Dict[str, int]]]:
    """Parse the "Config override knobs" tables:
    flag → (ClassName, {knob: line})."""
    out: Dict[str, Tuple[str, Dict[str, int]]] = {}
    current: str = ""
    for i, line in enumerate(doc_text.splitlines(), 1):
        stripped = line.strip()
        m = _KNOB_HEADING_RE.match(stripped)
        if m:
            current = m.group(1)
            out[current] = (m.group(2), {})
            continue
        if stripped.startswith("#"):
            # any other heading closes the table: a later unrelated
            # backticked-first-column table must not be misattributed to
            # the last knob table
            current = ""
            continue
        if current:
            row = _KNOB_ROW_RE.match(stripped)
            if row and row.group(1) not in ("knob",):
                out[current][1].setdefault(row.group(1), i)
    return out


def drift_findings(
    fields_by_class: Dict[str, List[str]],
    flags_by_cli: Dict[str, Set[str]],
    doc_text: str,
    rule_id: str = "config-drift",
    doc_path: str = OPERATIONS_MD,
    config_path: str = CONFIG_PY,
) -> List[Diagnostic]:
    """Pure cross-check (unit-testable with synthetic inputs)."""
    out: List[Diagnostic] = []
    tables = knob_tables(doc_text)
    doc_flags = documented_flags(doc_text)
    # 1. override-reachable fields ⊆ knob tables; table rows ⊆ fields;
    #    and the flag itself must exist on the learner CLI (a knob table
    #    for a flag argparse rejects is a broken runbook)
    learner_flags = flags_by_cli.get(OPERATOR_CLIS[0])
    for flag, cls in sorted(OVERRIDE_FLAGS.items()):
        if learner_flags is not None and flag not in learner_flags:
            out.append(
                Diagnostic(
                    OPERATOR_CLIS[0], 0, rule_id,
                    f"override flag {flag} (→ {cls}) is declared in "
                    f"OVERRIDE_FLAGS but the learner CLI does not define "
                    f"it — add the add_argument or drop the mapping",
                    context=flag,
                )
            )
        fields = fields_by_class.get(cls)
        if fields is None:
            continue
        table = tables.get(flag)
        if table is None:
            out.append(
                Diagnostic(
                    doc_path, 0, rule_id,
                    f"no '### {flag} ({cls})' knob table in OPERATIONS.md "
                    f"'Config override knobs' — every {flag} K=V-reachable "
                    f"field must be documented there",
                    context=flag,
                )
            )
            continue
        doc_cls, knobs = table
        if doc_cls != cls:
            out.append(
                Diagnostic(
                    doc_path, 0, rule_id,
                    f"knob table for {flag} names {doc_cls} but the CLI "
                    f"maps it to {cls}",
                    context=flag,
                )
            )
        for field in fields:
            if field not in knobs:
                out.append(
                    Diagnostic(
                        config_path, 0, rule_id,
                        f"{cls}.{field} is reachable via '{flag} "
                        f"{field}=V' but missing from the OPERATIONS.md "
                        f"{flag} knob table — document it",
                        context=f"{flag}.{field}",
                    )
                )
        for knob, line in sorted(knobs.items()):
            if knob not in fields:
                out.append(
                    Diagnostic(
                        doc_path, line, rule_id,
                        f"OPERATIONS.md documents {flag} knob {knob!r} "
                        f"but {cls} has no such field — stale docs or a "
                        f"renamed field",
                        context=f"{flag}.{knob}",
                    )
                )
    # 2. documented flags must exist somewhere
    all_flags: Set[str] = set()
    for flags in flags_by_cli.values():
        all_flags |= flags
    for flag, line in sorted(doc_flags.items()):
        if flag not in all_flags and flag not in OVERRIDE_FLAGS:
            out.append(
                Diagnostic(
                    doc_path, line, rule_id,
                    f"OPERATIONS.md mentions {flag} but no entrypoint "
                    f"defines it — broken runbook command",
                    context=flag,
                )
            )
    # 3. operator-facing CLI flags must be documented
    for cli in OPERATOR_CLIS:
        for flag in sorted(flags_by_cli.get(cli, ())):
            if flag not in doc_flags:
                out.append(
                    Diagnostic(
                        cli, 0, rule_id,
                        f"{flag} is defined by {cli} but never mentioned "
                        f"in OPERATIONS.md — operators cannot discover "
                        f"it; add it to the topology/debugging sections "
                        f"or the CLI flag table",
                        context=flag,
                    )
                )
    return out


class ConfigCliDriftRule(Rule):
    id = "config-drift"
    summary = (
        "override-reachable config fields and CLI flags match the "
        "OPERATIONS.md tables"
    )

    def paths(self) -> Iterable[str]:
        return (CONFIG_PY, OPERATIONS_MD) + ALL_CLIS

    def check(self, files: Dict[str, FileCtx]) -> List[Diagnostic]:
        cfg = files.get(CONFIG_PY)
        doc = files.get(OPERATIONS_MD)
        if cfg is None or doc is None:
            return []
        flags_by_cli = {
            rel: cli_flags(files[rel].source)
            for rel in ALL_CLIS
            if rel in files
        }
        return drift_findings(
            dataclass_fields(cfg.source),
            flags_by_cli,
            doc.source,
            self.id,
        )
