"""alert-drift pass: the alert rule table and the OPERATIONS.md runbook agree.

The alert engine (``utils/alerts.py``, ISSUE 13) encodes the runbook's
failure thresholds as machine-evaluated rules, and every rule carries a
mandatory runbook anchor — a backticked ``rb:<name>`` token in the
"Failure modes" table of docs/OPERATIONS.md. The two artifacts drift
independently: a reworded runbook row silently orphans the rule that
pages on it, and a newly documented failure mode ships without anyone
deciding whether a machine can watch it. This pass cross-checks BOTH
ways, statically (AST + regex — no import of alerts.py, which pulls the
telemetry registry):

* every ``AlertRule.runbook`` anchor must exist in OPERATIONS.md — a
  rule can never point at a deleted runbook row;
* every runbook-table row must carry exactly one ``rb:`` anchor — new
  failure modes cannot dodge the contract;
* every anchor must be referenced by at least one rule OR waived in
  ``ALERT_WAIVERS`` with a reason — a documented failure mode with a
  watchable signal gets a rule or an explicit decision not to;
* waivers must be live: a waived anchor that no longer exists in the
  doc, or that a rule now covers, is stale and fails;
* the "Alert catalog" table mirrors the rule table row-for-row: every
  rule has a catalog row, every catalog row names a real rule;
* (ISSUE 15) every rule's ``key=`` must name a telemetry key SOME code
  actually emits (statically extracted, the telemetry-drift machinery) —
  a rule watching a renamed or never-wired key is silent forever, which
  is worse than no rule: the runbook row reads as covered. Pattern keys
  (``fleet/*/...``) are exempt — their members are runtime peer labels.

Rule fields must be LITERALS — a computed ``runbook=`` escapes the
cross-check and is flagged as not statically checkable.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Tuple

from dotaclient_tpu.lint.core import Diagnostic, FileCtx, Rule

ALERTS_PY = "dotaclient_tpu/utils/alerts.py"
OPERATIONS_MD = "docs/OPERATIONS.md"

_ANCHOR_RE = re.compile(r"`(rb:[a-z0-9-]+)`")
_RULE_NAME_RE = re.compile(r"`([a-z][a-z0-9_]*)`")

FAILURE_MODES_HEADING = "## Failure modes"
ALERT_CATALOG_HEADING = "## Alert catalog"


# -- extraction ---------------------------------------------------------------


def _assigned_value(node: ast.AST, name: str) -> Optional[ast.AST]:
    """The RHS of ``name = ...`` or ``name: T = ...`` (module level or
    not), else None."""
    if (
        isinstance(node, ast.Assign)
        and len(node.targets) == 1
        and isinstance(node.targets[0], ast.Name)
        and node.targets[0].id == name
    ):
        return node.value
    if (
        isinstance(node, ast.AnnAssign)
        and isinstance(node.target, ast.Name)
        and node.target.id == name
    ):
        return node.value
    return None


def extract_rules(
    tree: ast.AST, path: str = ALERTS_PY
) -> Tuple[List[Dict[str, object]], List[Diagnostic]]:
    """AST-extract the ``RULES`` tuple's ``AlertRule(...)`` entries as
    ``{"name", "runbook", "line"}`` dicts. Non-literal name/runbook
    fields flag — they would silently escape the cross-check."""
    rules: List[Dict[str, object]] = []
    problems: List[Diagnostic] = []
    for node in ast.walk(tree):
        value = _assigned_value(node, "RULES")
        if value is None:
            continue
        elts = value.elts if isinstance(value, (ast.Tuple, ast.List)) else []
        for call in elts:
            if not (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Name)
                and call.func.id == "AlertRule"
            ):
                continue
            fields: Dict[str, object] = {"line": call.lineno}
            # positional arg 0 is `name` by the dataclass layout
            if call.args and isinstance(call.args[0], ast.Constant):
                fields["name"] = call.args[0].value
            for kw in call.keywords:
                if kw.arg in ("name", "runbook", "key") and isinstance(
                    kw.value, ast.Constant
                ):
                    fields[kw.arg] = kw.value.value
            for required in ("name", "runbook"):
                if not isinstance(fields.get(required), str):
                    problems.append(
                        Diagnostic(
                            path,
                            call.lineno,
                            "alert-drift",
                            f"AlertRule {required}= is not a string "
                            f"literal — the rules↔runbook cross-check "
                            f"cannot see it; use a literal",
                        )
                    )
            if isinstance(fields.get("name"), str) and isinstance(
                fields.get("runbook"), str
            ):
                rules.append(fields)
    return rules, problems


def extract_waivers(tree: ast.AST) -> Dict[str, str]:
    """Literal-eval the ``ALERT_WAIVERS`` dict (anchor → reason)."""
    for node in ast.walk(tree):
        value = _assigned_value(node, "ALERT_WAIVERS")
        if value is not None:
            try:
                return dict(ast.literal_eval(value))
            except (ValueError, SyntaxError):
                return {}
    return {}


def _section_rows(
    doc: str, heading: str
) -> List[Tuple[int, str]]:
    """Table body rows (1-based line no, text) of the markdown section
    under ``heading`` — header and ``|---|`` separator rows skipped."""
    rows: List[Tuple[int, str]] = []
    in_section = False
    seen_table_lines = 0
    for i, line in enumerate(doc.splitlines(), 1):
        stripped = line.strip()
        if stripped.startswith("## "):
            in_section = stripped.startswith(heading)
            seen_table_lines = 0
            continue
        if not in_section or not stripped.startswith("|"):
            continue
        seen_table_lines += 1
        if seen_table_lines <= 2:
            continue   # header + separator
        rows.append((i, stripped))
    return rows


def runbook_anchors(doc: str) -> Tuple[Dict[str, int], List[Diagnostic]]:
    """Anchors (→ line) of the Failure-modes table, plus a diagnostic per
    row that carries none — every failure mode must enter the contract."""
    anchors: Dict[str, int] = {}
    problems: List[Diagnostic] = []
    for line_no, row in _section_rows(doc, FAILURE_MODES_HEADING):
        found = _ANCHOR_RE.findall(row)
        if not found:
            problems.append(
                Diagnostic(
                    OPERATIONS_MD,
                    line_no,
                    "alert-drift",
                    "runbook row carries no `rb:<anchor>` token — every "
                    "documented failure mode needs an anchor so the alert "
                    "table (utils/alerts.py RULES) or its waiver list can "
                    "reference it",
                    context=row[:60],
                )
            )
            continue
        for a in found:
            anchors.setdefault(a, line_no)
    return anchors, problems


def catalog_rule_names(doc: str) -> Dict[str, int]:
    """First backticked token of each Alert-catalog row → line no."""
    out: Dict[str, int] = {}
    for line_no, row in _section_rows(doc, ALERT_CATALOG_HEADING):
        m = _RULE_NAME_RE.search(row)
        if m:
            out.setdefault(m.group(1), line_no)
    return out


def rule_key_findings(
    rules: List[Dict[str, object]],
    emitted: "set[str]",
    rule_id: str = "alert-drift",
) -> List[Diagnostic]:
    """Every non-pattern rule key must be an emitted telemetry key
    (ISSUE 15): a rule over a ghost key can never fire, silently
    un-watching its runbook row."""
    out: List[Diagnostic] = []
    for r in rules:
        key = r.get("key")
        if not isinstance(key, str):
            continue
        if any(ch in key for ch in "*?["):
            continue  # runtime-labeled families (per-peer mirrors)
        if key not in emitted:
            out.append(
                Diagnostic(
                    ALERTS_PY, int(r["line"]), rule_id,  # type: ignore[arg-type]
                    f"rule {r['name']!r} watches telemetry key {key!r} "
                    f"but no emission site exists in the package — the "
                    f"rule can never fire; fix the key or the emitter",
                    context=key,
                )
            )
    return out


# -- the cross-check ----------------------------------------------------------


def drift_findings(
    rules: List[Dict[str, object]],
    waivers: Dict[str, str],
    doc: str,
    rule_id: str = "alert-drift",
) -> List[Diagnostic]:
    """Pure cross-check (unit-testable: feed a doctored doc)."""
    out: List[Diagnostic] = []
    anchors, row_problems = runbook_anchors(doc)
    out.extend(row_problems)
    referenced = set()
    seen_names: Dict[str, int] = {}
    for r in rules:
        name, anchor, line = str(r["name"]), str(r["runbook"]), int(r["line"])  # type: ignore[arg-type]
        if name in seen_names:
            out.append(
                Diagnostic(
                    ALERTS_PY, line, rule_id,
                    f"duplicate alert rule name {name!r} (first at line "
                    f"{seen_names[name]}) — rule names key the catalog "
                    f"and the event stream",
                )
            )
        seen_names.setdefault(name, line)
        referenced.add(anchor)
        if anchor not in anchors:
            out.append(
                Diagnostic(
                    ALERTS_PY, line, rule_id,
                    f"rule {name!r} points at runbook anchor {anchor!r} "
                    f"which does not exist in the docs/OPERATIONS.md "
                    f"'Failure modes' table — the row was deleted or "
                    f"renamed; fix the anchor or restore the row",
                    context=anchor,
                )
            )
    for anchor, line_no in sorted(anchors.items()):
        if anchor in referenced:
            if anchor in waivers:
                out.append(
                    Diagnostic(
                        ALERTS_PY, 0, rule_id,
                        f"stale waiver: anchor {anchor!r} is waived in "
                        f"ALERT_WAIVERS but a rule now covers it — delete "
                        f"the waiver",
                        context=anchor,
                    )
                )
            continue
        if anchor not in waivers:
            out.append(
                Diagnostic(
                    OPERATIONS_MD, line_no, rule_id,
                    f"documented failure mode {anchor!r} has neither an "
                    f"alert rule (utils/alerts.py RULES) nor an explicit "
                    f"ALERT_WAIVERS entry naming why it is not "
                    f"machine-watchable",
                    context=anchor,
                )
            )
    for anchor in sorted(waivers):
        if anchor not in anchors:
            out.append(
                Diagnostic(
                    ALERTS_PY, 0, rule_id,
                    f"stale waiver: ALERT_WAIVERS entry {anchor!r} matches "
                    f"no anchor in the docs/OPERATIONS.md 'Failure modes' "
                    f"table",
                    context=anchor,
                )
            )
    # the Alert catalog mirrors the rule table row-for-row
    catalog = catalog_rule_names(doc)
    for r in rules:
        name = str(r["name"])
        if name not in catalog:
            out.append(
                Diagnostic(
                    OPERATIONS_MD, 0, rule_id,
                    f"alert rule {name!r} has no row in the "
                    f"docs/OPERATIONS.md 'Alert catalog' table — operators "
                    f"grep that table during incidents",
                    context=name,
                )
            )
    for name, line_no in sorted(catalog.items()):
        if name not in seen_names:
            out.append(
                Diagnostic(
                    OPERATIONS_MD, line_no, rule_id,
                    f"'Alert catalog' row names rule {name!r} which does "
                    f"not exist in utils/alerts.py RULES — stale docs or a "
                    f"renamed rule",
                    context=name,
                )
            )
    return out


class AlertDriftRule(Rule):
    id = "alert-drift"
    summary = (
        "alert rules and the OPERATIONS.md runbook/catalog agree both ways"
    )

    def paths(self) -> Iterable[str]:
        from dotaclient_tpu.lint.core import package_py_files

        # the whole package: rule keys are validated against the
        # statically-extracted emitted-key set (rule_key_findings)
        return [ALERTS_PY, OPERATIONS_MD] + package_py_files()

    def check(self, files: Dict[str, FileCtx]) -> List[Diagnostic]:
        from dotaclient_tpu.lint.telemetry_drift import extract_emitted

        alerts = files.get(ALERTS_PY)
        doc = files.get(OPERATIONS_MD)
        if alerts is None or alerts.tree is None:
            return []
        rules, problems = extract_rules(alerts.tree)
        waivers = extract_waivers(alerts.tree)
        out = list(problems)
        out.extend(
            drift_findings(
                rules, waivers, doc.source if doc is not None else "", self.id
            )
        )
        # unresolvable-emission diagnostics belong to telemetry-drift;
        # here the extraction only feeds the rule-key existence check
        emitted, _sites, _problems = extract_emitted(files)
        out.extend(rule_key_findings(rules, emitted, self.id))
        return out
