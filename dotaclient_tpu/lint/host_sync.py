"""host-sync pass: no per-step host↔device syncs in the hot-path modules.

The learner's throughput story rests on a discipline, not a mechanism: the
train loop is dispatch-only, and device values are fetched exactly once per
``log_every`` boundary (docs/ARCHITECTURE.md "Observability", "Pipelined
data path"). That discipline regresses silently — one stray
``float(metrics["loss"])`` in the loop turns dispatch-rate training into
sync-rate training, and nothing crashes.

This pass is the static tripwire (grown from the PR 2 standalone
``scripts/check_host_sync.py``, which remains as a thin CLI wrapper with
byte-compatible exit codes). It AST-scans the hot-path modules for the
call patterns that read device values onto the host:

* ``np.asarray(...)`` / ``np.array(...)``
* ``jax.device_get(...)``
* ``<x>.item()``
* ``<x>.block_until_ready()`` / ``jax.block_until_ready(...)``
* ``float(...)``

and flags each occurrence that is neither inside an ALLOWED function
(construction/checkpoint/boundary code that runs off the hot path by
design — ``ALLOWED_FUNCS``) nor annotated at the line. Two annotation
spellings are honored: the historical ``# host-sync-ok: <why>`` (hundreds
of sites predate the framework) and the framework-standard
``# lint-ok: host-sync(<why>)``.

Static analysis cannot prove a ``float()`` touches a device value — most
annotated ones wrap host integers — but every NEW unannotated occurrence
is exactly the kind of line a reviewer must look at. The point is
friction: adding a sync to the hot path requires either an annotation
(visible in review) or an allowlist edit (more visible).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from dotaclient_tpu.lint.core import Diagnostic, FileCtx, Rule

# Functions that legitimately sync: construction, checkpoint/restore, and
# log-boundary drains. Regressions INSIDE these functions are
# boundary-cadence, not per-step — out of scope for this pass (the
# telemetry tests count actual fetches per step). Note _publish_weights is
# deliberately NOT here anymore (ISSUE 5): with the async snapshot engine
# it must be dispatch-only on the train thread — any sync pattern added to
# it now needs a visible annotation.
ALLOWED_FUNCS: Dict[str, Set[str]] = {
    "dotaclient_tpu/train/learner.py": {
        "__init__",
        "_pipeline_state",
        "_restore_pipeline",
        "_flush_league_reports",
        "_publish_pipeline_gauges",
        "_maybe_save_best",
        "main",
    },
    "dotaclient_tpu/buffer/trajectory_buffer.py": {
        "__init__",
        "_matches_slot",
        "_payload_finite",      # admission door: host arrays only (ISSUE 6)
        "_payload_in_bounds",   # admission door: host arrays only (ISSUE 7)
        "state_dict",
        "load_state_dict",
        "_publish_telemetry",
        "metrics",
    },
    # Health monitor (ISSUE 6): submit/take_pending run on the train
    # thread and must stay host-only; the fold side receives ALREADY
    # fetched scalars (the engine's one batched transfer) — its float()
    # casts are annotated at the line.
    "dotaclient_tpu/train/health.py": set(),
    # One-pass advantage plane (ISSUE 14): the consume-time pass runs on
    # the train thread between a gather and a donated epoch step — it
    # must be dispatch-only end to end (a hidden device_get there would
    # serialize every consumed batch behind device compute); no
    # function-level pass.
    "dotaclient_tpu/train/advantage.py": set(),
    # Outcome attribution plane (ISSUE 15): the aggregator tick runs on
    # the train thread at log boundaries (in-proc modes) and on the fleet
    # aggregator thread (external modes) — it must stay pure host
    # registry arithmetic, and the recording helpers run at actor episode
    # boundaries / stats-drain folds with ALREADY-fetched host scalars;
    # every sync-shaped cast is annotated at the line.
    "dotaclient_tpu/outcome/aggregator.py": {"__init__"},
    "dotaclient_tpu/outcome/records.py": set(),
    # Pipeline utilization plane (ISSUE 16): pure host interval
    # arithmetic — the accountant runs inline on the train / actor /
    # batcher threads at existing phase boundaries, so any device touch
    # here would tax every attributed phase; no function-level pass.
    "dotaclient_tpu/utils/utilization.py": set(),
    # The snapshot engine IS the designated sync site (ISSUE 5): its one
    # batched fetch is annotated at the line, everything else must stay
    # host-only — no function-level pass.
    "dotaclient_tpu/train/snapshot.py": set(),
    # Checkpointing: restores are user-initiated and sync by design; the
    # save path must do exactly ONE batched fetch (annotated) and the
    # snapshot-thread entry point (save_host) none at all.
    "dotaclient_tpu/utils/checkpoint.py": {
        "shape_mismatches",
        "restore",
        "restore_weights",
        "restore_config",
        "restore_pipeline",
    },
}

# Modules where only the PUBLISH path is in scope (ISSUE 5): the transports
# are big and mostly reader-side, but publish_weights runs on the learner's
# snapshot thread (async) or train thread (sync debug mode) — a host↔device
# sync slipping in there silently re-serializes the fanout behind device
# work. Only the named functions are scanned; the rest of each module is
# out of this pass's scope.
SCAN_ONLY_FUNCS: Dict[str, Set[str]] = {
    # consume_decoded (ISSUE 7) feeds the buffer's consume-time upcast:
    # it runs on the learner thread every ingest and its byte accounting
    # must stay host-int arithmetic — a sync pattern there would serialize
    # the whole ingest drain behind device work.
    "dotaclient_tpu/transport/socket_transport.py": {
        "publish_weights", "_writer_loop", "consume_decoded",
    },
    "dotaclient_tpu/transport/shm_transport.py": {
        "publish_weights", "consume_decoded",
    },
    "dotaclient_tpu/transport/queues.py": {"publish_weights"},
    # The shared byte-accounting body both consume_decoded paths call
    # (ISSUE 7 review round 3): the accounting itself lives here now, so
    # the tripwire must follow it.
    "dotaclient_tpu/transport/serialize.py": {"decode_drained_payloads"},
}

ANNOTATION = "host-sync-ok"
_FRAMEWORK_ANNOTATION = "lint-ok: host-sync("


def _pattern_of(call: ast.Call) -> Optional[str]:
    """Name of the sync pattern a Call node matches, or None."""
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id == "float":
        return "float()"
    if isinstance(fn, ast.Attribute):
        base = fn.value
        base_name = base.id if isinstance(base, ast.Name) else None
        if fn.attr in ("asarray", "array") and base_name == "np":
            return f"np.{fn.attr}()"
        if fn.attr == "device_get" and base_name == "jax":
            return "jax.device_get()"
        if fn.attr == "item" and not call.args:
            return ".item()"
        if fn.attr == "block_until_ready":
            return ".block_until_ready()"
    return None


class _Scanner(ast.NodeVisitor):
    def __init__(self) -> None:
        self.func_stack: List[str] = []
        self.hits: List[Tuple[int, str, Optional[str]]] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        pat = _pattern_of(node)
        if pat is not None:
            # innermost NAMED def wins: closures like after_step() get
            # their own identity instead of hiding under train()
            fn = self.func_stack[-1] if self.func_stack else None
            self.hits.append((node.lineno, pat, fn))
        self.generic_visit(node)


def scan_source(
    source: str,
    allowed_funcs: Set[str],
    filename: str = "<string>",
    scan_only: Optional[Set[str]] = None,
) -> List[Tuple[int, str, Optional[str]]]:
    """Structured findings for one module: (line, pattern, func) triples
    that are neither allowed nor annotated (either spelling).

    ``scan_only`` restricts the scan to the named functions (the publish-
    path modules); ``None`` scans the whole module."""
    tree = ast.parse(source, filename)
    scanner = _Scanner()
    scanner.visit(tree)
    lines = source.splitlines()
    out: List[Tuple[int, str, Optional[str]]] = []
    for lineno, pat, func in scanner.hits:
        if scan_only is not None and func not in scan_only:
            continue
        if func in allowed_funcs:
            continue
        here = lines[lineno - 1] if lineno - 1 < len(lines) else ""
        above = lines[lineno - 2] if lineno >= 2 else ""
        if any(
            mark in text
            for mark in (ANNOTATION, _FRAMEWORK_ANNOTATION)
            for text in (here, above)
        ):
            continue
        out.append((lineno, pat, func))
    return out


def _message(pat: str, func: Optional[str]) -> str:
    where = f"in {func}()" if func else "at module level"
    return (
        f"{pat} {where} — a host↔device sync pattern on the hot path; "
        f"move it behind a log_every boundary, or annotate "
        f"'# {ANNOTATION}: <why>' if it only touches host values"
    )


def check_source(
    source: str,
    allowed_funcs: Set[str],
    filename: str = "<string>",
    scan_only: Optional[Set[str]] = None,
) -> List[str]:
    """Violation strings for one module's source (empty = clean) — the
    historical ``scripts/check_host_sync.py`` surface, byte-compatible
    with its pre-framework output (tests/test_telemetry.py pins it)."""
    return [
        f"{filename}:{lineno}: {_message(pat, func)}"
        for lineno, pat, func in scan_source(
            source, allowed_funcs, filename, scan_only=scan_only
        )
    ]


class HostSyncRule(Rule):
    id = "host-sync"
    summary = (
        "hot-path modules carry no unannotated host<->device sync patterns"
    )

    def paths(self) -> Iterable[str]:
        return sorted(ALLOWED_FUNCS) + sorted(SCAN_ONLY_FUNCS)

    def check(self, files: Dict[str, FileCtx]) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for rel, allowed in sorted(ALLOWED_FUNCS.items()):
            ctx = files.get(rel)
            if ctx is None:
                continue
            for lineno, pat, func in scan_source(ctx.source, allowed, rel):
                out.append(
                    Diagnostic(
                        rel, lineno, self.id, _message(pat, func),
                        context=func or "",
                    )
                )
        for rel, only in sorted(SCAN_ONLY_FUNCS.items()):
            ctx = files.get(rel)
            if ctx is None:
                continue
            for lineno, pat, func in scan_source(
                ctx.source, set(), rel, scan_only=only
            ):
                out.append(
                    Diagnostic(
                        rel, lineno, self.id, _message(pat, func),
                        context=func or "",
                    )
                )
        return out


def run_standalone(argv: Optional[List[str]] = None) -> int:
    """The ``scripts/check_host_sync.py`` entry point: exit 0 when clean,
    1 with per-line diagnostics on stderr — byte-compatible with the
    pre-framework script so existing CI wiring keeps working."""
    import argparse
    import os
    import sys

    from dotaclient_tpu.lint.core import REPO_ROOT

    p = argparse.ArgumentParser(description=__doc__)
    p.parse_args(argv)
    all_violations: List[str] = []
    for rel, allowed in sorted(ALLOWED_FUNCS.items()):
        with open(os.path.join(REPO_ROOT, rel)) as f:
            all_violations.extend(check_source(f.read(), allowed, rel))
    for rel, only in sorted(SCAN_ONLY_FUNCS.items()):
        with open(os.path.join(REPO_ROOT, rel)) as f:
            all_violations.extend(
                check_source(f.read(), set(), rel, scan_only=only)
            )
    if all_violations:
        print("host-sync discipline check FAILED:", file=sys.stderr)
        for v in all_violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    scanned = sorted(ALLOWED_FUNCS) + sorted(SCAN_ONLY_FUNCS)
    print(f"host-sync discipline OK: {', '.join(scanned)}")
    return 0
