"""Learner-side outcome aggregation: counters → windowed curves + alerts.

``OutcomeAggregator.tick()`` is a pure host pass over the telemetry
registry: it collapses the outcome counter totals (the learner's own
``outcome/`` counters — in-process actor modes — plus every
``fleet/<peer>/outcome/...`` mirror the FleetAggregator delta-merged
from external actors' snapshot frames) into a sliding window and
publishes the curves as gauges. No thread of its own and no device
traffic: in external-transport modes the FleetAggregator's tick hook
drives it at fleet cadence (wall clock — a starved learner still
evaluates outcome staleness), in the in-process modes the learner ticks
it at log boundaries. Both callers serialize through ``_lock``, so the
modal ownership can never race (OWNERSHIP-mapped in lint/ownership.py;
the whole module is scanned by the host-sync lint pass with no
allowance).

Published gauges (eager-created at construction — the
``--require-outcome`` schema tier holds for ANY learner JSONL):

* ``outcome/win_rate/{vs_scripted,vs_league,overall}`` — windowed
  win-rates, initialized to the 0.5 NEUTRAL PRIOR and only updated once
  a window holds ``min_episodes`` episodes of that bucket: the
  ``win_rate_collapse`` alert can then watch the gauge directly without
  false-firing on runs that play no scripted games at all.
* ``outcome/episode_len_p50`` — windowed median episode length (env
  steps), from the power-of-two histogram (2× resolution, the
  ``telemetry.Timer`` convention); 0 until armed.
* ``outcome/episode_len_anomaly`` — 1.0 while the armed window's p50
  sits below ``ep_len_floor`` (degenerate instant-reset episodes: an
  env/reset bug, not a skill signal); the alert watches this derived
  binary so the unarmed state can never false-fire.
* ``outcome/reward/<term>`` — windowed per-episode mean of each weighted
  shaping term (the reward decomposition: "the policy stopped winning
  because the tower term collapsed" is readable from the curves).
* ``outcome/episodes_total`` / ``outcome/episodes_recent`` — lifetime
  total across sources / episodes inside the current window.
* ``outcome/stream_age_s`` — seconds since the episode total last
  advanced, −1 until the FIRST episode ever arrives (arming): the
  ``outcome_stream_stale`` alert fires only when a previously-live
  outcome stream stops.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from dotaclient_tpu.outcome.records import (
    BUCKETS,
    N_LEN_BUCKETS,
    REWARD_TERMS,
    counter_totals,
)
from dotaclient_tpu.utils import telemetry

__all__ = ["OutcomeAggregator"]

# Win-rate gauges: the two attributable buckets plus the overall rate
# (vs_selfplay alone is ~0.5 by construction and reads from "overall").
_RATE_BUCKETS = ("vs_scripted", "vs_league", "overall")


class OutcomeAggregator:
    """Windowed outcome curves over the registry's outcome counters."""

    def __init__(
        self,
        registry: Optional[telemetry.Registry] = None,
        window_s: float = 120.0,
        min_episodes: int = 8,
        # the pow2-histogram p50 is an upper bound with minimum value 2
        # (bucket 0's bound), so the floor sits at 4: a bucket-0 median —
        # single-step episodes — is the degenerate-reset signature
        ep_len_floor: float = 4.0,
    ) -> None:
        self._reg = (
            registry if registry is not None else telemetry.get_registry()
        )
        self.window_s = float(window_s)
        self.min_episodes = int(min_episodes)
        self.ep_len_floor = float(ep_len_floor)
        self._lock = threading.Lock()
        # (t, totals) samples spanning the window; the oldest retained
        # sample is the delta baseline
        self._samples: Deque[Tuple[float, Dict[str, float]]] = deque()
        self._armed = False
        self._last_total_eps = 0.0
        self._last_episode_t = 0.0
        # eager keys + neutral priors (see module docstring)
        for bucket in _RATE_BUCKETS:
            self._reg.gauge(f"outcome/win_rate/{bucket}").set(0.5)
        self._reg.gauge("outcome/episode_len_p50")
        self._reg.gauge("outcome/episode_len_anomaly")
        self._reg.gauge("outcome/stream_age_s").set(-1.0)
        self._reg.gauge("outcome/episodes_total")
        self._reg.gauge("outcome/episodes_recent")
        for term in REWARD_TERMS:
            self._reg.gauge(f"outcome/reward/{term}")

    # -- the periodic pass --------------------------------------------------

    def tick(self, now: Optional[float] = None) -> None:
        """One merge + curve-update pass. Host dict arithmetic only —
        callable from the fleet aggregator's thread (external modes) or
        the train thread at log boundaries (in-process modes); the lock
        serializes the modal callers."""
        if now is None:
            now = time.monotonic()
        counters, _ = self._reg.counters_and_gauges()
        totals = counter_totals(counters)
        with self._lock:
            if not self._samples:
                # empty baseline: the FIRST tick's window covers every
                # episode since construction — without it, outcomes that
                # completed before the first tick would be swallowed by
                # the self-baseline and never enter a curve
                self._samples.append((now, {}))
            self._samples.append((now, totals))
            while (
                len(self._samples) > 2
                and now - self._samples[0][0] > self.window_s
            ):
                self._samples.popleft()
            base = self._samples[0][1]
            delta = {
                k: totals.get(k, 0.0) - base.get(k, 0.0) for k in totals
            }
            self._publish(now, totals, delta)

    def _total_eps(self, d: Dict[str, float]) -> float:
        return sum(d.get(f"outcome/episodes/{b}", 0.0) for b in BUCKETS)

    def _publish(
        self,
        now: float,
        totals: Dict[str, float],
        delta: Dict[str, float],
    ) -> None:
        total_eps = self._total_eps(totals)
        d_eps = self._total_eps(delta)
        self._reg.gauge("outcome/episodes_total").set(total_eps)
        self._reg.gauge("outcome/episodes_recent").set(d_eps)
        # stream liveness: armed at the first episode ever observed, age
        # measured from the last tick that saw the total advance
        if total_eps > self._last_total_eps or (
            total_eps > 0 and not self._armed
        ):
            self._armed = True
            self._last_episode_t = now
        self._last_total_eps = total_eps
        self._reg.gauge("outcome/stream_age_s").set(
            now - self._last_episode_t if self._armed else -1.0
        )
        # windowed win-rates: updated only once the window carries signal
        # (the gauges otherwise HOLD — last value, or the 0.5 prior)
        for bucket in _RATE_BUCKETS:
            if bucket == "overall":
                eps, wins = d_eps, sum(
                    delta.get(f"outcome/wins/{b}", 0.0) for b in BUCKETS
                )
            else:
                eps = delta.get(f"outcome/episodes/{bucket}", 0.0)
                wins = delta.get(f"outcome/wins/{bucket}", 0.0)
            if eps >= self.min_episodes:
                self._reg.gauge(f"outcome/win_rate/{bucket}").set(
                    wins / eps
                )
        # windowed episode-length p50 from the pow2 histogram deltas
        if d_eps >= self.min_episodes:
            p50 = self._hist_p50(delta)
            self._reg.gauge("outcome/episode_len_p50").set(p50)
            self._reg.gauge("outcome/episode_len_anomaly").set(
                1.0 if p50 < self.ep_len_floor else 0.0
            )
        # reward decomposition: windowed per-episode mean per term
        if d_eps > 0:
            for term in REWARD_TERMS:
                self._reg.gauge(f"outcome/reward/{term}").set(
                    delta.get(f"outcome/reward_sum/{term}", 0.0) / d_eps
                )

    @staticmethod
    def _hist_p50(delta: Dict[str, float]) -> float:
        counts = [
            delta.get(f"outcome/ep_len_hist/{i:02d}", 0.0)
            for i in range(N_LEN_BUCKETS)
        ]
        total = sum(counts)
        if total <= 0:
            return 0.0
        target = total / 2.0
        seen = 0.0
        for i, c in enumerate(counts):
            seen += c
            if seen >= target:
                # bucket upper bound, the Timer.quantile convention
                return float(2 ** (i + 1))   # host-sync-ok: host int
        return float(2 ** N_LEN_BUCKETS)   # host-sync-ok: host int
