"""In-graph episode-outcome reductions for the device/fused rollout.

The device rollout (and fused mode, which runs the same
``_rollout_impl`` inside its one donated program) never touches the host
per chunk, so outcome extraction there must be done-masked reductions
INSIDE the program, accumulated in the actor's device-resident stats and
fetched only by the existing decimated stats drain — the Podracer
constraint the whole plane is designed around (no new host syncs;
``lint/host_sync.py`` guards the aggregator side).

:func:`chunk_outcome_stats` is the single reduction both the rollout
program and the parity tests call: given the per-step done/win/length
streams of one chunk it produces exactly the scalars
``records.fold_device_stats`` folds into the ``outcome/`` counters —
pinned bitwise against host-loop recording on the same streams
(tests/test_outcome.py), the PR 10/11 parity-digest pattern.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp

from dotaclient_tpu.outcome.records import BUCKETS, N_LEN_BUCKETS


def bucket_masks(
    n_games: int, opponent: str, n_anchor_games: int
) -> Dict[str, jnp.ndarray]:
    """Static per-game opponent-bucket masks [N] for one pool config.

    Scripted modes: every game is vs_scripted. Self-play: every game is
    the mirror. League: the first ``n_anchor_games`` games are pinned to
    a scripted anchor (``envs.vec_lane_sim.apply_anchor_games`` puts
    them at the FRONT — the same split ``DeviceActor._league_game_mask``
    relies on for PFSP attribution), the rest play snapshots.
    """
    idx = jnp.arange(n_games)
    if opponent == "selfplay":
        scripted = jnp.zeros(n_games, bool)
        league = jnp.zeros(n_games, bool)
        selfplay = jnp.ones(n_games, bool)
    elif opponent == "league":
        scripted = idx < n_anchor_games
        league = ~scripted
        selfplay = jnp.zeros(n_games, bool)
    else:
        scripted = jnp.ones(n_games, bool)
        league = jnp.zeros(n_games, bool)
        selfplay = jnp.zeros(n_games, bool)
    return {
        "vs_scripted": scripted, "vs_league": league, "vs_selfplay": selfplay
    }


def zero_outcome_stats(n_games: Optional[int] = None) -> Dict[str, jnp.ndarray]:
    """The outcome slice of the device actor's stats accumulator.

    Scalar-shaped by default (the historical drain contract). With
    ``n_games`` the accumulator is PER-GAME partials — ``[N]`` counters and
    an ``[N, N_LEN_BUCKETS]`` histogram — the lane-sharded fused layout:
    each mesh shard accumulates its own games' rows and nothing in the
    rollout program ever reduces across the game axis (no collective); the
    host sums the game axis at drain time (:func:`reduce_outcome_stats`).
    The partial shapes are shard-count independent, so a checkpointed
    accumulator restores across mesh sizes unchanged.
    """
    if n_games is None:
        z = jnp.zeros((), jnp.float32)
        hist = jnp.zeros((N_LEN_BUCKETS,), jnp.float32)
    else:
        z = jnp.zeros((n_games,), jnp.float32)
        hist = jnp.zeros((n_games, N_LEN_BUCKETS), jnp.float32)
    out: Dict[str, jnp.ndarray] = {}
    for bucket in BUCKETS:
        out[f"out_eps_{bucket}"] = z
        out[f"out_wins_{bucket}"] = z
    out["out_ep_len_sum"] = z
    out["out_ep_len_hist"] = hist
    return out


def chunk_outcome_partials(
    ep_done: jnp.ndarray,
    win: jnp.ndarray,
    ep_len: jnp.ndarray,
    masks: Optional[Dict[str, jnp.ndarray]] = None,
) -> Dict[str, jnp.ndarray]:
    """Done-masked PER-GAME outcome reductions over one chunk's stream.

    ``ep_done``/``win`` are boolean ``[..., N]`` (any leading step axes),
    ``ep_len`` the integer episode length in env steps at the done site
    (0 where not done). ``masks`` are the static per-game bucket masks
    ([N], broadcast across leading axes); ``None`` buckets everything
    vs_scripted (the parity tests' single-bucket mode).

    Only the LEADING (step) axes are reduced — the game axis survives, so
    under the lane-sharded fused layout every reduction is shard-local:
    counters come out ``[N]``, the length histogram ``[N, N_LEN_BUCKETS]``
    (a one-hot bucket sum per game — a scatter-add across games would
    gather the whole batch onto every device). Every accumulated value is
    an exact small-integer count/length in f32, so summing the game axis
    later (:func:`reduce_outcome_stats`) is bitwise independent of how the
    games were sharded.
    """
    done_f = ep_done.astype(jnp.float32)
    win_f = (win & ep_done).astype(jnp.float32)
    lead = tuple(range(done_f.ndim - 1))
    out: Dict[str, jnp.ndarray] = {}
    for bucket in BUCKETS:
        if masks is None:
            m = (
                jnp.ones(ep_done.shape[-1], bool)
                if bucket == "vs_scripted"
                else jnp.zeros(ep_done.shape[-1], bool)
            )
        else:
            m = masks[bucket]
        mf = m.astype(jnp.float32)
        out[f"out_eps_{bucket}"] = (done_f * mf).sum(lead)
        out[f"out_wins_{bucket}"] = (win_f * mf).sum(lead)
    lens = ep_len.astype(jnp.float32) * done_f
    out["out_ep_len_sum"] = lens.sum(lead)
    # power-of-two bucket index via EXACT integer threshold compares —
    # idx = #{i >= 1 : len >= 2^i} == bit_length-1 clipped, the host
    # convention (records.len_bucket). A float log2 formulation would be
    # 1 ulp from flipping a bucket at exact power-of-two lengths on
    # backends with approximated transcendentals (TPU) — and timeout-
    # adjudicated episodes all share ONE exact length, so a single flip
    # would move every one of them (review finding). Non-done slots carry
    # one-hot weight 0, so their index never matters.
    safe = jnp.maximum(ep_len, 1).astype(jnp.int32)
    idx = sum(
        (safe >= (1 << i)).astype(jnp.int32)
        for i in range(1, N_LEN_BUCKETS)
    )
    onehot = (
        idx[..., None] == jnp.arange(N_LEN_BUCKETS, dtype=jnp.int32)
    ).astype(jnp.float32)
    out["out_ep_len_hist"] = (onehot * done_f[..., None]).sum(lead)
    return out


def reduce_outcome_stats(
    partials: Dict[str, jnp.ndarray]
) -> Dict[str, jnp.ndarray]:
    """Fold the game axis out of per-game partials: counters ``[N]`` →
    scalars, histogram ``[N, B]`` → ``[B]`` — the shapes
    ``records.fold_device_stats`` consumes. Works on device arrays and on
    host numpy alike (the drain reduces AFTER the fetch). Scalar-shaped
    inputs pass through unchanged, so the reducer is safe on either
    accumulator layout."""
    out: Dict[str, jnp.ndarray] = {}
    for k, v in partials.items():
        if k == "out_ep_len_hist":
            out[k] = v.sum(axis=0) if v.ndim == 2 else v
        else:
            out[k] = v.sum() if getattr(v, "ndim", 0) else v
    return out


def chunk_outcome_stats(
    ep_done: jnp.ndarray,
    win: jnp.ndarray,
    ep_len: jnp.ndarray,
    masks: Optional[Dict[str, jnp.ndarray]] = None,
) -> Dict[str, jnp.ndarray]:
    """Scalar-shaped outcome reductions (the historical contract): the
    per-game partials with the game axis summed out. Bitwise identical to
    the pre-partials formulation — every partial is an exact integer-valued
    count in f32."""
    return reduce_outcome_stats(
        chunk_outcome_partials(ep_done, win, ep_len, masks)
    )
