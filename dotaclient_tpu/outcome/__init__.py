"""Outcome attribution plane (ISSUE 15).

The run-time planes before this one observe *throughput* — tracing
(ISSUE 12) explains where a chunk's time went, fleet health (ISSUE 13)
says which peers are alive — but nothing live says whether the policy is
actually WINNING, against whom, or why a regression happened. This
package closes that loop end to end:

* **Extraction** (``records.py`` + ``ingraph.py``): per-lane episode
  outcomes — win/loss, episode length, reward decomposition by shaping
  term, opponent bucket (scripted anchor vs league snapshot vs mirror
  self-play), side — surfaced at episode boundary from BOTH rollout
  paths. Host pools record through ``actor/window_stats.py`` into the
  process telemetry registry at the episode-end site they already own;
  the device/fused rollout accumulates the same facts as done-masked
  in-graph reductions inside the rollout program (``ingraph.py``),
  flushed with the existing decimated stats drain — zero added host
  syncs (``lint/host_sync.py`` scans the aggregator module whole).

* **Transport**: the outcome counters are ordinary telemetry counters
  under ``outcome/``, so external actors ship them inside the EXISTING
  fleet metric snapshot frames (``utils/fleet.py`` — same codec, same
  CRC/quarantine discipline on both lanes, no new frame kind) and the
  learner's ``FleetAggregator`` delta-merges them per peer exactly like
  every other counter (a restarted actor never double-counts).

* **Aggregation** (``aggregator.py``): the learner-side
  ``OutcomeAggregator`` merges local counters + fleet mirrors into
  windowed curves — ``outcome/win_rate/{vs_scripted,vs_league,overall}``,
  ``outcome/episode_len_p50``, per-term ``outcome/reward/<term>`` means —
  restart-safe, eager-created so ``check_telemetry_schema.py
  --require-outcome`` validates ANY learner JSONL.

* **Surfacing**: alert rules with runbook anchors (win-rate collapse,
  episode-length anomaly, outcome-stream staleness) in the PR 13 engine,
  ``scripts/outcome_report.py`` (curves + per-opponent table +
  ``OUTCOME_STATUS`` line), an outcome panel in
  ``scripts/fleet_status.py``, and a ``bench.py outcome`` stage pinning
  ``stages.outcome_overhead``.
"""

from dotaclient_tpu.outcome.records import (  # noqa: F401
    BUCKETS,
    N_LEN_BUCKETS,
    REWARD_TERMS,
    SIDES,
    add_reward_terms,
    ensure_actor_metrics,
    fold_device_stats,
    len_bucket,
    opponent_bucket,
    record_episode,
)
from dotaclient_tpu.outcome.aggregator import OutcomeAggregator  # noqa: F401

__all__ = [
    "BUCKETS",
    "N_LEN_BUCKETS",
    "REWARD_TERMS",
    "SIDES",
    "OutcomeAggregator",
    "add_reward_terms",
    "ensure_actor_metrics",
    "fold_device_stats",
    "len_bucket",
    "opponent_bucket",
    "record_episode",
]
