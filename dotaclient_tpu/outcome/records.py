"""Episode-outcome records: the actor-side counter schema + recording API.

One schema, two producers. Host pools (``ActorPool``/``VecActorPool``)
call :func:`record_episode` / :func:`add_reward_terms` directly at the
episode-end / step sites they already own (via the
``actor/window_stats.py`` mixin); the device/fused rollout accumulates
the same facts in-graph (``outcome/ingraph.py``) and
:func:`fold_device_stats` folds the drained stat scalars into these SAME
counters at the existing stats-drain cadence. Either way the facts land
as monotone registry counters under ``outcome/``, which

* ride the fleet snapshot frames to the learner from external actors
  (``utils/fleet.py`` ships the ``outcome/`` namespace; counters are
  delta-merged per peer, so a supervisor-restarted actor never
  double-counts), and
* feed the learner-side ``OutcomeAggregator`` windows locally in the
  in-process actor modes.

Episode length is counted in ENV STEPS (observation cadence) and
histogrammed into power-of-two buckets (the ``telemetry.Timer``
convention: bucket ``i`` covers lengths in ``[2^i, 2^(i+1))``, last
bucket open-ended) so a cross-process p50 is derivable from shipped
scalars — a mean alone cannot distinguish "all episodes normal" from
"half instant, half timeout".
"""

from __future__ import annotations

from typing import Dict, Mapping

from dotaclient_tpu.config import RewardConfig
from dotaclient_tpu.utils import telemetry

# Opponent buckets: who the learner-controlled side actually played.
# "vs_scripted" = a scripted bot (scripted_easy/hard opponents, and league
# anchor games — the tier-2 honesty metric's denominator), "vs_league" =
# a frozen snapshot opponent, "vs_selfplay" = the mirror (live params both
# sides; win-rate ~0.5 by construction, reported for completeness).
BUCKETS = ("vs_scripted", "vs_league", "vs_selfplay")

SIDES = ("radiant", "dire")

# Reward shaping terms — the RewardConfig field set, in table order.
REWARD_TERMS = tuple(RewardConfig().as_dict())

# Power-of-two episode-length histogram buckets (env steps). 12 buckets
# reach 2^11 = 2048+ steps — past any configured max_dota_time horizon.
N_LEN_BUCKETS = 12


def opponent_bucket(opponent: str) -> str:
    """The outcome bucket a pool's NON-anchor games belong to, from the
    env opponent mode (league anchor games are bucketed vs_scripted by
    the callers that know the anchor split)."""
    if opponent in ("scripted_easy", "scripted_hard"):
        return "vs_scripted"
    if opponent == "selfplay":
        return "vs_selfplay"
    return "vs_league"


def len_bucket(ep_len_steps: float) -> int:
    """Histogram bucket index for one episode length (env steps)."""
    n = max(int(ep_len_steps), 1)
    return min(n.bit_length() - 1, N_LEN_BUCKETS - 1)


def ensure_actor_metrics(reg: telemetry.Registry) -> None:
    """Eager-create every actor-side outcome counter, so fleet snapshots
    ship the full (zeroed) set from a peer's first frame and
    ``check_telemetry_schema.py --require-outcome`` validates any learner
    JSONL deterministically (the Learner calls this at construction in
    every actor mode)."""
    for bucket in BUCKETS:
        reg.counter(f"outcome/episodes/{bucket}")
        reg.counter(f"outcome/wins/{bucket}")
    for side in SIDES:
        reg.counter(f"outcome/episodes_side/{side}")
    reg.counter("outcome/ep_len_sum")
    for i in range(N_LEN_BUCKETS):
        reg.counter(f"outcome/ep_len_hist/{i:02d}")
    for term in REWARD_TERMS:
        reg.counter(f"outcome/reward_sum/{term}")


def record_episode(
    reg: telemetry.Registry,
    bucket: str,
    won: bool,
    ep_len_steps: float,
    side: str = "radiant",
) -> None:
    """One completed episode's outcome → the registry counters (host
    pools' episode-end site; counted once per game, owner-lane
    convention)."""
    reg.counter(f"outcome/episodes/{bucket}").inc()
    if won:
        reg.counter(f"outcome/wins/{bucket}").inc()
    reg.counter(f"outcome/episodes_side/{side}").inc()
    reg.counter("outcome/ep_len_sum").inc(float(max(ep_len_steps, 0.0)))   # host-sync-ok: host scalar (episode length)
    reg.counter(f"outcome/ep_len_hist/{len_bucket(ep_len_steps):02d}").inc()


def add_reward_terms(
    reg: telemetry.Registry, term_sums: Mapping[str, float]
) -> None:
    """Accumulate one step's WEIGHTED per-term reward sums (summed over
    the pool's learner lanes) into the decomposition counters."""
    for term, v in term_sums.items():
        if v:
            reg.counter(f"outcome/reward_sum/{term}").inc(float(v))   # host-sync-ok: host floats (caller-summed term values)


def fold_device_stats(
    reg: telemetry.Registry,
    stats: Mapping[str, object],
    owner_side: str = "radiant",
) -> None:
    """Fold one drained device-stats window (``DeviceActor`` /
    fused-mode in-graph reductions, already fetched to host numpy by the
    stats drain) into the same counters the host pools increment
    directly. Runs at stats-drain cadence on whichever thread performed
    the fetch (the snapshot thread in async mode) — host arithmetic
    only."""
    episodes = 0.0
    for bucket in BUCKETS:
        eps = float(stats.get(f"out_eps_{bucket}", 0.0))    # host-sync-ok: drained host stats
        wins = float(stats.get(f"out_wins_{bucket}", 0.0))  # host-sync-ok: drained host stats
        if eps:
            reg.counter(f"outcome/episodes/{bucket}").inc(eps)
            episodes += eps
        if wins:
            reg.counter(f"outcome/wins/{bucket}").inc(wins)
    if episodes:
        # the device actor's episodes are all owner-side games
        reg.counter(f"outcome/episodes_side/{owner_side}").inc(episodes)
    len_sum = float(stats.get("out_ep_len_sum", 0.0))       # host-sync-ok: drained host stats
    if len_sum:
        reg.counter("outcome/ep_len_sum").inc(len_sum)
    hist = stats.get("out_ep_len_hist")
    if hist is not None:
        for i in range(N_LEN_BUCKETS):
            v = float(hist[i])   # host-sync-ok: drained host stats
            if v:
                reg.counter(f"outcome/ep_len_hist/{i:02d}").inc(v)
    terms = stats.get("out_reward_terms")
    if isinstance(terms, Mapping):
        add_reward_terms(
            reg, {t: float(v) for t, v in terms.items()}   # host-sync-ok: drained host stats
        )


def counter_totals(counters: Mapping[str, float]) -> Dict[str, float]:
    """Collapse a registry counters dict into outcome totals: the
    learner's own ``outcome/...`` counters plus every fleet per-peer
    mirror (``fleet/<peer>/outcome/...`` — already delta-merged by the
    FleetAggregator, so summing across peers is restart-safe)."""
    totals: Dict[str, float] = {}
    for name, v in counters.items():
        if name.startswith("outcome/"):
            totals[name] = totals.get(name, 0.0) + v
        elif name.startswith("fleet/") and "/outcome/" in name:
            suffix = name.split("/outcome/", 1)[1]
            key = f"outcome/{suffix}"
            totals[key] = totals.get(key, 0.0) + v
    return totals
