"""Batched featurizer + rewards over the vectorized sim's arrays.

``features.featurizer`` defines the observation contract (feature columns,
mask semantics, slot-0-is-self layout) from a WorldState proto; this module
produces the *same* contract for every lane of a ``VecLaneSim`` in one shot —
pure array arithmetic, no protos, no Python-per-unit work (SURVEY.md §7
hard-part 2; VERDICT round 1 "vectorize the featurizer").

Per-lane unit ordering is [self, other heroes (by player slot), creep slots,
towers] — a *static* permutation of the sim's slot layout, so the gather
indices are computed once. The scalar featurizer orders live units
contiguously by (type, handle) instead; the two orderings differ, which is
fine because slot identity is carried by ``unit_handles``/masks and each
trajectory is internally consistent (the policy never sees both layouts in
one chunk). Feature *semantics* parity with the scalar featurizer is tested
in ``tests/test_vec_sim.py`` by featurizing the same game state both ways.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from dotaclient_tpu.config import ActionSpec, ObsSpec
from dotaclient_tpu.envs.lane_sim import (
    NUKE_MANA,
    NUKE_RANGE,
    TEAM_DIRE,
    TEAM_RADIANT,
)
from dotaclient_tpu.envs.vec_lane_sim import VecLaneSim
from dotaclient_tpu.features import featurizer as F
from dotaclient_tpu.features.reward import WEIGHTS, fold_terms
from dotaclient_tpu.protos import dota_pb2 as pb


class VecFeaturizer:
    """Featurizes ``agent_players`` lanes of every game in one call.

    Output arrays are flattened over lanes: leading axis ``L = n_games ×
    len(agent_players)``, lane ``l = game * A + a``.
    """

    def __init__(
        self,
        sim: VecLaneSim,
        obs_spec: ObsSpec,
        action_spec: ActionSpec,
        agent_players: Sequence[int],
    ) -> None:
        spec = sim.spec
        S, P = spec.max_units, spec.n_players
        if obs_spec.max_units != S:
            raise ValueError(
                f"ObsSpec.max_units ({obs_spec.max_units}) must equal the sim "
                f"slot count ({S}) for the vectorized path"
            )
        if action_spec.max_units != S:
            raise ValueError("ActionSpec.max_units must equal sim slot count")
        self.sim = sim
        self.obs_spec = obs_spec
        self.action_spec = action_spec
        self.agent_players = np.asarray(agent_players, np.int64)
        A = len(self.agent_players)

        # perm[a] = unit ordering for agent a: self, other heroes, creeps,
        # towers (static — computed once).
        perm = np.zeros((A, S), np.int64)
        creeps = np.arange(spec.creep_lo, S)
        towers = np.arange(spec.tower_lo, spec.creep_lo)
        for a, p in enumerate(self.agent_players):
            others = [q for q in range(P) if q != p]
            perm[a] = np.concatenate([[p], others, creeps, towers])
        self.perm = perm                                   # [A, S]
        self.n_lanes = sim.n_games * A

    # -- observations ------------------------------------------------------

    def featurize_all(self) -> Dict[str, np.ndarray]:
        """All lanes' observations: dict of arrays with leading axis L."""
        sim, spec = self.sim, self.sim.spec
        N, S, P = spec.n_games, spec.max_units, spec.n_players
        A = len(self.agent_players)
        ap = self.agent_players
        perm = self.perm                                    # [A, S]

        def g(arr: np.ndarray) -> np.ndarray:
            """Gather [N, S] → [N, A, S] in per-lane unit order."""
            return arr[:, perm]

        unit_type = g(sim.unit_type)
        team = g(sim.team)
        alive = g(sim.alive)
        x, y = g(sim.x), g(sim.y)
        health, health_max = g(sim.health), g(sim.health_max)
        mana, mana_max = g(sim.mana), g(sim.mana_max)
        castable = g(sim.hero_castable())

        my_team = sim.team[:, ap][:, :, None]               # [N, A, 1]
        sign = np.where(sim.team[:, ap] == 2, 1.0, -1.0)[:, :, None]
        me_x = sim.x[:, ap][:, :, None]
        me_y = sim.y[:, ap][:, :, None]
        me_alive = sim.alive[:, ap]                         # [N, A]

        present = (unit_type != 0) & (alive | (unit_type == pb.UNIT_HERO))
        is_hero = unit_type == pb.UNIT_HERO
        is_creep = unit_type == pb.UNIT_LANE_CREEP
        is_tower = unit_type == pb.UNIT_TOWER
        is_ally = (team == my_team) & present
        is_self = np.zeros((N, A, S), bool)
        is_self[:, :, 0] = present[:, :, 0]
        dx = (x - me_x) * sign / F._POS_SCALE
        dy = (y - me_y) / F._POS_SCALE
        dist = np.hypot(x - me_x, y - me_y)
        deniable = is_ally & ~is_self & is_creep & (health < 0.5 * health_max)

        f = np.zeros((N, A, S, self.obs_spec.unit_features), np.float32)
        cols = (
            is_hero, is_creep, is_tower, is_ally, present & ~is_ally, is_self,
            x * sign / F._POS_SCALE, y / F._POS_SCALE, dx, dy, dist / F._POS_SCALE,
            health / np.maximum(health_max, 1.0), health_max / F._HP_SCALE,
            mana / np.maximum(mana_max, 1.0),
            g(sim.damage) / F._DMG_SCALE, g(sim.attack_range) / F._RANGE_SCALE,
            g(sim.move_speed) / F._SPEED_SCALE, g(sim.armor) / F._ARMOR_SCALE,
            g(sim.level) / F._LEVEL_SCALE, alive, castable, deniable,
        )
        for i, c in enumerate(cols):
            f[..., i] = c
        f *= present[..., None]

        # target masks (scalar-featurizer rules)
        self_castable = castable[:, :, 0]                   # [N, A]
        cast_range = np.where(self_castable, NUKE_RANGE, 0.0)[:, :, None]
        is_enemy = present & (team != my_team)
        attackable = (
            present & alive & (is_enemy | deniable) & ~is_self
            & me_alive[:, :, None]
        )
        cast_tgt = (
            is_enemy & alive & (dist <= cast_range) & me_alive[:, :, None]
        )

        mask_action = np.zeros((N, A, self.action_spec.n_action_types), bool)
        mask_action[..., pb.ACTION_NOOP] = True
        mask_action[..., pb.ACTION_MOVE] = me_alive
        mask_action[..., pb.ACTION_ATTACK_UNIT] = attackable.any(-1)
        mask_action[..., pb.ACTION_CAST] = self_castable & cast_tgt.any(-1)
        mask_ability = np.zeros((N, A, self.action_spec.max_abilities), bool)
        mask_ability[..., 0] = mask_action[..., pb.ACTION_CAST]

        # globals
        tower_r = sim.tower_slot(TEAM_RADIANT)
        tower_d = sim.tower_slot(TEAM_DIRE)
        tower_hp = np.stack(
            [
                sim.health[:, tower_r] / np.maximum(sim.health_max[:, tower_r], 1.0),
                sim.health[:, tower_d] / np.maximum(sim.health_max[:, tower_d], 1.0),
            ],
            axis=1,
        )                                                   # [N, 2] (rad, dire)
        team_row = sim.team[:, :P]
        kills_rad = (sim.kills[:, :P] * (team_row == TEAM_RADIANT)).sum(1)
        kills_dire = (sim.kills[:, :P] * (team_row == TEAM_DIRE)).sum(1)
        i_rad = (my_team[:, :, 0] == TEAM_RADIANT)          # [N, A]
        kill_diff = np.where(
            i_rad, kills_rad[:, None] - kills_dire[:, None],
            kills_dire[:, None] - kills_rad[:, None],
        )
        own_tower = np.where(i_rad, tower_hp[:, 0:1], tower_hp[:, 1:2])
        enemy_tower = np.where(i_rad, tower_hp[:, 1:2], tower_hp[:, 0:1])

        gl = np.zeros((N, A, self.obs_spec.global_features), np.float32)
        gl[..., 0] = sim.dota_time[:, None] / F._TIME_SCALE
        gl[..., 1] = np.where(i_rad, 1.0, -1.0)
        gl[..., 2] = sim.gold[:, ap] / F._GOLD_SCALE
        gl[..., 3] = sim.xp[:, ap] / F._XP_SCALE
        gl[..., 4] = sim.level[:, ap] / F._LEVEL_SCALE
        gl[..., 5] = kill_diff / 10.0
        gl[..., 6] = own_tower
        gl[..., 7] = enemy_tower

        L = N * A
        def flat(arr: np.ndarray) -> np.ndarray:
            return arr.reshape((L,) + arr.shape[2:])

        return {
            "units": flat(f),
            "unit_mask": flat(present),
            "unit_handles": flat(
                np.broadcast_to((perm + 1).astype(np.int32)[None], (N, A, S)).copy()
            ),
            "globals": flat(gl),
            "hero_id": sim.hero_ids[:, ap].reshape(-1).astype(np.int32),
            "mask_action_type": flat(mask_action),
            "mask_target_unit": flat(attackable),
            "mask_cast_target": flat(cast_tgt),
            "mask_ability": flat(mask_ability),
        }

    # -- action translation ------------------------------------------------

    def actions_to_sim(self, packed: np.ndarray) -> Dict[str, np.ndarray]:
        """Policy head indices [L, 5] (HEADS order: action_type, move_x,
        move_y, target_unit, ability) → sim action arrays [N, P].

        Obs target slots map back to sim slots through the static ``perm``;
        players not in ``agent_players`` get type=-1 (scripted/no-op).
        """
        sim, spec = self.sim, self.sim.spec
        N, P = spec.n_games, spec.n_players
        A = len(self.agent_players)
        packed = packed.reshape(N, A, 5)

        out = {
            "type": np.full((N, P), -1, np.int32),
            "move_x": np.zeros((N, P), np.int32),
            "move_y": np.zeros((N, P), np.int32),
            "target_slot": np.zeros((N, P), np.int64),
            "ability": np.zeros((N, P), np.int32),
        }
        ap = self.agent_players
        out["type"][:, ap] = packed[..., 0]
        # canonical → world frame: Dire lanes mirror the move-x bin (the
        # featurizer mirrored their observations; see featurize)
        mirror = sim.team[:, ap] != 2
        mx = packed[..., 1]
        out["move_x"][:, ap] = np.where(
            mirror, self.action_spec.move_bins - 1 - mx, mx
        )
        out["move_y"][:, ap] = packed[..., 2]
        # obs slot → sim slot
        obs_slot = np.clip(packed[..., 3], 0, spec.max_units - 1)
        sim_slot = np.take_along_axis(
            np.broadcast_to(self.perm[None], (N, A, spec.max_units)),
            obs_slot[..., None],
            axis=2,
        )[..., 0]
        out["target_slot"][:, ap] = sim_slot
        out["ability"][:, ap] = packed[..., 4]
        return out


class VecRewards:
    """Shaped reward for every lane from sim-state deltas — the vector form
    of ``features.reward.shaped_reward`` (same WEIGHTS, same components)."""

    def __init__(
        self,
        sim: VecLaneSim,
        agent_players: Sequence[int],
        weights: Optional[Dict[str, float]] = None,
    ) -> None:
        self.sim = sim
        self.agent_players = np.asarray(agent_players, np.int64)
        self.weights = dict(WEIGHTS if weights is None else weights)
        # last compute()'s weighted per-term sums (outcome decomposition)
        self.last_term_sums: Dict[str, float] = {
            t: 0.0 for t in self.weights
        }
        self.snapshot()

    def _state(self) -> Dict[str, np.ndarray]:
        sim = self.sim
        P = sim.spec.n_players
        ap = self.agent_players
        hero_hp_frac = np.where(
            sim.alive[:, :P],
            sim.health[:, :P] / np.maximum(sim.health_max[:, :P], 1.0),
            0.0,
        )                                                   # [N, P]
        tower_frac = np.stack(
            [
                sim.health[:, sim.tower_slot(TEAM_RADIANT)]
                / np.maximum(sim.health_max[:, sim.tower_slot(TEAM_RADIANT)], 1.0),
                sim.health[:, sim.tower_slot(TEAM_DIRE)]
                / np.maximum(sim.health_max[:, sim.tower_slot(TEAM_DIRE)], 1.0),
            ],
            axis=1,
        )
        # destroyed towers leave the scalar worldstate → scalar reward reads
        # them as 0; alive-masking matches that.
        tower_alive = np.stack(
            [
                sim.alive[:, sim.tower_slot(TEAM_RADIANT)],
                sim.alive[:, sim.tower_slot(TEAM_DIRE)],
            ],
            axis=1,
        )
        tower_frac = np.where(tower_alive, tower_frac, 0.0)
        team_row = sim.team[:, :P]
        # mean enemy-hero hp fraction per team viewpoint
        rad_mask = team_row == TEAM_RADIANT
        def mean_where(mask: np.ndarray) -> np.ndarray:
            cnt = np.maximum(mask.sum(1), 1)
            return (hero_hp_frac * mask).sum(1) / cnt
        mean_rad = mean_where(rad_mask)                     # mean hp of radiant heroes
        mean_dire = mean_where(~rad_mask)
        return {
            "gold": sim.gold[:, ap].copy(),
            "xp": sim.xp[:, ap].copy(),
            "hp": hero_hp_frac[:, ap].copy(),
            "last_hits": sim.last_hits[:, ap].copy(),
            "denies": sim.denies[:, ap].copy(),
            "kills": sim.kills[:, ap].copy(),
            "deaths": sim.deaths[:, ap].copy(),
            "tower": tower_frac,                            # [N, 2] rad, dire
            "mean_hp_rad": mean_rad,
            "mean_hp_dire": mean_dire,
            "done": sim.done.copy(),
        }

    def snapshot(self) -> None:
        self._prev = self._state()

    def compute(self) -> np.ndarray:
        """Per-lane shaped reward [L] for the interval since ``snapshot``;
        re-snapshots afterwards."""
        sim = self.sim
        cur = self._state()
        prev = self._prev
        ap = self.agent_players
        my_team = sim.team[:, ap]                           # [N, A]
        i_rad = my_team == TEAM_RADIANT

        enemy_hp_prev = np.where(i_rad, prev["mean_hp_dire"][:, None], prev["mean_hp_rad"][:, None])
        enemy_hp_cur = np.where(i_rad, cur["mean_hp_dire"][:, None], cur["mean_hp_rad"][:, None])
        enemy_tower_prev = np.where(i_rad, prev["tower"][:, 1:2], prev["tower"][:, 0:1])
        enemy_tower_cur = np.where(i_rad, cur["tower"][:, 1:2], cur["tower"][:, 0:1])
        own_tower_prev = np.where(i_rad, prev["tower"][:, 0:1], prev["tower"][:, 1:2])
        own_tower_cur = np.where(i_rad, cur["tower"][:, 0:1], cur["tower"][:, 1:2])

        WEIGHTS = self.weights
        # only the step the game ends pays the win term (done stays True
        # until the runtime resets the game)
        just_ended = sim.done & ~prev["done"] & (sim.winning_team != 0)
        win_sign = np.where(
            sim.winning_team[:, None] == my_team, 1.0, -1.0
        )
        # weighted per-term breakdown, summed in the historical term
        # order; the per-term sums feed the outcome plane's reward
        # decomposition (outcome/reward_sum/<term>)
        weighted = {
            "xp": WEIGHTS["xp"] * (cur["xp"] - prev["xp"]),
            "gold": WEIGHTS["gold"] * (cur["gold"] - prev["gold"]),
            "hp": WEIGHTS["hp"] * (cur["hp"] - prev["hp"]),
            "enemy_hp": WEIGHTS["enemy_hp"] * -(enemy_hp_cur - enemy_hp_prev),
            "last_hits": WEIGHTS["last_hits"]
            * (cur["last_hits"] - prev["last_hits"]),
            "denies": WEIGHTS["denies"] * (cur["denies"] - prev["denies"]),
            "kills": WEIGHTS["kills"] * (cur["kills"] - prev["kills"]),
            "deaths": WEIGHTS["deaths"] * (cur["deaths"] - prev["deaths"]),
            "tower_damage": WEIGHTS["tower_damage"]
            * (enemy_tower_prev - enemy_tower_cur),
            "own_tower": WEIGHTS["own_tower"]
            * (own_tower_cur - own_tower_prev),
            "win": WEIGHTS["win"] * win_sign * just_ended[:, None],
        }
        r = fold_terms(weighted)
        self.last_term_sums = {
            term: float(arr.sum()) for term, arr in weighted.items()
        }
        self._prev = cur
        return r.reshape(-1).astype(np.float32)
