"""Shaped reward from consecutive worldstate deltas.

The reference computes a shaped reward inside its rollout worker from
worldstate deltas — xp, gold, hp, last-hits, denies, kills, tower damage, and
the win signal (SURVEY.md §2.1 "Rollout worker"; reconstructed — the reference
checkout was an empty mount). Implemented here as a pure function of
(previous, current) worldstates so it is trivially unit-testable and the actor
runtime carries no hidden reward state.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from dotaclient_tpu.protos import dota_pb2 as pb

# Per-component weights: the defaults of config.RewardConfig (the single
# source of truth — per-run overrides come through the config tree).
# Magnitudes follow the shaping the reference family used: dense
# micro-rewards for farm/harass, sparse large terms for kills, towers and
# the win.
from dotaclient_tpu.config import RewardConfig

WEIGHTS: Dict[str, float] = dict(RewardConfig().as_dict())


def _player(ws: pb.WorldState, player_id: int) -> Optional[pb.Player]:
    for p in ws.players:
        if p.player_id == player_id:
            return p
    return None


def _hero(ws: pb.WorldState, player_id: int) -> Optional[pb.Unit]:
    for u in ws.units:
        if u.unit_type == pb.UNIT_HERO and u.player_id == player_id:
            return u
    return None


def _hp_frac(unit: Optional[pb.Unit]) -> float:
    if unit is None or not unit.is_alive:
        return 0.0
    return unit.health / max(unit.health_max, 1.0)


def _tower_hp_frac(ws: pb.WorldState, team_id: int) -> float:
    for u in ws.units:
        if u.unit_type == pb.UNIT_TOWER and u.team_id == team_id:
            return u.health / max(u.health_max, 1.0)
    return 0.0  # destroyed towers leave the worldstate


def reward_components(
    prev: pb.WorldState, cur: pb.WorldState, player_id: int
) -> Dict[str, float]:
    """Per-component shaped reward for ``player_id`` over one interval."""
    p0, p1 = _player(prev, player_id), _player(cur, player_id)
    h0, h1 = _hero(prev, player_id), _hero(cur, player_id)
    if p1 is None:
        return {k: 0.0 for k in WEIGHTS}
    my_team = p1.team_id
    enemy_team = 2 if my_team == 3 else 3

    # Enemy hero hp: mean fraction over enemy heroes (harass signal).
    def enemy_hp_mean(ws: pb.WorldState) -> float:
        fracs = [
            _hp_frac(u)
            for u in ws.units
            if u.unit_type == pb.UNIT_HERO and u.team_id != my_team
        ]
        return sum(fracs) / len(fracs) if fracs else 0.0

    comps = {
        "xp": (p1.xp - p0.xp) if p0 else 0.0,
        "gold": (p1.gold - p0.gold) if p0 else 0.0,
        "hp": _hp_frac(h1) - _hp_frac(h0),
        "enemy_hp": -(enemy_hp_mean(cur) - enemy_hp_mean(prev)),
        "last_hits": float((h1.last_hits if h1 else 0) - (h0.last_hits if h0 else 0)),
        "denies": float((h1.denies if h1 else 0) - (h0.denies if h0 else 0)),
        "kills": float((p1.kills if p1 else 0) - (p0.kills if p0 else 0)),
        "deaths": float((p1.deaths if p1 else 0) - (p0.deaths if p0 else 0)),
        "tower_damage": _tower_hp_frac(prev, enemy_team)
        - _tower_hp_frac(cur, enemy_team),
        "own_tower": _tower_hp_frac(cur, my_team)
        - _tower_hp_frac(prev, my_team),
        "win": 0.0,
    }
    if cur.game_state == pb.GAME_STATE_POST_GAME and cur.winning_team:
        comps["win"] = 1.0 if cur.winning_team == my_team else -1.0
    return comps


def fold_terms(weighted: Dict[str, object]):
    """Left-fold a weighted per-term breakdown in table (insertion)
    order — THE summation-order contract of the reward decomposition
    (ISSUE 15): every producer (the numpy ``VecRewards``, the jnp
    ``shaped_reward_terms``, the device rollout body) folds through this
    one helper, so the scalar reward stays BIT-IDENTICAL to the
    historical single-expression sum and the device-vs-host parity pins
    cannot be broken by restructuring one copy of the fold. Works on any
    ``+``-able values (floats, numpy, jnp arrays)."""
    total = None
    for arr in weighted.values():
        total = arr if total is None else total + arr
    return total


def shaped_reward(
    prev: pb.WorldState,
    cur: pb.WorldState,
    player_id: int,
    weights: Optional[Dict[str, float]] = None,
) -> Tuple[float, Dict[str, float]]:
    """Scalar shaped reward plus the weighted per-component breakdown."""
    w = WEIGHTS if weights is None else weights
    comps = reward_components(prev, cur, player_id)
    weighted = {k: w[k] * v for k, v in comps.items()}
    return sum(weighted.values()), weighted
