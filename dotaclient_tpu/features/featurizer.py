"""WorldState proto → fixed-shape arrays; action indices → Action proto.

The reference featurizes inside its rollout worker (SURVEY.md §3.1: "featurize:
worldstate → per-unit tensors + action masks", reconstructed — the reference
checkout was an empty mount). Two deliberate departures, both TPU-motivated
(SURVEY.md §7 step 2):

* **Fixed shapes.** Every observation is padded to ``ObsSpec.max_units`` slots
  regardless of the live unit count, so the jitted policy never recompiles and
  XLA can tile the unit-encoder matmuls onto the MXU. Validity is carried in
  masks, never in shapes.
* **Pure functions.** ``featurize`` is a pure proto→numpy map with no carried
  state; reward shaping (which *does* need the previous worldstate) lives in
  ``features/reward.py``.

Unit slot 0 is always the controlled hero ("self"); remaining units are laid
out heroes-first in deterministic (unit_type, handle) order so the target-unit
attention head sees a stable arrangement.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional

import numpy as np

from dotaclient_tpu.config import ActionSpec, ObsSpec
from dotaclient_tpu.protos import dota_pb2 as pb

# Normalization scales. The sim's lane is ±2000 units; times are in seconds.
_POS_SCALE = 2000.0
_TIME_SCALE = 600.0
_HP_SCALE = 2000.0
_GOLD_SCALE = 3000.0
_XP_SCALE = 2500.0
_DMG_SCALE = 150.0
_RANGE_SCALE = 700.0
_SPEED_SCALE = 400.0
_ARMOR_SCALE = 20.0
_LEVEL_SCALE = 10.0

# Feature column meanings for the per-unit vector (ObsSpec.unit_features == 22).
UNIT_FEATURES = (
    "is_hero", "is_creep", "is_tower", "is_ally", "is_enemy", "is_self",
    "x", "y", "dx_self", "dy_self", "dist_self",
    "health_frac", "health_max", "mana_frac",
    "attack_damage", "attack_range", "move_speed", "armor", "level",
    "is_alive", "ability_castable", "deniable",
)

GLOBAL_FEATURES = (
    "dota_time", "team_sign", "gold", "xp", "level",
    "kill_diff", "own_tower_hp", "enemy_tower_hp",
)


@dataclasses.dataclass(frozen=True)
class Observation:
    """One featurized worldstate from a single player's perspective.

    All arrays have static shapes drawn from (ObsSpec, ActionSpec); batching
    is a plain ``np.stack`` over instances.
    """

    units: np.ndarray          # f32 [max_units, unit_features]
    unit_mask: np.ndarray      # bool [max_units] — slot holds a live unit
    unit_handles: np.ndarray   # i32 [max_units] — proto handle per slot (0=pad)
    globals: np.ndarray        # f32 [global_features]
    hero_id: np.ndarray        # i32 [] — controlled hero id (hero embedding)
    # Per-head legality masks (True == legal). Illegal actions must never be
    # sampled; the policy applies these before softmax. The target head has
    # two masks because legality is conditional on the action type: ATTACK may
    # hit any enemy or a deniable allied creep; CAST only enemies inside the
    # nuke's range.
    mask_action_type: np.ndarray   # bool [n_action_types]
    mask_target_unit: np.ndarray   # bool [max_units] — ATTACK targets
    mask_cast_target: np.ndarray   # bool [max_units] — CAST targets
    mask_ability: np.ndarray       # bool [max_abilities]


def _unit_sort_key(unit: pb.Unit) -> tuple:
    # Heroes first, then creeps, towers, buildings; stable by handle.
    order = {
        pb.UNIT_HERO: 0,
        pb.UNIT_LANE_CREEP: 1,
        pb.UNIT_TOWER: 2,
        pb.UNIT_BUILDING: 3,
    }
    return (order.get(unit.unit_type, 9), unit.handle)


def featurize(
    world_state: pb.WorldState,
    player_id: int,
    obs_spec: ObsSpec,
    action_spec: ActionSpec,
) -> Observation:
    """Featurize ``world_state`` from ``player_id``'s perspective."""
    U, F = obs_spec.max_units, obs_spec.unit_features
    if action_spec.max_units != U:
        raise ValueError(
            "ActionSpec.max_units must equal ObsSpec.max_units (the target "
            f"head indexes unit slots): {action_spec.max_units} != {U}"
        )
    units_arr = np.zeros((U, F), dtype=np.float32)
    unit_mask = np.zeros((U,), dtype=bool)
    unit_handles = np.zeros((U,), dtype=np.int32)
    mask_target = np.zeros((U,), dtype=bool)
    mask_cast = np.zeros((U,), dtype=bool)
    mask_ability = np.zeros((action_spec.max_abilities,), dtype=bool)

    me: Optional[pb.Unit] = None
    for unit in world_state.units:
        if unit.unit_type == pb.UNIT_HERO and unit.player_id == player_id:
            me = unit
            break

    my_team = me.team_id if me is not None else world_state.team_id
    mx = me.location.x if me is not None else 0.0
    my_ = me.location.y if me is not None else 0.0
    me_alive = bool(me is not None and me.is_alive)
    # Team-canonical frame: +x always points at the ENEMY tower (the map is
    # symmetric about x=0, towers at ±LANE_HALF_LENGTH). Without this a
    # policy is side-specific — trained as Radiant it cannot be executed on
    # Dire lanes (league opponents, eval mirrors), which shows up as wild
    # side asymmetries in self-play. decode_action mirrors move_x back.
    sign = 1.0 if my_team == 2 else -1.0

    others = sorted(
        (u for u in world_state.units if me is None or u.handle != me.handle),
        key=_unit_sort_key,
    )
    ordered = ([me] if me is not None else []) + others

    # Cast range comes from the worldstate itself (Ability.cast_range of the
    # controlled hero's castable ability), not from baked game knowledge.
    cast_range = 0.0
    if me is not None:
        for a in me.abilities:
            if a.castable and a.cast_range > 0.0:
                cast_range = max(cast_range, a.cast_range)
    any_attackable = False
    any_nukable = False
    self_castable = False

    for slot, unit in enumerate(ordered[:U]):
        is_self = me is not None and unit.handle == me.handle
        is_ally = unit.team_id == my_team
        dx = (unit.location.x - mx) * sign / _POS_SCALE
        dy = (unit.location.y - my_) / _POS_SCALE
        dist = float(np.hypot(unit.location.x - mx, unit.location.y - my_))
        castable = any(a.castable for a in unit.abilities)
        deniable = (
            is_ally
            and not is_self
            and unit.unit_type == pb.UNIT_LANE_CREEP
            and unit.health < 0.5 * unit.health_max
        )
        units_arr[slot] = (
            float(unit.unit_type == pb.UNIT_HERO),
            float(unit.unit_type == pb.UNIT_LANE_CREEP),
            float(unit.unit_type == pb.UNIT_TOWER),
            float(is_ally),
            float(not is_ally),
            float(is_self),
            unit.location.x * sign / _POS_SCALE,
            unit.location.y / _POS_SCALE,
            dx,
            dy,
            dist / _POS_SCALE,
            unit.health / max(unit.health_max, 1.0),
            unit.health_max / _HP_SCALE,
            unit.mana / max(unit.mana_max, 1.0),
            unit.attack_damage / _DMG_SCALE,
            unit.attack_range / _RANGE_SCALE,
            unit.movement_speed / _SPEED_SCALE,
            unit.armor / _ARMOR_SCALE,
            unit.level / _LEVEL_SCALE,
            float(unit.is_alive),
            float(castable),
            float(deniable),
        )
        unit_mask[slot] = True
        unit_handles[slot] = unit.handle
        if is_self:
            self_castable = castable
            continue
        if not unit.is_alive:
            continue
        attack_ok = (not is_ally) or deniable
        if me_alive and attack_ok:
            mask_target[slot] = True
            any_attackable = True
        if me_alive and not is_ally and cast_range > 0.0 and dist <= cast_range:
            mask_cast[slot] = True
            any_nukable = True

    # Global features from the self player's scoreboard entry.
    my_player: Optional[pb.Player] = None
    kill_diff = 0.0
    for p in world_state.players:
        if p.player_id == player_id:
            my_player = p
    if my_player is not None:
        my_kills = sum(
            p.kills for p in world_state.players if p.team_id == my_team
        )
        enemy_kills = sum(
            p.kills for p in world_state.players if p.team_id != my_team
        )
        kill_diff = float(my_kills - enemy_kills)

    own_tower_hp, enemy_tower_hp = 0.0, 0.0
    for unit in world_state.units:
        if unit.unit_type == pb.UNIT_TOWER:
            frac = unit.health / max(unit.health_max, 1.0)
            if unit.team_id == my_team:
                own_tower_hp = frac
            else:
                enemy_tower_hp = frac

    globals_arr = np.zeros((obs_spec.global_features,), dtype=np.float32)
    globals_arr[: len(GLOBAL_FEATURES)] = (
        world_state.dota_time / _TIME_SCALE,
        1.0 if my_team == 2 else -1.0,
        (my_player.gold if my_player else 0.0) / _GOLD_SCALE,
        (my_player.xp if my_player else 0.0) / _XP_SCALE,
        (me.level if me is not None else 0) / _LEVEL_SCALE,
        kill_diff / 10.0,
        own_tower_hp,
        enemy_tower_hp,
    )

    mask_action = np.zeros((action_spec.n_action_types,), dtype=bool)
    mask_action[pb.ACTION_NOOP] = True
    if me_alive:
        mask_action[pb.ACTION_MOVE] = True
        mask_action[pb.ACTION_ATTACK_UNIT] = any_attackable
        mask_action[pb.ACTION_CAST] = self_castable and any_nukable
    if mask_action[pb.ACTION_CAST]:
        mask_ability[0] = True  # one nuke in slot 0 for now

    return Observation(
        units=units_arr,
        unit_mask=unit_mask,
        unit_handles=unit_handles,
        globals=globals_arr,
        hero_id=np.asarray(me.hero_id if me is not None else 0, dtype=np.int32),
        mask_action_type=mask_action,
        mask_target_unit=mask_target,
        mask_cast_target=mask_cast,
        mask_ability=mask_ability,
    )


def observation_to_dict(obs: Observation) -> Dict[str, np.ndarray]:
    return {f.name: getattr(obs, f.name) for f in dataclasses.fields(Observation)}


def stack_observations(obs_list) -> Dict[str, np.ndarray]:
    """Stack N observations into batched arrays (leading axis N)."""
    return {
        f.name: np.stack([getattr(o, f.name) for o in obs_list])
        for f in dataclasses.fields(Observation)
    }


def decode_action(
    action_indices: Mapping[str, int],
    obs: Observation,
    player_id: int,
    move_bins: int = 9,
) -> pb.Action:
    """Inverse codec: per-head indices sampled by the policy → Action proto.

    ``target_unit`` head indices are slot positions; the featurizer's
    ``unit_handles`` column recovers the proto handle.
    """
    a_type = int(action_indices["action_type"])
    action = pb.Action(player_id=player_id, type=a_type)
    if a_type == pb.ACTION_MOVE:
        mx_idx = int(action_indices["move_x"])
        if obs.globals[1] < 0:  # Dire: canonical frame is x-mirrored
            mx_idx = move_bins - 1 - mx_idx
        action.move_x = mx_idx
        action.move_y = int(action_indices["move_y"])
    elif a_type in (pb.ACTION_ATTACK_UNIT, pb.ACTION_CAST):
        slot = int(action_indices["target_unit"])
        action.target_handle = int(obs.unit_handles[slot])
        if a_type == pb.ACTION_CAST:
            action.ability_slot = int(action_indices["ability"])
    return action
