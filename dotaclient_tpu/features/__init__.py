"""Featurization boundary: worldstate protos ↔ fixed-shape arrays."""

from dotaclient_tpu.features.featurizer import (
    GLOBAL_FEATURES,
    Observation,
    UNIT_FEATURES,
    decode_action,
    featurize,
    observation_to_dict,
    stack_observations,
)
from dotaclient_tpu.features.reward import (
    WEIGHTS,
    reward_components,
    shaped_reward,
)

__all__ = [
    "GLOBAL_FEATURES",
    "Observation",
    "UNIT_FEATURES",
    "WEIGHTS",
    "decode_action",
    "featurize",
    "observation_to_dict",
    "reward_components",
    "shaped_reward",
    "stack_observations",
]
