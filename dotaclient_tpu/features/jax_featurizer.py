"""Featurization, rewards, and action translation over ``jax_lane_sim``
states — pure jnp functions, composable into the on-device rollout scan.

Port of ``features.vec_featurizer`` (same observation contract, same static
per-lane slot permutation, same reward WEIGHTS); parity with the numpy path
is tested in ``tests/test_jax_sim.py``. Everything here traces into the one
XLA program that ``actor.device_rollout`` builds (SURVEY.md §7 hard-part 2).
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from dotaclient_tpu.config import ActionSpec, ObsSpec
from dotaclient_tpu.envs.jax_lane_sim import SimState, hero_castable
from dotaclient_tpu.envs.lane_sim import NUKE_RANGE, TEAM_RADIANT
from dotaclient_tpu.envs.vec_lane_sim import VecSimSpec
from dotaclient_tpu.features import featurizer as F
from dotaclient_tpu.features.reward import WEIGHTS as _DEFAULT_WEIGHTS
from dotaclient_tpu.protos import dota_pb2 as pb


def build_perm(spec: VecSimSpec, agent_players: Sequence[int]) -> np.ndarray:
    """Static per-lane unit ordering [A, S]: self, other heroes, creeps,
    towers (identical to ``VecFeaturizer``'s)."""
    S, P = spec.max_units, spec.n_players
    creeps = np.arange(spec.creep_lo, S)
    towers = np.arange(spec.tower_lo, spec.creep_lo)
    perm = np.zeros((len(agent_players), S), np.int64)
    for a, p in enumerate(agent_players):
        others = [q for q in range(P) if q != p]
        perm[a] = np.concatenate([[p], others, creeps, towers])
    return perm


class JaxFeaturizer:
    """Pure featurize/translate functions bound to a static lane layout."""

    def __init__(
        self,
        spec: VecSimSpec,
        obs_spec: ObsSpec,
        action_spec: ActionSpec,
        agent_players: Sequence[int],
    ) -> None:
        if obs_spec.max_units != spec.max_units:
            raise ValueError("ObsSpec.max_units must equal sim slot count")
        if action_spec.max_units != spec.max_units:
            raise ValueError("ActionSpec.max_units must equal sim slot count")
        self.spec = spec
        self.obs_spec = obs_spec
        self.action_spec = action_spec
        self.agent_players = tuple(int(p) for p in agent_players)
        self._ap = jnp.asarray(self.agent_players, jnp.int32)
        self.perm = build_perm(spec, agent_players)            # np [A, S]
        self._perm_j = jnp.asarray(self.perm)
        self.n_lanes = spec.n_games * len(self.agent_players)

    # -- observations ------------------------------------------------------

    def featurize(self, state: SimState) -> Dict[str, jnp.ndarray]:
        """All lanes' observations; arrays with leading axis L = N*A."""
        spec = self.spec
        N, S, P = spec.n_games, spec.max_units, spec.n_players
        A = len(self.agent_players)
        ap = self._ap
        perm = self._perm_j

        def g(arr):
            return arr[:, perm]                                # [N, A, S]

        unit_type = g(state.unit_type)
        team = g(state.team)
        alive = g(state.alive)
        x, y = g(state.x), g(state.y)
        health, health_max = g(state.health), g(state.health_max)
        mana, mana_max = g(state.mana), g(state.mana_max)
        castable = g(hero_castable(state))

        my_team = state.team[:, ap][:, :, None]
        # team-canonical frame: +x points at the enemy tower for BOTH sides
        # (see features/featurizer.py featurize); actions_to_sim un-mirrors
        sign = jnp.where(my_team == TEAM_RADIANT, 1.0, -1.0)
        me_x = state.x[:, ap][:, :, None]
        me_y = state.y[:, ap][:, :, None]
        me_alive = state.alive[:, ap]

        present = (unit_type != 0) & (alive | (unit_type == pb.UNIT_HERO))
        is_hero = unit_type == pb.UNIT_HERO
        is_creep = unit_type == pb.UNIT_LANE_CREEP
        is_tower = unit_type == pb.UNIT_TOWER
        is_ally = (team == my_team) & present
        is_self = jnp.zeros((N, A, S), bool).at[:, :, 0].set(present[:, :, 0])
        dx = (x - me_x) * sign / F._POS_SCALE
        dy = (y - me_y) / F._POS_SCALE
        dist = jnp.hypot(x - me_x, y - me_y)
        deniable = is_ally & ~is_self & is_creep & (health < 0.5 * health_max)

        cols = (
            is_hero, is_creep, is_tower, is_ally, present & ~is_ally, is_self,
            x * sign / F._POS_SCALE, y / F._POS_SCALE, dx, dy, dist / F._POS_SCALE,
            health / jnp.maximum(health_max, 1.0), health_max / F._HP_SCALE,
            mana / jnp.maximum(mana_max, 1.0),
            g(state.damage) / F._DMG_SCALE,
            g(state.attack_range) / F._RANGE_SCALE,
            g(state.move_speed) / F._SPEED_SCALE,
            g(state.armor) / F._ARMOR_SCALE,
            g(state.level) / F._LEVEL_SCALE, alive, castable, deniable,
        )
        f = jnp.stack([c.astype(jnp.float32) for c in cols], axis=-1)
        f = f * present[..., None]

        self_castable = castable[:, :, 0]
        cast_range = jnp.where(self_castable, NUKE_RANGE, 0.0)[:, :, None]
        is_enemy = present & (team != my_team)
        attackable = (
            present & alive & (is_enemy | deniable) & ~is_self
            & me_alive[:, :, None]
        )
        cast_tgt = is_enemy & alive & (dist <= cast_range) & me_alive[:, :, None]

        mask_action = (
            jnp.zeros((N, A, self.action_spec.n_action_types), bool)
            .at[..., pb.ACTION_NOOP].set(True)
            .at[..., pb.ACTION_MOVE].set(me_alive)
            .at[..., pb.ACTION_ATTACK_UNIT].set(attackable.any(-1))
            .at[..., pb.ACTION_CAST].set(self_castable & cast_tgt.any(-1))
        )
        mask_ability = (
            jnp.zeros((N, A, self.action_spec.max_abilities), bool)
            .at[..., 0].set(mask_action[..., pb.ACTION_CAST])
        )

        tower_r, tower_d = self.spec.tower_lo, self.spec.tower_lo + 1
        tower_hp = jnp.stack(
            [
                state.health[:, tower_r] / jnp.maximum(state.health_max[:, tower_r], 1.0),
                state.health[:, tower_d] / jnp.maximum(state.health_max[:, tower_d], 1.0),
            ],
            axis=1,
        )
        team_row = state.team[:, :P]
        kills_rad = (state.kills[:, :P] * (team_row == TEAM_RADIANT)).sum(1)
        kills_dire = (state.kills[:, :P] * (team_row != TEAM_RADIANT)).sum(1)
        i_rad = my_team[:, :, 0] == TEAM_RADIANT
        kill_diff = jnp.where(
            i_rad, (kills_rad - kills_dire)[:, None], (kills_dire - kills_rad)[:, None]
        ).astype(jnp.float32)
        own_tower = jnp.where(i_rad, tower_hp[:, 0:1], tower_hp[:, 1:2])
        enemy_tower = jnp.where(i_rad, tower_hp[:, 1:2], tower_hp[:, 0:1])

        gl = jnp.stack(
            [
                jnp.broadcast_to(
                    (state.dota_time / F._TIME_SCALE)[:, None], (N, A)
                ),
                jnp.where(i_rad, 1.0, -1.0),
                state.gold[:, ap] / F._GOLD_SCALE,
                state.xp[:, ap] / F._XP_SCALE,
                state.level[:, ap] / F._LEVEL_SCALE,
                kill_diff / 10.0,
                own_tower,
                enemy_tower,
            ],
            axis=-1,
        ).astype(jnp.float32)
        pad = self.obs_spec.global_features - gl.shape[-1]
        if pad:
            gl = jnp.concatenate([gl, jnp.zeros((N, A, pad), jnp.float32)], -1)

        L = N * A

        def flat(arr):
            return arr.reshape((L,) + arr.shape[2:])

        return {
            "units": flat(f),
            "unit_mask": flat(present),
            "unit_handles": jnp.broadcast_to(
                (perm + 1).astype(jnp.int32)[None], (N, A, S)
            ).reshape(L, S),
            "globals": flat(gl),
            "hero_id": state.hero_ids[:, ap].reshape(-1).astype(jnp.int32),
            "mask_action_type": flat(mask_action),
            "mask_target_unit": flat(attackable),
            "mask_cast_target": flat(cast_tgt),
            "mask_ability": flat(mask_ability),
        }

    # -- action translation ------------------------------------------------

    def actions_to_sim(self, packed: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        """Policy head indices [L, 5] → sim action arrays [N, P]; non-agent
        players get type = -1 (scripted players are overridden in-sim)."""
        spec = self.spec
        N, P, S = spec.n_games, spec.n_players, spec.max_units
        A = len(self.agent_players)
        packed = packed.reshape(N, A, 5)
        ap = self._ap

        obs_slot = jnp.clip(packed[..., 3], 0, S - 1)
        sim_slot = jnp.take_along_axis(
            jnp.broadcast_to(self._perm_j[None], (N, A, S)).astype(jnp.int32),
            obs_slot[..., None].astype(jnp.int32), axis=2,
        )[..., 0]

        def scatter(col):
            return jnp.full((N, P), -1, jnp.int32).at[:, ap].set(col)

        # canonical → world: Dire lanes mirror the move-x bin back (teams
        # are static by player index — players ≥ team_size are Dire)
        mirror = jnp.asarray(
            [p >= self.spec.team_size for p in self.agent_players]
        )[None, :]
        mx = jnp.where(
            mirror, self.action_spec.move_bins - 1 - packed[..., 1],
            packed[..., 1],
        )
        return {
            "type": scatter(packed[..., 0]),
            "move_x": jnp.zeros((N, P), jnp.int32).at[:, ap].set(mx),
            "move_y": jnp.zeros((N, P), jnp.int32).at[:, ap].set(packed[..., 2]),
            "target_slot": jnp.zeros((N, P), jnp.int32).at[:, ap].set(sim_slot),
            "ability": jnp.zeros((N, P), jnp.int32).at[:, ap].set(packed[..., 4]),
        }


def shaped_reward_terms(
    spec: VecSimSpec,
    agent_players: Sequence[int],
    prev: SimState,
    cur: SimState,
    weights=None,
):
    """Weighted per-term shaped-reward breakdown, each term a per-lane
    [L] array (jnp port of ``VecRewards``; same components as
    ``features.reward``; ``weights`` overrides the default table —
    Python floats, so they are compile-time constants). The dict is in
    the historical summation order — :func:`shaped_rewards` left-folds
    it, so the scalar reward is bit-identical to the pre-decomposition
    chain — and the per-term sums are what the device rollout
    accumulates for the outcome plane's reward decomposition
    (``outcome/reward_sum/<term>``, ISSUE 15)."""
    WEIGHTS = _DEFAULT_WEIGHTS if weights is None else weights
    P = spec.n_players
    ap = jnp.asarray(tuple(int(p) for p in agent_players), jnp.int32)

    def hero_hp_frac(s: SimState) -> jnp.ndarray:
        return jnp.where(
            s.alive[:, :P],
            s.health[:, :P] / jnp.maximum(s.health_max[:, :P], 1.0),
            0.0,
        )

    def tower_frac(s: SimState) -> jnp.ndarray:
        tr, td = spec.tower_lo, spec.tower_lo + 1
        frac = jnp.stack(
            [
                s.health[:, tr] / jnp.maximum(s.health_max[:, tr], 1.0),
                s.health[:, td] / jnp.maximum(s.health_max[:, td], 1.0),
            ],
            axis=1,
        )
        alive = jnp.stack([s.alive[:, tr], s.alive[:, td]], axis=1)
        return jnp.where(alive, frac, 0.0)

    team_row = cur.team[:, :P]
    rad_mask = team_row == TEAM_RADIANT

    def team_mean_hp(s: SimState) -> Tuple[jnp.ndarray, jnp.ndarray]:
        hp = hero_hp_frac(s)
        cnt_r = jnp.maximum(rad_mask.sum(1), 1)
        cnt_d = jnp.maximum((~rad_mask).sum(1), 1)
        return (hp * rad_mask).sum(1) / cnt_r, (hp * ~rad_mask).sum(1) / cnt_d

    mean_r0, mean_d0 = team_mean_hp(prev)
    mean_r1, mean_d1 = team_mean_hp(cur)
    tower0, tower1 = tower_frac(prev), tower_frac(cur)

    my_team = cur.team[:, ap]
    i_rad = my_team == TEAM_RADIANT
    e_hp0 = jnp.where(i_rad, mean_d0[:, None], mean_r0[:, None])
    e_hp1 = jnp.where(i_rad, mean_d1[:, None], mean_r1[:, None])
    e_tw0 = jnp.where(i_rad, tower0[:, 1:2], tower0[:, 0:1])
    e_tw1 = jnp.where(i_rad, tower1[:, 1:2], tower1[:, 0:1])
    o_tw0 = jnp.where(i_rad, tower0[:, 0:1], tower0[:, 1:2])
    o_tw1 = jnp.where(i_rad, tower1[:, 0:1], tower1[:, 1:2])

    def d(field):
        return getattr(cur, field)[:, ap] - getattr(prev, field)[:, ap]

    hp0 = hero_hp_frac(prev)[:, ap]
    hp1 = hero_hp_frac(cur)[:, ap]

    just_ended = cur.done & ~prev.done & (cur.winning_team != 0)
    win_sign = jnp.where(cur.winning_team[:, None] == my_team, 1.0, -1.0)
    terms = {
        "xp": WEIGHTS["xp"] * d("xp"),
        "gold": WEIGHTS["gold"] * d("gold"),
        "hp": WEIGHTS["hp"] * (hp1 - hp0),
        "enemy_hp": WEIGHTS["enemy_hp"] * -(e_hp1 - e_hp0),
        "last_hits": WEIGHTS["last_hits"] * d("last_hits"),
        "denies": WEIGHTS["denies"] * d("denies"),
        "kills": WEIGHTS["kills"] * d("kills"),
        "deaths": WEIGHTS["deaths"] * d("deaths"),
        "tower_damage": WEIGHTS["tower_damage"] * (e_tw0 - e_tw1),
        "own_tower": WEIGHTS["own_tower"] * (o_tw1 - o_tw0),
        "win": WEIGHTS["win"] * win_sign * just_ended[:, None],
    }
    return {
        term: arr.reshape(-1).astype(jnp.float32)
        for term, arr in terms.items()
    }


def shaped_rewards(
    spec: VecSimSpec,
    agent_players: Sequence[int],
    prev: SimState,
    cur: SimState,
    weights=None,
) -> jnp.ndarray:
    """Per-lane shaped reward [L]: the left-fold of
    :func:`shaped_reward_terms` in table order (``features.reward.
    fold_terms`` — bit-identical to the historical single-expression
    sum)."""
    from dotaclient_tpu.features.reward import fold_terms

    return fold_terms(
        shaped_reward_terms(spec, agent_players, prev, cur, weights)
    )
