"""Pallas (Mosaic) TPU kernels."""

from dotaclient_tpu.ops.pallas.lstm import (
    HAVE_PALLAS,
    lstm_sequence,
    lstm_sequence_pallas,
    lstm_sequence_reference,
)

__all__ = [
    "HAVE_PALLAS",
    "lstm_sequence",
    "lstm_sequence_pallas",
    "lstm_sequence_reference",
]
