"""Pallas fused LSTM-sequence kernel (the one custom-kernel candidate,
SURVEY.md §2.2 row 1 / §7 hard-part 1).

Measured context (BASELINE.md "Pallas decision"): at production shapes the
`nn.scan` LSTM is ~15 µs of a ~55 µs sequence forward and a single-digit
percent of the 388 µs train step, so the DEFAULT core stays `nn.scan` —
XLA already fuses the per-step matmul+elementwise well at H=128. This
kernel exists as the measured alternative for *wider* cores, where keeping
the weights pinned in VMEM across all T steps pays: one `pallas_call` runs
the whole sequence, double-reading nothing from HBM.

Cell math (gate order i, f, g, o — pinned by `lstm_sequence_reference`,
which is both the spec and the fallback):

    gates = x_t @ Wx + h @ Wh + b
    c' = σ(f)·c + σ(i)·tanh(g);  h' = σ(o)·tanh(c')
    (h, c) ← (h', c') · (1 - reset_t)   applied BEFORE the step

Gradients: `custom_vjp` with a recompute backward — the forward runs the
kernel, the backward re-runs the reference under `jax.vjp` (rematerialized
BPTT; residuals are just the inputs). Numerics parity is tested in
interpreter mode on CPU and compiled on TPU.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

try:  # pallas ships with jax; guard anyway for exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    HAVE_PALLAS = False


def _cell(x_t, h, c, wx, wh, b, reset_t):
    keep = (1.0 - reset_t)[:, None]
    h = h * keep
    c = c * keep
    gates = x_t @ wx + h @ wh + b
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, c


def lstm_sequence_reference(
    x: jnp.ndarray,        # f32 [B, T, D]
    h0: jnp.ndarray,       # f32 [B, H]
    c0: jnp.ndarray,       # f32 [B, H]
    wx: jnp.ndarray,       # f32 [D, 4H]
    wh: jnp.ndarray,       # f32 [H, 4H]
    b: jnp.ndarray,        # f32 [4H]
    resets: jnp.ndarray,   # f32 [B, T]
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Spec implementation: plain lax.scan. Returns (hs [B, T, H], (hT, cT))."""

    def step(carry, inp):
        h, c = carry
        x_t, r_t = inp
        h, c = _cell(x_t, h, c, wx, wh, b, r_t)
        return (h, c), h

    (hT, cT), hs = jax.lax.scan(
        step, (h0, c0), (jnp.moveaxis(x, 1, 0), jnp.moveaxis(resets, 1, 0))
    )
    return jnp.moveaxis(hs, 0, 1), (hT, cT)


def _kernel(x_ref, h0_ref, c0_ref, wx_ref, wh_ref, b_ref, r_ref,
            hs_ref, hT_ref, cT_ref):
    """Whole-sequence LSTM in one kernel: weights live in VMEM for all T
    steps; time-major refs so the sequential loop indexes the leading axis."""
    T = x_ref.shape[0]
    wx = wx_ref[:]
    wh = wh_ref[:]
    b = b_ref[:]

    def body(t, carry):
        h, c = carry
        x_t = x_ref[t]
        r_t = r_ref[t]
        keep = (1.0 - r_t)[:, None]
        h = h * keep
        c = c * keep
        gates = (
            jnp.dot(x_t, wx, preferred_element_type=jnp.float32)
            + jnp.dot(h, wh, preferred_element_type=jnp.float32)
            + b[None, :]
        )
        H = h.shape[-1]
        i = gates[:, :H]
        f = gates[:, H:2 * H]
        g = gates[:, 2 * H:3 * H]
        o = gates[:, 3 * H:]
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        hs_ref[t] = h
        return h, c

    h, c = jax.lax.fori_loop(0, T, body, (h0_ref[:], c0_ref[:]))
    hT_ref[:] = h
    cT_ref[:] = c


def _pallas_forward(x, h0, c0, wx, wh, b, resets, interpret):
    B, T, D = x.shape
    H = h0.shape[-1]
    x_tm = jnp.moveaxis(x, 1, 0)          # [T, B, D]
    r_tm = jnp.moveaxis(resets, 1, 0)     # [T, B]
    hs_tm, hT, cT = pl.pallas_call(
        _kernel,
        out_shape=(
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ),
        interpret=interpret,
    )(x_tm, h0, c0, wx, wh, b, r_tm)
    return jnp.moveaxis(hs_tm, 0, 1), (hT, cT)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def lstm_sequence_pallas(x, h0, c0, wx, wh, b, resets, interpret=False):
    """Fused-kernel LSTM sequence; same contract as the reference."""
    return _pallas_forward(x, h0, c0, wx, wh, b, resets, interpret)


def _fwd(x, h0, c0, wx, wh, b, resets, interpret):
    out = _pallas_forward(x, h0, c0, wx, wh, b, resets, interpret)
    return out, (x, h0, c0, wx, wh, b, resets)


def _bwd(interpret, residuals, cotangents):
    # recompute-backward: BPTT through the reference implementation — the
    # kernel is forward-only, gradients rematerialize in XLA
    x, h0, c0, wx, wh, b, resets = residuals
    _, vjp = jax.vjp(
        lambda x_, h0_, c0_, wx_, wh_, b_: lstm_sequence_reference(
            x_, h0_, c0_, wx_, wh_, b_, resets
        ),
        x, h0, c0, wx, wh, b,
    )
    grads = vjp(cotangents)
    return (*grads, None)  # resets are not differentiated


lstm_sequence_pallas.defvjp(_fwd, _bwd)


def lstm_sequence(
    x, h0, c0, wx, wh, b, resets,
    use_pallas: bool = True,
    interpret_ok: bool = False,
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Dispatch: fused kernel on TPU; off-TPU the reference scan — the
    interpreter-mode kernel (Python-emulated, very slow) only when
    explicitly requested via ``interpret_ok`` (numerics tests)."""
    on_tpu = jax.default_backend() == "tpu"
    if not (use_pallas and HAVE_PALLAS) or (not on_tpu and not interpret_ok):
        return lstm_sequence_reference(x, h0, c0, wx, wh, b, resets)
    return lstm_sequence_pallas(x, h0, c0, wx, wh, b, resets, not on_tpu)
