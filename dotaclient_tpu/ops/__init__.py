"""Custom ops: Pallas kernels and their reference implementations.

The measured keep-or-kill policy (BASELINE.md "Pallas decision"): kernels
live here when profiling on the real chip justifies them; each ships with a
pure-JAX reference that doubles as spec, fallback, and recompute-backward.
"""
