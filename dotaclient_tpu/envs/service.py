"""gRPC environment service and client.

The reference talks to dotaservice over gRPC with generated stubs (SURVEY.md
§2.1 "Proto bindings"). This sandbox has protoc but not the grpc codegen
plugin, so the service is registered through grpc's generic-handler API with
explicit (de)serializers — same wire behavior, no generated ``*_pb2_grpc.py``.

Service: ``dotatpu.DotaService`` with unary RPCs ``reset`` / ``observe`` /
``act`` (SURVEY.md §1). One game per server instance, as with dotaservice;
the actor runtime multiplexes many channels.
"""

from __future__ import annotations

import asyncio
from typing import Optional

import grpc
import grpc.aio

from dotaclient_tpu.envs.env_api import DotaEnvCore
from dotaclient_tpu.protos import dota_pb2 as pb

SERVICE_NAME = "dotatpu.DotaService"


class FakeDotaService:
    """asyncio gRPC servicer wrapping one :class:`DotaEnvCore`."""

    def __init__(self) -> None:
        self._core = DotaEnvCore()
        self._lock = asyncio.Lock()

    async def reset(self, request: pb.GameConfig, context) -> pb.InitialObservation:
        async with self._lock:
            return self._core.reset(request)

    async def observe(self, request: pb.ObserveRequest, context) -> pb.ObserveResponse:
        async with self._lock:
            return self._core.observe(request)

    async def act(self, request: pb.Actions, context) -> pb.Empty:
        async with self._lock:
            return self._core.act(request)


def _service_handlers(servicer: FakeDotaService) -> grpc.GenericRpcHandler:
    rpcs = {
        "reset": grpc.unary_unary_rpc_method_handler(
            servicer.reset,
            request_deserializer=pb.GameConfig.FromString,
            response_serializer=pb.InitialObservation.SerializeToString,
        ),
        "observe": grpc.unary_unary_rpc_method_handler(
            servicer.observe,
            request_deserializer=pb.ObserveRequest.FromString,
            response_serializer=pb.ObserveResponse.SerializeToString,
        ),
        "act": grpc.unary_unary_rpc_method_handler(
            servicer.act,
            request_deserializer=pb.Actions.FromString,
            response_serializer=pb.Empty.SerializeToString,
        ),
    }
    return grpc.method_handlers_generic_handler(SERVICE_NAME, rpcs)


async def serve_env(host: str = "127.0.0.1", port: int = 0) -> tuple:
    """Start a single-env server. Returns ``(server, bound_port)``."""
    server = grpc.aio.server()
    server.add_generic_rpc_handlers((_service_handlers(FakeDotaService()),))
    bound = server.add_insecure_port(f"{host}:{port}")
    await server.start()
    return server, bound


class DotaServiceClient:
    """Async client with the same reset/observe/act surface as
    :class:`dotaclient_tpu.envs.env_api.LocalDotaEnv`."""

    def __init__(self, channel: grpc.aio.Channel):
        self._channel = channel
        prefix = f"/{SERVICE_NAME}/"
        self._reset = channel.unary_unary(
            prefix + "reset",
            request_serializer=pb.GameConfig.SerializeToString,
            response_deserializer=pb.InitialObservation.FromString,
        )
        self._observe = channel.unary_unary(
            prefix + "observe",
            request_serializer=pb.ObserveRequest.SerializeToString,
            response_deserializer=pb.ObserveResponse.FromString,
        )
        self._act = channel.unary_unary(
            prefix + "act",
            request_serializer=pb.Actions.SerializeToString,
            response_deserializer=pb.Empty.FromString,
        )

    @classmethod
    def connect(cls, address: str) -> "DotaServiceClient":
        return cls(grpc.aio.insecure_channel(address))

    async def reset(self, config: pb.GameConfig) -> pb.InitialObservation:
        return await self._reset(config)

    async def observe(self, team_id: int) -> pb.ObserveResponse:
        return await self._observe(pb.ObserveRequest(team_id=team_id))

    async def act(self, actions: pb.Actions) -> pb.Empty:
        return await self._act(actions)

    async def close(self) -> None:
        await self._channel.close()
