"""Pure-JAX lane simulator: the environment as a jittable device function.

Third (and fastest) implementation of the lane-game rules, after the scalar
``lane_sim`` (gRPC/proto boundary) and the numpy ``vec_lane_sim`` (vectorized
host path). Here the entire game — scripted bots included — is a pure
function over a pytree of device arrays, so the whole actor rollout loop
(policy step + env step + reward) compiles into ONE XLA program and runs for
T steps without touching the host (SURVEY.md §7 hard-part 2; the
Anakin/Podracer architecture, PAPERS.md [P:7]). On links where a host↔device
round trip costs ~100 ms this is the difference between ~1e3 and ~1e6
frames/sec.

Semantics: a line-for-line port of ``vec_lane_sim.VecLaneSim`` (same phase
order, same resolution rules, same constants by import); exact-state parity
between the two is tested in ``tests/test_jax_sim.py`` over wave-free
horizons, and statistically across full episodes. The only intentional
difference: creep-wave y-jitter draws from the single batch PRNG key carried
in ``SimState`` rather than per-game numpy streams.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dotaclient_tpu.envs.lane_sim import (
    ATTACKS_PER_SECOND,
    CREEP_ARMOR,
    CREEP_DAMAGE,
    CREEP_HP,
    CREEP_RANGE,
    CREEP_SPEED,
    CREEP_WAVE_PERIOD,
    CREEP_XP,
    CREEPS_PER_WAVE,
    DENY_XP_FACTOR,
    GENERIC_HERO,
    GOLD_PASSIVE_PER_SEC,
    GOLD_PER_HERO_KILL,
    GOLD_PER_LASTHIT,
    HERO_STATS,
    LANE_HALF_LENGTH,
    MAX_LEVEL,
    NUKE_BASE_DAMAGE,
    NUKE_COOLDOWN,
    NUKE_DAMAGE_PER_LEVEL,
    NUKE_MANA,
    NUKE_RANGE,
    NUKE_SLOT,
    RESPAWN_BASE_SECONDS,
    RESPAWN_PER_LEVEL_SECONDS,
    TEAM_DIRE,
    TEAM_RADIANT,
    TICKS_PER_SECOND,
    TOWER_ARMOR,
    TOWER_DAMAGE,
    TOWER_HP,
    TOWER_RANGE,
    TOWER_X,
    XP_PER_HERO_KILL,
    XP_PER_LEVEL,
    XP_RADIUS,
)
from dotaclient_tpu.envs.vec_lane_sim import VecSimSpec
from dotaclient_tpu.protos import dota_pb2 as pb

_BIG = 1e9


class SimState(NamedTuple):
    """All arrays have leading axis N (games); unit axis S = spec.max_units."""

    unit_type: jnp.ndarray     # i32 [N, S]
    team: jnp.ndarray          # i32 [N, S]
    x: jnp.ndarray             # f32 [N, S]
    y: jnp.ndarray             # f32 [N, S]
    health: jnp.ndarray        # f32 [N, S]
    health_max: jnp.ndarray    # f32 [N, S]
    mana: jnp.ndarray          # f32 [N, S]
    mana_max: jnp.ndarray      # f32 [N, S]
    damage: jnp.ndarray        # f32 [N, S]
    attack_range: jnp.ndarray  # f32 [N, S]
    move_speed: jnp.ndarray    # f32 [N, S]
    armor: jnp.ndarray         # f32 [N, S]
    level: jnp.ndarray         # i32 [N, S]
    alive: jnp.ndarray         # bool [N, S]
    attack_cd: jnp.ndarray     # f32 [N, S]
    ability_cd: jnp.ndarray    # f32 [N, S]
    xp: jnp.ndarray            # f32 [N, S] (hero slots)
    gold: jnp.ndarray          # f32 [N, S]
    last_hits: jnp.ndarray     # i32 [N, S]
    denies: jnp.ndarray        # i32 [N, S]
    kills: jnp.ndarray         # i32 [N, S]
    deaths: jnp.ndarray        # i32 [N, S]
    respawn_at: jnp.ndarray    # f32 [N, S]
    dota_time: jnp.ndarray     # f32 [N]
    tick: jnp.ndarray          # i32 [N]
    done: jnp.ndarray          # bool [N]
    winning_team: jnp.ndarray  # i32 [N]
    next_wave_at: jnp.ndarray  # f32 [N]
    hero_ids: jnp.ndarray      # i32 [N, P]
    control_modes: jnp.ndarray # i32 [N, P]
    key: jnp.ndarray           # PRNG key (batch-wide)


Actions = Dict[str, jnp.ndarray]   # type/move_x/move_y/target_slot/ability, [N, P]


def _armor_mult(armor: jnp.ndarray) -> jnp.ndarray:
    return 1.0 - (0.06 * armor) / (1.0 + 0.06 * armor)


def _hero_stats_table() -> np.ndarray:
    """Dense hero_id → stats lookup (row 0.. = generic fallback)."""
    n = max(HERO_STATS) + 1
    table = np.tile(np.asarray(GENERIC_HERO, np.float32), (n + 1, 1))
    for hid, stats in HERO_STATS.items():
        table[hid] = stats
    return table


def init_state(
    spec: VecSimSpec,
    hero_ids: jnp.ndarray,
    control_modes: jnp.ndarray,
    key: jnp.ndarray,
) -> SimState:
    """Fresh batch of games (the jittable analogue of ``VecLaneSim.reset``
    over all rows)."""
    N, S, P = spec.n_games, spec.max_units, spec.n_players
    f0 = jnp.zeros((N, S), jnp.float32)
    i0 = jnp.zeros((N, S), jnp.int32)
    state = SimState(
        unit_type=i0, team=i0, x=f0, y=f0,
        health=f0, health_max=jnp.ones((N, S), jnp.float32),
        mana=f0, mana_max=f0, damage=f0, attack_range=f0,
        move_speed=f0, armor=f0, level=jnp.ones((N, S), jnp.int32),
        alive=jnp.zeros((N, S), bool), attack_cd=f0, ability_cd=f0,
        xp=f0, gold=f0, last_hits=i0, denies=i0, kills=i0, deaths=i0,
        respawn_at=jnp.full((N, S), -1.0, jnp.float32),
        dota_time=jnp.zeros((N,), jnp.float32),
        tick=jnp.zeros((N,), jnp.int32),
        done=jnp.zeros((N,), bool),
        winning_team=jnp.zeros((N,), jnp.int32),
        next_wave_at=jnp.zeros((N,), jnp.float32),
        hero_ids=jnp.asarray(hero_ids, jnp.int32),
        control_modes=jnp.asarray(control_modes, jnp.int32),
        key=key,
    )

    # heroes (slot == player id; Radiant first)
    pslots = jnp.arange(P)
    team_row = jnp.where(pslots < spec.team_size, TEAM_RADIANT, TEAM_DIRE)
    side = jnp.where(team_row == TEAM_RADIANT, -1.0, 1.0)
    table = jnp.asarray(_hero_stats_table())
    stats = table[jnp.clip(state.hero_ids, 0, table.shape[0] - 1)]  # [N, P, 6]

    def set_cols(arr, vals):
        return arr.at[:, :P].set(vals)

    state = state._replace(
        unit_type=set_cols(state.unit_type, pb.UNIT_HERO),
        team=set_cols(state.team, jnp.broadcast_to(team_row, (N, P))),
        x=set_cols(state.x, jnp.broadcast_to(side * (LANE_HALF_LENGTH - 300.0), (N, P))),
        y=set_cols(state.y, jnp.broadcast_to(60.0 * (pslots % 5), (N, P)).astype(jnp.float32)),
        health=set_cols(state.health, stats[..., 0]),
        health_max=set_cols(state.health_max, stats[..., 0]),
        mana=set_cols(state.mana, stats[..., 1]),
        mana_max=set_cols(state.mana_max, stats[..., 1]),
        damage=set_cols(state.damage, stats[..., 2]),
        attack_range=set_cols(state.attack_range, stats[..., 3]),
        move_speed=set_cols(state.move_speed, stats[..., 4]),
        armor=set_cols(state.armor, stats[..., 5]),
        alive=set_cols(state.alive, True),
    )

    # towers
    for k, team in enumerate((TEAM_RADIANT, TEAM_DIRE)):
        t = spec.tower_lo + k
        state = state._replace(
            unit_type=state.unit_type.at[:, t].set(pb.UNIT_TOWER),
            team=state.team.at[:, t].set(team),
            x=state.x.at[:, t].set(TOWER_X[team]),
            health=state.health.at[:, t].set(TOWER_HP),
            health_max=state.health_max.at[:, t].set(TOWER_HP),
            damage=state.damage.at[:, t].set(TOWER_DAMAGE),
            attack_range=state.attack_range.at[:, t].set(TOWER_RANGE),
            armor=state.armor.at[:, t].set(TOWER_ARMOR),
            alive=state.alive.at[:, t].set(True),
        )

    key, sub = jax.random.split(state.key)
    state = _spawn_waves(spec, state._replace(key=key), jnp.ones((N,), bool), sub)
    return state._replace(next_wave_at=jnp.full((N,), CREEP_WAVE_PERIOD, jnp.float32))


def reset_where(spec: VecSimSpec, state: SimState, mask: jnp.ndarray) -> SimState:
    """Re-initialize the games where ``mask`` — pure/jittable (fresh rows are
    computed for the whole batch and merged where the mask holds)."""
    key, sub = jax.random.split(state.key)
    fresh = init_state(spec, state.hero_ids, state.control_modes, sub)

    def merge(a, b):
        m = mask.reshape((-1,) + (1,) * (a.ndim - 1))
        return jnp.where(m, a, b)

    # the PRNG key has no game axis — it is threaded, not merged
    out = {
        k: merge(getattr(fresh, k), getattr(state, k))
        for k in SimState._fields
        if k != "key"
    }
    return SimState(key=key, **out)


def _spawn_waves(
    spec: VecSimSpec, state: SimState, due: jnp.ndarray, key: jnp.ndarray
) -> SimState:
    """Spawn one creep wave per team where ``due`` (claiming free pool slots)."""
    C = spec.creeps_per_team
    for i, team in enumerate((TEAM_RADIANT, TEAM_DIRE)):
        lo = spec.creep_lo + i * C
        pool = slice(lo, lo + C)
        sign = 1.0 if team == TEAM_RADIANT else -1.0
        free = ~state.alive[:, pool]                            # [N, C]
        order = jnp.cumsum(free, axis=1) - 1
        take = free & (order < CREEPS_PER_WAVE) & due[:, None]
        k = order.astype(jnp.float32)
        jitter = jax.random.uniform(
            jax.random.fold_in(key, i), free.shape, minval=-40.0, maxval=40.0
        )

        def w(arr, val):
            return arr.at[:, pool].set(jnp.where(take, val, arr[:, pool]))

        state = state._replace(
            unit_type=w(state.unit_type, pb.UNIT_LANE_CREEP),
            team=w(state.team, team),
            x=w(state.x, TOWER_X[team] + sign * (250.0 + 40.0 * k)),
            y=w(state.y, jitter),
            health=w(state.health, CREEP_HP),
            health_max=w(state.health_max, CREEP_HP),
            damage=w(state.damage, CREEP_DAMAGE),
            attack_range=w(state.attack_range, CREEP_RANGE),
            move_speed=w(state.move_speed, CREEP_SPEED),
            armor=w(state.armor, CREEP_ARMOR),
            level=w(state.level, 1),
            alive=w(state.alive, True),
            attack_cd=w(state.attack_cd, 0.0),
        )
    return state


def _pairwise_dist(state: SimState) -> jnp.ndarray:
    dx = state.x[:, :, None] - state.x[:, None, :]
    dy = state.y[:, :, None] - state.y[:, None, :]
    return jnp.hypot(dx, dy)


def hero_castable(state: SimState) -> jnp.ndarray:
    return (
        (state.unit_type == pb.UNIT_HERO)
        & (state.ability_cd <= 0.0)
        & (state.mana >= NUKE_MANA)
    )


# ---------------------------------------------------------------------------
# step
# ---------------------------------------------------------------------------


def step(
    spec: VecSimSpec,
    state: SimState,
    actions: Actions,
    scripted_possible: bool = True,
) -> SimState:
    """One observation interval for every non-done game (pure; jit this or a
    scan over it). Mirrors ``VecLaneSim.step`` phase for phase.

    ``scripted_possible`` is STATIC: control_modes is a traced array, so XLA
    cannot prune the scripted-bot subgraph on its own — callers that know no
    player is scripted (self-play, league) pass False and skip it entirely.
    """
    N, S, P = spec.n_games, spec.max_units, spec.n_players
    live = ~state.done
    dt = spec.ticks_per_obs / TICKS_PER_SECOND
    dist = _pairwise_dist(state)

    a_type = jnp.where(actions["type"] < 0, pb.ACTION_NOOP, actions["type"])
    move_x = actions["move_x"]
    move_y = actions["move_y"]
    target = jnp.clip(actions["target_slot"], 0, S - 1).astype(jnp.int32)
    ability = actions["ability"]

    if scripted_possible:
        scripted = state.control_modes != pb.CONTROL_AGENT
        sa = _scripted_actions(spec, state, dist)
        a_type = jnp.where(scripted, sa["type"], a_type)
        move_x = jnp.where(scripted, sa["move_x"], move_x)
        move_y = jnp.where(scripted, sa["move_y"], move_y)
        target = jnp.where(scripted, sa["target_slot"], target)
        ability = jnp.where(scripted, sa["ability"], ability)

    hero_alive = state.alive[:, :P] & live[:, None]
    n_idx = jnp.arange(N)[:, None]

    # 1. movement
    half = (spec.move_bins - 1) / 2.0
    moving = hero_alive & (a_type == pb.ACTION_MOVE)
    mdx = (move_x - half) / max(half, 1.0)
    mdy = (move_y - half) / max(half, 1.0)
    norm = jnp.hypot(mdx, mdy)
    ok = moving & (norm > 1e-6)
    scale = jnp.where(ok, state.move_speed[:, :P] * dt / jnp.maximum(norm, 1e-9), 0.0)
    new_hx = jnp.clip(state.x[:, :P] + mdx * scale, -LANE_HALF_LENGTH, LANE_HALF_LENGTH)
    new_hy = jnp.clip(state.y[:, :P] + mdy * scale, -400.0, 400.0)
    state = state._replace(
        x=state.x.at[:, :P].set(jnp.where(ok, new_hx, state.x[:, :P])),
        y=state.y.at[:, :P].set(jnp.where(ok, new_hy, state.y[:, :P])),
    )

    # 2. hero attacks / casts (phase A)
    tgt_dist = dist[n_idx, jnp.arange(P)[None, :], target]
    t_alive = state.alive[n_idx, target]
    t_team = state.team[n_idx, target]
    t_type = state.unit_type[n_idx, target]
    t_hp = state.health[n_idx, target]
    t_hpmax = state.health_max[n_idx, target]
    my_team = state.team[:, :P]

    is_deny = (t_team == my_team) & (t_type == pb.UNIT_LANE_CREEP) & (
        t_hp < 0.5 * t_hpmax
    )
    attack_ok = (
        hero_alive
        & (a_type == pb.ACTION_ATTACK_UNIT)
        & t_alive
        & ((t_team != my_team) | is_deny)
        & (tgt_dist <= state.attack_range[:, :P] + 50.0)
        & (state.attack_cd[:, :P] <= 0.0)
    )
    cast_ok = (
        hero_alive
        & (a_type == pb.ACTION_CAST)
        & (ability == NUKE_SLOT)
        & t_alive
        & (t_team != my_team)
        & (tgt_dist <= NUKE_RANGE)
        & (state.ability_cd[:, :P] <= 0.0)
        & (state.mana[:, :P] >= NUKE_MANA)
    )
    state = state._replace(
        attack_cd=state.attack_cd.at[:, :P].set(
            jnp.where(attack_ok, 1.0 / ATTACKS_PER_SECOND, state.attack_cd[:, :P])
        ),
        mana=state.mana.at[:, :P].set(
            jnp.where(cast_ok, state.mana[:, :P] - NUKE_MANA, state.mana[:, :P])
        ),
        ability_cd=state.ability_cd.at[:, :P].set(
            jnp.where(cast_ok, NUKE_COOLDOWN, state.ability_cd[:, :P])
        ),
    )
    raw = jnp.where(attack_ok, state.damage[:, :P], 0.0) + jnp.where(
        cast_ok,
        NUKE_BASE_DAMAGE + NUKE_DAMAGE_PER_LEVEL * state.level[:, :P],
        0.0,
    )
    hit = attack_ok | cast_ok
    t_mult = _armor_mult(state.armor[n_idx, target])
    # one-hot matmul, NOT scatter-add: XLA scatter combines duplicate
    # indices in unspecified order (f32 non-associativity then flips kill
    # thresholds run-to-run); a reduction has a fixed order and maps to the
    # MXU anyway
    onehot_t = jax.nn.one_hot(target, S, dtype=jnp.float32)     # [N, P, S]
    dmg = jnp.einsum("np,nps->ns", jnp.where(hit, raw * t_mult, 0.0), onehot_t)
    state = _resolve_deaths(
        spec, state, dmg, dist,
        hero_hit=hit, hero_target=target, hero_deny=is_deny & attack_ok,
    )

    # 3. creeps and towers act (phase B, phase-start targeting world)
    state = _step_ai(spec, state, dist, dt, live)

    # 4. clocks, regen, respawns, waves, timeout
    state = _step_clocks(spec, state, dt, live)
    return state


def _resolve_deaths(
    spec: VecSimSpec,
    state: SimState,
    dmg: jnp.ndarray,
    dist: jnp.ndarray,
    hero_hit=None,
    hero_target=None,
    hero_deny=None,
) -> SimState:
    N, S, P = spec.n_games, spec.max_units, spec.n_players
    n_idx = jnp.arange(N)[:, None]
    pre_alive = state.alive
    health = jnp.where(pre_alive, state.health - dmg, state.health)
    died = pre_alive & (health <= 0.0)
    health = jnp.where(died, 0.0, health)
    alive = pre_alive & ~died
    state = state._replace(health=health, alive=alive)

    is_creep = state.unit_type == pb.UNIT_LANE_CREEP
    is_hero = state.unit_type == pb.UNIT_HERO
    died_creep = died & is_creep
    died_hero = died & is_hero
    died_tower = died & (state.unit_type == pb.UNIT_TOWER)

    denied_creep = jnp.zeros((N, S), bool)
    if hero_hit is not None:
        # kill credit: lowest player index whose landed attack targeted the
        # dead slot (argmax over bool picks the first True)
        credit = hero_hit[:, :, None] & (
            hero_target[:, :, None] == jnp.arange(S)[None, None, :]
        )                                                       # [N, P, S]
        by_hero = died & credit.any(axis=1)                     # [N, S]
        first_p = jnp.argmax(credit, axis=1)                    # [N, S]
        deny_credit = jnp.take_along_axis(hero_deny, first_p, axis=1)  # [N, S]

        cred_creep = by_hero & is_creep
        denied_creep = cred_creep & deny_credit
        lasthit = cred_creep & ~deny_credit
        cred_hero = by_hero & is_hero

        # deterministic reduction over victim slots (see dmg comment above)
        onehot_p = jax.nn.one_hot(first_p, S, dtype=jnp.float32)  # [N, S, S]

        def reduce_p(vals):
            return jnp.einsum("ns,nsp->np", vals.astype(jnp.float32), onehot_p)

        state = state._replace(
            denies=state.denies + reduce_p(denied_creep).astype(jnp.int32),
            last_hits=state.last_hits + reduce_p(lasthit).astype(jnp.int32),
            kills=state.kills + reduce_p(cred_hero).astype(jnp.int32),
            gold=state.gold + reduce_p(
                GOLD_PER_LASTHIT * lasthit + GOLD_PER_HERO_KILL * cred_hero
            ),
        )
        state = _grant_xp(
            spec, state, reduce_p(XP_PER_HERO_KILL * cred_hero)[:, :P]
        )

    # creep XP: living enemy heroes within radius split it
    xp_each = jnp.where(denied_creep, CREEP_XP * DENY_XP_FACTOR, CREEP_XP)
    hero_d = dist[:, :P, :]                                     # [N, P, S]
    eligible = (
        state.alive[:, :P, None]
        & (state.team[:, :P, None] != state.team[:, None, :])
        & (hero_d <= XP_RADIUS)
        & died_creep[:, None, :]
    )                                                           # [N, P, S]
    cnt = jnp.maximum(eligible.sum(axis=1), 1)                  # [N, S]
    share = (eligible * (xp_each / cnt)[:, None, :]).sum(axis=2)  # [N, P]
    state = _grant_xp(spec, state, share)

    # hero deaths: respawn timers
    hp_slots = died_hero[:, :P]
    state = state._replace(
        deaths=state.deaths.at[:, :P].add(hp_slots.astype(jnp.int32)),
        respawn_at=state.respawn_at.at[:, :P].set(
            jnp.where(
                hp_slots,
                state.dota_time[:, None]
                + RESPAWN_BASE_SECONDS
                + RESPAWN_PER_LEVEL_SECONDS * state.level[:, :P],
                state.respawn_at[:, :P],
            )
        ),
    )

    # tower death ends the game
    rad_died = died_tower[:, spec.tower_lo]
    dire_died = died_tower[:, spec.tower_lo + 1]
    any_died = rad_died | dire_died
    return state._replace(
        done=state.done | any_died,
        winning_team=jnp.where(
            dire_died, TEAM_RADIANT,
            jnp.where(rad_died, TEAM_DIRE, state.winning_team),
        ),
    )


def _grant_xp(spec: VecSimSpec, state: SimState, xp_gain: jnp.ndarray) -> SimState:
    """Add XP [N, P] to hero slots; closed-form level-ups (level =
    1 + floor(xp/220) capped, +40 maxHP/heal, +20 maxMana, +4 damage per
    level — elementwise, so simultaneous grants cannot double-apply)."""
    P = spec.n_players
    xp = state.xp.at[:, :P].add(xp_gain)
    cur = state.level[:, :P]
    new = jnp.minimum(
        MAX_LEVEL, (xp[:, :P] // XP_PER_LEVEL).astype(jnp.int32) + 1
    )
    gained = jnp.maximum(new - cur, 0).astype(jnp.float32)
    hp_max = state.health_max.at[:, :P].add(40.0 * gained)
    return state._replace(
        xp=xp,
        level=state.level.at[:, :P].set(jnp.maximum(cur, new)),
        health_max=hp_max,
        health=state.health.at[:, :P].set(
            jnp.minimum(state.health[:, :P] + 40.0 * gained, hp_max[:, :P])
        ),
        mana_max=state.mana_max.at[:, :P].add(20.0 * gained),
        damage=state.damage.at[:, :P].add(4.0 * gained),
    )


def _step_ai(
    spec: VecSimSpec, state: SimState, dist: jnp.ndarray, dt: float, live: jnp.ndarray
) -> SimState:
    N, S = spec.n_games, spec.max_units
    alive = state.alive & live[:, None]
    enemy = (
        alive[:, :, None]
        & alive[:, None, :]
        & (state.team[:, :, None] != state.team[:, None, :])
    )
    d_masked = jnp.where(enemy, dist, _BIG)

    is_creep = (state.unit_type == pb.UNIT_LANE_CREEP) & alive
    is_tower = (state.unit_type == pb.UNIT_TOWER) & alive

    nearest = d_masked.argmin(axis=2)
    nearest_d = jnp.take_along_axis(d_masked, nearest[:, :, None], 2)[:, :, 0]
    can_attack = is_creep & (nearest_d <= state.attack_range + 20.0)
    attacking = can_attack & (state.attack_cd <= 0.0)

    in_tower_range = d_masked <= state.attack_range[:, :, None]
    t_pref = jnp.where(
        in_tower_range,
        d_masked
        + jnp.where(state.unit_type[:, None, :] == pb.UNIT_HERO, 1e6, 0.0),
        _BIG * 2.0,
    )
    t_near = t_pref.argmin(axis=2)
    t_attacking = (
        is_tower & (t_pref.min(axis=2) < _BIG) & (state.attack_cd <= 0.0)
    )

    atk = attacking | t_attacking
    tgt = jnp.where(t_attacking, t_near, nearest)
    state = state._replace(
        attack_cd=jnp.where(atk, 1.0 / ATTACKS_PER_SECOND, state.attack_cd)
    )
    n_idx = jnp.arange(N)[:, None]
    t_mult = _armor_mult(state.armor[n_idx, tgt])
    # deterministic one-hot reduction (see phase-A dmg comment)
    onehot_t = jax.nn.one_hot(tgt, S, dtype=jnp.float32)        # [N, S, S]
    dmg = jnp.einsum(
        "na,nas->ns", jnp.where(atk, state.damage * t_mult, 0.0), onehot_t
    )
    state = _resolve_deaths(spec, state, dmg, dist)

    marching = is_creep & ~can_attack & state.alive
    goal_x = jnp.where(
        state.team == TEAM_RADIANT, TOWER_X[TEAM_DIRE], TOWER_X[TEAM_RADIANT]
    )
    step_len = state.move_speed * dt
    delta = goal_x - state.x
    return state._replace(
        x=jnp.where(
            marching,
            state.x + jnp.sign(delta) * jnp.minimum(step_len, jnp.abs(delta)),
            state.x,
        )
    )


def _step_clocks(
    spec: VecSimSpec, state: SimState, dt: float, live: jnp.ndarray
) -> SimState:
    N, P = spec.n_games, spec.n_players
    livef = live.astype(jnp.float32)[:, None]
    dota_time = jnp.where(live, state.dota_time + dt, state.dota_time)
    state = state._replace(
        dota_time=dota_time,
        tick=jnp.where(live, state.tick + spec.ticks_per_obs, state.tick),
        attack_cd=jnp.maximum(0.0, state.attack_cd - dt * livef),
        ability_cd=jnp.maximum(0.0, state.ability_cd - dt * livef),
    )
    hero_alive = (state.unit_type == pb.UNIT_HERO) & state.alive & live[:, None]
    state = state._replace(
        gold=jnp.where(hero_alive, state.gold + GOLD_PASSIVE_PER_SEC * dt, state.gold),
        health=jnp.where(
            hero_alive,
            jnp.minimum(state.health + 1.5 * dt, state.health_max),
            state.health,
        ),
        mana=jnp.where(
            hero_alive,
            jnp.minimum(state.mana + 1.0 * dt, state.mana_max),
            state.mana,
        ),
    )

    # respawns
    hero_dead = (
        (state.unit_type == pb.UNIT_HERO) & ~state.alive & live[:, None]
        & (state.respawn_at >= 0.0)
        & (state.respawn_at <= state.dota_time[:, None])
    )
    pslots = jnp.arange(P)
    team_row = state.team[:, :P]
    side = jnp.where(team_row == TEAM_RADIANT, -1.0, 1.0)
    hd = hero_dead[:, :P]
    state = state._replace(
        alive=state.alive.at[:, :P].set(state.alive[:, :P] | hd),
        health=state.health.at[:, :P].set(
            jnp.where(hd, state.health_max[:, :P], state.health[:, :P])
        ),
        mana=state.mana.at[:, :P].set(
            jnp.where(hd, state.mana_max[:, :P], state.mana[:, :P])
        ),
        x=state.x.at[:, :P].set(
            jnp.where(hd, side * (LANE_HALF_LENGTH - 300.0), state.x[:, :P])
        ),
        y=state.y.at[:, :P].set(
            jnp.where(hd, (60.0 * (pslots % 5)).astype(jnp.float32), state.y[:, :P])
        ),
        respawn_at=state.respawn_at.at[:, :P].set(
            jnp.where(hd, -1.0, state.respawn_at[:, :P])
        ),
    )

    # waves
    wave_due = live & ~state.done & (state.dota_time >= state.next_wave_at)
    key, sub = jax.random.split(state.key)
    state = _spawn_waves(spec, state._replace(key=key), wave_due, sub)
    state = state._replace(
        next_wave_at=jnp.where(
            wave_due, state.dota_time + CREEP_WAVE_PERIOD, state.next_wave_at
        )
    )

    # timeout adjudication: (tower hp, team kills, team gold) lexicographic
    timed_out = live & ~state.done & (state.dota_time >= spec.max_dota_time)
    team_row_p = state.team[:, :P]
    is_rad = team_row_p == TEAM_RADIANT
    rk = (state.kills[:, :P] * is_rad).sum(1).astype(jnp.float32)
    dk = (state.kills[:, :P] * ~is_rad).sum(1).astype(jnp.float32)
    rg = (state.gold[:, :P] * is_rad).sum(1)
    dg = (state.gold[:, :P] * ~is_rad).sum(1)
    rt = state.health[:, spec.tower_lo]
    dt_ = state.health[:, spec.tower_lo + 1]
    r_wins = (rt > dt_) | ((rt == dt_) & ((rk > dk) | ((rk == dk) & (rg > dg))))
    d_wins = (dt_ > rt) | ((rt == dt_) & ((dk > rk) | ((rk == dk) & (dg > rg))))
    return state._replace(
        done=state.done | timed_out,
        winning_team=jnp.where(
            timed_out,
            jnp.where(r_wins, TEAM_RADIANT, jnp.where(d_wins, TEAM_DIRE, 0)),
            state.winning_team,
        ),
    )


# ---------------------------------------------------------------------------
# scripted bots (jnp port of vec_lane_sim.scripted_actions_vec)
# ---------------------------------------------------------------------------


def _scripted_actions(
    spec: VecSimSpec, state: SimState, dist: jnp.ndarray
) -> Actions:
    N, S, P = spec.n_games, spec.max_units, spec.n_players
    half = (spec.move_bins - 1) / 2.0
    my_team = state.team[:, :P]
    hard = state.control_modes == pb.CONTROL_SCRIPTED_HARD
    hero_alive = state.alive[:, :P]
    hp_frac = state.health[:, :P] / jnp.maximum(state.health_max[:, :P], 1.0)

    enemy = state.alive[:, None, :] & (state.team[:, None, :] != my_team[:, :, None])
    pd = dist[:, :P, :]
    d_enemy = jnp.where(enemy, pd, _BIG)

    is_hero_s = state.unit_type == pb.UNIT_HERO
    is_creep_s = state.unit_type == pb.UNIT_LANE_CREEP
    enemy_hero = enemy & is_hero_s[:, None, :]
    d_ehero = jnp.where(enemy_hero, pd, _BIG)

    out_type = jnp.full((N, P), pb.ACTION_NOOP, jnp.int32)
    out_mx = jnp.zeros((N, P), jnp.int32)
    out_my = jnp.zeros((N, P), jnp.int32)
    out_tgt = jnp.zeros((N, P), jnp.int32)
    out_abl = jnp.zeros((N, P), jnp.int32)

    def move_toward(mask, gx, gy, outs):
        o_type, o_mx, o_my = outs
        dx = gx - state.x[:, :P]
        dy = gy - state.y[:, :P]
        norm = jnp.hypot(dx, dy)
        okm = mask & (norm >= 1e-6)
        mx = jnp.clip(
            jnp.round(half + half * dx / jnp.maximum(norm, 1e-9)), 0, spec.move_bins - 1
        ).astype(jnp.int32)
        my = jnp.clip(
            jnp.round(half + half * dy / jnp.maximum(norm, 1e-9)), 0, spec.move_bins - 1
        ).astype(jnp.int32)
        return (
            jnp.where(okm, pb.ACTION_MOVE, o_type),
            jnp.where(okm, mx, o_mx),
            jnp.where(okm, my, o_my),
        )

    todo = hero_alive

    # HARD retreat
    near_ehero = d_ehero.min(axis=2) <= 900.0
    retreat = todo & hard & (hp_frac < 0.3) & near_ehero
    own_tower_x = jnp.where(
        my_team == TEAM_RADIANT, TOWER_X[TEAM_RADIANT], TOWER_X[TEAM_DIRE]
    ).astype(jnp.float32)
    out_type, out_mx, out_my = move_toward(
        retreat, own_tower_x, jnp.zeros_like(own_tower_x), (out_type, out_mx, out_my)
    )
    todo = todo & ~retreat

    # HARD nuke lowest-HP enemy hero in range
    castable = (state.mana[:, :P] >= NUKE_MANA) & (state.ability_cd[:, :P] <= 0.0)
    nukable = enemy_hero & (pd <= NUKE_RANGE)
    hp_key = jnp.where(nukable, state.health[:, None, :], _BIG)
    nuke_tgt = hp_key.argmin(axis=2).astype(jnp.int32)
    can_nuke = todo & hard & castable & nukable.any(axis=2)
    out_type = jnp.where(can_nuke, pb.ACTION_CAST, out_type)
    out_tgt = jnp.where(can_nuke, nuke_tgt, out_tgt)
    out_abl = jnp.where(can_nuke, NUKE_SLOT, out_abl)
    todo = todo & ~can_nuke

    in_range = enemy & (pd <= state.attack_range[:, :P, None] + 50.0)

    # HARD last-hit killable creep
    eff_dmg = state.damage[:, :P, None] * _armor_mult(state.armor[:, None, :])
    killable = in_range & is_creep_s[:, None, :] & (state.health[:, None, :] <= eff_dmg)
    kill_tgt = jnp.where(killable, state.health[:, None, :], _BIG).argmin(2).astype(jnp.int32)
    do_lh = todo & hard & killable.any(axis=2)
    out_type = jnp.where(do_lh, pb.ACTION_ATTACK_UNIT, out_type)
    out_tgt = jnp.where(do_lh, kill_tgt, out_tgt)
    todo = todo & ~do_lh

    # HARD harass enemy hero while healthy
    heroes_in_range = in_range & is_hero_s[:, None, :]
    harass_tgt = jnp.where(heroes_in_range, state.health[:, None, :], _BIG).argmin(2).astype(jnp.int32)
    do_harass = todo & hard & heroes_in_range.any(axis=2) & (hp_frac >= 0.5)
    out_type = jnp.where(do_harass, pb.ACTION_ATTACK_UNIT, out_type)
    out_tgt = jnp.where(do_harass, harass_tgt, out_tgt)
    todo = todo & ~do_harass

    # HARD pressure lowest-HP creep in range
    creeps_in_range = in_range & is_creep_s[:, None, :]
    press_tgt = jnp.where(creeps_in_range, state.health[:, None, :], _BIG).argmin(2).astype(jnp.int32)
    do_press = todo & hard & creeps_in_range.any(axis=2)
    out_type = jnp.where(do_press, pb.ACTION_ATTACK_UNIT, out_type)
    out_tgt = jnp.where(do_press, press_tgt, out_tgt)
    todo = todo & ~do_press

    # EASY / fallback: attack nearest in range
    near_tgt = jnp.where(in_range, pd, _BIG).argmin(2).astype(jnp.int32)
    do_atk = todo & in_range.any(axis=2)
    out_type = jnp.where(do_atk, pb.ACTION_ATTACK_UNIT, out_type)
    out_tgt = jnp.where(do_atk, near_tgt, out_tgt)
    todo = todo & ~do_atk

    # march toward nearest enemy (or mid)
    nearest_any = d_enemy.argmin(axis=2)
    has_enemy = d_enemy.min(axis=2) < _BIG
    n_idx = jnp.arange(N)[:, None]
    gx = jnp.where(has_enemy, state.x[n_idx, nearest_any], 0.0)
    gy = jnp.where(has_enemy, state.y[n_idx, nearest_any], 0.0)
    out_type, out_mx, out_my = move_toward(todo, gx, gy, (out_type, out_mx, out_my))

    return {
        "type": out_type, "move_x": out_mx, "move_y": out_my,
        "target_slot": out_tgt, "ability": out_abl,
    }
