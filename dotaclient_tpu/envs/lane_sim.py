"""Deterministic toy 1v1/2v2/5v5 mid-lane simulator.

Stands in for the Dota 2 process + dotaservice pair the reference drives over
gRPC (SURVEY.md §3.5: reset spawns the game, observe streams
``CMsgBotWorldState``-shaped protos, act enqueues bot orders). The reference
repo has no such test double — its de-facto test was watching TensorBoard
against the live game (SURVEY.md §4) — so this sim is the rebuild's designed
substitute: a closed-form lane with creep waves, last-hit/deny gold, XP and
levels, one castable nuke, towers, deaths/respawns and a win condition, rich
enough to exercise every action head and the shaped-reward terms.

Everything is plain host-side Python/numpy: the environment is not a TPU
citizen (SURVEY.md §2.4) — device work begins at the featurizer.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from dotaclient_tpu.protos import dota_pb2 as pb

# Team ids follow the Dota convention the reference's protos use.
TEAM_RADIANT = 2
TEAM_DIRE = 3
TEAMS = (TEAM_RADIANT, TEAM_DIRE)

TICKS_PER_SECOND = 30
LANE_HALF_LENGTH = 2000.0
TOWER_X = {TEAM_RADIANT: -LANE_HALF_LENGTH, TEAM_DIRE: LANE_HALF_LENGTH}
CREEP_WAVE_PERIOD = 30.0
CREEPS_PER_WAVE = 4
MAX_LEVEL = 10

# XP required to reach level i+1 from level i.
XP_PER_LEVEL = 220.0
XP_RADIUS = 1200.0
DENY_XP_FACTOR = 0.3  # fraction of creep XP granted to enemies when denied

GOLD_PER_LASTHIT = 40.0
GOLD_PASSIVE_PER_SEC = 1.7
GOLD_PER_HERO_KILL = 200.0
XP_PER_HERO_KILL = 280.0
RESPAWN_BASE_SECONDS = 6.0
RESPAWN_PER_LEVEL_SECONDS = 2.0

NUKE_SLOT = 0
NUKE_MANA = 50.0
NUKE_COOLDOWN = 10.0
NUKE_RANGE = 600.0
NUKE_BASE_DAMAGE = 75.0
NUKE_DAMAGE_PER_LEVEL = 25.0

# Small per-hero stat table (hero pool per BASELINE.json:8 — Nevermore / Lina
# / Sniper — plus generic fallbacks).
HERO_STATS = {
    # hero_id: (hp, mana, damage, attack_range, move_speed, armor)
    1: (550.0, 270.0, 52.0, 500.0, 310.0, 2.0),   # "nevermore"
    2: (480.0, 360.0, 48.0, 650.0, 295.0, 1.0),   # "lina"
    3: (500.0, 300.0, 45.0, 550.0, 290.0, 1.5),   # "sniper"
}
GENERIC_HERO = (520.0, 300.0, 48.0, 550.0, 300.0, 1.5)

CREEP_HP = 550.0
CREEP_DAMAGE = 20.0
CREEP_RANGE = 110.0
CREEP_SPEED = 325.0
CREEP_ARMOR = 2.0
CREEP_XP = 60.0

TOWER_HP = 1800.0
TOWER_DAMAGE = 110.0
TOWER_RANGE = 700.0
TOWER_ARMOR = 10.0

ATTACKS_PER_SECOND = 1.0


def _armor_multiplier(armor: float) -> float:
    return 1.0 - (0.06 * armor) / (1.0 + 0.06 * armor)


@dataclasses.dataclass
class SimUnit:
    handle: int
    unit_type: int
    team_id: int
    x: float
    y: float
    health: float
    health_max: float
    mana: float = 0.0
    mana_max: float = 0.0
    damage: float = 0.0
    attack_range: float = 0.0
    move_speed: float = 0.0
    armor: float = 0.0
    player_id: int = -1
    hero_id: int = 0
    level: int = 1
    xp: float = 0.0
    gold: float = 0.0
    last_hits: int = 0
    denies: int = 0
    kills: int = 0
    deaths: int = 0
    attack_cooldown: float = 0.0
    ability_cooldown: float = 0.0
    respawn_at: float = -1.0
    alive: bool = True

    def dist(self, other: "SimUnit") -> float:
        return math.hypot(self.x - other.x, self.y - other.y)


class LaneSim:
    """One lane, two teams. Deterministic given (config.seed, action stream)."""

    def __init__(self, config: pb.GameConfig):
        self.config = config
        self.rng = np.random.default_rng(config.seed)
        self.ticks_per_obs = max(1, config.ticks_per_observation or 6)
        self.max_dota_time = config.max_dota_time or 600.0
        self.move_bins = config.move_bins or 9
        self.dota_time = 0.0
        self.tick = 0
        self._next_handle = 1
        self._next_wave_at = 0.0
        self.units: Dict[int, SimUnit] = {}
        self.game_state = pb.GAME_STATE_IN_PROGRESS
        self.winning_team = 0
        self.heroes: List[SimUnit] = []
        self.towers: Dict[int, SimUnit] = {}

        picks = list(config.hero_picks)
        if not picks:
            picks = [
                pb.HeroPick(team_id=TEAM_RADIANT, hero_id=1, control_mode=pb.CONTROL_AGENT),
                pb.HeroPick(team_id=TEAM_DIRE, hero_id=1, control_mode=pb.CONTROL_SCRIPTED_EASY),
            ]
        self.control_modes: Dict[int, int] = {}
        player_id = 0
        for pick in picks:
            hero = self._spawn_hero(player_id, pick.team_id, pick.hero_id)
            self.control_modes[player_id] = pick.control_mode
            self.heroes.append(hero)
            player_id += 1

        for team in TEAMS:
            self.towers[team] = self._spawn_tower(team)
        self._spawn_wave()

    # -- spawning ----------------------------------------------------------

    def _handle(self) -> int:
        h = self._next_handle
        self._next_handle += 1
        return h

    def _hero_spawn_pos(self, team_id: int, player_id: int) -> tuple:
        side = -1.0 if team_id == TEAM_RADIANT else 1.0
        return (side * (LANE_HALF_LENGTH - 300.0), 60.0 * (player_id % 5))

    def _spawn_hero(self, player_id: int, team_id: int, hero_id: int) -> SimUnit:
        hp, mana, dmg, rng_, speed, armor = HERO_STATS.get(hero_id, GENERIC_HERO)
        x, y = self._hero_spawn_pos(team_id, player_id)
        unit = SimUnit(
            handle=self._handle(), unit_type=pb.UNIT_HERO, team_id=team_id,
            x=x, y=y, health=hp, health_max=hp, mana=mana, mana_max=mana,
            damage=dmg, attack_range=rng_, move_speed=speed, armor=armor,
            player_id=player_id, hero_id=hero_id,
        )
        self.units[unit.handle] = unit
        return unit

    def _spawn_tower(self, team_id: int) -> SimUnit:
        unit = SimUnit(
            handle=self._handle(), unit_type=pb.UNIT_TOWER, team_id=team_id,
            x=TOWER_X[team_id], y=0.0, health=TOWER_HP, health_max=TOWER_HP,
            damage=TOWER_DAMAGE, attack_range=TOWER_RANGE, armor=TOWER_ARMOR,
        )
        self.units[unit.handle] = unit
        return unit

    def _spawn_wave(self) -> None:
        for team in TEAMS:
            sign = 1.0 if team == TEAM_RADIANT else -1.0
            for i in range(CREEPS_PER_WAVE):
                unit = SimUnit(
                    handle=self._handle(), unit_type=pb.UNIT_LANE_CREEP,
                    team_id=team,
                    x=TOWER_X[team] + sign * (250.0 + 40.0 * i),
                    y=float(self.rng.uniform(-40.0, 40.0)),
                    health=CREEP_HP, health_max=CREEP_HP, damage=CREEP_DAMAGE,
                    attack_range=CREEP_RANGE, move_speed=CREEP_SPEED,
                    armor=CREEP_ARMOR,
                )
                self.units[unit.handle] = unit
        self._next_wave_at = self.dota_time + CREEP_WAVE_PERIOD

    # -- queries -----------------------------------------------------------

    @property
    def done(self) -> bool:
        return self.game_state == pb.GAME_STATE_POST_GAME

    def hero_for_player(self, player_id: int) -> SimUnit:
        return self.heroes[player_id]

    def living(self, team_id: Optional[int] = None) -> List[SimUnit]:
        return [
            u for u in self.units.values()
            if u.alive and (team_id is None or u.team_id == team_id)
        ]

    def enemies_of(self, team_id: int) -> List[SimUnit]:
        return [u for u in self.units.values() if u.alive and u.team_id != team_id]

    # -- stepping ----------------------------------------------------------

    def step(self, actions: Dict[int, pb.Action]) -> None:
        """Advance one observation interval (``ticks_per_obs`` game ticks).

        ``actions`` maps player_id -> Action for agent-controlled players;
        scripted players are driven internally. An agent-controlled hero with
        no submitted action no-ops (it is never handed to the scripted bots).
        Unknown player ids are ignored.
        """
        if self.done:
            return
        dt = self.ticks_per_obs / TICKS_PER_SECOND
        n_players = len(self.heroes)
        full_actions = {
            pid: a for pid, a in actions.items() if 0 <= pid < n_players
        }
        for hero in self.heroes:
            if hero.player_id not in full_actions:
                mode = self.control_modes.get(hero.player_id, pb.CONTROL_SCRIPTED_EASY)
                if mode == pb.CONTROL_AGENT:
                    continue  # no order this interval -> no-op
                full_actions[hero.player_id] = scripted_action(
                    self, hero, mode, self.move_bins
                )

        # 1. apply orders (movement now; attack/cast intents resolved below)
        intents: Dict[int, pb.Action] = {}
        for player_id, action in full_actions.items():
            hero = self.heroes[player_id]
            if not hero.alive:
                continue
            if action.type == pb.ACTION_MOVE:
                half = (self.move_bins - 1) / 2.0
                dx = (action.move_x - half) / max(half, 1.0)
                dy = (action.move_y - half) / max(half, 1.0)
                norm = math.hypot(dx, dy)
                if norm > 1e-6:
                    scale = hero.move_speed * dt / norm
                    hero.x = float(np.clip(hero.x + dx * scale, -LANE_HALF_LENGTH, LANE_HALF_LENGTH))
                    hero.y = float(np.clip(hero.y + dy * scale, -400.0, 400.0))
            elif action.type in (pb.ACTION_ATTACK_UNIT, pb.ACTION_CAST):
                intents[player_id] = action

        # 2. hero attack / cast resolution
        for player_id, action in intents.items():
            hero = self.heroes[player_id]
            if not hero.alive:
                continue
            target = self.units.get(action.target_handle)
            if target is None or not target.alive:
                continue
            if action.type == pb.ACTION_ATTACK_UNIT:
                deny = target.team_id == hero.team_id
                if deny and not (
                    target.unit_type == pb.UNIT_LANE_CREEP
                    and target.health < 0.5 * target.health_max
                ):
                    continue  # denies only on own creeps under half HP
                if hero.dist(target) <= hero.attack_range + 50.0 and hero.attack_cooldown <= 0.0:
                    self._deal_damage(hero, target, hero.damage)
                    hero.attack_cooldown = 1.0 / ATTACKS_PER_SECOND
            else:  # ACTION_CAST
                if (
                    action.ability_slot == NUKE_SLOT
                    and hero.ability_cooldown <= 0.0
                    and hero.mana >= NUKE_MANA
                    and target.team_id != hero.team_id
                    and hero.dist(target) <= NUKE_RANGE
                ):
                    hero.mana -= NUKE_MANA
                    hero.ability_cooldown = NUKE_COOLDOWN
                    dmg = NUKE_BASE_DAMAGE + NUKE_DAMAGE_PER_LEVEL * hero.level
                    self._deal_damage(hero, target, dmg)

        # 3. creeps and towers act
        self._step_ai_units(dt)

        # 4. timers, regen, respawns, waves, win check
        self._step_clocks(dt)

    def _deal_damage(self, attacker: SimUnit, target: SimUnit, raw: float) -> None:
        target.health -= raw * _armor_multiplier(target.armor)
        if target.health <= 0.0 and target.alive:
            self._on_death(attacker, target)

    def _on_death(self, killer: SimUnit, victim: SimUnit) -> None:
        victim.alive = False
        victim.health = 0.0
        if victim.unit_type == pb.UNIT_LANE_CREEP:
            denied = killer.team_id == victim.team_id
            if killer.unit_type == pb.UNIT_HERO:
                if denied:
                    killer.denies += 1
                else:
                    killer.last_hits += 1
                    killer.gold += GOLD_PER_LASTHIT
            xp_each = CREEP_XP * (DENY_XP_FACTOR if denied else 1.0)
            enemy_heroes = [
                h for h in self.heroes
                if h.alive and h.team_id != victim.team_id
                and h.dist(victim) <= XP_RADIUS
            ]
            for h in enemy_heroes:
                self._grant_xp(h, xp_each / max(len(enemy_heroes), 1))
            del self.units[victim.handle]
        elif victim.unit_type == pb.UNIT_HERO:
            victim.deaths += 1
            if killer.unit_type == pb.UNIT_HERO:
                killer.kills += 1
                killer.gold += GOLD_PER_HERO_KILL
                self._grant_xp(killer, XP_PER_HERO_KILL)
            victim.respawn_at = self.dota_time + (
                RESPAWN_BASE_SECONDS + RESPAWN_PER_LEVEL_SECONDS * victim.level
            )
        elif victim.unit_type == pb.UNIT_TOWER:
            self.game_state = pb.GAME_STATE_POST_GAME
            self.winning_team = TEAM_RADIANT if victim.team_id == TEAM_DIRE else TEAM_DIRE

    def _grant_xp(self, hero: SimUnit, xp: float) -> None:
        hero.xp += xp
        while hero.level < MAX_LEVEL and hero.xp >= XP_PER_LEVEL * hero.level:
            hero.level += 1
            hero.health_max += 40.0
            hero.health = min(hero.health + 40.0, hero.health_max)
            hero.mana_max += 20.0
            hero.damage += 4.0

    def _step_ai_units(self, dt: float) -> None:
        for unit in list(self.units.values()):
            if not unit.alive or unit.unit_type == pb.UNIT_HERO:
                continue
            enemies = self.enemies_of(unit.team_id)
            if unit.unit_type == pb.UNIT_TOWER:
                # towers prefer creeps, then heroes, in range
                in_range = [e for e in enemies if unit.dist(e) <= unit.attack_range]
                in_range.sort(key=lambda e: (e.unit_type == pb.UNIT_HERO, unit.dist(e)))
                if in_range and unit.attack_cooldown <= 0.0:
                    self._deal_damage(unit, in_range[0], unit.damage)
                    unit.attack_cooldown = 1.0 / ATTACKS_PER_SECOND
                continue
            # lane creeps: attack nearest enemy in range else march toward
            # the enemy tower
            if not enemies:
                continue
            nearest = min(enemies, key=unit.dist)
            if unit.dist(nearest) <= unit.attack_range + 20.0:
                if unit.attack_cooldown <= 0.0:
                    self._deal_damage(unit, nearest, unit.damage)
                    unit.attack_cooldown = 1.0 / ATTACKS_PER_SECOND
            else:
                enemy_team = TEAM_DIRE if unit.team_id == TEAM_RADIANT else TEAM_RADIANT
                goal_x = TOWER_X[enemy_team]
                step = unit.move_speed * dt
                unit.x += math.copysign(min(step, abs(goal_x - unit.x)), goal_x - unit.x)

    def _step_clocks(self, dt: float) -> None:
        self.dota_time += dt
        self.tick += self.ticks_per_obs
        for unit in self.units.values():
            unit.attack_cooldown = max(0.0, unit.attack_cooldown - dt)
            unit.ability_cooldown = max(0.0, unit.ability_cooldown - dt)
            if unit.unit_type == pb.UNIT_HERO and unit.alive:
                unit.gold += GOLD_PASSIVE_PER_SEC * dt
                unit.health = min(unit.health + 1.5 * dt, unit.health_max)
                unit.mana = min(unit.mana + 1.0 * dt, unit.mana_max)
        for hero in self.heroes:
            if not hero.alive and 0.0 <= hero.respawn_at <= self.dota_time:
                hero.alive = True
                hero.health = hero.health_max
                hero.mana = hero.mana_max
                hero.x, hero.y = self._hero_spawn_pos(hero.team_id, hero.player_id)
                hero.respawn_at = -1.0
        if self.dota_time >= self._next_wave_at and not self.done:
            self._spawn_wave()
        if self.dota_time >= self.max_dota_time and not self.done:
            self.game_state = pb.GAME_STATE_POST_GAME
            # timeout adjudication: tower HP, then kills, then gold
            def score(team: int) -> tuple:
                return (
                    self.towers[team].health,
                    sum(h.kills for h in self.heroes if h.team_id == team),
                    sum(h.gold for h in self.heroes if h.team_id == team),
                )
            r, d = score(TEAM_RADIANT), score(TEAM_DIRE)
            self.winning_team = TEAM_RADIANT if r > d else TEAM_DIRE if d > r else 0

    # -- proto export ------------------------------------------------------

    def world_state(self, team_id: int) -> pb.WorldState:
        ws = pb.WorldState(
            team_id=team_id,
            game_time=self.dota_time,
            dota_time=self.dota_time,
            tick=self.tick,
            game_state=self.game_state,
            winning_team=self.winning_team,
        )
        for unit in self.units.values():
            # dead heroes stay in the worldstate with is_alive=False (as in
            # Valve's CMsgBotWorldState); dead creeps/towers are removed
            if not unit.alive and unit.unit_type != pb.UNIT_HERO:
                continue
            u = ws.units.add(
                handle=unit.handle, unit_type=unit.unit_type, team_id=unit.team_id,
                player_id=unit.player_id, hero_id=unit.hero_id,
                health=unit.health, health_max=unit.health_max,
                mana=unit.mana, mana_max=unit.mana_max, is_alive=unit.alive,
                level=unit.level, attack_damage=unit.damage,
                attack_range=unit.attack_range, armor=unit.armor,
                movement_speed=unit.move_speed, last_hits=unit.last_hits,
                denies=unit.denies,
            )
            u.location.x = unit.x
            u.location.y = unit.y
            if unit.unit_type == pb.UNIT_HERO:
                u.abilities.add(
                    slot=NUKE_SLOT, ability_id=1,
                    cooldown_remaining=unit.ability_cooldown,
                    level=unit.level,
                    castable=(unit.ability_cooldown <= 0.0 and unit.mana >= NUKE_MANA),
                    cast_range=NUKE_RANGE,
                )
        for hero in self.heroes:
            ws.players.add(
                player_id=hero.player_id, team_id=hero.team_id,
                hero_id=hero.hero_id, kills=hero.kills, deaths=hero.deaths,
                gold=hero.gold, xp=hero.xp,
            )
        return ws


# ---------------------------------------------------------------------------
# Scripted opponents (the "hard bot" the win-rate metric runs against,
# BASELINE.json:2)
# ---------------------------------------------------------------------------


def scripted_action(sim: LaneSim, hero: SimUnit, mode: int, move_bins: int = 9) -> pb.Action:
    """Deterministic bot controller. EASY marches and attacks the nearest
    enemy; HARD adds last-hit timing, low-HP retreat, and nuke usage."""
    action = pb.Action(player_id=hero.player_id, type=pb.ACTION_NOOP)
    if not hero.alive:
        return action
    enemies = sim.enemies_of(hero.team_id)
    hard = mode == pb.CONTROL_SCRIPTED_HARD
    enemy_heroes = [e for e in enemies if e.unit_type == pb.UNIT_HERO]

    if hard and hero.health < 0.3 * hero.health_max and any(
        hero.dist(e) <= 900.0 for e in enemy_heroes
    ):
        return _move_toward(hero, TOWER_X[hero.team_id], 0.0, move_bins)

    if hard and hero.mana >= NUKE_MANA and hero.ability_cooldown <= 0.0:
        nukable = [e for e in enemy_heroes if hero.dist(e) <= NUKE_RANGE]
        if nukable:
            target = min(nukable, key=lambda e: e.health)
            return pb.Action(
                player_id=hero.player_id, type=pb.ACTION_CAST,
                target_handle=target.handle, ability_slot=NUKE_SLOT,
            )

    in_range = [e for e in enemies if hero.dist(e) <= hero.attack_range + 50.0]
    if in_range:
        if hard:
            # last-hit discipline: prefer creeps that this attack would kill
            killable = [
                e for e in in_range
                if e.unit_type == pb.UNIT_LANE_CREEP
                and e.health <= hero.damage * _armor_multiplier(e.armor)
            ]
            if killable:
                return _attack(hero, min(killable, key=lambda e: e.health))
            # harass the enemy hero when healthier, otherwise pressure the
            # lowest-HP creep so the lane doesn't push into us
            heroes_in_range = [e for e in in_range if e.unit_type == pb.UNIT_HERO]
            if heroes_in_range and hero.health >= 0.5 * hero.health_max:
                return _attack(hero, min(heroes_in_range, key=lambda e: e.health))
            creeps_in_range = [e for e in in_range if e.unit_type == pb.UNIT_LANE_CREEP]
            if creeps_in_range:
                return _attack(hero, min(creeps_in_range, key=lambda e: e.health))
            return _attack(hero, min(in_range, key=hero.dist))
        return _attack(hero, min(in_range, key=hero.dist))

    # nothing in range: march toward mid / nearest enemy
    if enemies:
        nearest = min(enemies, key=hero.dist)
        return _move_toward(hero, nearest.x, nearest.y, move_bins)
    return _move_toward(hero, 0.0, 0.0, move_bins)


def _attack(hero: SimUnit, target: SimUnit) -> pb.Action:
    return pb.Action(
        player_id=hero.player_id, type=pb.ACTION_ATTACK_UNIT,
        target_handle=target.handle,
    )


def _move_toward(hero: SimUnit, x: float, y: float, move_bins: int) -> pb.Action:
    half = (move_bins - 1) / 2.0
    dx, dy = x - hero.x, y - hero.y
    norm = math.hypot(dx, dy)
    if norm < 1e-6:
        return pb.Action(player_id=hero.player_id, type=pb.ACTION_NOOP)
    mx = int(round(half + half * dx / norm))
    my = int(round(half + half * dy / norm))
    return pb.Action(
        player_id=hero.player_id, type=pb.ACTION_MOVE,
        move_x=int(np.clip(mx, 0, move_bins - 1)),
        move_y=int(np.clip(my, 0, move_bins - 1)),
    )
