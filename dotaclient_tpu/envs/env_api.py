"""Environment core shared by the in-process env and the gRPC service.

Mirrors the reset/observe/act RPC surface the reference's ``agent.py`` drives
against dotaservice (SURVEY.md §1 "Environment service", §3.5), with the same
multi-team semantics: each agent-controlled team submits ``Actions`` once per
observation interval; the sim advances when every agent team has acted
(scripted teams act internally).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from dotaclient_tpu.envs import lane_sim
from dotaclient_tpu.protos import dota_pb2 as pb


class DotaEnvCore:
    """One game. Not thread-safe; callers serialize access (asyncio)."""

    def __init__(self) -> None:
        self.sim: Optional[lane_sim.LaneSim] = None
        self._pending: Dict[int, pb.Actions] = {}
        self._agent_teams: List[int] = []

    @property
    def done(self) -> bool:
        return self.sim is None or self.sim.done

    def reset(self, config: pb.GameConfig) -> pb.InitialObservation:
        self.sim = lane_sim.LaneSim(config)
        self._pending.clear()
        self._agent_teams = sorted({
            pick.team_id
            for pick in (config.hero_picks or [])
            if pick.control_mode == pb.CONTROL_AGENT
        }) or [lane_sim.TEAM_RADIANT]
        return pb.InitialObservation(
            status=pb.STATUS_OK,
            world_states=[self.sim.world_state(t) for t in self._agent_teams],
        )

    def observe(self, request: pb.ObserveRequest) -> pb.ObserveResponse:
        if self.sim is None:
            return pb.ObserveResponse(status=pb.STATUS_FAILED)
        status = pb.STATUS_EPISODE_DONE if self.sim.done else pb.STATUS_OK
        return pb.ObserveResponse(
            status=status, world_state=self.sim.world_state(request.team_id)
        )

    def act(self, actions: pb.Actions) -> pb.Empty:
        """Record a team's actions; step once all agent teams have acted."""
        if self.sim is None or self.sim.done:
            return pb.Empty()
        self._pending[actions.team_id] = actions
        if all(t in self._pending for t in self._agent_teams):
            merged: Dict[int, pb.Action] = {}
            for team_actions in self._pending.values():
                for action in team_actions.actions:
                    # a team may only command its own heroes
                    if 0 <= action.player_id < len(self.sim.heroes) and (
                        self.sim.heroes[action.player_id].team_id
                        == team_actions.team_id
                    ):
                        merged[action.player_id] = action
            self._pending.clear()
            self.sim.step(merged)
        return pb.Empty()


class LocalDotaEnv:
    """In-process env with the same call surface as the gRPC client — the
    zero-overhead path used by tests and the batched actor runtime."""

    def __init__(self) -> None:
        self._core = DotaEnvCore()

    def reset(self, config: pb.GameConfig) -> pb.InitialObservation:
        return self._core.reset(config)

    def observe(self, team_id: int) -> pb.ObserveResponse:
        return self._core.observe(pb.ObserveRequest(team_id=team_id))

    def act(self, actions: pb.Actions) -> pb.Empty:
        return self._core.act(actions)

    @property
    def done(self) -> bool:
        return self._core.done
