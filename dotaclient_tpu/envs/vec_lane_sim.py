"""Vectorized lane simulator: N games stepped as numpy arrays.

``lane_sim.LaneSim`` (the scalar, proto-exporting sim) is the semantic
reference; this module implements the same game rules as structured arrays so
hundreds of games advance per ``step`` with no Python-per-unit work — the
host-side throughput fix for the actor hot loop (SURVEY.md §3.1 "the #1
throughput sin"; §7 hard-part 2). The scalar sim remains the gRPC-boundary
implementation (cluster parity, SURVEY.md §3.5); this one feeds the batched
in-process actor (`actor/vec_runtime.py`).

Layout (per game, fixed — TPU-critical: shapes never depend on live unit
count, SURVEY.md §7 step 2):

* slots ``[0, P)``: heroes, slot == player_id (P = 2 × team_size);
* slots ``[P, P+2)``: towers (Radiant then Dire);
* remaining slots: creeps — first half Radiant's pool, second half Dire's.
  Waves claim free (dead) slots in the team's pool; if a pool is full the
  overflow creeps are not spawned (bounded worldstate — the one deliberate
  divergence from the scalar sim's unbounded unit dict).

Known, documented divergences from the scalar sim (all from simultaneous
vs sequential resolution; game-rule constants are shared by import):

* damage within a phase is accumulated simultaneously, so two attackers can
  both "hit" a unit the scalar sim would have let only the first kill; kill
  credit goes to the lowest-index eligible attacker;
* creeps/towers choose targets from the phase-start world, so a creep that
  dies this phase still attacks (the scalar sim resolves AI units in handle
  order with immediate deaths);
* creep-wave y jitter is drawn from one ``default_rng(seed + game)`` stream
  per game rather than the scalar sim's single per-game stream.

Statistical parity with the scalar sim is tested in
``tests/test_vec_sim.py`` (same rules ⇒ same outcomes: hard bot beats easy
bot, last-hit gold arrives, towers fall, timeouts adjudicate identically).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from dotaclient_tpu.envs.lane_sim import (
    ATTACKS_PER_SECOND,
    CREEP_ARMOR,
    CREEP_DAMAGE,
    CREEP_HP,
    CREEP_RANGE,
    CREEP_SPEED,
    CREEP_WAVE_PERIOD,
    CREEP_XP,
    CREEPS_PER_WAVE,
    DENY_XP_FACTOR,
    GENERIC_HERO,
    GOLD_PASSIVE_PER_SEC,
    GOLD_PER_HERO_KILL,
    GOLD_PER_LASTHIT,
    XP_PER_HERO_KILL,
    HERO_STATS,
    LANE_HALF_LENGTH,
    MAX_LEVEL,
    NUKE_BASE_DAMAGE,
    NUKE_COOLDOWN,
    NUKE_DAMAGE_PER_LEVEL,
    NUKE_MANA,
    NUKE_RANGE,
    NUKE_SLOT,
    RESPAWN_BASE_SECONDS,
    RESPAWN_PER_LEVEL_SECONDS,
    TEAM_DIRE,
    TEAM_RADIANT,
    TICKS_PER_SECOND,
    TOWER_ARMOR,
    TOWER_DAMAGE,
    TOWER_HP,
    TOWER_RANGE,
    TOWER_X,
    XP_PER_LEVEL,
    XP_RADIUS,
)
from dotaclient_tpu.protos import dota_pb2 as pb

_BIG = 1e9


def _armor_mult(armor: np.ndarray) -> np.ndarray:
    return 1.0 - (0.06 * armor) / (1.0 + 0.06 * armor)


OPPONENT_CONTROL = {
    "scripted_easy": pb.CONTROL_SCRIPTED_EASY,
    "scripted_hard": pb.CONTROL_SCRIPTED_HARD,
    "selfplay": pb.CONTROL_AGENT,
    "league": pb.CONTROL_AGENT,
}


def draft_games(
    n_games: int,
    team_size: int,
    hero_pool: Sequence[int],
    opponent: str,
    seed: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Hero picks + control modes for a batch of games: (hero_ids [N, P],
    control_modes [N, P]). Radiant players are always agent-controlled; Dire
    control follows ``opponent``. Shared by every vectorized actor."""
    P = 2 * team_size
    rng = np.random.default_rng(seed)
    pool = np.asarray(hero_pool or (1,), np.int32)
    hero_ids = rng.choice(pool, size=(n_games, P)).astype(np.int32)
    control = np.full((n_games, P), pb.CONTROL_AGENT, np.int32)
    control[:, team_size:] = OPPONENT_CONTROL[opponent]
    return hero_ids, control


def apply_anchor_games(
    control: np.ndarray,    # i32 [N, P] from draft_games — mutated in place
    team_size: int,
    opponent: str,
    league_cfg,             # LeagueConfig
) -> int:
    """League anchor games (LeagueConfig.anchor_prob): pin the opponent
    side of the first K games to the scripted anchor bot, whose sim-side
    control override wins over any opponent-lane actions. Shared by every
    vectorized actor (device and host) so the selection scheme cannot
    drift. Returns K; with ``anchor_prob > 0`` at least one game anchors —
    a tiny env count must not silently round the knob to a no-op."""
    if opponent != "league" or league_cfg.anchor_prob <= 0:
        return 0
    n = control.shape[0]
    k = max(1, int(round(league_cfg.anchor_prob * n)))
    name = league_cfg.anchor_opponent
    if name == "mixed":
        # Strategy coverage follows the anchor distribution (measured:
        # hard-only anchors collapsed the easy-bot eval, BASELINE.md 30k
        # league run) — split anchors across both scripted bots per
        # anchor_easy_share, easy rounding up (it is the aggression test,
        # the style pure self-play loses first).
        share = min(1.0, max(0.0, league_cfg.anchor_easy_share))
        # round-before-ceil: float products like 0.07*100 == 7.0000…01
        # must not bump the easy count past the intended share
        n_easy = int(math.ceil(round(share * k, 9)))
        if 0.0 < share < 1.0:
            if k >= 2:
                # a fractional share means BOTH bots were requested —
                # neither may round to zero games (same principle as the
                # max(1, ...) guard above)
                n_easy = min(k - 1, max(1, n_easy))
            else:
                # one anchor game cannot host both bots: the majority
                # bot takes it (round-up-to-easy would invert a 0.1 share)
                n_easy = 1 if share >= 0.5 else 0
        control[:n_easy, team_size:] = OPPONENT_CONTROL["scripted_easy"]
        control[n_easy:k, team_size:] = OPPONENT_CONTROL["scripted_hard"]
    else:
        control[:k, team_size:] = OPPONENT_CONTROL[name]
    return k


@dataclasses.dataclass(frozen=True)
class VecSimSpec:
    """Static layout of a vectorized sim batch."""

    n_games: int
    team_size: int = 1
    max_units: int = 32          # total slots S (== ObsSpec.max_units)
    ticks_per_obs: int = 6
    max_dota_time: float = 600.0
    move_bins: int = 9

    @property
    def n_players(self) -> int:
        return 2 * self.team_size

    @property
    def tower_lo(self) -> int:
        return self.n_players

    @property
    def creep_lo(self) -> int:
        return self.n_players + 2

    @property
    def creeps_per_team(self) -> int:
        return (self.max_units - self.creep_lo) // 2


class VecLaneSim:
    """N concurrent games over shared arrays. All public state arrays have
    leading axis ``n_games``; unit-axis length is ``spec.max_units``."""

    def __init__(
        self,
        spec: VecSimSpec,
        hero_ids: np.ndarray,          # i32 [N, P] — hero per player slot
        control_modes: np.ndarray,     # i32 [N, P] — pb.CONTROL_* per player
        seed: int = 0,
    ) -> None:
        self.spec = spec
        N, S, P = spec.n_games, spec.max_units, spec.n_players
        if spec.creeps_per_team < CREEPS_PER_WAVE:
            raise ValueError(
                f"max_units={S} leaves {spec.creeps_per_team} creep slots per "
                f"team; need at least one wave ({CREEPS_PER_WAVE})"
            )
        self.hero_ids = np.asarray(hero_ids, np.int32).reshape(N, P)
        self.control_modes = np.asarray(control_modes, np.int32).reshape(N, P)
        self._seed = seed
        self.rngs = [np.random.default_rng(seed + g) for g in range(N)]

        # unit arrays [N, S]
        self.unit_type = np.zeros((N, S), np.int32)
        self.team = np.zeros((N, S), np.int32)
        self.x = np.zeros((N, S), np.float32)
        self.y = np.zeros((N, S), np.float32)
        self.health = np.zeros((N, S), np.float32)
        self.health_max = np.ones((N, S), np.float32)
        self.mana = np.zeros((N, S), np.float32)
        self.mana_max = np.zeros((N, S), np.float32)
        self.damage = np.zeros((N, S), np.float32)
        self.attack_range = np.zeros((N, S), np.float32)
        self.move_speed = np.zeros((N, S), np.float32)
        self.armor = np.zeros((N, S), np.float32)
        self.level = np.ones((N, S), np.int32)
        self.alive = np.zeros((N, S), bool)
        self.attack_cd = np.zeros((N, S), np.float32)
        self.ability_cd = np.zeros((N, S), np.float32)
        # hero-only stats live in the hero slots of the [N, S] arrays
        self.xp = np.zeros((N, S), np.float32)
        self.gold = np.zeros((N, S), np.float32)
        self.last_hits = np.zeros((N, S), np.int32)
        self.denies = np.zeros((N, S), np.int32)
        self.kills = np.zeros((N, S), np.int32)
        self.deaths = np.zeros((N, S), np.int32)
        self.respawn_at = np.full((N, S), -1.0, np.float32)

        # game arrays [N]
        self.dota_time = np.zeros((N,), np.float32)
        self.tick = np.zeros((N,), np.int64)
        self.done = np.zeros((N,), bool)
        self.winning_team = np.zeros((N,), np.int32)
        self._next_wave_at = np.zeros((N,), np.float32)
        # scratch: marks creeps denied this death phase (reduced XP)
        self._denied_flag = np.zeros((N, S), bool)

        self.reset(np.arange(N))

    # -- lifecycle ---------------------------------------------------------

    def reset(self, games: np.ndarray, seeds: Optional[np.ndarray] = None) -> None:
        """Re-initialize the given game rows (fresh episode)."""
        games = np.atleast_1d(np.asarray(games, np.int64))
        if games.size == 0:
            return
        spec = self.spec
        P, S = spec.n_players, spec.max_units
        if seeds is not None:
            for g, s in zip(games, np.atleast_1d(seeds)):
                self.rngs[int(g)] = np.random.default_rng(int(s))

        for arr in (
            self.unit_type, self.team, self.x, self.y, self.health,
            self.mana, self.mana_max, self.damage, self.attack_range,
            self.move_speed, self.armor, self.xp, self.gold,
            self.attack_cd, self.ability_cd,
        ):
            arr[games] = 0
        self.health_max[games] = 1.0
        self.level[games] = 1
        self.alive[games] = False
        self.last_hits[games] = 0
        self.denies[games] = 0
        self.kills[games] = 0
        self.deaths[games] = 0
        self.respawn_at[games] = -1.0
        self.dota_time[games] = 0.0
        self.tick[games] = 0
        self.done[games] = False
        self.winning_team[games] = 0

        # heroes: slot == player_id; Radiant players first, then Dire
        # (matches scalar-sim pick order built by ``build_game_config``).
        stats = np.array(
            [HERO_STATS.get(int(h), GENERIC_HERO)
             for h in self.hero_ids[games].ravel()],
            np.float32,
        ).reshape(len(games), P, 6)
        pslots = np.arange(P)
        team_row = np.where(pslots < spec.team_size, TEAM_RADIANT, TEAM_DIRE)
        side = np.where(team_row == TEAM_RADIANT, -1.0, 1.0)
        gi = games[:, None]
        self.unit_type[gi, pslots] = pb.UNIT_HERO
        self.team[gi, pslots] = team_row
        self.x[gi, pslots] = side * (LANE_HALF_LENGTH - 300.0)
        self.y[gi, pslots] = 60.0 * (pslots % 5)
        self.health[gi, pslots] = stats[..., 0]
        self.health_max[gi, pslots] = stats[..., 0]
        self.mana[gi, pslots] = stats[..., 1]
        self.mana_max[gi, pslots] = stats[..., 1]
        self.damage[gi, pslots] = stats[..., 2]
        self.attack_range[gi, pslots] = stats[..., 3]
        self.move_speed[gi, pslots] = stats[..., 4]
        self.armor[gi, pslots] = stats[..., 5]
        self.alive[gi, pslots] = True

        # towers
        for k, team in enumerate((TEAM_RADIANT, TEAM_DIRE)):
            t = spec.tower_lo + k
            self.unit_type[games, t] = pb.UNIT_TOWER
            self.team[games, t] = team
            self.x[games, t] = TOWER_X[team]
            self.y[games, t] = 0.0
            self.health[games, t] = TOWER_HP
            self.health_max[games, t] = TOWER_HP
            self.damage[games, t] = TOWER_DAMAGE
            self.attack_range[games, t] = TOWER_RANGE
            self.armor[games, t] = TOWER_ARMOR
            self.alive[games, t] = True

        self._spawn_waves(games)
        self._next_wave_at[games] = CREEP_WAVE_PERIOD

    def _creep_pool(self, team: int) -> np.ndarray:
        spec = self.spec
        lo = spec.creep_lo + (0 if team == TEAM_RADIANT else spec.creeps_per_team)
        return np.arange(lo, lo + spec.creeps_per_team)

    def _spawn_waves(self, games: np.ndarray) -> None:
        """Spawn one creep wave per team in each given game, claiming free
        slots in the team's pool (bounded — overflow creeps are skipped)."""
        spec = self.spec
        for team in (TEAM_RADIANT, TEAM_DIRE):
            pool = self._creep_pool(team)
            sign = 1.0 if team == TEAM_RADIANT else -1.0
            free = ~self.alive[np.ix_(games, pool)]              # [G, C]
            # rank free slots: k-th free slot gets wave position k
            order = np.cumsum(free, axis=1) - 1                  # [G, C]
            take = free & (order < CREEPS_PER_WAVE)
            g_idx, c_idx = np.nonzero(take)
            slots = pool[c_idx]
            rows = games[g_idx]
            k = order[g_idx, c_idx].astype(np.float32)
            self.unit_type[rows, slots] = pb.UNIT_LANE_CREEP
            self.team[rows, slots] = team
            self.x[rows, slots] = TOWER_X[team] + sign * (250.0 + 40.0 * k)
            jitter = np.array(
                [self.rngs[int(r)].uniform(-40.0, 40.0) for r in rows],
                np.float32,
            )
            self.y[rows, slots] = jitter
            self.health[rows, slots] = CREEP_HP
            self.health_max[rows, slots] = CREEP_HP
            self.damage[rows, slots] = CREEP_DAMAGE
            self.attack_range[rows, slots] = CREEP_RANGE
            self.move_speed[rows, slots] = CREEP_SPEED
            self.armor[rows, slots] = CREEP_ARMOR
            self.level[rows, slots] = 1
            self.alive[rows, slots] = True
            self.attack_cd[rows, slots] = 0.0

    # -- derived views -----------------------------------------------------

    @property
    def n_games(self) -> int:
        return self.spec.n_games

    def tower_slot(self, team: int) -> int:
        return self.spec.tower_lo + (0 if team == TEAM_RADIANT else 1)

    def player_team(self, player: int) -> int:
        return TEAM_RADIANT if player < self.spec.team_size else TEAM_DIRE

    def hero_castable(self) -> np.ndarray:
        """bool [N, S]: unit has the nuke off cooldown with mana (heroes)."""
        return (
            (self.unit_type == pb.UNIT_HERO)
            & (self.ability_cd <= 0.0)
            & (self.mana >= NUKE_MANA)
        )

    def _pairwise_dist(self) -> np.ndarray:
        """f32 [N, S, S] — distance between every slot pair."""
        dx = self.x[:, :, None] - self.x[:, None, :]
        dy = self.y[:, :, None] - self.y[:, None, :]
        return np.hypot(dx, dy)

    # -- stepping ----------------------------------------------------------

    def step(self, actions: Dict[str, np.ndarray]) -> None:
        """Advance every non-done game one observation interval.

        ``actions`` arrays are [N, P] int32: ``type``, ``move_x``, ``move_y``,
        ``target_slot`` (sim slot index), ``ability``. Players whose
        ``control_modes`` is scripted are driven internally, overriding the
        given arrays; CONTROL_AGENT players no-op when ``type`` < 0.
        """
        spec = self.spec
        N, S, P = spec.n_games, spec.max_units, spec.n_players
        live_games = ~self.done                                  # [N]
        dt = spec.ticks_per_obs / TICKS_PER_SECOND

        dist = self._pairwise_dist()
        a_type = np.where(
            actions["type"] < 0, pb.ACTION_NOOP, actions["type"]
        ).astype(np.int32).copy()
        move_x = actions["move_x"].astype(np.int32).copy()
        move_y = actions["move_y"].astype(np.int32).copy()
        target = actions["target_slot"].astype(np.int64).copy()
        ability = actions["ability"].astype(np.int32).copy()

        scripted = self.control_modes != pb.CONTROL_AGENT        # [N, P]
        if scripted.any():
            sa = scripted_actions_vec(self, dist)
            for name, dst in (
                ("type", a_type), ("move_x", move_x), ("move_y", move_y),
                ("target_slot", target), ("ability", ability),
            ):
                np.copyto(dst, sa[name], where=scripted)

        pslots = np.arange(P)
        hero_alive = self.alive[:, :P] & live_games[:, None]     # [N, P]
        target = np.clip(target, 0, S - 1)

        # 1. movement
        half = (spec.move_bins - 1) / 2.0
        moving = hero_alive & (a_type == pb.ACTION_MOVE)
        mdx = (move_x - half) / max(half, 1.0)
        mdy = (move_y - half) / max(half, 1.0)
        norm = np.hypot(mdx, mdy)
        ok = moving & (norm > 1e-6)
        scale = np.where(ok, self.move_speed[:, :P] * dt / np.maximum(norm, 1e-9), 0.0)
        self.x[:, :P] = np.where(
            ok,
            np.clip(self.x[:, :P] + mdx * scale, -LANE_HALF_LENGTH, LANE_HALF_LENGTH),
            self.x[:, :P],
        )
        self.y[:, :P] = np.where(
            ok, np.clip(self.y[:, :P] + mdy * scale, -400.0, 400.0), self.y[:, :P]
        )

        # 2. hero attacks / casts (phase A: heroes resolve before AI units,
        # as in the scalar sim's step ordering)
        tgt_dist = dist[np.arange(N)[:, None], pslots[None, :], target]  # [N, P]
        t_alive = self.alive[np.arange(N)[:, None], target]
        t_team = self.team[np.arange(N)[:, None], target]
        t_type = self.unit_type[np.arange(N)[:, None], target]
        t_hp = self.health[np.arange(N)[:, None], target]
        t_hpmax = self.health_max[np.arange(N)[:, None], target]
        my_team = self.team[:, :P]

        is_deny = (t_team == my_team) & (t_type == pb.UNIT_LANE_CREEP) & (
            t_hp < 0.5 * t_hpmax
        )
        attack_ok = (
            hero_alive
            & (a_type == pb.ACTION_ATTACK_UNIT)
            & t_alive
            & ((t_team != my_team) | is_deny)
            & (tgt_dist <= self.attack_range[:, :P] + 50.0)
            & (self.attack_cd[:, :P] <= 0.0)
        )
        cast_ok = (
            hero_alive
            & (a_type == pb.ACTION_CAST)
            & (ability == NUKE_SLOT)
            & t_alive
            & (t_team != my_team)
            & (tgt_dist <= NUKE_RANGE)
            & (self.ability_cd[:, :P] <= 0.0)
            & (self.mana[:, :P] >= NUKE_MANA)
        )
        self.attack_cd[:, :P] = np.where(
            attack_ok, 1.0 / ATTACKS_PER_SECOND, self.attack_cd[:, :P]
        )
        self.mana[:, :P] = np.where(cast_ok, self.mana[:, :P] - NUKE_MANA, self.mana[:, :P])
        self.ability_cd[:, :P] = np.where(cast_ok, NUKE_COOLDOWN, self.ability_cd[:, :P])

        raw = np.where(attack_ok, self.damage[:, :P], 0.0) + np.where(
            cast_ok,
            NUKE_BASE_DAMAGE + NUKE_DAMAGE_PER_LEVEL * self.level[:, :P],
            0.0,
        )
        dmg = np.zeros((N, S), np.float32)
        hit = attack_ok | cast_ok
        t_armor_mult = _armor_mult(self.armor[np.arange(N)[:, None], target])
        np.add.at(dmg, (np.nonzero(hit)[0], target[hit]), (raw * t_armor_mult)[hit])
        self._resolve_deaths(dmg, hit, target, is_deny & attack_ok, dist)

        # 3. creeps and towers act (phase B)
        self._step_ai(dist, dt, live_games)

        # 4. clocks, regen, respawn, waves, win/timeout
        self._step_clocks(dt, live_games)

    # -- internals ---------------------------------------------------------

    def _resolve_deaths(
        self,
        dmg: np.ndarray,               # accumulated damage [N, S]
        hero_hit: Optional[np.ndarray],    # [N, P] attacks that landed
        hero_target: Optional[np.ndarray], # [N, P] their sim-slot targets
        hero_deny: Optional[np.ndarray],   # [N, P] deny-attacks that landed
        dist: np.ndarray,              # [N, S, S]
    ) -> None:
        """Apply accumulated damage, then process deaths: credit, gold/XP,
        respawn timers, tower game-over."""
        spec = self.spec
        N, S, P = spec.n_games, spec.max_units, spec.n_players
        pre_alive = self.alive.copy()
        self.health = np.where(pre_alive, self.health - dmg, self.health).astype(np.float32)
        died = pre_alive & (self.health <= 0.0)
        if not died.any():
            return
        self.health = np.where(died, 0.0, self.health)
        self.alive &= ~died

        died_creep = died & (self.unit_type == pb.UNIT_LANE_CREEP)
        died_hero = died & (self.unit_type == pb.UNIT_HERO)
        died_tower = died & (self.unit_type == pb.UNIT_TOWER)

        # Kill credit (hero attackers only): lowest player index whose landed
        # attack targeted the dead unit this phase.
        if hero_hit is not None and (died_creep.any() or died_hero.any()):
            # landed[n, p] targeting slot s that died
            t_died = died[np.arange(N)[:, None], hero_target] & hero_hit  # [N, P]
            # For each dead unit slot, find min p among attackers of that slot.
            cn, cp = np.nonzero(t_died)
            cs = hero_target[cn, cp]
            # iterate only over landed kill credits (rare)
            seen = set()
            for n_, p_, s_ in zip(cn, cp, cs):
                if (n_, s_) in seen:
                    continue  # lowest p wins (np.nonzero is row-major sorted)
                seen.add((n_, s_))
                if self.unit_type[n_, s_] == pb.UNIT_LANE_CREEP:
                    if hero_deny is not None and hero_deny[n_, p_] and (
                        hero_target[n_, p_] == s_
                    ):
                        self.denies[n_, p_] += 1
                        # deny marker: enemies get reduced XP (handled below
                        # via denied mask)
                        self._denied_flag[n_, s_] = True
                    else:
                        self.last_hits[n_, p_] += 1
                        self.gold[n_, p_] += GOLD_PER_LASTHIT
                elif self.unit_type[n_, s_] == pb.UNIT_HERO:
                    self.kills[n_, p_] += 1
                    self.gold[n_, p_] += GOLD_PER_HERO_KILL
                    self._grant_xp_slots(
                        np.array([n_]), np.array([p_]),
                        np.array([XP_PER_HERO_KILL], np.float32),
                    )

        # Creep XP: enemy heroes within XP_RADIUS of the dying creep split it.
        if died_creep.any():
            dn, dslot = np.nonzero(died_creep)
            denied = self._denied_flag[dn, dslot]
            xp_each = np.where(denied, CREEP_XP * DENY_XP_FACTOR, CREEP_XP)
            hero_d = dist[dn, :, dslot][:, :P]                   # [D, P]
            hero_ok = (
                self.alive[dn, :P]
                & (self.team[dn, :P] != self.team[dn, dslot][:, None])
                & (hero_d <= XP_RADIUS)
            )
            n_share = hero_ok.sum(axis=1)
            share = xp_each / np.maximum(n_share, 1)
            rn, rp = np.nonzero(hero_ok)
            self._grant_xp_slots(dn[rn], rp, share[rn].astype(np.float32))
            self._denied_flag[dn, dslot] = False

        # Hero deaths: respawn timer.
        if died_hero.any():
            hn, hslot = np.nonzero(died_hero)
            self.deaths[hn, hslot] += 1
            self.respawn_at[hn, hslot] = self.dota_time[hn] + (
                RESPAWN_BASE_SECONDS
                + RESPAWN_PER_LEVEL_SECONDS * self.level[hn, hslot]
            )

        # Tower death ends the game.
        if died_tower.any():
            tn, tslot = np.nonzero(died_tower)
            self.done[tn] = True
            self.winning_team[tn] = np.where(
                self.team[tn, tslot] == TEAM_DIRE, TEAM_RADIANT, TEAM_DIRE
            )

    def _grant_xp_slots(
        self, games: np.ndarray, players: np.ndarray, xp: np.ndarray
    ) -> None:
        """Accumulate XP on hero slots and apply level-ups (vector form of the
        scalar sim's ``_grant_xp`` while-loop: level = 1 + floor(xp/220),
        capped; each level grants +40 maxHP/+heal, +20 maxMana, +4 damage)."""
        np.add.at(self.xp, (games, players), xp)
        # Level-ups are computed on UNIQUE (game, player) pairs from total XP
        # — with duplicates in one call (two creeps dying at once), per-entry
        # deltas would each see the same full XP jump and double-apply.
        S = self.spec.max_units
        uniq = np.unique(games.astype(np.int64) * S + players)
        gu, pu = uniq // S, uniq % S
        cur = self.level[gu, pu]
        new = np.minimum(
            MAX_LEVEL, (self.xp[gu, pu] // XP_PER_LEVEL).astype(np.int32) + 1
        )
        gained = np.maximum(new - cur, 0)
        if not gained.any():
            return
        g = gained.astype(np.float32)
        self.level[gu, pu] = np.maximum(cur, new)
        self.health_max[gu, pu] += 40.0 * g
        self.health[gu, pu] = np.minimum(
            self.health[gu, pu] + 40.0 * g, self.health_max[gu, pu]
        )
        self.mana_max[gu, pu] += 20.0 * g
        self.damage[gu, pu] += 4.0 * g

    def _step_ai(self, dist: np.ndarray, dt: float, live: np.ndarray) -> None:
        """Creeps attack/march, towers attack (phase-start world)."""
        spec = self.spec
        N, S, P = spec.n_games, spec.max_units, spec.n_players
        alive = self.alive & live[:, None]
        enemy = (
            alive[:, :, None]
            & alive[:, None, :]
            & (self.team[:, :, None] != self.team[:, None, :])
        )                                                       # [N, S, S]
        d_masked = np.where(enemy, dist, _BIG)

        is_creep = (self.unit_type == pb.UNIT_LANE_CREEP) & alive
        is_tower = (self.unit_type == pb.UNIT_TOWER) & alive

        # creeps: nearest enemy within range+20 → attack; else march in x
        nearest = d_masked.argmin(axis=2)                        # [N, S]
        nearest_d = np.take_along_axis(d_masked, nearest[:, :, None], 2)[:, :, 0]
        can_attack = is_creep & (nearest_d <= self.attack_range + 20.0)
        attacking = can_attack & (self.attack_cd <= 0.0)
        # towers: among IN-RANGE enemies, prefer creeps over heroes, then
        # nearest (the scalar sim filters to range first — an out-of-range
        # creep must not shadow an in-range hero)
        in_tower_range = d_masked <= self.attack_range[:, :, None]
        t_pref = np.where(
            in_tower_range,
            d_masked
            + np.where(self.unit_type[:, None, :] == pb.UNIT_HERO, 1e6, 0.0),
            _BIG * 2.0,
        )
        t_near = t_pref.argmin(axis=2)
        t_has_target = t_pref.min(axis=2) < _BIG
        t_attacking = is_tower & t_has_target & (self.attack_cd <= 0.0)

        atk = attacking | t_attacking
        tgt = np.where(t_attacking, t_near, nearest)
        self.attack_cd = np.where(atk, 1.0 / ATTACKS_PER_SECOND, self.attack_cd)
        dmg = np.zeros((N, S), np.float32)
        an, aslot = np.nonzero(atk)
        at = tgt[an, aslot]
        np.add.at(
            dmg, (an, at),
            self.damage[an, aslot] * _armor_mult(self.armor[an, at]),
        )
        self._resolve_deaths(dmg, None, None, None, dist)

        # march: creeps not in attack range move toward enemy tower (x only)
        marching = is_creep & ~can_attack & self.alive
        goal_x = np.where(self.team == TEAM_RADIANT, TOWER_X[TEAM_DIRE], TOWER_X[TEAM_RADIANT])
        step = self.move_speed * dt
        delta = goal_x - self.x
        self.x = np.where(
            marching,
            self.x + np.sign(delta) * np.minimum(step, np.abs(delta)),
            self.x,
        ).astype(np.float32)

    def _step_clocks(self, dt: float, live: np.ndarray) -> None:
        spec = self.spec
        P = spec.n_players
        self.dota_time = np.where(live, self.dota_time + dt, self.dota_time)
        self.tick = np.where(live, self.tick + spec.ticks_per_obs, self.tick)
        self.attack_cd = np.maximum(0.0, self.attack_cd - dt * live[:, None]).astype(np.float32)
        self.ability_cd = np.maximum(0.0, self.ability_cd - dt * live[:, None]).astype(np.float32)

        hero_alive = (self.unit_type == pb.UNIT_HERO) & self.alive & live[:, None]
        self.gold = np.where(hero_alive, self.gold + GOLD_PASSIVE_PER_SEC * dt, self.gold)
        self.health = np.where(
            hero_alive, np.minimum(self.health + 1.5 * dt, self.health_max), self.health
        ).astype(np.float32)
        self.mana = np.where(
            hero_alive, np.minimum(self.mana + 1.0 * dt, self.mana_max), self.mana
        ).astype(np.float32)

        # respawns
        hero_dead = (
            (self.unit_type == pb.UNIT_HERO) & ~self.alive & live[:, None]
            & (self.respawn_at >= 0.0)
            & (self.respawn_at <= self.dota_time[:, None])
        )
        if hero_dead.any():
            rn, rp = np.nonzero(hero_dead)
            self.alive[rn, rp] = True
            self.health[rn, rp] = self.health_max[rn, rp]
            self.mana[rn, rp] = self.mana_max[rn, rp]
            team_r = self.team[rn, rp]
            side = np.where(team_r == TEAM_RADIANT, -1.0, 1.0)
            self.x[rn, rp] = side * (LANE_HALF_LENGTH - 300.0)
            self.y[rn, rp] = 60.0 * (rp % 5)
            self.respawn_at[rn, rp] = -1.0

        # waves
        wave_due = live & ~self.done & (self.dota_time >= self._next_wave_at)
        if wave_due.any():
            games = np.nonzero(wave_due)[0]
            self._spawn_waves(games)
            self._next_wave_at[games] = self.dota_time[games] + CREEP_WAVE_PERIOD

        # timeout adjudication: (tower hp, team kills, team gold) lexicographic
        timed_out = live & ~self.done & (self.dota_time >= spec.max_dota_time)
        if timed_out.any():
            g = np.nonzero(timed_out)[0]
            self.done[g] = True
            r_slot, d_slot = self.tower_slot(TEAM_RADIANT), self.tower_slot(TEAM_DIRE)
            team_row = self.team[g, :P]
            is_rad = team_row == TEAM_RADIANT

            def team_sum(arr: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
                h = arr[g, :P].astype(np.float64)
                return (h * is_rad).sum(1), (h * ~is_rad).sum(1)

            rk, dk = team_sum(self.kills)
            rg, dg = team_sum(self.gold)
            rt = self.health[g, r_slot].astype(np.float64)
            dt_ = self.health[g, d_slot].astype(np.float64)
            r_wins = (rt > dt_) | ((rt == dt_) & ((rk > dk) | ((rk == dk) & (rg > dg))))
            d_wins = (dt_ > rt) | ((rt == dt_) & ((dk > rk) | ((rk == dk) & (dg > rg))))
            self.winning_team[g] = np.where(
                r_wins, TEAM_RADIANT, np.where(d_wins, TEAM_DIRE, 0)
            )

    # -- proto export (parity/debug boundary, not the hot path) ------------

    def world_state(self, game: int, team_id: int) -> pb.WorldState:
        """Export one game's view as a WorldState proto (same shape the
        scalar sim emits — used by parity tests and debugging)."""
        g = int(game)
        spec = self.spec
        ws = pb.WorldState(
            team_id=team_id,
            game_time=float(self.dota_time[g]),
            dota_time=float(self.dota_time[g]),
            tick=int(self.tick[g]),
            game_state=(
                pb.GAME_STATE_POST_GAME if self.done[g] else pb.GAME_STATE_IN_PROGRESS
            ),
            winning_team=int(self.winning_team[g]),
        )
        for s in range(spec.max_units):
            ut = int(self.unit_type[g, s])
            if ut == 0:
                continue
            if not self.alive[g, s] and ut != pb.UNIT_HERO:
                continue
            u = ws.units.add(
                handle=s + 1, unit_type=ut, team_id=int(self.team[g, s]),
                player_id=s if s < spec.n_players else -1,
                hero_id=int(self.hero_ids[g, s]) if s < spec.n_players else 0,
                health=float(self.health[g, s]),
                health_max=float(self.health_max[g, s]),
                mana=float(self.mana[g, s]), mana_max=float(self.mana_max[g, s]),
                is_alive=bool(self.alive[g, s]), level=int(self.level[g, s]),
                attack_damage=float(self.damage[g, s]),
                attack_range=float(self.attack_range[g, s]),
                armor=float(self.armor[g, s]),
                movement_speed=float(self.move_speed[g, s]),
                last_hits=int(self.last_hits[g, s]), denies=int(self.denies[g, s]),
            )
            u.location.x = float(self.x[g, s])
            u.location.y = float(self.y[g, s])
            if ut == pb.UNIT_HERO:
                u.abilities.add(
                    slot=NUKE_SLOT, ability_id=1,
                    cooldown_remaining=float(self.ability_cd[g, s]),
                    level=int(self.level[g, s]),
                    castable=bool(
                        self.ability_cd[g, s] <= 0.0 and self.mana[g, s] >= NUKE_MANA
                    ),
                    cast_range=NUKE_RANGE,
                )
        for p in range(spec.n_players):
            ws.players.add(
                player_id=p, team_id=int(self.team[g, p]),
                hero_id=int(self.hero_ids[g, p]), kills=int(self.kills[g, p]),
                deaths=int(self.deaths[g, p]), gold=float(self.gold[g, p]),
                xp=float(self.xp[g, p]),
            )
        return ws


# ---------------------------------------------------------------------------
# Vectorized scripted opponents (same decision rules as lane_sim.scripted_action)
# ---------------------------------------------------------------------------


def scripted_actions_vec(sim: VecLaneSim, dist: np.ndarray) -> Dict[str, np.ndarray]:
    """Compute scripted-bot actions for every player slot of every game.

    Vector form of ``lane_sim.scripted_action`` — EASY marches/attacks the
    nearest enemy; HARD adds low-HP retreat, nuke on the lowest-HP enemy hero
    in range, last-hit timing, and harass. Rows for CONTROL_AGENT players are
    computed too but ignored by the caller (cheaper than masking here).
    """
    spec = sim.spec
    N, S, P = spec.n_games, spec.max_units, spec.n_players
    pslots = np.arange(P)
    half = (spec.move_bins - 1) / 2.0

    my_team = sim.team[:, :P]                                    # [N, P]
    hard = sim.control_modes == pb.CONTROL_SCRIPTED_HARD
    hero_alive = sim.alive[:, :P]
    hp_frac = sim.health[:, :P] / np.maximum(sim.health_max[:, :P], 1.0)

    enemy = (
        sim.alive[:, None, :]
        & (sim.team[:, None, :] != my_team[:, :, None])
    )                                                            # [N, P, S]
    pd = dist[:, :P, :]                                          # [N, P, S]
    d_enemy = np.where(enemy, pd, _BIG)

    is_hero_s = sim.unit_type == pb.UNIT_HERO                    # [N, S]
    is_creep_s = sim.unit_type == pb.UNIT_LANE_CREEP
    enemy_hero = enemy & is_hero_s[:, None, :]
    d_ehero = np.where(enemy_hero, pd, _BIG)

    out_type = np.full((N, P), pb.ACTION_NOOP, np.int32)
    out_mx = np.zeros((N, P), np.int32)
    out_my = np.zeros((N, P), np.int32)
    out_tgt = np.zeros((N, P), np.int64)
    out_abl = np.zeros((N, P), np.int32)

    def set_move(mask: np.ndarray, gx: np.ndarray, gy: np.ndarray) -> None:
        """Discretized move-toward for masked (game, player) rows."""
        dx = gx - sim.x[:, :P]
        dy = gy - sim.y[:, :P]
        norm = np.hypot(dx, dy)
        ok = mask & (norm >= 1e-6)
        mx = np.clip(np.round(half + half * dx / np.maximum(norm, 1e-9)), 0, spec.move_bins - 1)
        my = np.clip(np.round(half + half * dy / np.maximum(norm, 1e-9)), 0, spec.move_bins - 1)
        out_type[ok] = pb.ACTION_MOVE
        out_mx[ok] = mx[ok].astype(np.int32)
        out_my[ok] = my[ok].astype(np.int32)

    todo = hero_alive.copy()

    # HARD retreat: hp < 30% and an enemy hero within 900 → run to own tower.
    near_ehero = (d_ehero.min(axis=2) <= 900.0)
    retreat = todo & hard & (hp_frac < 0.3) & near_ehero
    own_tower_x = np.where(my_team == TEAM_RADIANT, TOWER_X[TEAM_RADIANT], TOWER_X[TEAM_DIRE])
    set_move(retreat, own_tower_x, np.zeros_like(own_tower_x))
    todo &= ~retreat

    # HARD nuke: castable and an enemy hero within NUKE_RANGE → lowest HP.
    castable = (sim.mana[:, :P] >= NUKE_MANA) & (sim.ability_cd[:, :P] <= 0.0)
    nukable = enemy_hero & (pd <= NUKE_RANGE)
    hp_key = np.where(nukable, sim.health[:, None, :], _BIG)
    nuke_tgt = hp_key.argmin(axis=2)
    can_nuke = todo & hard & castable & nukable.any(axis=2)
    out_type[can_nuke] = pb.ACTION_CAST
    out_tgt[can_nuke] = nuke_tgt[can_nuke]
    out_abl[can_nuke] = NUKE_SLOT
    todo &= ~can_nuke

    in_range = enemy & (pd <= sim.attack_range[:, :P, None] + 50.0)  # [N,P,S]
    any_in_range = in_range.any(axis=2)

    # HARD last-hit: killable creep in range (health <= my damage after armor).
    eff_dmg = sim.damage[:, :P, None] * _armor_mult(sim.armor[:, None, :])
    killable = in_range & is_creep_s[:, None, :] & (sim.health[:, None, :] <= eff_dmg)
    kill_key = np.where(killable, sim.health[:, None, :], _BIG)
    kill_tgt = kill_key.argmin(axis=2)
    do_lh = todo & hard & killable.any(axis=2)
    out_type[do_lh] = pb.ACTION_ATTACK_UNIT
    out_tgt[do_lh] = kill_tgt[do_lh]
    todo &= ~do_lh

    # HARD harass: enemy hero in range while healthy → lowest-HP one.
    heroes_in_range = in_range & is_hero_s[:, None, :]
    harass_key = np.where(heroes_in_range, sim.health[:, None, :], _BIG)
    harass_tgt = harass_key.argmin(axis=2)
    do_harass = todo & hard & heroes_in_range.any(axis=2) & (hp_frac >= 0.5)
    out_type[do_harass] = pb.ACTION_ATTACK_UNIT
    out_tgt[do_harass] = harass_tgt[do_harass]
    todo &= ~do_harass

    # HARD pressure: lowest-HP creep in range.
    creeps_in_range = in_range & is_creep_s[:, None, :]
    press_key = np.where(creeps_in_range, sim.health[:, None, :], _BIG)
    press_tgt = press_key.argmin(axis=2)
    do_press = todo & hard & creeps_in_range.any(axis=2)
    out_type[do_press] = pb.ACTION_ATTACK_UNIT
    out_tgt[do_press] = press_tgt[do_press]
    todo &= ~do_press

    # EASY (and HARD fallback): attack nearest enemy in range.
    near_key = np.where(in_range, pd, _BIG)
    near_tgt = near_key.argmin(axis=2)
    do_atk = todo & any_in_range
    out_type[do_atk] = pb.ACTION_ATTACK_UNIT
    out_tgt[do_atk] = near_tgt[do_atk]
    todo &= ~do_atk

    # nothing in range: march toward nearest enemy (or mid if none).
    nearest_any = d_enemy.argmin(axis=2)
    has_enemy = d_enemy.min(axis=2) < _BIG
    gi = np.arange(N)[:, None]
    gx = np.where(has_enemy, sim.x[gi, nearest_any], 0.0)
    gy = np.where(has_enemy, sim.y[gi, nearest_any], 0.0)
    set_move(todo, gx, gy)

    return {
        "type": out_type, "move_x": out_mx, "move_y": out_my,
        "target_slot": out_tgt, "ability": out_abl,
    }
