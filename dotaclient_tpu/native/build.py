"""Build/load the native library (no pybind11 in this image — plain C ABI
via ctypes; g++ is in the base toolchain).

Usage:
    python -m dotaclient_tpu.native.build        # compile libdota_native.so
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "rollout_codec.cc")
_LIB = os.path.join(_DIR, "libdota_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def build(force: bool = False, out: Optional[str] = None) -> str:
    """Compile the shared library if missing/stale; returns its path."""
    out = out or _LIB
    with _lock:
        if (
            not force
            and os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(_SRC)
        ):
            return out
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
            "-o", out, _SRC,
        ]
        subprocess.run(cmd, check=True, capture_output=True)
        return out


class TensorEntry(ctypes.Structure):
    _fields_ = [
        ("name_off", ctypes.c_uint32), ("name_len", ctypes.c_uint32),
        ("dtype_off", ctypes.c_uint32), ("dtype_len", ctypes.c_uint32),
        ("data_off", ctypes.c_uint32), ("data_len", ctypes.c_uint32),
        ("shape", ctypes.c_int32 * 8), ("ndim", ctypes.c_int32),
    ]


class RolloutHeader(ctypes.Structure):
    _fields_ = [
        ("model_version", ctypes.c_int32), ("env_id", ctypes.c_int32),
        ("rollout_id", ctypes.c_uint64), ("length", ctypes.c_int32),
        ("total_reward", ctypes.c_float),
    ]


class EncodeTensor(ctypes.Structure):
    _fields_ = [
        ("name_off", ctypes.c_uint32), ("name_len", ctypes.c_uint32),
        ("dtype_off", ctypes.c_uint32), ("dtype_len", ctypes.c_uint32),
        ("data_ptr", ctypes.c_uint64), ("data_len", ctypes.c_uint64),
        ("shape", ctypes.c_int32 * 8), ("ndim", ctypes.c_int32),
    ]


def load_library(auto_build: bool = True) -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        if auto_build:
            build()
        lib = ctypes.CDLL(_LIB)
        if auto_build and not hasattr(lib, "dota_encode_rollout"):
            # Stale artifact with equal mtimes (image COPY, tarball): the
            # mtime check skipped the rebuild but the symbol set is old —
            # and dlopen caches by file, so rebuilding onto the SAME path
            # cannot refresh this process's handle. Compile to a fresh
            # path, load that, and promote it for future processes; if the
            # rebuild fails, keep the stale handle (decode still works —
            # the encode wrapper probes for its symbol before use).
            fresh = f"{_LIB}.fresh.{os.getpid()}"
            try:
                build(force=True, out=fresh)
                lib = ctypes.CDLL(fresh)
                os.replace(fresh, _LIB)
            except (OSError, subprocess.CalledProcessError):
                try:
                    os.unlink(fresh)
                except OSError:
                    pass
        lib.dota_decode_rollout.restype = ctypes.c_int32
        lib.dota_decode_rollout.argtypes = [
            # void* (not char*): callers pass bytes directly OR a raw
            # pointer into a memoryview (the shm lane's zero-copy frames)
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(RolloutHeader),
            ctypes.POINTER(TensorEntry), ctypes.c_int32,
        ]
        if hasattr(lib, "dota_encode_rollout"):  # absent on a stale handle
            lib.dota_encode_rollout.restype = ctypes.c_int64
            lib.dota_encode_rollout.argtypes = [
                ctypes.POINTER(RolloutHeader), ctypes.c_char_p,
                ctypes.POINTER(EncodeTensor), ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_uint64,
            ]
        _lib = lib
    except (
        OSError,
        subprocess.CalledProcessError,
        FileNotFoundError,
        AttributeError,  # unbuildable stale library missing a symbol
    ):
        _load_failed = True
        _lib = None
    return _lib


if __name__ == "__main__":
    print(build(force=True))
