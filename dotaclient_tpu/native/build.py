"""Build/load the native library (no pybind11 in this image — plain C ABI
via ctypes; g++ is in the base toolchain).

Usage:
    python -m dotaclient_tpu.native.build        # compile libdota_native.so
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "rollout_codec.cc")
_LIB = os.path.join(_DIR, "libdota_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def build(force: bool = False) -> str:
    """Compile the shared library if missing/stale; returns its path."""
    with _lock:
        if (
            not force
            and os.path.exists(_LIB)
            and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)
        ):
            return _LIB
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
            "-o", _LIB, _SRC,
        ]
        subprocess.run(cmd, check=True, capture_output=True)
        return _LIB


class TensorEntry(ctypes.Structure):
    _fields_ = [
        ("name_off", ctypes.c_uint32), ("name_len", ctypes.c_uint32),
        ("dtype_off", ctypes.c_uint32), ("dtype_len", ctypes.c_uint32),
        ("data_off", ctypes.c_uint32), ("data_len", ctypes.c_uint32),
        ("shape", ctypes.c_int32 * 8), ("ndim", ctypes.c_int32),
    ]


class RolloutHeader(ctypes.Structure):
    _fields_ = [
        ("model_version", ctypes.c_int32), ("env_id", ctypes.c_int32),
        ("rollout_id", ctypes.c_uint64), ("length", ctypes.c_int32),
        ("total_reward", ctypes.c_float),
    ]


def load_library(auto_build: bool = True) -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        if auto_build:
            build()
        lib = ctypes.CDLL(_LIB)
        lib.dota_decode_rollout.restype = ctypes.c_int32
        lib.dota_decode_rollout.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(RolloutHeader),
            ctypes.POINTER(TensorEntry), ctypes.c_int32,
        ]
        _lib = lib
    except (OSError, subprocess.CalledProcessError, FileNotFoundError):
        _load_failed = True
        _lib = None
    return _lib


if __name__ == "__main__":
    print(build(force=True))
