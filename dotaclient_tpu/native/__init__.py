"""Native (C++) runtime components, loaded via ctypes.

SURVEY.md §2.2: where the reference leaned on native dependencies, the
rebuild owns TPU-host-native equivalents. Currently:

* ``rollout_codec`` — single-pass wire parser for `Rollout` protos feeding
  zero-copy numpy views (the learner-ingest fast path).

Build on demand (``python -m dotaclient_tpu.native.build``) or implicitly on
first use; pure-Python fallbacks keep every environment working without a
toolchain.
"""

from dotaclient_tpu.native.build import build, load_library

__all__ = ["build", "load_library"]
