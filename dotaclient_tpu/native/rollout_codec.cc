// Fast-path rollout wire decoder (SURVEY.md §2.2 row 3).
//
// The reference's native surface for experience transport was protobuf's C++
// runtime under the Python bindings; here the hot direction — broker bytes →
// tensor views on the learner host — is a first-party, allocation-free wire
// parser for the `Rollout` message of dotaclient_tpu/protos/dota.proto:
//
//   message TensorProto { repeated int32 shape = 1; string dtype = 2;
//                         bytes data = 3; }
//   message Rollout     { int32 model_version = 1; int32 env_id = 2;
//                         uint64 rollout_id = 3; int32 length = 4;
//                         float total_reward = 5;
//                         map<string, TensorProto> arrays = 6; }
//
// The parser walks the buffer once and reports each named tensor as an
// (offset, length) pair into the ORIGINAL buffer, so Python materializes
// numpy arrays with zero-copy np.frombuffer views — no python-protobuf
// object tree, no per-field PyObject churn. Exposed as plain C for ctypes
// (pybind11 is not available in this image).
//
// Build: python -m dotaclient_tpu.native.build   (g++ -O2 -shared -fPIC)

#include <cstdint>
#include <cstring>

namespace {

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  void skip(uint64_t n) {
    if (static_cast<uint64_t>(end - p) < n) { ok = false; return; }
    p += n;
  }

  // Skip one field of the given wire type (after its tag was read).
  void skip_field(uint32_t wire_type) {
    switch (wire_type) {
      case 0: varint(); break;                    // varint
      case 1: skip(8); break;                     // fixed64
      case 2: skip(varint()); break;              // length-delimited
      case 5: skip(4); break;                     // fixed32
      default: ok = false;
    }
  }
};

}  // namespace

extern "C" {

// One decoded tensor entry: name and data are (offset, len) into the input
// buffer; shape is materialized (tensors are at most rank 4 here; 8 is slack).
struct TensorEntry {
  uint32_t name_off, name_len;
  uint32_t dtype_off, dtype_len;
  uint32_t data_off, data_len;
  int32_t shape[8];
  int32_t ndim;
};

struct RolloutHeader {
  int32_t model_version;
  int32_t env_id;
  uint64_t rollout_id;
  int32_t length;
  float total_reward;
};

// Parse one TensorProto body [p, p+len) relative to base buffer `base`.
static bool parse_tensor(const uint8_t* base, const uint8_t* p,
                         const uint8_t* end, TensorEntry* t) {
  Cursor c{p, end};
  t->ndim = 0;
  t->dtype_len = t->data_len = 0;
  while (c.ok && c.p < c.end) {
    uint64_t tag = c.varint();
    uint32_t field = tag >> 3, wt = tag & 7;
    if (field == 1 && wt == 2) {          // packed shape
      uint64_t n = c.varint();
      const uint8_t* stop = c.p + n;
      if (stop > c.end) return false;
      while (c.ok && c.p < stop && t->ndim < 8)
        t->shape[t->ndim++] = static_cast<int32_t>(c.varint());
      if (c.p != stop) return false;      // >8 dims unsupported
    } else if (field == 1 && wt == 0) {   // unpacked shape element
      if (t->ndim < 8) t->shape[t->ndim++] = static_cast<int32_t>(c.varint());
      else return false;
    } else if (field == 2 && wt == 2) {   // dtype
      uint64_t n = c.varint();
      t->dtype_off = static_cast<uint32_t>(c.p - base);
      t->dtype_len = static_cast<uint32_t>(n);
      c.skip(n);
    } else if (field == 3 && wt == 2) {   // data
      uint64_t n = c.varint();
      t->data_off = static_cast<uint32_t>(c.p - base);
      t->data_len = static_cast<uint32_t>(n);
      c.skip(n);
    } else {
      c.skip_field(wt);
    }
  }
  return c.ok;
}

// Decode a serialized Rollout. Returns the number of tensors found, or -1 on
// malformed input, or -2 if `max_entries` is too small. Header fields are
// written to *hdr.
int32_t dota_decode_rollout(const uint8_t* buf, uint64_t buf_len,
                            RolloutHeader* hdr, TensorEntry* entries,
                            int32_t max_entries) {
  Cursor c{buf, buf + buf_len};
  hdr->model_version = hdr->env_id = hdr->length = 0;
  hdr->rollout_id = 0;
  hdr->total_reward = 0.0f;
  int32_t count = 0;
  while (c.ok && c.p < c.end) {
    uint64_t tag = c.varint();
    if (!c.ok) return -1;
    uint32_t field = tag >> 3, wt = tag & 7;
    if (field == 1 && wt == 0) {
      hdr->model_version = static_cast<int32_t>(c.varint());
    } else if (field == 2 && wt == 0) {
      hdr->env_id = static_cast<int32_t>(c.varint());
    } else if (field == 3 && wt == 0) {
      hdr->rollout_id = c.varint();
    } else if (field == 4 && wt == 0) {
      hdr->length = static_cast<int32_t>(c.varint());
    } else if (field == 5 && wt == 5) {
      if (c.end - c.p < 4) return -1;
      std::memcpy(&hdr->total_reward, c.p, 4);
      c.skip(4);
    } else if (field == 6 && wt == 2) {   // map entry: key=1, value=2
      uint64_t n = c.varint();
      const uint8_t* stop = c.p + n;
      if (!c.ok || stop > c.end) return -1;
      if (count >= max_entries) return -2;
      TensorEntry* t = &entries[count];
      t->name_off = t->name_len = 0;
      Cursor m{c.p, stop};
      bool have_value = false;
      while (m.ok && m.p < m.end) {
        uint64_t mtag = m.varint();
        uint32_t mf = mtag >> 3, mwt = mtag & 7;
        if (mf == 1 && mwt == 2) {        // key
          uint64_t kn = m.varint();
          t->name_off = static_cast<uint32_t>(m.p - buf);
          t->name_len = static_cast<uint32_t>(kn);
          m.skip(kn);
        } else if (mf == 2 && mwt == 2) { // value: TensorProto
          uint64_t vn = m.varint();
          if (m.p + vn > m.end) return -1;
          if (!parse_tensor(buf, m.p, m.p + vn, t)) return -1;
          m.skip(vn);
          have_value = true;
        } else {
          m.skip_field(mwt);
        }
      }
      if (!m.ok || !have_value) return -1;
      ++count;
      c.p = stop;
    } else {
      c.skip_field(wt);
    }
  }
  return c.ok ? count : -1;
}

}  // extern "C"
