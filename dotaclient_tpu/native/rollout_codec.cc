// Fast-path rollout wire codec (SURVEY.md §2.2 row 3).
//
// The reference's native surface for experience transport was protobuf's C++
// runtime under the Python bindings; here BOTH hot directions are first-party
// single-pass wire code: decode (broker bytes → tensor views on the learner
// host, allocation-free) and encode (actor-side numpy buffers → wire bytes,
// one memcpy per tensor, no python-protobuf object tree). The message is the
// `Rollout` of dotaclient_tpu/protos/dota.proto:
//
//   message TensorProto { repeated int32 shape = 1; string dtype = 2;
//                         bytes data = 3; }
//   message Rollout     { int32 model_version = 1; int32 env_id = 2;
//                         uint64 rollout_id = 3; int32 length = 4;
//                         float total_reward = 5;
//                         map<string, TensorProto> arrays = 6; }
//
// The parser walks the buffer once and reports each named tensor as an
// (offset, length) pair into the ORIGINAL buffer, so Python materializes
// numpy arrays with zero-copy np.frombuffer views — no python-protobuf
// object tree, no per-field PyObject churn. Exposed as plain C for ctypes
// (pybind11 is not available in this image).
//
// Build: python -m dotaclient_tpu.native.build   (g++ -O2 -shared -fPIC)

#include <cstdint>
#include <cstring>

namespace {

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  void skip(uint64_t n) {
    if (static_cast<uint64_t>(end - p) < n) { ok = false; return; }
    p += n;
  }

  // Skip one field of the given wire type (after its tag was read).
  void skip_field(uint32_t wire_type) {
    switch (wire_type) {
      case 0: varint(); break;                    // varint
      case 1: skip(8); break;                     // fixed64
      case 2: skip(varint()); break;              // length-delimited
      case 5: skip(4); break;                     // fixed32
      default: ok = false;
    }
  }
};

}  // namespace

extern "C" {

// One decoded tensor entry: name and data are (offset, len) into the input
// buffer; shape is materialized (tensors are at most rank 4 here; 8 is slack).
struct TensorEntry {
  uint32_t name_off, name_len;
  uint32_t dtype_off, dtype_len;
  uint32_t data_off, data_len;
  int32_t shape[8];
  int32_t ndim;
};

struct RolloutHeader {
  int32_t model_version;
  int32_t env_id;
  uint64_t rollout_id;
  int32_t length;
  float total_reward;
};

// Parse one TensorProto body [p, p+len) relative to base buffer `base`.
static bool parse_tensor(const uint8_t* base, const uint8_t* p,
                         const uint8_t* end, TensorEntry* t) {
  Cursor c{p, end};
  t->ndim = 0;
  t->dtype_len = t->data_len = 0;
  while (c.ok && c.p < c.end) {
    uint64_t tag = c.varint();
    uint32_t field = tag >> 3, wt = tag & 7;
    if (field == 1 && wt == 2) {          // packed shape
      uint64_t n = c.varint();
      const uint8_t* stop = c.p + n;
      if (stop > c.end) return false;
      while (c.ok && c.p < stop && t->ndim < 8)
        t->shape[t->ndim++] = static_cast<int32_t>(c.varint());
      if (c.p != stop) return false;      // >8 dims unsupported
    } else if (field == 1 && wt == 0) {   // unpacked shape element
      if (t->ndim < 8) t->shape[t->ndim++] = static_cast<int32_t>(c.varint());
      else return false;
    } else if (field == 2 && wt == 2) {   // dtype
      uint64_t n = c.varint();
      t->dtype_off = static_cast<uint32_t>(c.p - base);
      t->dtype_len = static_cast<uint32_t>(n);
      c.skip(n);
    } else if (field == 3 && wt == 2) {   // data
      uint64_t n = c.varint();
      t->data_off = static_cast<uint32_t>(c.p - base);
      t->data_len = static_cast<uint32_t>(n);
      c.skip(n);
    } else {
      c.skip_field(wt);
    }
  }
  return c.ok;
}

// Decode a serialized Rollout. Returns the number of tensors found, or -1 on
// malformed input, or -2 if `max_entries` is too small. Header fields are
// written to *hdr.
int32_t dota_decode_rollout(const uint8_t* buf, uint64_t buf_len,
                            RolloutHeader* hdr, TensorEntry* entries,
                            int32_t max_entries) {
  Cursor c{buf, buf + buf_len};
  hdr->model_version = hdr->env_id = hdr->length = 0;
  hdr->rollout_id = 0;
  hdr->total_reward = 0.0f;
  int32_t count = 0;
  while (c.ok && c.p < c.end) {
    uint64_t tag = c.varint();
    if (!c.ok) return -1;
    uint32_t field = tag >> 3, wt = tag & 7;
    if (field == 1 && wt == 0) {
      hdr->model_version = static_cast<int32_t>(c.varint());
    } else if (field == 2 && wt == 0) {
      hdr->env_id = static_cast<int32_t>(c.varint());
    } else if (field == 3 && wt == 0) {
      hdr->rollout_id = c.varint();
    } else if (field == 4 && wt == 0) {
      hdr->length = static_cast<int32_t>(c.varint());
    } else if (field == 5 && wt == 5) {
      if (c.end - c.p < 4) return -1;
      std::memcpy(&hdr->total_reward, c.p, 4);
      c.skip(4);
    } else if (field == 6 && wt == 2) {   // map entry: key=1, value=2
      uint64_t n = c.varint();
      const uint8_t* stop = c.p + n;
      if (!c.ok || stop > c.end) return -1;
      if (count >= max_entries) return -2;
      TensorEntry* t = &entries[count];
      t->name_off = t->name_len = 0;
      Cursor m{c.p, stop};
      bool have_value = false;
      while (m.ok && m.p < m.end) {
        uint64_t mtag = m.varint();
        uint32_t mf = mtag >> 3, mwt = mtag & 7;
        if (mf == 1 && mwt == 2) {        // key
          uint64_t kn = m.varint();
          t->name_off = static_cast<uint32_t>(m.p - buf);
          t->name_len = static_cast<uint32_t>(kn);
          m.skip(kn);
        } else if (mf == 2 && mwt == 2) { // value: TensorProto
          uint64_t vn = m.varint();
          if (m.p + vn > m.end) return -1;
          if (!parse_tensor(buf, m.p, m.p + vn, t)) return -1;
          m.skip(vn);
          have_value = true;
        } else {
          m.skip_field(mwt);
        }
      }
      if (!m.ok || !have_value) return -1;
      ++count;
      c.p = stop;
    } else {
      c.skip_field(wt);
    }
  }
  return c.ok ? count : -1;
}

// ---------------------------------------------------------------------------
// Encoder: the actor→learner direction. Python hands one EncodeTensor per
// flattened pytree leaf (pointers into live numpy buffers — zero staging
// copies); the writer emits proto3 wire format that python-protobuf (and the
// decoder above) parse identically. Scalar header fields follow proto3
// default-omission, so byte streams match python-protobuf's own encoding of
// the same message modulo map-entry order (maps are unordered by contract).

// Filled on the Python side as ONE numpy structured array (per-field ctypes
// assignment is ~10x the cost of the whole C call); names/dtypes are offsets
// into a single concatenated strings blob, tensor payloads raw addresses of
// the (pinned) numpy buffers.
struct EncodeTensor {
  uint32_t name_off, name_len;    // into `strings`
  uint32_t dtype_off, dtype_len;  // into `strings`
  uint64_t data_ptr, data_len;    // raw buffer address
  int32_t shape[8];
  int32_t ndim;
};

namespace {

inline uint32_t varint_size(uint64_t v) {
  uint32_t n = 1;
  while (v >= 0x80) { v >>= 7; ++n; }
  return n;
}

struct Writer {
  uint8_t* p;

  void varint(uint64_t v) {
    while (v >= 0x80) { *p++ = static_cast<uint8_t>(v) | 0x80; v >>= 7; }
    *p++ = static_cast<uint8_t>(v);
  }
  void tag(uint32_t field, uint32_t wire_type) {
    varint((static_cast<uint64_t>(field) << 3) | wire_type);
  }
  void bytes(const uint8_t* src, uint64_t n) {
    std::memcpy(p, src, n);
    p += n;
  }
};

// Sizes of the variable-length pieces, computed once and reused by the
// writer so the output is laid down in one forward pass.
struct TensorSizes {
  uint64_t shape_payload;  // packed varints of the dims
  uint64_t tensor_body;    // TensorProto body (shape + dtype + data fields)
  uint64_t entry_body;     // map-entry body (key + value fields)
};

void tensor_sizes(const EncodeTensor& t, TensorSizes* s) {
  s->shape_payload = 0;
  for (int32_t i = 0; i < t.ndim; ++i)
    s->shape_payload += varint_size(static_cast<uint64_t>(
        static_cast<int64_t>(t.shape[i])));
  s->tensor_body = 0;
  if (t.ndim > 0)
    s->tensor_body += 1 + varint_size(s->shape_payload) + s->shape_payload;
  s->tensor_body += 1 + varint_size(t.dtype_len) + t.dtype_len;
  s->tensor_body += 1 + varint_size(t.data_len) + t.data_len;
  s->entry_body = 1 + varint_size(t.name_len) + t.name_len +
                  1 + varint_size(s->tensor_body) + s->tensor_body;
}

}  // namespace

// Encode a Rollout. Returns the exact number of bytes required; the output
// is written only when `cap` is sufficient (call once with cap=0 to size, or
// overprovision and accept the returned length). Returns -1 on invalid
// input (ndim out of range).
int64_t dota_encode_rollout(const RolloutHeader* hdr, const uint8_t* strings,
                            const EncodeTensor* tensors, int32_t n_tensors,
                            uint8_t* out, uint64_t cap) {
  uint64_t need = 0;
  if (hdr->model_version != 0)
    need += 1 + varint_size(static_cast<uint64_t>(
        static_cast<int64_t>(hdr->model_version)));
  if (hdr->env_id != 0)
    need += 1 + varint_size(static_cast<uint64_t>(
        static_cast<int64_t>(hdr->env_id)));
  if (hdr->rollout_id != 0) need += 1 + varint_size(hdr->rollout_id);
  if (hdr->length != 0)
    need += 1 + varint_size(static_cast<uint64_t>(
        static_cast<int64_t>(hdr->length)));
  if (hdr->total_reward != 0.0f) need += 1 + 4;
  for (int32_t i = 0; i < n_tensors; ++i) {
    if (tensors[i].ndim < 0 || tensors[i].ndim > 8) return -1;
    TensorSizes s;
    tensor_sizes(tensors[i], &s);
    need += 1 + varint_size(s.entry_body) + s.entry_body;
  }
  if (need > cap) return static_cast<int64_t>(need);

  Writer w{out};
  // proto3 varints encode negative int32 as 10-byte two's complement; the
  // int64_t casts above/below reproduce that (header ids are never negative
  // in practice, but wire compatibility should not depend on it).
  if (hdr->model_version != 0) {
    w.tag(1, 0);
    w.varint(static_cast<uint64_t>(static_cast<int64_t>(hdr->model_version)));
  }
  if (hdr->env_id != 0) {
    w.tag(2, 0);
    w.varint(static_cast<uint64_t>(static_cast<int64_t>(hdr->env_id)));
  }
  if (hdr->rollout_id != 0) {
    w.tag(3, 0);
    w.varint(hdr->rollout_id);
  }
  if (hdr->length != 0) {
    w.tag(4, 0);
    w.varint(static_cast<uint64_t>(static_cast<int64_t>(hdr->length)));
  }
  if (hdr->total_reward != 0.0f) {
    w.tag(5, 5);
    std::memcpy(w.p, &hdr->total_reward, 4);
    w.p += 4;
  }
  for (int32_t i = 0; i < n_tensors; ++i) {
    const EncodeTensor& t = tensors[i];
    TensorSizes s;
    tensor_sizes(t, &s);
    w.tag(6, 2);                       // map entry
    w.varint(s.entry_body);
    w.tag(1, 2);                       // key
    w.varint(t.name_len);
    w.bytes(strings + t.name_off, t.name_len);
    w.tag(2, 2);                       // value: TensorProto
    w.varint(s.tensor_body);
    if (t.ndim > 0) {
      w.tag(1, 2);                     // packed shape
      w.varint(s.shape_payload);
      for (int32_t d = 0; d < t.ndim; ++d)
        w.varint(static_cast<uint64_t>(static_cast<int64_t>(t.shape[d])));
    }
    w.tag(2, 2);                       // dtype
    w.varint(t.dtype_len);
    w.bytes(strings + t.dtype_off, t.dtype_len);
    w.tag(3, 2);                       // data
    w.varint(t.data_len);
    w.bytes(reinterpret_cast<const uint8_t*>(t.data_ptr), t.data_len);
  }
  return static_cast<int64_t>(w.p - out);
}

}  // extern "C"
