"""Device tracing and debug-mode numerics checking.

SURVEY.md §5.1-5.2: the reference had wall-clock prints and TensorBoard
scalars only; the rebuild's observability is two complementary layers:

* **Pipeline telemetry** (``utils/telemetry.py`` — see the "Observability"
  section of docs/ARCHITECTURE.md): host-side per-stage spans, queue/
  staleness/occupancy gauges, and counters across
  actor→transport→buffer→learner, drained to console/JSONL/tensorboardX by
  ``MetricsLogger``. That layer answers *which stage* is slow or starved.
* **This module**: jax.profiler device traces viewable in TensorBoard
  (tensorboard-plugin-profile) and `checkify`-instrumented train steps for
  NaN/Inf hunting. This layer answers *why* a device stage is slow.

A third layer joined in ISSUE 12: the pipeline TRACING plane
(``utils/tracing.py``, ``--trace-jsonl``) follows individual chunks and
weight versions ACROSS processes (hop timelines, critical-path and
staleness attribution via ``scripts/trace_report.py``) and wraps the jit
entry points with compile/retrace accounting. Spans say which stage,
tracing says which hop of which chunk, this module's profiler says why
the device program itself is slow.

Usage:
    with trace("runs/profile"):           # device trace of the block
        learner.train(100)

    python -m dotaclient_tpu.train.learner --profile-dir runs/profile
    python -m dotaclient_tpu.train.learner --checkify   # debug numerics
    python -m dotaclient_tpu.train.learner --metrics-jsonl run.jsonl  # spans
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(logdir: Optional[str]) -> Iterator[None]:
    """jax.profiler device trace over the enclosed block (no-op when
    ``logdir`` is None). View: tensorboard --logdir <logdir>."""
    if logdir is None:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# The checkify-instrumented train step lives in train/ppo.py
# (make_train_step(debug_checkify=True)); named scopes are applied directly
# at the policy's phase boundaries (models/policy.py).
