"""Ops utilities: metrics, telemetry, checkpointing, profiling, debug."""

from dotaclient_tpu.utils import telemetry
from dotaclient_tpu.utils.checkpoint import CheckpointManager
from dotaclient_tpu.utils.metrics import MetricsLogger

__all__ = ["CheckpointManager", "MetricsLogger", "telemetry"]
