"""Orbax checkpointing of the full learner state.

The reference does ``torch.save(state_dict)`` every K steps with a resume
flag (SURVEY.md §5.4; reconstructed — the reference checkout was an empty
mount). Here a checkpoint restores the *exact* training step: params,
optimizer state, step/version counters, and the config that produced them.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
from typing import Any, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.train.ppo import TrainState, init_train_state
from dotaclient_tpu.utils import faults, telemetry

logger = logging.getLogger(__name__)


def shape_mismatches(got: Any, want: Any) -> list:
    """Leaf-by-leaf shape comparison of two same-structure pytrees; returns
    human-readable ``"(got) != (want)"`` strings for every mismatched leaf.
    Shared by the pipeline restore below and the learner's ``init_from``
    compatibility check so the validation idiom cannot drift."""
    try:
        tree = jax.tree.map(
            lambda g, w: None
            if np.shape(g) == np.shape(w)
            else f"{np.shape(g)} != {np.shape(w)}",
            got,
            want,
        )
    except (ValueError, TypeError) as e:
        # Different tree STRUCTURE (e.g. a different model core): report it
        # as one mismatch rather than crashing the comparison.
        return [f"tree structure differs: {e}"]
    return [
        m
        for m in jax.tree.leaves(tree, is_leaf=lambda x: isinstance(x, str))
        if isinstance(m, str)
    ]


class CheckpointManager:
    """Thin orbax wrapper with the repo's state layout."""

    # How long a save/wait may block on the writer lock before giving up.
    # The lock is only ever contended when the async snapshot thread is
    # mid-save (ISSUE 5); a wedged disk holding it must not turn a
    # graceful stop into a hang — a periodic save degrades (counted), a
    # forced save raises loudly instead of parking forever.
    LOCK_TIMEOUT_S = 120.0

    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._tel = telemetry.get_registry()
        self._faults = faults.get()
        # Serializes writers: with the async snapshot engine (ISSUE 5) the
        # snapshot thread's periodic saves and the train thread's forced
        # end-of-run/crash saves target the same orbax manager; the drain
        # in the graceful path makes overlap rare, but the lock makes it
        # impossible.
        self._save_lock = threading.Lock()
        # eager-create: a run that never fails a save still reports the 0
        # (check_telemetry_schema.py --require-faults pins this key)
        self._tel.counter("checkpoint/save_failures_total")

    def save(
        self,
        state: TrainState,
        config: RunConfig,
        force: bool = False,
        pipeline: Optional[Any] = None,
    ) -> bool:
        """Save the train state (+ config); ``pipeline`` optionally carries
        the rest of the system — trajectory-buffer contents/cursors and the
        actor's device state (sim, carries, PRNG) — so a restore resumes the
        EXACT pipeline, not just the weights (SURVEY.md §5.4; VERDICT round 1
        item 9).

        This is the SYNC entry point (end-of-run/drain, crash rescue,
        best-model rotation, sync-snapshots debugging): it blocks on one
        batched device→host fetch of the whole state — one sync, not
        leaves-many ``np.asarray`` round trips — then hands the host arrays
        to :meth:`save_host`. The async snapshot engine fetches on its own
        thread and calls :meth:`save_host` directly."""
        host_state = jax.device_get(  # host-sync-ok: ONE batched fetch — the sync save path (boundary/tail cadence)
            {
                "step": state.step,
                "version": state.version,
                "params": state.params,
                "opt_state": state.opt_state,
            }
        )
        if pipeline is not None:
            pipeline = jax.device_get(pipeline)  # host-sync-ok: one batched fetch, forced/end-of-run cadence
        return self.save_host(host_state, config, force=force, pipeline=pipeline)

    def save_host(
        self,
        host_state: Any,
        config: RunConfig,
        force: bool = False,
        pipeline: Optional[Any] = None,
    ) -> bool:
        """Write an already-fetched host-array state dict (``step``,
        ``version``, ``params``, ``opt_state``) — no device traffic; the
        snapshot thread's entry point (ISSUE 5).

        Failure policy (ISSUE 4): a PERIODIC save (``force=False``) that
        hits an I/O error — disk full, permissions yanked, a previous async
        write surfacing its exception (checked below via the manager's
        error latch before each attempt) — degrades to a warning plus the
        ``checkpoint/save_failures_total`` counter and returns False: losing
        one periodic snapshot must not kill a training loop that is
        otherwise healthy. A forced save (the end-of-run/drain snapshot) RE-
        RAISES — silently losing the final checkpoint must stay loud."""
        if self._faults is not None and self._faults.fire(
            "checkpoint.fail_write"
        ):
            injected: Optional[BaseException] = OSError(
                "injected fault: checkpoint.fail_write (simulated full disk)"
            )
        else:
            injected = None
        step = int(np.asarray(host_state["step"]))  # host-sync-ok: host array
        items = dict(
            state=ocp.args.StandardSave(
                jax.tree.map(np.asarray, host_state)  # host-sync-ok: host arrays (int leaves → np scalars for orbax)
            ),
            config=ocp.args.JsonSave(dataclasses.asdict(config)),
        )
        if pipeline is not None:
            items["pipeline"] = ocp.args.StandardSave(
                jax.tree.map(np.asarray, pipeline)  # host-sync-ok: host arrays
            )
        if not self._save_lock.acquire(timeout=self.LOCK_TIMEOUT_S):
            # the other writer (almost certainly the snapshot thread, on a
            # wedged disk) has held the lock past any reasonable save
            msg = (
                f"checkpoint writer lock not acquired within "
                f"{self.LOCK_TIMEOUT_S:.0f}s — a concurrent (async) save "
                f"appears wedged; step {step} was NOT written"
            )
            if force:
                raise RuntimeError(msg)
            self._tel.counter("checkpoint/save_failures_total").inc()
            logger.warning("%s", msg)
            return False
        try:
            return self._save_host_locked(
                step, items, force, pipeline, injected
            )
        finally:
            self._save_lock.release()

    def _save_host_locked(
        self,
        step: int,
        items: dict,
        force: bool,
        pipeline: Optional[Any],
        injected: Optional[BaseException],
    ) -> bool:
        """The write itself; caller holds ``_save_lock``."""
        # A PREVIOUS async orbax write that failed after its save() call
        # returned surfaces at this join; drain it here so this save's own
        # outcome stays attributable — same degrade policy, counted once
        # per surfaced failure.
        try:
            self._wait_for_prev_save()
        except Exception as e:  # noqa: BLE001 - orbax wraps freely
            if force:
                raise
            self._tel.counter("checkpoint/save_failures_total").inc()
            logger.warning(
                "an earlier async checkpoint write failed (%s: %s) "
                "— counted; attempting the save at step %d anyway",
                type(e).__name__, e, step,
            )
        try:
            if injected is not None:
                raise injected
            # A periodic (weights-only) save and the end-of-run pipeline
            # save land on the SAME step whenever the run length is a
            # multiple of checkpoint_every; orbax refuses to overwrite an
            # existing step. The pipeline save strictly supersedes the
            # weights-only one, so replace it; without new content there
            # is nothing to add — skip.
            if step in self._mgr.all_steps():
                if pipeline is None:
                    return False
                self._wait_for_prev_save()
                self._mgr.delete(step)
                # the replacement save MUST NOT be declined: with
                # force=False orbax's should_save rejects any step <=
                # latest, which after the delete would mean guaranteed
                # loss of step `step`. (A crash between delete and save
                # durability can still lose it — replace-in-place is not
                # atomic; the periodic saves around it bound the damage
                # to one checkpoint interval.)
                force = True
            saved = self._mgr.save(
                step, args=ocp.args.Composite(**items), force=force
            )
        except (OSError, ValueError, RuntimeError) as e:
            if force:
                raise   # end-of-run/drain snapshot: loss must stay loud
            self._tel.counter("checkpoint/save_failures_total").inc()
            logger.warning(
                "periodic checkpoint save at step %d failed (%s: %s) — "
                "training continues; fix the storage before the next "
                "snapshot window or the run loses restore granularity",
                step, type(e).__name__, e,
            )
            return False
        return bool(saved)

    def restore_pipeline(self, template: Any) -> Tuple[Optional[Any], str]:
        """Restore the pipeline extras of the latest step into ``template``'s
        structure. Returns (state, "") on success; (None, "") when the
        checkpoint simply has no pipeline entry; (None, reason) when one
        exists but could not be restored (shape/layout mismatch) — callers
        must surface that loudly, not silently degrade."""
        step = self._mgr.latest_step()
        if step is None:
            return None, ""
        try:
            has_pipeline = "pipeline" in (self._mgr.item_metadata(step) or {})
        except Exception:
            has_pipeline = True  # unknown: attempt and report failure
        if not has_pipeline:
            return None, ""
        try:
            restored = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    pipeline=ocp.args.StandardRestore(
                        jax.tree.map(np.asarray, template)
                    )
                ),
            )
        except (KeyError, FileNotFoundError, ValueError, TypeError) as e:
            return None, f"{type(e).__name__}: {e}"
        out = restored["pipeline"]
        # orbax StandardRestore does NOT enforce the template's shapes — a
        # checkpoint from a different run config (say 1v1 lanes restored
        # into a 5v5 learner) round-trips with the WRONG leaf shapes and
        # only explodes later, deep inside a jitted rollout. Reject it
        # here so callers degrade to weights-only, loudly.
        bad = shape_mismatches(out, template)
        if bad:
            return None, f"pipeline leaf shape mismatch: {bad[0]} (+{len(bad) - 1} more)"
        return out, ""

    def _wait_for_prev_save(self) -> None:
        """Join the previous (async) orbax save from ANY thread.

        orbax 0.7's ``wait_until_finished`` clears its finalize-thread slot
        only when the waiting thread is the one that REQUESTED the save;
        with the snapshot engine (ISSUE 5), periodic saves (snapshot
        thread) and forced end-of-run/crash saves (train thread) alternate
        on one manager, and the stale slot then trips orbax's
        ``assert self._finalize_thread is None`` on the next save. Join,
        then clear the dead thread from the slot ourselves — exactly what
        the owner-thread path does."""
        try:
            self._mgr.wait_until_finished()
        finally:
            lock = getattr(self._mgr, "_finalize_thread_lock", None)
            if lock is not None:
                with lock:
                    t = getattr(self._mgr, "_finalize_thread", None)
                    if t is not None and not t.is_alive():
                        self._mgr._finalize_thread = None

    def wait(self) -> None:
        # never overlaps an in-flight async save; bounded for the same
        # reason as save_host — a wedged writer must fail loudly, not hang
        if not self._save_lock.acquire(timeout=self.LOCK_TIMEOUT_S):
            raise RuntimeError(
                f"checkpoint writer lock not acquired within "
                f"{self.LOCK_TIMEOUT_S:.0f}s — a concurrent (async) save "
                f"appears wedged"
            )
        try:
            self._wait_for_prev_save()
        finally:
            self._save_lock.release()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def _latest_step_or_raise(self) -> int:
        step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        return step

    @staticmethod
    def _decode_config(raw: Any) -> RunConfig:
        return RunConfig.from_json(json.dumps(raw))

    def restore_config(self) -> RunConfig:
        """Restore only the RunConfig of the latest checkpoint — the
        bootstrap for tools that must build the model tree BEFORE they can
        restore weights (the checkpoint's own config is authoritative for
        its parameter shapes; guessing a config risks a template mismatch).
        """
        restored = self._mgr.restore(
            self._latest_step_or_raise(),
            args=ocp.args.Composite(config=ocp.args.JsonRestore()),
        )
        return self._decode_config(restored["config"])

    def restore_weights(self) -> Tuple[Any, int]:
        """Weights-only restore of the latest step: ``(params, step)``.

        Restores the state item WITHOUT a structure template (as-saved
        layout), so it works across optimizer configurations — e.g. seeding
        a KL-adaptive-lr run (whose opt_state carries an injected
        hyperparams leaf) from a plain-Adam source checkpoint. Callers
        validate the params' shapes against their own model (the learner's
        ``init_from`` path does); the source's opt_state is ignored
        entirely, matching init_from's fresh-moments contract.
        """
        step = self._latest_step_or_raise()
        restored = self._mgr.restore(
            step, args=ocp.args.Composite(state=ocp.args.StandardRestore())
        )
        raw = restored["state"]
        return (
            jax.tree.map(jax.numpy.asarray, raw["params"]),
            int(np.asarray(raw["step"])),
        )

    def restore(
        self, config: RunConfig, abstract_state: Optional[TrainState] = None
    ) -> Tuple[TrainState, RunConfig]:
        """Restore the latest checkpoint into a TrainState.

        ``abstract_state`` provides the target pytree structure; built from
        ``config`` when omitted.
        """
        step = self._latest_step_or_raise()
        if abstract_state is None:
            from dotaclient_tpu.models import init_params, make_policy

            policy = make_policy(config.model, config.obs, config.actions)
            params = init_params(policy, jax.random.PRNGKey(0))
            abstract_state = init_train_state(params, config.ppo)
        template = {
            "step": np.asarray(abstract_state.step),
            "version": np.asarray(abstract_state.version),
            "params": jax.tree.map(np.asarray, abstract_state.params),
            "opt_state": jax.tree.map(np.asarray, abstract_state.opt_state),
        }
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                config=ocp.args.JsonRestore(),
            ),
        )
        raw = restored["state"]
        state = TrainState(
            step=jax.numpy.asarray(raw["step"]),
            version=jax.numpy.asarray(raw["version"]),
            params=jax.tree.map(jax.numpy.asarray, raw["params"]),
            opt_state=jax.tree.map(jax.numpy.asarray, raw["opt_state"]),
        )
        cfg = self._decode_config(restored["config"])
        return state, cfg

    def close(self) -> None:
        self._mgr.close()
