"""Orbax checkpointing of the full learner state.

The reference does ``torch.save(state_dict)`` every K steps with a resume
flag (SURVEY.md §5.4; reconstructed — the reference checkout was an empty
mount). Here a checkpoint restores the *exact* training step: params,
optimizer state, step/version counters, and the config that produced them.

Integrity + retention (ISSUE 6, the guardian's *recover* stage):

* every save writes a sidecar **integrity manifest**
  (``<dir>/manifests/<step>.json``: per-leaf shape/dtype + content
  digest, reusing the transport layer's memory-bandwidth CRC fold) from
  the host arrays already in hand — no extra device traffic;
* every restore **verifies** the manifest and *walks back*: a corrupt or
  unreadable latest step is counted (``checkpoint/manifest_failures_total``),
  warned about, and skipped in favor of the previous manifest-valid save
  — a torn write or bit-rotted leaf degrades restore granularity instead
  of crashing the relaunch;
* a ``last_good`` **retention slot** (``<dir>/last_good``, its own
  max_to_keep=1 manager) holds the newest save whose steps the health
  guardian verified — outside the main rolling GC, so divergence rollback
  (train/learner.py) always has a healthy restore point even after the
  main retention loop has moved on.

Sharded-state contract (ISSUE 10): saves always write HOST-LAYOUT arrays
— ``jax.device_get`` assembles mesh-sharded leaves (replicated params
read from shard 0; TP-partitioned leaves gather) — so checkpoints are
device-count-free. Restores symmetrically return host-layout/uncommitted
arrays; the CALLER re-commits to its current mesh (the learner's
``state_shardings`` device_put), which is what makes an 8-chip checkpoint
restore into a 1-chip run and vice versa (tests/test_multichip.py).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
from typing import Any, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.train.ppo import TrainState, init_train_state
from dotaclient_tpu.utils import faults, telemetry

logger = logging.getLogger(__name__)


class CheckpointIntegrityError(RuntimeError):
    """A restored checkpoint failed its integrity-manifest verification."""


def _plain_tree(tree: Any) -> Any:
    """Canonicalize a state tree to orbax's storage shape: NamedTuples
    (optax states) become dicts of their fields, tuples become lists.
    Saved trees carry the live NamedTuple nodes while a template-free
    restore returns plain dicts — the manifest must hash BOTH to the same
    leaf paths or every verified restore would read as corrupt."""
    if hasattr(tree, "_fields"):   # NamedTuple
        return {k: _plain_tree(v) for k, v in tree._asdict().items()}
    if isinstance(tree, dict):
        return {k: _plain_tree(v) for k, v in tree.items()}
    if isinstance(tree, (tuple, list)):
        return [_plain_tree(v) for v in tree]
    return tree


def build_manifest(host_state: Any) -> dict:
    """Per-leaf shape/dtype/digest record of an already-fetched host state
    tree. The digest is the transport layer's CRC fold
    (``serialize.frame_crc32`` — XOR-fold + CRC32, memory-bandwidth fast),
    so manifest cost is one pass over bytes the save writes anyway."""
    from dotaclient_tpu.transport.serialize import frame_crc32

    flat, _ = jax.tree_util.tree_flatten_with_path(_plain_tree(host_state))
    leaves = {}
    for path, leaf in flat:
        a = np.ascontiguousarray(leaf)
        leaves[jax.tree_util.keystr(path)] = {
            "shape": list(a.shape),
            "dtype": str(a.dtype),
            "crc": frame_crc32(a.tobytes()),
        }
    return {"version": 1, "leaves": leaves}


def verify_manifest(manifest: dict, host_state: Any) -> None:
    """Raise :class:`CheckpointIntegrityError` on the first leaf whose
    shape, dtype, or content digest differs from the manifest (or on a
    leaf-set mismatch)."""
    got = build_manifest(host_state)["leaves"]
    want = manifest.get("leaves", {})
    if set(got) != set(want):
        missing = sorted(set(want) - set(got))[:3]
        extra = sorted(set(got) - set(want))[:3]
        raise CheckpointIntegrityError(
            f"leaf set differs from manifest (missing {missing}, "
            f"unexpected {extra})"
        )
    for key, spec in want.items():
        g = got[key]
        for field in ("shape", "dtype", "crc"):
            if g[field] != spec[field]:
                raise CheckpointIntegrityError(
                    f"leaf {key!r} {field} mismatch: restored "
                    f"{g[field]!r} != saved {spec[field]!r}"
                )


def shape_mismatches(got: Any, want: Any) -> list:
    """Leaf-by-leaf shape comparison of two same-structure pytrees; returns
    human-readable ``"(got) != (want)"`` strings for every mismatched leaf.
    Shared by the pipeline restore below and the learner's ``init_from``
    compatibility check so the validation idiom cannot drift."""
    try:
        tree = jax.tree.map(
            lambda g, w: None
            if np.shape(g) == np.shape(w)
            else f"{np.shape(g)} != {np.shape(w)}",
            got,
            want,
        )
    except (ValueError, TypeError) as e:
        # Different tree STRUCTURE (e.g. a different model core): report it
        # as one mismatch rather than crashing the comparison.
        return [f"tree structure differs: {e}"]
    return [
        m
        for m in jax.tree.leaves(tree, is_leaf=lambda x: isinstance(x, str))
        if isinstance(m, str)
    ]


class CheckpointManager:
    """Thin orbax wrapper with the repo's state layout."""

    # How long a save/wait may block on the writer lock before giving up.
    # The lock is only ever contended when the async snapshot thread is
    # mid-save (ISSUE 5); a wedged disk holding it must not turn a
    # graceful stop into a hang — a periodic save degrades (counted), a
    # forced save raises loudly instead of parking forever.
    LOCK_TIMEOUT_S = 120.0

    def __init__(
        self, directory: str, max_to_keep: int = 3, _is_slot: bool = False
    ) -> None:
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._tel = telemetry.get_registry()
        self._faults = faults.get()
        # Serializes writers: with the async snapshot engine (ISSUE 5) the
        # snapshot thread's periodic saves and the train thread's forced
        # end-of-run/crash saves target the same orbax manager; the drain
        # in the graceful path makes overlap rare, but the lock makes it
        # impossible.
        self._save_lock = threading.Lock()
        # last_good retention slot (ISSUE 6): a nested manager holding the
        # newest health-verified save, outside the main rolling GC.
        # Lazily created at the first mark_good save; _is_slot stops the
        # nesting at one level (the slot has no slot).
        self._is_slot = _is_slot
        self._slot_mgr: Optional["CheckpointManager"] = None
        # The step a walk-back restore actually landed on (may be older
        # than latest when the newest save failed integrity); pipeline
        # restore follows it so state and pipeline never come from
        # different steps.
        self.last_restored_step: Optional[int] = None
        # eager-create: a run that never fails a save still reports the 0s
        # (check_telemetry_schema.py --require-faults / --require-health)
        self._tel.counter("checkpoint/save_failures_total")
        self._tel.counter("checkpoint/manifest_failures_total")

    # -- integrity manifests -------------------------------------------------

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, "manifests", f"{step}.json")

    def _write_manifest(self, step: int, host_state: Any) -> None:
        """Sidecar write (temp+rename, like every marker in this repo);
        failure degrades — a save without a manifest restores unverified,
        exactly like a pre-ISSUE-6 checkpoint."""
        try:
            os.makedirs(os.path.join(self.directory, "manifests"), exist_ok=True)
            path = self._manifest_path(step)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"step": step, **build_manifest(host_state)}, f)
            os.replace(tmp, path)
        except OSError as e:
            logger.warning(
                "checkpoint manifest write for step %d failed (%s) — the "
                "save stands but will restore UNVERIFIED", step, e,
            )

    def _gc_manifests(self) -> None:
        """Drop sidecars whose step the rolling retention already deleted."""
        mdir = os.path.join(self.directory, "manifests")
        try:
            names = os.listdir(mdir)
        except OSError:
            return
        live = set(self._mgr.all_steps())
        for name in names:
            stem = name[:-5] if name.endswith(".json") else ""
            if stem.isdigit() and int(stem) not in live:
                try:
                    os.unlink(os.path.join(mdir, name))
                except OSError:
                    pass

    def _verify_step(self, step: int, host_state: Any) -> None:
        """Verify ``host_state`` against step's manifest. A step without a
        manifest (legacy writer, failed sidecar write) passes unverified;
        a manifest that exists but mismatches — or an injected
        ``checkpoint.corrupt_manifest`` fault — raises
        :class:`CheckpointIntegrityError`."""
        if self._faults is not None and self._faults.fire(
            "checkpoint.corrupt_manifest"
        ):
            raise CheckpointIntegrityError(
                "injected fault: checkpoint.corrupt_manifest (chaos harness)"
            )
        try:
            with open(self._manifest_path(step)) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            return
        except (OSError, json.JSONDecodeError) as e:
            raise CheckpointIntegrityError(
                f"manifest for step {step} unreadable: {e}"
            ) from e
        verify_manifest(manifest, host_state)

    def save(
        self,
        state: TrainState,
        config: RunConfig,
        force: bool = False,
        pipeline: Optional[Any] = None,
        mark_good: bool = False,
    ) -> bool:
        """Save the train state (+ config); ``pipeline`` optionally carries
        the rest of the system — trajectory-buffer contents/cursors and the
        actor's device state (sim, carries, PRNG) — so a restore resumes the
        EXACT pipeline, not just the weights (SURVEY.md §5.4; VERDICT round 1
        item 9).

        This is the SYNC entry point (end-of-run/drain, crash rescue,
        best-model rotation, sync-snapshots debugging): it blocks on one
        batched device→host fetch of the whole state — one sync, not
        leaves-many ``np.asarray`` round trips — then hands the host arrays
        to :meth:`save_host`. The async snapshot engine fetches on its own
        thread and calls :meth:`save_host` directly."""
        host_state = jax.device_get(  # host-sync-ok: ONE batched fetch — the sync save path (boundary/tail cadence)
            {
                "step": state.step,
                "version": state.version,
                "params": state.params,
                "opt_state": state.opt_state,
            }
        )
        if pipeline is not None:
            pipeline = jax.device_get(pipeline)  # host-sync-ok: one batched fetch, forced/end-of-run cadence
        return self.save_host(
            host_state, config, force=force, pipeline=pipeline,
            mark_good=mark_good,
        )

    def save_host(
        self,
        host_state: Any,
        config: RunConfig,
        force: bool = False,
        pipeline: Optional[Any] = None,
        mark_good: bool = False,
    ) -> bool:
        """Write an already-fetched host-array state dict (``step``,
        ``version``, ``params``, ``opt_state``) — no device traffic; the
        snapshot thread's entry point (ISSUE 5).

        Failure policy (ISSUE 4): a PERIODIC save (``force=False``) that
        hits an I/O error — disk full, permissions yanked, a previous async
        write surfacing its exception (checked below via the manager's
        error latch before each attempt) — degrades to a warning plus the
        ``checkpoint/save_failures_total`` counter and returns False: losing
        one periodic snapshot must not kill a training loop that is
        otherwise healthy. A forced save (the end-of-run/drain snapshot) RE-
        RAISES — silently losing the final checkpoint must stay loud."""
        if self._faults is not None and self._faults.fire(
            "checkpoint.fail_write"
        ):
            injected: Optional[BaseException] = OSError(
                "injected fault: checkpoint.fail_write (simulated full disk)"
            )
        else:
            injected = None
        step = int(np.asarray(host_state["step"]))  # host-sync-ok: host array
        host_np = jax.tree.map(np.asarray, host_state)  # host-sync-ok: host arrays (int leaves → np scalars for orbax)
        items = dict(
            state=ocp.args.StandardSave(host_np),
            config=ocp.args.JsonSave(dataclasses.asdict(config)),
        )
        if pipeline is not None:
            items["pipeline"] = ocp.args.StandardSave(
                jax.tree.map(np.asarray, pipeline)  # host-sync-ok: host arrays
            )
        if not self._save_lock.acquire(timeout=self.LOCK_TIMEOUT_S):
            # the other writer (almost certainly the snapshot thread, on a
            # wedged disk) has held the lock past any reasonable save
            msg = (
                f"checkpoint writer lock not acquired within "
                f"{self.LOCK_TIMEOUT_S:.0f}s — a concurrent (async) save "
                f"appears wedged; step {step} was NOT written"
            )
            if force:
                raise RuntimeError(msg)
            self._tel.counter("checkpoint/save_failures_total").inc()
            logger.warning("%s", msg)
            return False
        try:
            saved = self._save_host_locked(
                step, items, force, pipeline, injected
            )
            if saved:
                # Sidecar integrity manifest (digest of the host arrays
                # just handed to orbax) + sidecar GC for steps the rolling
                # retention dropped. Written before orbax's async finalize
                # completes — a finalize-time failure surfaces at the next
                # save's join and that step then fails restore loudly, the
                # same outcome as a digest mismatch.
                self._write_manifest(step, host_np)
                self._gc_manifests()
                if mark_good and not self._is_slot:
                    self._save_last_good(step, host_np, config)
            return saved
        finally:
            self._save_lock.release()

    def _save_last_good(self, step: int, host_np: Any, config: RunConfig) -> None:
        """Mirror a health-verified save into the ``last_good`` slot (its
        own max_to_keep=1 manager — the main rolling GC can never eat it).
        Best-effort: slot I/O failure degrades to the save-failure counter;
        the main save already stands."""
        try:
            slot = self._last_good_slot()
            if step in slot._mgr.all_steps():
                # a rollback-then-retrain run re-reaches old step numbers;
                # the fresh (re-verified) save supersedes the stale slot
                slot._wait_for_prev_save()
                slot._mgr.delete(step)
            slot.save_host(
                {k: host_np[k] for k in ("step", "version", "params", "opt_state")},
                config, force=True,
            )
            self._tel.gauge("health/last_good_step").set(float(step))   # host-sync-ok: host int
        except Exception as e:  # noqa: BLE001 - protection layer, never fatal
            self._tel.counter("checkpoint/save_failures_total").inc()
            logger.warning(
                "last_good slot update at step %d failed (%s: %s) — the "
                "main save stands; rollback protection is stale until the "
                "next healthy checkpoint", step, type(e).__name__, e,
            )

    def _last_good_slot(self) -> "CheckpointManager":
        if self._slot_mgr is None:
            self._slot_mgr = CheckpointManager(
                os.path.join(self.directory, "last_good"),
                max_to_keep=1, _is_slot=True,
            )
        return self._slot_mgr

    def _save_host_locked(
        self,
        step: int,
        items: dict,
        force: bool,
        pipeline: Optional[Any],
        injected: Optional[BaseException],
    ) -> bool:
        """The write itself; caller holds ``_save_lock``."""
        # A PREVIOUS async orbax write that failed after its save() call
        # returned surfaces at this join; drain it here so this save's own
        # outcome stays attributable — same degrade policy, counted once
        # per surfaced failure.
        try:
            self._wait_for_prev_save()
        except Exception as e:  # noqa: BLE001 - orbax wraps freely
            if force:
                raise
            self._tel.counter("checkpoint/save_failures_total").inc()
            logger.warning(
                "an earlier async checkpoint write failed (%s: %s) "
                "— counted; attempting the save at step %d anyway",
                type(e).__name__, e, step,
            )
        try:
            if injected is not None:
                raise injected
            # A save can land on a step that already exists: the end-of-run
            # pipeline save supersedes the periodic weights-only save on the
            # same step, and a divergence-rollback run (ISSUE 6) legitimately
            # RE-REACHES step numbers of its abandoned timeline (whose saves
            # rollback discards, but a walk-back-skipped corrupt step can
            # linger). orbax refuses to overwrite; the newest content always
            # supersedes, so replace.
            replacing = step in self._mgr.all_steps()
            if replacing:
                self._wait_for_prev_save()
                self._mgr.delete(step)
            # the replacement save MUST NOT be declined: with force=False
            # orbax's should_save rejects any step <= latest, which after
            # the delete would mean guaranteed loss of step `step`. (A
            # crash between delete and save durability can still lose it —
            # replace-in-place is not atomic; the periodic saves around it
            # bound the damage to one checkpoint interval.) Only the orbax
            # decline-override escalates: the raise-vs-degrade policy below
            # stays the CALLER's `force` — a periodic save that happens to
            # collide must still degrade on I/O failure, not kill the run.
            saved = self._mgr.save(
                step, args=ocp.args.Composite(**items),
                force=force or replacing,
            )
        except (OSError, ValueError, RuntimeError) as e:
            if force:
                raise   # end-of-run/drain snapshot: loss must stay loud
            self._tel.counter("checkpoint/save_failures_total").inc()
            logger.warning(
                "periodic checkpoint save at step %d failed (%s: %s) — "
                "training continues; fix the storage before the next "
                "snapshot window or the run loses restore granularity",
                step, type(e).__name__, e,
            )
            return False
        return bool(saved)

    def _restore_stepwise(self, attempt) -> Any:
        """Walk the saved steps newest-first, calling ``attempt(step)``
        until one succeeds; every failing step — an orbax read error, a
        layout mismatch, or an integrity-manifest failure raised inside
        ``attempt`` — is counted (``checkpoint/manifest_failures_total``),
        warned about, and skipped in favor of the previous save. A corrupt
        LATEST checkpoint therefore degrades restore granularity by one
        interval instead of crashing the relaunch (ISSUE 6). Re-raises the
        last error when every step fails."""
        steps = sorted(self._mgr.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        last_err: Optional[BaseException] = None
        for i, step in enumerate(steps):
            try:
                out = attempt(step)
            except (
                CheckpointIntegrityError, KeyError, FileNotFoundError,
                OSError, ValueError, TypeError, RuntimeError,
            ) as e:
                last_err = e
                self._tel.counter("checkpoint/manifest_failures_total").inc()
                logger.warning(
                    "checkpoint restore at step %d failed integrity/read "
                    "(%s: %s) — %s", step, type(e).__name__, e,
                    "walking back to the previous save"
                    if i + 1 < len(steps) else "no older save to walk back to",
                )
                continue
            self.last_restored_step = step
            return out
        raise last_err  # type: ignore[misc]  # loop ran: steps is non-empty

    def restore_pipeline(
        self, template: Any, step: Optional[int] = None
    ) -> Tuple[Optional[Any], str]:
        """Restore the pipeline extras into ``template``'s structure —
        from ``step``, defaulting to the step the preceding state restore
        landed on (walk-back aware), else the latest. Returns (state, "")
        on success; (None, "") when the checkpoint simply has no pipeline
        entry; (None, reason) when one exists but could not be restored
        (shape/layout mismatch) — callers must surface that loudly, not
        silently degrade."""
        if step is None:
            step = (
                self.last_restored_step
                if self.last_restored_step is not None
                else self._mgr.latest_step()
            )
        if step is None:
            return None, ""
        try:
            has_pipeline = "pipeline" in (self._mgr.item_metadata(step) or {})
        except Exception:
            has_pipeline = True  # unknown: attempt and report failure
        if not has_pipeline:
            return None, ""
        try:
            restored = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    pipeline=ocp.args.StandardRestore(
                        jax.tree.map(np.asarray, template)
                    )
                ),
            )
        except (KeyError, FileNotFoundError, ValueError, TypeError) as e:
            return None, f"{type(e).__name__}: {e}"
        out = restored["pipeline"]
        # orbax StandardRestore does NOT enforce the template's shapes — a
        # checkpoint from a different run config (say 1v1 lanes restored
        # into a 5v5 learner) round-trips with the WRONG leaf shapes and
        # only explodes later, deep inside a jitted rollout. Reject it
        # here so callers degrade to weights-only, loudly.
        bad = shape_mismatches(out, template)
        if bad:
            return None, f"pipeline leaf shape mismatch: {bad[0]} (+{len(bad) - 1} more)"
        return out, ""

    def _wait_for_prev_save(self) -> None:
        """Join the previous (async) orbax save from ANY thread.

        orbax 0.7's ``wait_until_finished`` clears its finalize-thread slot
        only when the waiting thread is the one that REQUESTED the save;
        with the snapshot engine (ISSUE 5), periodic saves (snapshot
        thread) and forced end-of-run/crash saves (train thread) alternate
        on one manager, and the stale slot then trips orbax's
        ``assert self._finalize_thread is None`` on the next save. Join,
        then clear the dead thread from the slot ourselves — exactly what
        the owner-thread path does."""
        try:
            self._mgr.wait_until_finished()
        finally:
            lock = getattr(self._mgr, "_finalize_thread_lock", None)
            if lock is not None:
                with lock:
                    t = getattr(self._mgr, "_finalize_thread", None)
                    if t is not None and not t.is_alive():
                        self._mgr._finalize_thread = None

    def wait(self) -> None:
        # never overlaps an in-flight async save; bounded for the same
        # reason as save_host — a wedged writer must fail loudly, not hang
        if not self._save_lock.acquire(timeout=self.LOCK_TIMEOUT_S):
            raise RuntimeError(
                f"checkpoint writer lock not acquired within "
                f"{self.LOCK_TIMEOUT_S:.0f}s — a concurrent (async) save "
                f"appears wedged"
            )
        try:
            self._wait_for_prev_save()
        finally:
            self._save_lock.release()
        if self._slot_mgr is not None:
            # the last_good slot write finalizes on its own orbax thread;
            # an interpreter exiting before it lands races the executor
            # shutdown ("cannot schedule new futures") — join it too
            self._slot_mgr.wait()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    # -- last_good retention slot (ISSUE 6) ---------------------------------

    def last_good_step(self) -> Optional[int]:
        """Step held by the ``last_good`` slot, or None when the guardian
        has not yet verified a save (fresh run, or health disabled)."""
        slot_dir = os.path.join(self.directory, "last_good")
        if self._slot_mgr is None and not os.path.isdir(slot_dir):
            return None
        return self._last_good_slot().latest_step()

    def restore_last_good(
        self, config: RunConfig, abstract_state: Optional[TrainState] = None
    ) -> Optional[Tuple[TrainState, RunConfig]]:
        """Restore the last health-verified save (divergence rollback's
        restore point). None when the slot is empty; integrity-verified
        like every restore."""
        if self.last_good_step() is None:
            return None
        return self._last_good_slot().restore(config, abstract_state)

    def discard_steps_above(self, step: int) -> int:
        """Delete every save newer than ``step`` (divergence rollback:
        checkpoints of the abandoned timeline must not be restorable, and
        the retrained timeline will re-reach their step numbers). Returns
        the number of deleted saves."""
        if not self._save_lock.acquire(timeout=self.LOCK_TIMEOUT_S):
            raise RuntimeError(
                f"checkpoint writer lock not acquired within "
                f"{self.LOCK_TIMEOUT_S:.0f}s — cannot discard the "
                f"abandoned timeline's saves"
            )
        try:
            self._wait_for_prev_save()
            doomed = [s for s in self._mgr.all_steps() if s > step]
            for s in doomed:
                self._mgr.delete(s)
            self._gc_manifests()
            return len(doomed)
        finally:
            self._save_lock.release()

    def _latest_step_or_raise(self) -> int:
        step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        return step

    @staticmethod
    def _decode_config(raw: Any) -> RunConfig:
        return RunConfig.from_json(json.dumps(raw))

    def restore_config(self) -> RunConfig:
        """Restore only the RunConfig of the latest checkpoint — the
        bootstrap for tools that must build the model tree BEFORE they can
        restore weights (the checkpoint's own config is authoritative for
        its parameter shapes; guessing a config risks a template mismatch).
        """
        restored = self._mgr.restore(
            self._latest_step_or_raise(),
            args=ocp.args.Composite(config=ocp.args.JsonRestore()),
        )
        return self._decode_config(restored["config"])

    def restore_weights(self) -> Tuple[Any, int]:
        """Weights-only restore of the latest step: ``(params, step)``.

        Restores the state item WITHOUT a structure template (as-saved
        layout), so it works across optimizer configurations — e.g. seeding
        a KL-adaptive-lr run (whose opt_state carries an injected
        hyperparams leaf) from a plain-Adam source checkpoint. Callers
        validate the params' shapes against their own model (the learner's
        ``init_from`` path does); the source's opt_state is ignored
        entirely, matching init_from's fresh-moments contract.
        """
        def attempt(step: int):
            restored = self._mgr.restore(
                step, args=ocp.args.Composite(state=ocp.args.StandardRestore())
            )
            raw = restored["state"]
            self._verify_step(step, raw)
            return (
                jax.tree.map(jax.numpy.asarray, raw["params"]),
                int(np.asarray(raw["step"])),   # host-sync-ok: restored host array
            )

        return self._restore_stepwise(attempt)

    def restore(
        self, config: RunConfig, abstract_state: Optional[TrainState] = None
    ) -> Tuple[TrainState, RunConfig]:
        """Restore the latest checkpoint into a TrainState.

        ``abstract_state`` provides the target pytree structure; built from
        ``config`` when omitted.
        """
        if abstract_state is None:
            from dotaclient_tpu.models import init_params, make_policy

            policy = make_policy(config.model, config.obs, config.actions)
            params = init_params(policy, jax.random.PRNGKey(0))
            abstract_state = init_train_state(params, config.ppo)
        template = {
            "step": np.asarray(abstract_state.step),
            "version": np.asarray(abstract_state.version),
            "params": jax.tree.map(np.asarray, abstract_state.params),
            "opt_state": jax.tree.map(np.asarray, abstract_state.opt_state),
        }

        def attempt(step: int):
            restored = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(template),
                    config=ocp.args.JsonRestore(),
                ),
            )
            raw = restored["state"]
            self._verify_step(step, raw)
            state = TrainState(
                step=jax.numpy.asarray(raw["step"]),
                version=jax.numpy.asarray(raw["version"]),
                params=jax.tree.map(jax.numpy.asarray, raw["params"]),
                opt_state=jax.tree.map(jax.numpy.asarray, raw["opt_state"]),
            )
            return state, self._decode_config(restored["config"])

        return self._restore_stepwise(attempt)

    def close(self) -> None:
        if self._slot_mgr is not None:
            self._slot_mgr.close()
        self._mgr.close()
