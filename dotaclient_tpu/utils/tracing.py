"""Pipeline tracing plane: cross-process trace context + device hooks.

Telemetry (``utils/telemetry.py``) answers *which stage is slow on
average* — per-process counters and span timers. It cannot answer the
questions every remaining ROADMAP direction hinges on: *where did THIS
chunk's latency go across five processes*, and *which hop ages the
weights an actor collects with* (IMPACT makes staleness a first-class
quantity; Podracer-style scaling lives on measured end-to-end
attribution — PAPERS.md). This module is that instrument:

* **Cross-process trace context.** A sampled rollout chunk (and every
  weights-publish frame) carries a compact trace record as one extra
  in-band entry on the existing ``__wire_cast__``-style marker
  discipline (``serialize._TRACE_MARKER``): origin pid/actor id, a
  unique trace id, the weights version at collect, and
  monotonic-clock epoch-aligned hop timestamps. The record is stamped
  at actor encode (hops ``collect``/``encode``) and extended host-side
  at every later hop — wire receive + CRC verify (one stamp, ``recv``:
  both lanes verify in the same pass), ingest decode (``consume``),
  buffer admission (``admit``), consume gather (``gather``; ring
  residency = gather − admit), and train dispatch (``dispatch``) — on
  both the socket and shm lanes, through both codecs. Serve
  request/reply frames carry the same record (``encode``→``recv``→
  ``reply``→``done``).

* **Clock alignment.** Every timestamp is ``time.monotonic()`` plus a
  per-process epoch offset captured at import, so intra-process deltas
  are monotonic-exact and cross-process joins are wall-clock-aligned.
  Same-host processes (the shm lane's whole premise, and the chaos
  harness topology) share one monotonic source modulo the offset
  capture jitter (µs); cross-host joins inherit NTP error — documented
  in docs/ARCHITECTURE.md "Pipeline tracing".

* **Lifecycle events** stream to a per-process JSONL trace log
  (``--trace-jsonl``), sampled via ``telemetry.trace_sample_n``.
  Records are enqueued LOCK-FREE on the hot path (a GIL-atomic deque
  append — the SnapshotEngine division of labor) and drained by one
  writer thread; when tracing is off the hot paths pay exactly one
  pointer test (``tracing.get() is None`` captured at construction —
  the ``utils/faults.py`` discipline, pinned by test).
  ``scripts/trace_report.py`` joins the logs of a learner+actors+serve
  run into per-chunk latency histograms, a critical-path breakdown,
  and a weight-staleness attribution table.

* **Device observability hooks.** :func:`instrument_jit` wraps the jit
  entry points the learner/buffer/serve own: per-program compile and
  retrace counters (``compile/<program>/...`` + the process-wide
  ``compile/{compiles,retraces}_total``), elapsed compile time, and
  XLA cost analysis (flops / bytes accessed) logged ONCE per compile —
  never per step. :func:`update_memory_gauges` reads
  ``jax.local_devices()`` memory stats into ``mem/hbm_peak_bytes``,
  degrading to 0 on backends (CPU) that report none.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from dotaclient_tpu.utils import telemetry

__all__ = [
    "Tracer",
    "TraceWriter",
    "configure",
    "get",
    "shutdown",
    "now",
    "ensure_metrics",
    "new_record",
    "record_to_blob",
    "parse_blob",
    "append_hop",
    "instrument_jit",
    "update_memory_gauges",
]

# Epoch-aligned monotonic clock: monotonic deltas within a process,
# wall-aligned across processes (captured once; see module docstring).
_EPOCH_OFFSET = time.time() - time.monotonic()


def now() -> float:
    """Epoch-aligned monotonic timestamp (seconds)."""
    return time.monotonic() + _EPOCH_OFFSET


# Wire blobs are padded to a fixed width so the native encoder's
# per-layout template cache (serialize._SPEC_CACHE keys on shapes) sees
# ONE traced layout per rollout structure instead of one per blob length.
TRACE_WIRE_LEN = 192

def ensure_metrics(registry: Optional[telemetry.Registry] = None) -> None:
    """Eager-create the trace/compile/mem keys so
    `check_telemetry_schema.py --require-trace` validates any learner
    JSONL deterministically (zeros when nothing fired)."""
    reg = registry if registry is not None else telemetry.get_registry()
    for key in (
        "trace/emitted_total",
        "trace/dropped_total",
        "compile/compiles_total",
        "compile/retraces_total",
        "compile/compile_time_s_total",
    ):
        reg.counter(key)
    reg.gauge("mem/hbm_peak_bytes")


# -- trace records -----------------------------------------------------------
#
# Host form: {"tid": str, "pid": int, "actor": int, "wv": int,
#             "hops": [[name, ts], ...]}.
# Wire form: newline-joined ASCII, one header line then one line per hop,
# padded with spaces to TRACE_WIRE_LEN:
#     tid=<id> pid=<int> actor=<int> wv=<int>
#     h <name> <ts.6f>


def new_record(tid: str, actor: int, weights_version: int) -> Dict[str, Any]:
    return {
        "tid": tid,
        "pid": os.getpid(),
        "actor": int(actor),
        "wv": int(weights_version),
        "hops": [],
    }


def append_hop(
    record: Dict[str, Any], name: str, ts: Optional[float] = None
) -> Dict[str, Any]:
    record["hops"].append([name, now() if ts is None else ts])
    return record


def record_to_blob(record: Dict[str, Any], pad: bool = True) -> bytes:
    lines = [
        f"tid={record['tid']} pid={record['pid']} "
        f"actor={record['actor']} wv={record['wv']}"
    ]
    lines += [f"h {name} {ts:.6f}" for name, ts in record["hops"]]
    blob = "\n".join(lines).encode()
    if pad and len(blob) < TRACE_WIRE_LEN:
        blob = blob.ljust(TRACE_WIRE_LEN, b" ")
    return blob


def parse_blob(blob: Any) -> Optional[Dict[str, Any]]:
    """Wire blob → host record; None on anything unparseable (a corrupt
    trace entry must never take a consume path down)."""
    try:
        text = bytes(blob).decode("ascii", "replace")
    except (TypeError, ValueError):
        return None
    record: Optional[Dict[str, Any]] = None
    for line in text.split("\n"):
        line = line.strip()
        if not line:
            continue
        if line.startswith("tid="):
            fields = dict(
                kv.split("=", 1) for kv in line.split() if "=" in kv
            )
            try:
                record = {
                    "tid": fields["tid"],
                    "pid": int(fields["pid"]),
                    "actor": int(fields["actor"]),
                    "wv": int(fields["wv"]),
                    "hops": [],
                }
            except (KeyError, ValueError):
                return None
        elif line.startswith("h ") and record is not None:
            parts = line.split()
            if len(parts) == 3:
                try:
                    record["hops"].append([parts[1], float(parts[2])])
                except ValueError:
                    return None
    return record


def stamp_serve_recv(meta: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Serve-lane twin of :func:`stamp_wire_hops`: one ``recv`` stamp
    (receive + CRC verify happen in the same ``_recv_frame`` pass) on a
    decoded request's record."""
    record = parse_blob(meta.get("trace_blob"))
    if record is None:
        return None
    record["hops"].append(["recv", now()])
    meta["trace"] = record
    return record


def weights_record(version: int) -> Dict[str, Any]:
    """The publish-side trace record a weights frame carries (ISSUE 12):
    origin pid + a ``publish`` hop, so actor-side apply events can
    attribute fanout latency. ``actor=-1`` marks the learner origin."""
    rec = new_record(f"w{os.getpid():x}-{int(version):x}", -1, version)
    return append_hop(rec, "publish")


def stamp_wire_hops(
    meta: Dict[str, Any], recv_ts: Optional[float]
) -> Optional[Dict[str, Any]]:
    """Promote a decoded payload's raw in-band blob (``meta["trace_blob"]``)
    to the host record (``meta["trace"]``) and stamp the learner-side
    ingest hops: ``recv`` (transport receive + CRC verify — one stamp,
    both lanes verify in the same pass) and ``consume`` (drain decode).
    An unparseable blob is silently dropped — tracing must never take a
    consume path down."""
    record = parse_blob(meta.get("trace_blob"))
    if record is None:
        return None
    if recv_ts is not None:
        record["hops"].append(["recv", recv_ts])
    record["hops"].append(["consume", now()])
    meta["trace"] = record
    return record


# -- the writer thread -------------------------------------------------------


class TraceWriter:
    """Per-process trace-event sink: lock-free producer deque + ONE writer
    thread appending JSON lines (the SnapshotEngine division of labor —
    hot paths never touch the file). The queue is bounded: when the
    writer falls behind, NEW events drop (counted in
    ``trace/dropped_total``) — a wedged disk must never backpressure the
    train loop. Every drained batch is flushed line-complete, so a
    SIGKILL'd process (the chaos harness's bread and butter) tears at
    most the line the OS was mid-writing — which the shared
    torn-line-tolerant reader (``telemetry.load_jsonl``) drops."""

    MAX_QUEUE = 8192

    def __init__(
        self, path: str, registry: Optional[telemetry.Registry] = None
    ) -> None:
        reg = registry if registry is not None else telemetry.get_registry()
        self._emitted = reg.counter("trace/emitted_total")
        self._dropped = reg.counter("trace/dropped_total")
        # line-buffered: each write() is one complete line on disk
        self._f = open(path, "a", buffering=1)
        self._queue: deque = deque()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._run, name="trace-writer", daemon=True
        )
        self._thread.start()

    def enqueue(self, event: Dict[str, Any]) -> None:
        """Hot-path entry: one length check + one GIL-atomic append."""
        if self._stopped:
            return
        if len(self._queue) >= self.MAX_QUEUE:
            self._dropped.inc()
            return
        self._queue.append(event)

    def _run(self) -> None:
        while True:
            drained = 0
            while self._queue:
                event = self._queue.popleft()
                try:
                    self._f.write(json.dumps(event, sort_keys=True) + "\n")
                except (OSError, ValueError, TypeError):
                    self._dropped.inc()
                    continue
                drained += 1
            if drained:
                self._emitted.inc(drained)
                try:
                    self._f.flush()
                except OSError:
                    pass
            if self._stopped and not self._queue:
                return
            time.sleep(0.05)

    def close(self, timeout: float = 5.0) -> None:
        """Drain and stop the writer, then close the file durably
        (flush + fsync — the atomic-close half of the JsonlSink
        durability contract)."""
        self._stopped = True
        self._thread.join(timeout)
        # lint-ok: thread-ownership(join() above — the writer thread has
        # provably exited before this thread touches the file)
        f = self._f
        try:
            f.flush()
            os.fsync(f.fileno())
        except (OSError, ValueError):
            pass
        try:
            f.close()
        except OSError:
            pass


class Tracer:
    """Sampling + event emission for one process."""

    def __init__(
        self,
        jsonl_path: Optional[str],
        sample_n: int,
        registry: Optional[telemetry.Registry] = None,
    ) -> None:
        self.sample_n = max(1, int(sample_n))
        self._seq = 0
        self.pid = os.getpid()
        self._writer = (
            TraceWriter(jsonl_path, registry) if jsonl_path else None
        )

    def should_sample(self) -> bool:
        """One int increment + one modulo — the whole tracing-enabled
        hot-path cost for an unsampled chunk."""
        self._seq += 1
        return self._seq % self.sample_n == 0

    def next_tid(self, actor: int) -> str:
        return f"{self.pid:x}-{actor & 0xFFFF:x}-{self._seq:x}"

    def emit(self, event: str, **fields: Any) -> None:
        if self._writer is not None:
            self._writer.enqueue(
                {"ts": now(), "pid": self.pid, "event": event, **fields}
            )

    def emit_chunk(self, record: Dict[str, Any]) -> None:
        """One chunk's merged trace record (emitted at its terminal hop
        in this process)."""
        if self._writer is not None:
            self._writer.enqueue(
                {
                    "ts": now(),
                    "pid": self.pid,
                    "event": "chunk",
                    "tid": record["tid"],
                    "origin_pid": record["pid"],
                    "actor": record["actor"],
                    "wv": record["wv"],
                    # snapshot, not alias: the in-proc delivery path keeps
                    # appending hops to the live record after this emit,
                    # racing the writer thread's serialization otherwise
                    "hops": list(record["hops"]),
                }
            )

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


_ACTIVE: Optional[Tracer] = None


def get() -> Optional[Tracer]:
    """The process tracer, or None when tracing is off. Hot paths capture
    this ONCE at construction (the faults.get() discipline) so the
    disabled cost is a single ``is not None`` test."""
    return _ACTIVE


def configure(
    jsonl_path: Optional[str],
    sample_n: Optional[int] = None,
    registry: Optional[telemetry.Registry] = None,
) -> Optional[Tracer]:
    """Install (or, with ``jsonl_path=None``, remove) the process tracer.
    Call BEFORE constructing pools/buffers/learners — they capture
    ``get()`` at init. ``sample_n`` defaults to
    ``telemetry.trace_sample_n``."""
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.close()
        _ACTIVE = None
    if jsonl_path is None:
        return None
    ensure_metrics(registry)
    n = telemetry.trace_sample_n if sample_n is None else sample_n
    _ACTIVE = Tracer(jsonl_path, n, registry)
    return _ACTIVE


def shutdown() -> None:
    """Flush and close the process tracer (clean-exit paths)."""
    configure(None)


# -- device observability hooks ----------------------------------------------


class InstrumentedJit:
    """Transparent wrapper over a jitted callable counting compiles.

    Detection: ``jax.jit``'s C++ dispatch cache grows by one entry per
    compiled signature; comparing ``_cache_size()`` around the call
    costs two cheap host reads per dispatch and zero device traffic.
    On a compile (cache grew — or, when the backend exposes no cache
    probe, the wrapper's first call) the per-program and process-wide
    counters advance, elapsed time (trace + compile + first execution;
    compile dominates) is recorded, and XLA cost analysis runs ONCE —
    never per step. ``retraces`` = compiles beyond this wrapper's first
    (the "a shape bump recompiled the program" signal).

    Attribute access (``.lower``, ``._cache_size``) delegates to the
    wrapped function, so call sites that introspect the jit keep
    working.
    """

    def __init__(
        self,
        fn: Any,
        name: str,
        registry: Optional[telemetry.Registry] = None,
    ) -> None:
        reg = registry if registry is not None else telemetry.get_registry()
        self._fn = fn
        self._name = name
        self._seen = 0
        self._compiles = reg.counter("compile/compiles_total")
        self._retraces = reg.counter("compile/retraces_total")
        self._time = reg.counter("compile/compile_time_s_total")
        # per-program keys: program names are the finite set declared in
        # lint/telemetry_drift.py DYNAMIC_KEY_EXPANSIONS — add new names
        # there (and to the ARCHITECTURE wildcard row) when instrumenting
        # a new entry point
        self._p_compiles = reg.counter(f"compile/{name}/compiles_total")
        self._p_retraces = reg.counter(f"compile/{name}/retraces_total")
        self._p_last = reg.gauge(f"compile/{name}/last_compile_s")

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        n0 = self._cache_entries()
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        n1 = self._cache_entries()
        if (n1 is not None and n0 is not None and n1 > n0) or (
            n1 is None and self._seen == 0
        ):
            self._on_compile(time.perf_counter() - t0, args, kwargs)
        return out

    def _cache_entries(self) -> Optional[int]:
        try:
            return self._fn._cache_size()
        except Exception:  # noqa: BLE001 - probe-free backends degrade
            return None

    def _on_compile(self, elapsed: float, args: tuple, kwargs: dict) -> None:
        self._seen += 1
        self._compiles.inc()
        self._p_compiles.inc()
        if self._seen > 1:
            self._retraces.inc()
            self._p_retraces.inc()
        self._time.inc(elapsed)
        self._p_last.set(elapsed)
        flops = bytes_accessed = 0.0
        try:
            # abstract re-trace only (no second backend compile); on a
            # donating program whose inputs were just consumed this can
            # raise on a deleted buffer — cost analysis then degrades to
            # zeros rather than ever touching the dispatch path
            cost = self._fn.lower(*args, **kwargs).cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else None
            if isinstance(cost, dict):
                flops = float(cost.get("flops", 0.0) or 0.0)
                bytes_accessed = float(
                    cost.get("bytes accessed", 0.0) or 0.0
                )
        except Exception:  # noqa: BLE001 - analysis is best-effort
            pass
        tracer = get()
        if tracer is not None:
            tracer.emit(
                "compile",
                program=self._name,
                n=self._seen,
                elapsed_s=round(elapsed, 6),
                flops=flops,
                bytes_accessed=bytes_accessed,
            )

    def __getattr__(self, item: str) -> Any:
        return getattr(object.__getattribute__(self, "_fn"), item)


def instrument_jit(
    fn: Any, name: str, registry: Optional[telemetry.Registry] = None
) -> InstrumentedJit:
    """Wrap a jitted callable with compile/retrace accounting. The
    donation lint (lint/donation.py) unwraps this call, so
    ``self.step = tracing.instrument_jit(jax.jit(..., donate_argnums=...),
    "step")`` keeps its use-after-donate tracking."""
    return InstrumentedJit(fn, name, registry)


def update_memory_gauges(
    registry: Optional[telemetry.Registry] = None,
) -> float:
    """Refresh ``mem/hbm_peak_bytes`` from the local devices' allocator
    stats (max peak across devices). Host-only metadata reads — safe at
    log-boundary cadence. CPU backends report no stats → gauge stays at
    its eager-created 0 (graceful degrade, pinned by test)."""
    reg = registry if registry is not None else telemetry.get_registry()
    peak = 0.0
    try:
        import jax

        for dev in jax.local_devices():
            stats = None
            try:
                stats = dev.memory_stats()
            except Exception:  # noqa: BLE001 - backend without stats
                stats = None
            if stats:
                peak = max(peak, float(stats.get("peak_bytes_in_use", 0)))
    except Exception:  # noqa: BLE001 - no backend at all (import-light use)
        peak = 0.0
    if peak:
        reg.gauge("mem/hbm_peak_bytes").set(peak)
    else:
        reg.gauge("mem/hbm_peak_bytes")
    return peak
