"""Host-side metrics emission — a thin facade over ``utils.telemetry``.

Parity with the reference's tensorboardX scalar set — loss terms, entropy,
reward components, rollout throughput, win-rate (SURVEY.md §5.5;
reconstructed — the reference checkout was an empty mount) — extended with
the pipeline telemetry registry: every ``log()`` merges the registry
snapshot (per-stage spans, queue/staleness/occupancy gauges) into the
emitted scalars, so the ``*_recent`` window-stat keys and the telemetry
keys travel through the same sinks.

Sinks: console (legacy short line — telemetry keys are elided there),
tensorboardX when available (a missing install degrades to a warning, never
a crash), and JSONL for headless/bench runs. Metrics arrive as jit-returned
device dicts already fetched by the caller; everything here is host-side and
out of the hot path.
"""

from __future__ import annotations

import time
from typing import Dict, Mapping, Optional

import numpy as np

from dotaclient_tpu.utils import telemetry


class MetricsLogger:
    def __init__(
        self,
        logdir: Optional[str] = None,
        console: bool = True,
        jsonl: Optional[str] = None,
        registry: Optional[telemetry.Registry] = None,
    ) -> None:
        self.registry = registry if registry is not None else telemetry.get_registry()
        self.console = console
        self._t0 = time.time()
        self._sinks = []
        if console:
            self._sinks.append(telemetry.ConsoleSink(self._t0))
        if logdir is not None:
            tb = telemetry.TensorBoardSink.create(logdir)
            if tb is not None:
                self._sinks.append(tb)
        if jsonl is not None:
            self._sinks.append(telemetry.JsonlSink(jsonl))

    def log(
        self, step: int, scalars: Mapping[str, float], prefix: str = ""
    ) -> Dict[str, float]:
        """Emit ``scalars`` plus the registry snapshot to every sink;
        returns the merged flat dict (what a caller should retain as the
        last-logged metrics)."""
        flat = {f"{prefix}{k}": float(np.asarray(v)) for k, v in scalars.items()}
        return self._emit(step, flat, console=True)

    def log_files_only(
        self, step: int, scalars: Mapping[str, float]
    ) -> Dict[str, float]:
        """Like :meth:`log` but skips the console sink — the end-of-run
        snapshot that closes a JSONL record without spamming stdout."""
        flat = {k: float(np.asarray(v)) for k, v in scalars.items()}
        return self._emit(step, flat, console=False)

    def _emit(
        self, step: int, flat: Dict[str, float], console: bool
    ) -> Dict[str, float]:
        flat.update(self.registry.snapshot())
        for sink in self._sinks:
            if console or not isinstance(sink, telemetry.ConsoleSink):
                sink.emit(step, flat)
        return flat

    def emit_event(self, event: Dict[str, object]) -> None:
        """Broadcast one structured event (the ``ALERT`` channel, ISSUE
        13) to every sink that speaks events (the JSONL sink's
        flush-per-emit line), plus one greppable console line — the
        chaos harness and operators both read it. Thread-safe: called
        from the fleet aggregator's thread."""
        import json

        for sink in self._sinks:
            fn = getattr(sink, "emit_event", None)
            if fn is not None:
                fn(event)
        if self.console:
            print(
                f"ALERT {json.dumps(event, sort_keys=True)}", flush=True
            )

    def close(self) -> None:
        for sink in self._sinks:
            sink.close()
