"""Host-side metrics emission: TensorBoard scalars + console.

Parity with the reference's tensorboardX scalar set — loss terms, entropy,
reward components, rollout throughput, win-rate (SURVEY.md §5.5;
reconstructed — the reference checkout was an empty mount). Metrics arrive as
jit-returned device dicts; everything here is host-side and out of the hot
path.
"""

from __future__ import annotations

import time
from typing import Dict, Mapping, Optional

import numpy as np


class MetricsLogger:
    def __init__(self, logdir: Optional[str] = None, console: bool = True) -> None:
        self._writer = None
        self.console = console
        if logdir is not None:
            from tensorboardX import SummaryWriter

            self._writer = SummaryWriter(logdir)
        self._t0 = time.time()

    def log(self, step: int, scalars: Mapping[str, float], prefix: str = "") -> None:
        flat: Dict[str, float] = {}
        for k, v in scalars.items():
            name = f"{prefix}{k}"
            flat[name] = float(np.asarray(v))
        if self._writer is not None:
            for name, v in flat.items():
                self._writer.add_scalar(name, v, step)
        if self.console:
            parts = " ".join(f"{k}={v:.4g}" for k, v in sorted(flat.items()))
            print(f"[{time.time() - self._t0:8.1f}s] step {step}: {parts}", flush=True)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
