"""Comma-separated dataclass-field overrides for CLI flags.

One shared parser behind every ``--ppo a=1,b=2``-style flag (the demo's
fine-tune knobs and the learner CLI's cluster parity), so field-name
validation, type casting, and enum checks cannot drift between
entrypoints. Raises ``ValueError`` — callers map it to their own error
surface (``argparse.error`` in the CLIs).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict


def parse_dataclass_overrides(cls: Any, text: str, flag: str) -> Dict[str, Any]:
    """Parse ``"k=v,k2=v2"`` into a dict of typed values for ``cls`` fields.

    Casting follows the field's declared type: str fields take the raw
    string, int fields ``int()``, bool fields accept true/false/1/0,
    everything else ``float()``. Unknown names and uncastable values
    raise ``ValueError`` mentioning ``flag``.
    """
    fields = {f.name: f.type for f in dataclasses.fields(cls)}
    out: Dict[str, Any] = {}
    for kv in text.split(","):
        k, _, v = kv.partition("=")
        k = k.strip()
        if k not in fields:
            raise ValueError(
                f"{flag}: unknown field {k!r} (one of {sorted(fields)})"
            )
        if fields[k] in (str, "str"):
            caster: Any = str
        elif fields[k] in (bool, "bool"):
            def caster(s: str) -> bool:   # noqa: E731 — named for errors
                low = s.lower()
                if low in ("true", "1"):
                    return True
                if low in ("false", "0"):
                    return False
                raise ValueError(s)

            caster.__name__ = "bool"
        elif fields[k] in (int, "int"):
            caster = int
        else:
            caster = float
        try:
            out[k] = caster(v.strip())
        except ValueError:
            raise ValueError(
                f"{flag}: bad {caster.__name__} for {k!r}: {v!r}"
            ) from None
    # Enum-like string fields die at parse time, not minutes later at the
    # first train-step trace (after initial evals burned TPU wall-clock).
    if "adv_norm" in fields and out.get("adv_norm") is not None:
        from dotaclient_tpu.config import ADV_NORM_MODES

        if out["adv_norm"] not in ADV_NORM_MODES:
            raise ValueError(
                f"{flag}: adv_norm must be one of {ADV_NORM_MODES}, "
                f"got {out['adv_norm']!r}"
            )
    if "advantage" in fields and out.get("advantage") is not None:
        from dotaclient_tpu.config import ADVANTAGE_MODES

        if out["advantage"] not in ADVANTAGE_MODES:
            raise ValueError(
                f"{flag}: advantage must be one of {ADVANTAGE_MODES}, "
                f"got {out['advantage']!r}"
            )
    if "request_wire_dtype" in fields and out.get("request_wire_dtype") is not None:
        from dotaclient_tpu.transport.serialize import ROLLOUT_WIRE_DTYPES

        if out["request_wire_dtype"] not in ROLLOUT_WIRE_DTYPES:
            raise ValueError(
                f"{flag}: request_wire_dtype must be one of "
                f"{ROLLOUT_WIRE_DTYPES}, got {out['request_wire_dtype']!r}"
            )
    return out
