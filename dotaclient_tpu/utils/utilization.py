"""Pipeline utilization plane: always-on phase accounting (ISSUE 16).

Attributes every wall-clock second of a process to one of a CLOSED set of
phases via monotonic interval accumulation at the phase boundaries the
code already has — no sampling (PR 12's tracing samples 1/N chunks and
merges offline; this plane is the always-on complement), no extra device
work, no per-step host syncs. Each process class gets its own taxonomy:

* **learner** — ``dispatch_inflight`` (the donated train step: measured
  as the host time inside the dispatch call, which in a throughput-bound
  loop blocks on donation/back-pressure and is the host-observable proxy
  for device busy time), ``ingest_wait`` (buffer below min consumable),
  ``gather`` (batch staging/assembly), ``advantage_pass`` (consume-time
  value+GAE host dispatch), ``publish_stall``, ``checkpoint_stall``, and
  the residual ``host_other``. Duty cycle = the dispatch_inflight
  fraction of the fold window.
* **actor pools** (host + vec) — ``env_step`` / ``featurize`` /
  ``encode`` / ``ship_wait`` + residual ``other``.
* **serve** — ``window_wait`` / ``dispatch`` / ``reply`` + residual
  ``other`` on the batcher thread.

Fractions are normalized by the fold window so they sum to 1.0 by
construction (the residual absorbs unattributed time; clock noise is
clamped). The learner fold additionally maintains a rolling steps/s EMA
and a slow warmup-armed baseline EMA: ``util/throughput_regression``
latches to 1 while the fast EMA drops below ``REGRESSION_RATIO`` × the
baseline — the cross-run perf-regression sentinel two alert rules watch
(``learner_duty_cycle_low``, ``throughput_regression``; see
``utils/alerts.py`` and docs/OPERATIONS.md).

Cost discipline (the ``faults.get()`` pattern, pinned by tests): every
factory eager-creates its ``util/*`` gauges so
``check_telemetry_schema.py --require-utilization`` validates ANY
learner JSONL deterministically, then returns ``None`` when the module
knob ``enabled`` is off — a disabled call site costs one pointer test.
``util/duty_cycle`` initializes to the neutral 1.0 (and ``util/armed``
to 0) so the duty-cycle alert cannot fire before the first fold arms the
plane.
"""

from __future__ import annotations

import math
import time
from typing import Dict, Optional, Tuple

from dotaclient_tpu.utils import telemetry

# Module knob: bench.py's utilization stage flips this off for its
# baseline variant; everything else leaves it on (the plane is designed
# to be always-on — the bench stage gates its overhead at <= 2%).
enabled: bool = True

# steps/s smoothing: the fast EMA tracks the current regime, the slow
# baseline EMA remembers the run's demonstrated throughput. Both are
# TIME-CONSTANT weighted (alpha = 1 - exp(-window / tau)): fold windows
# on the fused path vary from milliseconds (host racing ahead of async
# dispatches) to seconds (blocked on donation), and a fixed per-sample
# alpha would let a 20 ms window's wild rate swing the EMA as hard as a
# 10 s one. Warmup seeds both EMAs with the CUMULATIVE rate over the
# whole warmup span for the same reason; the sentinel arms only after
# WARMUP_WINDOWS folds and trips when fast < ratio * slow.
EMA_TAU_S = 30.0
BASELINE_TAU_S = 600.0
WARMUP_WINDOWS = 3
REGRESSION_RATIO = 0.7

LEARNER_PHASES = (
    "dispatch_inflight",
    "ingest_wait",
    "gather",
    "advantage_pass",
    "publish_stall",
    "checkpoint_stall",
    "host_other",
)
ACTOR_PHASES = ("env_step", "featurize", "encode", "ship_wait", "other")
SERVE_PHASES = ("window_wait", "dispatch", "reply", "other")


def ensure_learner_keys(reg: telemetry.Registry) -> Dict[str, telemetry.Gauge]:
    """Eager-create the learner-side ``util/*`` gauges; returns handles.

    Called even when the plane is disabled, so the schema tier holds for
    any learner JSONL. Key names are literal (the telemetry-drift lint
    statically resolves every emission)."""
    handles: Dict[str, telemetry.Gauge] = {}
    for key in (
        "util/armed",
        "util/duty_cycle",
        "util/steps_per_sec_ema",
        "util/steps_per_sec_baseline",
        "util/throughput_regression",
        "util/phase/dispatch_inflight",
        "util/phase/ingest_wait",
        "util/phase/gather",
        "util/phase/advantage_pass",
        "util/phase/publish_stall",
        "util/phase/checkpoint_stall",
        "util/phase/host_other",
    ):
        handles[key] = reg.gauge(key)
    # neutral until the first fold: an eager-created 0.0 would trip the
    # learner_duty_cycle_low rule before any accounting happened
    if handles["util/armed"].value == 0.0:
        handles["util/duty_cycle"].set(1.0)
    return handles


def ensure_actor_keys(reg: telemetry.Registry) -> Dict[str, telemetry.Gauge]:
    """Eager-create the actor-pool ``util/actor/*`` phase gauges."""
    handles: Dict[str, telemetry.Gauge] = {}
    for key in (
        "util/actor/env_step",
        "util/actor/featurize",
        "util/actor/encode",
        "util/actor/ship_wait",
        "util/actor/other",
    ):
        handles[key] = reg.gauge(key)
    return handles


def ensure_serve_keys(reg: telemetry.Registry) -> Dict[str, telemetry.Gauge]:
    """Eager-create the serve-side ``util/serve/*`` phase gauges."""
    handles: Dict[str, telemetry.Gauge] = {}
    for key in (
        "util/serve/window_wait",
        "util/serve/dispatch",
        "util/serve/reply",
        "util/serve/other",
    ):
        handles[key] = reg.gauge(key)
    return handles


class PhaseAccountant:
    """Monotonic interval accumulator over a closed phase set.

    Single-thread owned (the lint ownership map pins which thread):
    ``phase()`` adds a measured interval to a named bucket, ``fold()``
    normalizes the window into per-phase fraction gauges — the residual
    phase absorbs whatever the buckets did not claim — and resets. The
    fractions sum to 1.0 by construction: the denominator is
    ``max(window, accounted)``, so clock noise (accounted microseconds
    past the window edge) shrinks the residual to 0 instead of pushing
    the sum past 1."""

    def __init__(
        self,
        gauges: Dict[str, telemetry.Gauge],
        phases: Tuple[str, ...],
        residual: str,
        now: Optional[float] = None,
    ) -> None:
        self._gauges = gauges
        self._phases = phases
        self._residual = residual
        self._acc: Dict[str, float] = {p: 0.0 for p in phases}
        self._window_start = time.perf_counter() if now is None else now

    def phase(self, name: str, seconds: float) -> None:
        if seconds > 0.0:
            self._acc[name] += seconds

    def fold(
        self, now: Optional[float] = None
    ) -> Tuple[Dict[str, float], float]:
        """→ (phase fractions, window seconds); resets the window."""
        now = time.perf_counter() if now is None else now
        window = now - self._window_start
        if window <= 0.0:
            return {}, 0.0
        accounted = sum(self._acc.values())
        residual_s = max(0.0, window - accounted)
        denom = max(window, accounted)
        fractions: Dict[str, float] = {}
        for name in self._phases:
            v = residual_s if name == self._residual else self._acc[name]
            frac = v / denom
            self._gauges[name].set(frac)
            fractions[name] = frac
            self._acc[name] = 0.0
        self._window_start = now
        return fractions, window


class LearnerUtilization:
    """The learner's accountant + the throughput sentinel state.

    ``fold(step)`` runs at the existing host-sync boundaries (the
    ``_publish_pipeline_gauges`` sites — log_every cadence and the final
    flush), so the plane adds zero per-step host work beyond interval
    arithmetic."""

    def __init__(self, handles: Dict[str, telemetry.Gauge]) -> None:
        phase_gauges = {
            p: handles[f"util/phase/{p}"] for p in LEARNER_PHASES
        }
        self._acct = PhaseAccountant(
            phase_gauges, LEARNER_PHASES, residual="host_other"
        )
        self._armed = handles["util/armed"]
        self._duty = handles["util/duty_cycle"]
        self._ema = handles["util/steps_per_sec_ema"]
        self._baseline = handles["util/steps_per_sec_baseline"]
        self._regression = handles["util/throughput_regression"]
        self._last_step: Optional[int] = None
        self._ema_v = 0.0
        self._baseline_v = 0.0
        self._windows = 0
        self._warm_steps = 0
        self._warm_span = 0.0

    def phase(self, name: str, seconds: float) -> None:
        self._acct.phase(name, seconds)

    def fold(
        self, step: int, now: Optional[float] = None
    ) -> Dict[str, float]:
        fractions, window = self._acct.fold(now)
        if not fractions:
            return {}
        self._duty.set(fractions["dispatch_inflight"])
        self._armed.set(1.0)
        # step must have ADVANCED: a zero-step window only happens when a
        # boundary double-folds (the end-of-run flush re-folding at the
        # final step) — a rate-0 sample there would poison the EMA and
        # spuriously latch the sentinel. A genuinely wedged learner never
        # reaches a fold at all (the duty-cycle rule covers that mode).
        if self._last_step is not None and step > self._last_step:
            rate = (step - self._last_step) / window
            self._windows += 1
            if self._windows <= WARMUP_WINDOWS:
                # warmup: both EMAs track the cumulative rate over the
                # whole warmup span — duration-weighted by construction,
                # so a 20 ms host-racing window cannot arm the baseline
                # at an anomalous regime; the sentinel stays disarmed
                # through compile transients either way
                self._warm_steps += step - self._last_step
                self._warm_span += window
                self._ema_v = self._warm_steps / self._warm_span
                self._baseline_v = self._ema_v
            else:
                a_fast = 1.0 - math.exp(-window / EMA_TAU_S)
                a_slow = 1.0 - math.exp(-window / BASELINE_TAU_S)
                self._ema_v += a_fast * (rate - self._ema_v)
                self._baseline_v += a_slow * (rate - self._baseline_v)
            self._ema.set(self._ema_v)
            self._baseline.set(self._baseline_v)
            regressed = (
                self._windows > WARMUP_WINDOWS
                and self._baseline_v > 0.0
                and self._ema_v < REGRESSION_RATIO * self._baseline_v
            )
            self._regression.set(1.0 if regressed else 0.0)
        self._last_step = step
        return fractions


class PoolUtilization:
    """Actor/serve accountant: phase fractions + a cadence-gated fold
    (one monotonic compare per loop turn when due-check fails)."""

    def __init__(
        self,
        gauges: Dict[str, telemetry.Gauge],
        phases: Tuple[str, ...],
        prefix: str,
        interval_s: float,
    ) -> None:
        phase_gauges = {p: gauges[f"{prefix}{p}"] for p in phases}
        self._acct = PhaseAccountant(phase_gauges, phases, residual="other")
        self._interval = max(0.25, float(interval_s))  # host-sync-ok: host-only config scalar
        self._last_fold = time.perf_counter()

    def phase(self, name: str, seconds: float) -> None:
        self._acct.phase(name, seconds)

    def maybe_fold(
        self, now: Optional[float] = None
    ) -> Optional[Dict[str, float]]:
        now = time.perf_counter() if now is None else now
        if now - self._last_fold < self._interval:
            return None
        self._last_fold = now
        fractions, _ = self._acct.fold(now)
        return fractions or None


def make_learner(
    registry: Optional[telemetry.Registry] = None,
) -> Optional[LearnerUtilization]:
    reg = registry if registry is not None else telemetry.get_registry()
    handles = ensure_learner_keys(reg)
    if not enabled:
        return None
    return LearnerUtilization(handles)


def make_actor(
    registry: Optional[telemetry.Registry] = None,
    interval_s: Optional[float] = None,
) -> Optional[PoolUtilization]:
    reg = registry if registry is not None else telemetry.get_registry()
    handles = ensure_actor_keys(reg)
    if not enabled:
        return None
    itv = telemetry.fleet_interval_s if interval_s is None else interval_s
    return PoolUtilization(handles, ACTOR_PHASES, "util/actor/", itv)


def make_serve(
    registry: Optional[telemetry.Registry] = None,
    interval_s: Optional[float] = None,
) -> Optional[PoolUtilization]:
    reg = registry if registry is not None else telemetry.get_registry()
    handles = ensure_serve_keys(reg)
    if not enabled:
        return None
    itv = telemetry.fleet_interval_s if interval_s is None else interval_s
    return PoolUtilization(handles, SERVE_PHASES, "util/serve/", itv)
