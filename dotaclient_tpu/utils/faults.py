"""Fault-injection registry: deterministic chaos for the pipeline's hot paths.

At production scale actor/learner fleets treat worker failure as a
steady-state condition, not an exception (Podracer, arXiv:2104.06272;
IMPACT, arXiv:1912.00167) — which means the failure paths are *code*, and
code that never runs rots. This module makes every failure mode the
fault-tolerance layer handles injectable on demand, so the chaos harness
(``scripts/chaos_run.py``) and the tier-1 chaos smoke (tests/test_faults.py)
can exercise them deterministically.

Spec grammar (env var ``DOTA_FAULTS`` or :func:`configure`): a
comma-separated list of entries, each one of

* ``site@N``  — trigger fault ``site`` on its Nth event (1-based, one-shot):
  ``transport.corrupt_frame@5`` corrupts exactly the 5th frame published.
* ``site@N+M`` — trigger on the Nth event and every Mth after it:
  ``transport.corrupt_frame@5+10`` corrupts frames 5, 15, 25, ...
* ``site=V``  — a value fault, read with :func:`FaultRegistry.value`:
  ``transport.delay_send=0.01`` sleeps 10 ms before every frame send.

Sites wired in this repo (grep for the literal to find the hook):

* ``transport.corrupt_frame``  — producer writes a corrupt CRC trailer
  (socket ``publish_rollout_bytes`` and the shm ring producer).
* ``transport.drop_conn``      — socket actor hard-closes its connection
  after the Nth published frame (simulated connection loss).
* ``transport.delay_send``     — seconds slept before each frame send.
* ``checkpoint.fail_write``    — ``CheckpointManager.save`` raises an
  injected ``OSError`` (simulated full disk) on its Nth call.
* ``learner.fail_train_step``  — ``Learner._optimize`` raises on its Nth
  call (exercises ``--on-crash-checkpoint``).
* ``learner.nan_grad``         — ``Learner._optimize`` poisons its Nth
  batch's rewards with NaN before dispatch (buffered train paths): the
  realistic NaN-gradient divergence the training health guardian
  (ISSUE 6, train/health.py) must detect, contain, and roll back.
* ``checkpoint.corrupt_manifest`` — the Nth integrity-manifest
  verification at restore fails as if the save were corrupt on disk
  (exercises the walk-back-to-previous-valid-save path).
* ``actor.nonfinite_payload``  — the vec actor pool poisons its Nth
  shipped rollout's rewards with NaN (exercises the learner buffer's
  semantic admission control, ``buffer/nonfinite_rejected_total``).

Cost discipline: the registry is **None when disabled** — hot paths cache
``faults.get()`` once at construction and the steady-state cost is a single
``is not None`` test (the shm drain hot loop carries no per-frame fault
branch at all; corruption is injected at the producer). Every actual firing
is counted in ``faults/injected_total`` so a chaos run can prove its
schedule executed.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

_ENV = "DOTA_FAULTS"


class FaultSpecError(ValueError):
    pass


class FaultRegistry:
    """Parsed fault spec + per-site event counters (thread-safe)."""

    def __init__(self, spec: str) -> None:
        self.spec = spec
        self._at: Dict[str, int] = {}        # site -> first event that fires
        self._every: Dict[str, int] = {}     # site -> repeat period (0 = once)
        self._values: Dict[str, float] = {}  # site -> value fault
        self._counts: Dict[str, int] = {}    # site -> events observed
        self._fired: Dict[str, int] = {}     # site -> times actually fired
        self._lock = threading.Lock()
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            if "=" in entry:
                site, _, raw = entry.partition("=")
                try:
                    self._values[site.strip()] = float(raw)
                except ValueError as e:
                    raise FaultSpecError(
                        f"bad value fault {entry!r}: {e}"
                    ) from e
            elif "@" in entry:
                site, _, raw = entry.partition("@")
                raw, _, period = raw.partition("+")
                try:
                    at = int(raw)
                    every = int(period) if period else 0
                except ValueError as e:
                    raise FaultSpecError(
                        f"bad trigger fault {entry!r}: {e}"
                    ) from e
                if at < 1 or every < 0:
                    raise FaultSpecError(
                        f"bad trigger fault {entry!r}: N must be >= 1"
                    )
                self._at[site.strip()] = at
                self._every[site.strip()] = every
            else:
                raise FaultSpecError(
                    f"fault entry {entry!r} is neither site@N nor site=V"
                )

    def fire(self, site: str) -> bool:
        """Record one event at ``site``; True when the spec says to inject.

        Sites absent from the spec never fire (and cost one dict miss)."""
        at = self._at.get(site)
        if at is None:
            return False
        with self._lock:
            self._counts[site] = n = self._counts.get(site, 0) + 1
            every = self._every[site]
            hit = n == at or (every > 0 and n > at and (n - at) % every == 0)
            if hit:
                self._fired[site] = self._fired.get(site, 0) + 1
        if hit:
            from dotaclient_tpu.utils import telemetry

            telemetry.get_registry().counter("faults/injected_total").inc()
        return hit

    def value(self, site: str, default: float = 0.0) -> float:
        """Value faults (``site=V``): the configured V, or ``default``."""
        return self._values.get(site, default)

    def fired(self, site: str) -> int:
        with self._lock:
            return self._fired.get(site, 0)


# Disabled == None: hot paths cache the result of get() and pay one
# ``is not None`` per event. Parsed lazily so importing this module costs
# nothing and subprocesses pick the spec up from their own environment.
_ACTIVE: Optional[FaultRegistry] = None
_LOADED = False
_LOAD_LOCK = threading.Lock()


def get() -> Optional[FaultRegistry]:
    """The process-wide registry, or None when fault injection is off."""
    global _ACTIVE, _LOADED
    if not _LOADED:
        with _LOAD_LOCK:
            if not _LOADED:
                spec = os.environ.get(_ENV, "")
                _ACTIVE = FaultRegistry(spec) if spec.strip() else None
                _LOADED = True
    return _ACTIVE


def configure(spec: Optional[str]) -> Optional[FaultRegistry]:
    """Install a spec programmatically (tests; None disables). Overrides the
    environment. NOTE: components cache ``get()`` at construction, so
    configure BEFORE building the transports/learner under test."""
    global _ACTIVE, _LOADED
    with _LOAD_LOCK:
        _ACTIVE = FaultRegistry(spec) if spec and spec.strip() else None
        _LOADED = True
    return _ACTIVE
