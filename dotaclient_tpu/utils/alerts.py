"""Runbook-encoded alert engine: the OPERATIONS.md failure table as code.

Every failure threshold the runbook documents — staleness spikes,
corrupt-frame streaks, buffer starvation, serve p99 blowups — used to be
prose a human had to notice after the fact. This module turns the runbook
into machinery (ISSUE 13): a declarative rule table over telemetry
registry values, evaluated on the fleet aggregator's thread at
``telemetry.fleet_interval_s`` cadence, with firing/resolving emitted as
structured ``ALERT`` JSONL events through the learner's metrics sink
(flush-per-emit, so a SIGKILL'd learner's last alerts survive).

Rule predicates (``AlertRule.kind``):

* ``threshold`` — compare the watched value against ``value`` with
  ``op`` (``>``/``<``/``>=``/``<=``).
* ``rate`` — rate of change of a (monotone) counter over ``window_s``
  seconds, compared ``> value`` per second. ``value=0`` means "any
  increase fires".
* ``stale`` — the watched key has not CHANGED for more than ``value``
  seconds (a heartbeat-shaped signal going quiet).

``for_s`` is the debounce: the condition must hold continuously that long
before the alert fires; a firing alert resolves at the first evaluation
where the condition clears. ``key`` may be an ``fnmatch`` pattern
(``fleet/*/serve/p99_latency_ms``) aggregated across matching keys with
``agg`` (``max`` for levels, ``sum`` for counters). A key with no data in
the snapshot is skipped — rules over planes a run does not exercise
(serve, fleet peers) stay silent instead of false-firing.

**Every rule carries a mandatory OPERATIONS.md runbook anchor**
(``rb:<name>``, a backticked token in the "Failure modes" table). The
``alert-drift`` pass of ``python -m dotaclient_tpu.lint`` cross-checks
BOTH ways: a rule can never point at a deleted runbook row, and every
documented failure mode must have a rule or an explicit entry in
``ALERT_WAIVERS`` naming why it is not machine-watchable. The "Alert
catalog" table in OPERATIONS.md mirrors this table row-for-row and is
checked against it too.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from dotaclient_tpu.utils import telemetry

__all__ = ["AlertRule", "AlertEngine", "RULES", "ALERT_WAIVERS"]


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative runbook rule. All fields are literals by contract —
    the ``alert-drift`` lint pass reads them via AST, so a computed field
    would escape the rules↔runbook cross-check."""

    name: str              # stable id (the Alert catalog row key)
    key: str               # registry key or fnmatch pattern it watches
    kind: str              # "threshold" | "rate" | "stale"
    value: float           # threshold level / rate-per-sec bound / stale seconds
    op: str = ">"          # threshold comparison
    window_s: float = 60.0   # rate-of-change lookback
    for_s: float = 0.0     # condition must hold this long before firing
    agg: str = "max"       # pattern-key aggregation: "max" | "sum"
    severity: str = "warn"   # "warn" | "page"
    runbook: str = ""      # MANDATORY `rb:<anchor>` in docs/OPERATIONS.md
    summary: str = ""


# The shipped rule table: the existing runbook, encoded. Thresholds are
# deliberately conservative defaults — each row's full triage story lives
# at its runbook anchor, and the Alert catalog table in OPERATIONS.md
# mirrors this tuple (both machine-checked by the alert-drift lint pass).
RULES: Tuple[AlertRule, ...] = (
    AlertRule(
        # buffer/batch_staleness, not actor/weight_staleness: the engine
        # only runs in external-transport mode, where the learner has no
        # in-process pool and pins actor/weight_staleness to 0 — the
        # consume-time gauge is the signal that actually moves there
        "weight_staleness_high", key="buffer/batch_staleness",
        kind="threshold", op=">", value=64.0, for_s=10.0, severity="warn",
        runbook="rb:staleness-spike",
        summary="consumed batches trained on weights > 64 versions old",
    ),
    AlertRule(
        "fleet_peer_stale", key="fleet/peers_stale",
        kind="threshold", op=">", value=0.0, for_s=0.0, severity="page",
        runbook="rb:fleet-peer-stale",
        summary="a fleet peer stopped reporting metric snapshots",
    ),
    AlertRule(
        "corrupt_frame_rate", key="transport/frames_corrupt_total",
        kind="rate", value=0.02, window_s=30.0, severity="warn",
        runbook="rb:corrupt-frames",
        summary="wire frames failing CRC faster than background rate",
    ),
    AlertRule(
        "peer_quarantined", key="transport/peers_quarantined",
        kind="rate", value=0.0, window_s=60.0, severity="page",
        runbook="rb:corrupt-frames",
        summary="a peer was quarantined for a poison-frame streak",
    ),
    AlertRule(
        "buffer_starved", key="buffer/occupancy",
        kind="threshold", op="<", value=0.02, for_s=60.0, severity="warn",
        runbook="rb:buffer-starvation",
        summary="trajectory ring near-empty: the learner is starved",
    ),
    AlertRule(
        "nonfinite_ingest", key="buffer/nonfinite_rejected_total",
        kind="rate", value=0.0, window_s=60.0, severity="warn",
        runbook="rb:nonfinite-payload",
        summary="actors shipping NaN/Inf payloads (admission rejecting)",
    ),
    AlertRule(
        "intbound_ingest", key="buffer/intbound_rejected_total",
        kind="rate", value=0.0, window_s=60.0, severity="warn",
        runbook="rb:intbound-reject",
        summary="f32-wire actor exceeding the narrow ring's int bounds",
    ),
    AlertRule(
        "stale_ingest_rejections", key="buffer/stale_rejected_total",
        kind="rate", value=0.5, window_s=30.0, severity="warn",
        runbook="rb:stale-rejection",
        summary="ingest rejecting over-stale frames faster than churn",
    ),
    AlertRule(
        "health_latched", key="health/nonfinite_steps_total",
        kind="rate", value=0.0, window_s=60.0, severity="page",
        runbook="rb:divergence",
        summary="the in-graph health probe flagged a non-finite step",
    ),
    AlertRule(
        "checkpoint_save_failures", key="checkpoint/save_failures_total",
        kind="rate", value=0.0, window_s=120.0, severity="page",
        runbook="rb:disk-full",
        summary="periodic checkpoint saves degrading (disk/permissions)",
    ),
    AlertRule(
        "manifest_failures", key="checkpoint/manifest_failures_total",
        kind="rate", value=0.0, window_s=120.0, severity="page",
        runbook="rb:corrupt-checkpoint",
        summary="checkpoint integrity manifests failing verification",
    ),
    AlertRule(
        "snapshot_errors", key="snapshot/errors_total",
        kind="rate", value=0.0, window_s=60.0, severity="warn",
        runbook="rb:snapshot-failures",
        summary="async snapshot jobs (publish/metrics) failing",
    ),
    AlertRule(
        "weights_publish_stalled", key="transport/weights_published",
        kind="stale", value=120.0, severity="warn",
        runbook="rb:snapshot-failures",
        summary="no weights publish reached the transport for 2 minutes",
    ),
    AlertRule(
        "trace_drops", key="trace/dropped_total",
        kind="rate", value=1.0, window_s=30.0, severity="warn",
        runbook="rb:trace-drops",
        summary="trace writer falling behind: events dropped",
    ),
    AlertRule(
        "serve_p99_over_budget", key="fleet/*/serve/p99_latency_ms",
        kind="threshold", op=">", value=100.0, agg="max", for_s=10.0,
        severity="warn", runbook="rb:serve-latency",
        summary="a serve peer's p99 reply latency exceeds the budget",
    ),
    AlertRule(
        "reconnect_storm", key="fleet/*/transport/reconnects_total",
        kind="rate", value=0.5, window_s=30.0, agg="sum", severity="warn",
        runbook="rb:learner-crash",
        summary="fleet-wide reconnect storm: actors losing the learner",
    ),
    # -- outcome attribution plane (ISSUE 15; dotaclient_tpu/outcome/) --
    AlertRule(
        # the gauge initializes to the 0.5 NEUTRAL PRIOR and only moves
        # once a window holds OutcomeAggregator.min_episodes scripted
        # games, so runs that play no scripted bot can never false-fire
        "win_rate_collapse", key="outcome/win_rate/vs_scripted",
        kind="threshold", op="<", value=0.2, for_s=120.0, severity="page",
        runbook="rb:win-rate-collapse",
        summary="windowed win-rate vs scripted bots collapsed below 0.2",
    ),
    AlertRule(
        # derived binary set by the OutcomeAggregator (1 while the ARMED
        # window's p50 episode length sits below its floor — degenerate
        # instant-reset episodes); watching the binary instead of the raw
        # p50 keeps the unarmed zero state from false-firing
        "episode_len_anomaly", key="outcome/episode_len_anomaly",
        kind="threshold", op=">", value=0.0, for_s=60.0, severity="warn",
        runbook="rb:episode-len-anomaly",
        summary="median episode length degenerate: envs are churn-resetting",
    ),
    AlertRule(
        # −1 until the first episode ever arrives (arming), then seconds
        # since the fleet-wide episode total last advanced — fires only
        # when a previously-live outcome stream stops
        "outcome_stream_stale", key="outcome/stream_age_s",
        kind="threshold", op=">", value=90.0, for_s=0.0, severity="warn",
        runbook="rb:outcome-stale",
        summary="no completed-episode outcome reached the learner for 90 s",
    ),
    # -- pipeline utilization plane (ISSUE 16; utils/utilization.py) ----
    AlertRule(
        # util/duty_cycle initializes to the NEUTRAL 1.0 and only moves
        # once the first fold arms the plane, so a just-started learner
        # (or one with the accountant disabled) can never false-fire
        "learner_duty_cycle_low", key="util/duty_cycle",
        kind="threshold", op="<", value=0.1, for_s=120.0, severity="warn",
        runbook="rb:duty-cycle-low",
        summary="donated dispatch in flight under 10% of wall-clock",
    ),
    AlertRule(
        # binary sentinel set by the learner fold: 1 while the fast
        # steps/s EMA runs below REGRESSION_RATIO x the warmup-armed
        # baseline EMA — watching the latch instead of the raw EMA keeps
        # compile transients (baseline unarmed) from false-firing
        "throughput_regression", key="util/throughput_regression",
        kind="threshold", op=">", value=0.5, for_s=60.0, severity="warn",
        runbook="rb:throughput-regression",
        summary="learner steps/s EMA regressed below 0.7x its baseline",
    ),
    # -- serve-fleet failover (ISSUE 19; dotaclient_tpu/serve/router.py) -
    AlertRule(
        # the router's probe plane declares a backend DEAD only after the
        # router_dead_after_s grace window of failed reconnects — this
        # gauge is zero in every healthy fleet, so any nonzero value is a
        # page. Rules with no data are skipped, so learner registries
        # (no router/ keys) never evaluate it.
        "serve_peer_dead", key="router/backends_dead",
        kind="threshold", op=">", value=0.0, for_s=0.0, severity="page",
        runbook="rb:serve-peer-dead",
        summary="a serve backend is dead past the probe grace window",
    ),
    AlertRule(
        # every re-home is a state discontinuity for a live game (carry
        # reset, or a shadow-row transfer) — a nonzero rate means the
        # fleet is actively failing over and capacity planning should
        # hear about it even after the page resolves
        "sessions_rehomed_burst", key="router/sessions_rehomed_total",
        kind="rate", value=0.0, window_s=60.0, severity="warn",
        runbook="rb:sessions-rehomed",
        summary="sessions re-homing off dead serve backends",
    ),
)


# Documented failure modes with NO alert rule, by runbook anchor, each
# with the reason it is not machine-watchable from the learner's registry.
# The alert-drift lint pass fails when an anchor has neither a rule nor a
# waiver — and when a waiver goes stale (anchor deleted, or a rule now
# covers it). Keep this a PLAIN DICT LITERAL: the pass literal-evals it.
ALERT_WAIVERS: Dict[str, str] = {
    "rb:actor-death": (
        "supervisor-restarted churn is steady state; sustained silence "
        "pages via rb:fleet-peer-stale instead"
    ),
    "rb:graceful-drain": "clean-exit path; exit code is the signal",
    "rb:half-open-conn": (
        "idle drops auto-heal per connection; a fleet-wide stall also "
        "surfaces as rb:fleet-peer-stale silence"
    ),
    "rb:garbage-sender": (
        "covered by the rb:corrupt-frames rules (same counters)"
    ),
    "rb:crash-pending-snapshot": (
        "post-mortem signal read from the LAST line after death; nothing "
        "to watch while alive"
    ),
    "rb:stall-diagnostics": (
        "diagnostic gauge pair with no universal threshold; compared "
        "against bench stages by a human"
    ),
    "rb:advantage-speedup": (
        "bench-time capability gate; the runtime overlap fraction varies "
        "legitimately with consume patterns (serial consume-time passes "
        "are correct, just unoverlapped) — compared against bench stages "
        "by a human"
    ),
    "rb:divergence-exhausted": (
        "terminal non-zero exit is its own page; the precursor pages via "
        "rb:divergence"
    ),
    "rb:divergence-no-ckpt": (
        "config-time condition warned once at startup, not a runtime level"
    ),
    "rb:cross-process-latency": (
        "needs a traced run and trace_report's critical path; no single "
        "registry level encodes it"
    ),
    "rb:tpu-preflight": "startup tool (run_multichip.py), not a live signal",
    "rb:fused-lane-divisibility": (
        "construction-time ValueError before any compile; the process "
        "never reaches a runtime level to watch"
    ),
    "rb:serve-stuck-window": (
        "needs a cross-rate comparison (requests vs dispatches) the rule "
        "grammar deliberately excludes; p99 blowups page via "
        "rb:serve-latency"
    ),
    "rb:serve-version-skew": (
        "surfaces as the serve server's corrupt-frame/quarantine "
        "counters — rb:corrupt-frames covers the watchable half"
    ),
    "rb:serve-slots": (
        "capacity planning, not an incident: rejects are by design at "
        "the configured ceiling"
    ),
    "rb:lint-ci": "CI-time failure; never reachable from a running fleet",
    "rb:alerts-stuck": (
        "the alert plane cannot page on itself; operator row for reading "
        "alerts/active directly"
    ),
}


def _match_keys(pattern: str, snapshot: Mapping[str, float]) -> List[float]:
    import fnmatch

    return [
        v for k, v in snapshot.items()
        if v is not None and fnmatch.fnmatchcase(k, pattern)
    ]


class _RuleState:
    __slots__ = ("since", "active", "samples", "last_value", "last_change")

    def __init__(self) -> None:
        self.since: Optional[float] = None     # condition-true start
        self.active = False
        self.samples: deque = deque()          # (t, value) for rate rules
        self.last_value: Optional[float] = None  # for stale rules
        self.last_change: Optional[float] = None


class AlertEngine:
    """Evaluate the rule table against registry snapshots.

    Single-threaded by contract: ``evaluate`` runs on the fleet
    aggregator's thread (lint/ownership.py maps the aggregator; this
    engine is its private state). ``emit`` receives one dict per
    fire/resolve transition — the learner wires it to
    ``MetricsLogger.emit_event`` so ``ALERT`` events ride the metrics
    JSONL's flush-per-emit durability."""

    def __init__(
        self,
        rules: Optional[Tuple[AlertRule, ...]] = None,
        registry: Optional[telemetry.Registry] = None,
        emit: Optional[Callable[[Dict[str, object]], None]] = None,
    ) -> None:
        self.rules = RULES if rules is None else tuple(rules)
        for rule in self.rules:
            if not rule.runbook.startswith("rb:"):
                raise ValueError(
                    f"alert rule {rule.name!r} has no OPERATIONS.md runbook "
                    f"anchor (rb:<name>) — every rule must point operators "
                    f"at its triage row"
                )
        reg = registry if registry is not None else telemetry.get_registry()
        # eager-created so `check_telemetry_schema.py --require-fleet`
        # validates any learner JSONL deterministically
        for key in ("alerts/fired_total", "alerts/resolved_total"):
            reg.counter(key)
        reg.gauge("alerts/active")
        self._reg = reg
        self._emit = emit
        self._state: Dict[str, _RuleState] = {
            rule.name: _RuleState() for rule in self.rules
        }

    # -- predicate plumbing ------------------------------------------------

    def _observe(
        self, rule: AlertRule, snapshot: Mapping[str, float]
    ) -> Optional[float]:
        if "*" in rule.key or "?" in rule.key or "[" in rule.key:
            values = _match_keys(rule.key, snapshot)
            if not values:
                return None
            return sum(values) if rule.agg == "sum" else max(values)
        v = snapshot.get(rule.key)
        return None if v is None else float(v)

    def _condition(
        self, rule: AlertRule, st: _RuleState, value: float, now: float
    ) -> bool:
        if rule.kind == "threshold":
            return {
                ">": value > rule.value,
                "<": value < rule.value,
                ">=": value >= rule.value,
                "<=": value <= rule.value,
            }[rule.op]
        if rule.kind == "rate":
            if st.samples and value < st.samples[-1][1]:
                st.samples.clear()   # counter reset: restart the window
            st.samples.append((now, value))
            while st.samples and now - st.samples[0][0] > rule.window_s:
                st.samples.popleft()
            if len(st.samples) < 2:
                return False
            t0, v0 = st.samples[0]
            span = now - t0
            return span > 0 and (value - v0) / span > rule.value
        if rule.kind == "stale":
            if st.last_value is None or value != st.last_value:
                st.last_value = value
                st.last_change = now
                return False
            return (
                st.last_change is not None
                and now - st.last_change > rule.value
            )
        raise ValueError(f"unknown alert rule kind {rule.kind!r}")

    # -- the evaluation tick -----------------------------------------------

    def evaluate(
        self,
        snapshot: Optional[Mapping[str, float]] = None,
        now: Optional[float] = None,
    ) -> Tuple[List[str], List[str]]:
        """One evaluation pass; returns (fired rule names, resolved rule
        names). ``now`` is injectable for the debounce/rate tests.

        The default snapshot is counters + gauges only — rules never
        address timer-stat leaves, and skipping them keeps a tick at
        microseconds where a full ``Registry.snapshot()`` pays every
        timer's stat computation."""
        if snapshot is None:
            counters, gauges = self._reg.counters_and_gauges()
            snapshot = {**counters, **gauges}
        if now is None:
            now = time.monotonic()
        fired: List[str] = []
        resolved: List[str] = []
        active = 0
        for rule in self.rules:
            st = self._state[rule.name]
            value = self._observe(rule, snapshot)
            cond = (
                self._condition(rule, st, value, now)
                if value is not None
                else False
            )
            if cond:
                if st.since is None:
                    st.since = now
                if not st.active and now - st.since >= rule.for_s:
                    st.active = True
                    fired.append(rule.name)
                    self._reg.counter("alerts/fired_total").inc()
                    self._event(rule, "fired", value)
            else:
                st.since = None
                if st.active:
                    st.active = False
                    resolved.append(rule.name)
                    self._reg.counter("alerts/resolved_total").inc()
                    self._event(rule, "resolved", value)
            if st.active:
                active += 1
        self._reg.gauge("alerts/active").set(float(active))
        return fired, resolved

    def active_rules(self) -> List[str]:
        return [n for n, st in self._state.items() if st.active]

    def _event(
        self, rule: AlertRule, state: str, value: Optional[float]
    ) -> None:
        if self._emit is None:
            return
        self._emit(
            {
                "event": "ALERT",
                "state": state,
                "rule": rule.name,
                "severity": rule.severity,
                "runbook": rule.runbook,
                "key": rule.key,
                "kind": rule.kind,
                "value": None if value is None else float(value),
                "threshold": rule.value,
                "summary": rule.summary,
            }
        )
