"""Fleet health plane: in-band metrics fanout + learner-side aggregation.

Every process in the split topology (learner, N actors, serve) writes its
own metrics JSONL; nothing merged them while the run was alive, so "is
the fleet healthy RIGHT NOW" had no answer (ISSUE 13). This module closes
the loop over the lanes the fleet already has:

* **Snapshot frames.** Actors and serve processes push one compact
  metric snapshot — counter totals + gauge values from their telemetry
  registry, filtered to the fleet-relevant namespaces — upstream every
  ``telemetry.fleet_interval_s`` seconds, serialized through the
  EXISTING rollout codec (scalar float64 leaves under ``c/``/``g/``
  prefixes; peer identity rides the rollout header: pid in
  ``model_version``, peer id in ``env_id``, snapshot seq in
  ``rollout_id``, peer kind in ``length``). The frames ride a new wire
  frame kind on the shared CRC/quarantine discipline of BOTH transports
  (socket kind 5; shm: the length word's high bit) — a corrupt snapshot
  counts and streaks exactly like a corrupt rollout.

* **FleetPublisher** (actor/serve side). Captured ONCE at pool
  construction like the tracer (``fleet.get()`` — the faults.get()
  discipline): with the fanout off, the ship path pays a single pointer
  test; on, one monotonic-clock compare per call plus the snapshot
  encode at cadence.

* **FleetAggregator** (learner side). Transport reader threads hand it
  decoded snapshots (``ingest`` — parked under a lock); its OWN thread
  (graftlint OWNERSHIP-mapped) merges them at fleet cadence into
  per-peer keys (``fleet/<peer>/<metric>`` — counters delta-merged so a
  restarted pid never double-counts, gauges last-write-wins, plus a
  derived ``fleet/<peer>/env_fps`` rate) and fleet rollups
  (``fleet/agg/<metric>/{min,max,mean}`` across live peers). Peer
  death/silence is itself a signal: a peer quiet for
  ``stale_after_s`` shows in ``fleet/peers_stale``, which the
  ``fleet_peer_stale`` alert rule (utils/alerts.py) pages on. The alert
  engine evaluates on this same thread, so rule state never races.

All rollup and alert keys are eager-created at construction so
``check_telemetry_schema.py --require-fleet`` validates ANY learner
JSONL deterministically; per-peer keys are dynamic and documented as the
``fleet/<peer>/*`` wildcard family (declared in
lint/telemetry_drift.py DYNAMIC_KEY_EXPANSIONS).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from dotaclient_tpu.utils import telemetry

__all__ = [
    "FleetPublisher",
    "FleetAggregator",
    "encode_snapshot",
    "decode_snapshot",
    "peer_label",
    "configure",
    "get",
    "shutdown",
]

# Namespaces a peer ships in its snapshot — the compact subset that the
# fleet table and rollups feed on (span timers never ship: their stat
# leaves are derived, not mergeable). "outcome/" is the outcome
# attribution plane (ISSUE 15): episode outcomes ride the SAME snapshot
# frames — no new frame kind — and delta-merge per peer like every other
# counter.
SNAPSHOT_PREFIXES = (
    "actor/", "transport/", "serve/", "faults/", "trace/", "shm/",
    "outcome/", "util/",
)

# Peer kinds, indexed by the rollout header's `length` field. The peer
# label is `<kind initial><peer id>` — `a0`, `s7788` — a STABLE name
# across process restarts (actors key on their seed, serve servers on
# their listen port), so a supervisor restart updates the SAME peer row
# instead of leaking a new one — and the fleet_peer_stale page resolves
# on the fresh incarnation's first snapshot.
PEER_KINDS = ("actor", "serve")


def peer_label(kind: str, peer_id: int) -> str:
    """The ONE derivation of a fleet peer's stable label
    (``<kind initial><peer id>``: ``a0``, ``s7788``). Serve peers key on
    their listen port, so the serve-fleet router (ISSUE 19) can name a
    backend's fleet row — ``fleet/<label>/serve/p99_latency_ms`` — from
    nothing but the address it routes to."""
    return f"{kind[0]}{int(peer_id)}"

# Fleet rollups: metric name → (source kind, peer-side key). "gauge" =
# last value per peer, "counter" = delta-merged total per peer, "rate" =
# per-second rate of the named counter between snapshots.
AGG_SOURCES: Dict[str, Tuple[str, str]] = {
    "weight_staleness": ("gauge", "actor/weight_refresh_lag"),
    "env_fps": ("rate", "actor/env_steps"),
    "reconnects": ("counter", "transport/reconnects_total"),
    "corrupt_frames": ("counter", "transport/frames_corrupt_total"),
    # utilization plane (ISSUE 16): the actor-side ship stall fraction —
    # a fleet-wide climb means the learner-side ingest path (or the wire)
    # is the bottleneck, not the envs
    "ship_wait": ("gauge", "util/actor/ship_wait"),
}
AGG_STATS = ("min", "max", "mean")
# The 15 eager-created rollup gauges — keep in sync with the
# ("fleet/agg/", "") expansion in lint/telemetry_drift.py and the
# FLEET_KEYS tier in scripts/check_telemetry_schema.py.
AGG_KEYS = tuple(
    f"{metric}/{stat}" for metric in AGG_SOURCES for stat in AGG_STATS
)

# Snapshot payloads must fit the native codec's entry table
# (serialize._MAX_TENSORS = 64): cap the shipped leaves. The cut is
# deterministic AND priority-aware — fleet-critical operational keys
# (the rollup sources, liveness counters) are kept ahead of the outcome
# plane's keys, and within the outcome plane the episode-length
# histogram buckets go first: dropping a histogram tail degrades the
# p50's resolution, dropping transport/reconnects_total would blind an
# alert rule (pinned by test).
_MAX_SNAPSHOT_LEAVES = 60


def _cut_priority(name: str) -> int:
    if name.startswith("outcome/ep_len_hist/"):
        return 2
    if name.startswith("outcome/"):
        return 1
    return 0


# -- snapshot codec -----------------------------------------------------------


def encode_snapshot(
    peer_id: int,
    kind: str,
    seq: int,
    counters: Dict[str, float],
    gauges: Dict[str, float],
    pid: Optional[int] = None,
) -> bytes:
    """One metric snapshot → wire bytes, through the existing rollout
    codec (``encode_rollout_bytes``): each metric is a scalar float64
    leaf named ``c/<key>`` (counter total) or ``g/<key>`` (gauge value).
    Counter totals are CUMULATIVE — the aggregator delta-merges them
    receiver-side (the Prometheus counter pattern), which survives both
    lost frames and peer restarts."""
    from dotaclient_tpu.transport.serialize import encode_rollout_bytes

    flat: Dict[str, np.ndarray] = {}
    names = sorted(
        (n for n in (*counters, *gauges) if n.startswith(SNAPSHOT_PREFIXES)),
        key=lambda n: (_cut_priority(n), n),
    )[:_MAX_SNAPSHOT_LEAVES]
    keep = set(names)
    for name, v in counters.items():
        if name in keep:
            flat[f"c/{name}"] = np.float64(v)
    for name, v in gauges.items():
        if name in keep:
            flat[f"g/{name}"] = np.float64(v)
    payload = encode_rollout_bytes(
        flat,
        # pid override: tests exercise the restarted-incarnation
        # delta-merge without forking
        model_version=os.getpid() if pid is None else int(pid),
        env_id=int(peer_id),
        rollout_id=int(seq),
        length=PEER_KINDS.index(kind),
        total_reward=0.0,
    )
    return bytes(payload)


def decode_snapshot(payload: Any) -> Optional[Dict[str, Any]]:
    """Wire bytes → snapshot dict, or None on anything unparseable (a
    malformed snapshot must never take a reader thread down)."""
    from dotaclient_tpu.transport.serialize import (
        decode_rollout_bytes,
        flatten_tree,
    )

    try:
        meta, arrays = decode_rollout_bytes(payload)
        flat = flatten_tree(arrays)
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        for name, arr in flat.items():
            # scalar leaves; reshape(-1)[0] also accepts a 1-element
            # vector (numpy deprecation-proof either way)
            if name.startswith("c/"):
                counters[name[2:]] = float(np.asarray(arr).reshape(-1)[0])
            elif name.startswith("g/"):
                gauges[name[2:]] = float(np.asarray(arr).reshape(-1)[0])
        kind_idx = int(meta["length"])
        kind = (
            PEER_KINDS[kind_idx]
            if 0 <= kind_idx < len(PEER_KINDS)
            else "actor"
        )
        return {
            "peer": peer_label(kind, int(meta["env_id"])),
            "kind": kind,
            "pid": int(meta["model_version"]),
            "seq": int(meta["rollout_id"]),
            "counters": counters,
            "gauges": gauges,
        }
    except Exception:  # noqa: BLE001 - disposable-peer failure model
        return None


# -- the peer side ------------------------------------------------------------


class FleetPublisher:
    """Peer-side snapshot shipper. ``maybe_publish`` is the only hot-path
    entry: one monotonic compare per call, the encode+send only at
    cadence. Send errors propagate — on the actor they engage the same
    reconnect machinery as a failed rollout publish."""

    def __init__(
        self,
        peer_id: int,
        kind: str = "actor",
        interval_s: Optional[float] = None,
        registry: Optional[telemetry.Registry] = None,
    ) -> None:
        if kind not in PEER_KINDS:
            raise ValueError(f"unknown fleet peer kind {kind!r}")
        self.peer_id = int(peer_id)
        self.kind = kind
        self.interval_s = (
            telemetry.fleet_interval_s if interval_s is None else interval_s
        )
        self._reg = (
            registry if registry is not None else telemetry.get_registry()
        )
        self._last = 0.0
        self._seq = 0

    def maybe_publish(self, transport: Any, force: bool = False) -> bool:
        now = time.monotonic()
        if not force and now - self._last < self.interval_s:
            return False
        publish = getattr(transport, "publish_metrics_bytes", None)
        if publish is None:
            return False   # lane without a metrics channel (AMQP, in-proc)
        self._last = now
        counters, gauges = self._reg.counters_and_gauges()
        publish(
            encode_snapshot(self.peer_id, self.kind, self._seq, counters, gauges)
        )
        self._seq += 1
        return True


_PUBLISHER: Optional[FleetPublisher] = None


def get() -> Optional[FleetPublisher]:
    """The process's fleet publisher, or None when the fanout is off.
    Pools capture this ONCE at construction (the faults.get()/tracing
    discipline) so the disabled cost is a single ``is not None`` test."""
    return _PUBLISHER


def configure(
    peer_id: int,
    kind: str = "actor",
    interval_s: Optional[float] = None,
    registry: Optional[telemetry.Registry] = None,
) -> Optional[FleetPublisher]:
    """Install the process publisher (call BEFORE constructing pools —
    they capture ``get()`` at init). ``interval_s`` defaults to
    ``telemetry.fleet_interval_s``; <= 0 removes the publisher."""
    global _PUBLISHER
    iv = telemetry.fleet_interval_s if interval_s is None else interval_s
    if iv is None or iv <= 0:
        _PUBLISHER = None
        return None
    _PUBLISHER = FleetPublisher(peer_id, kind, iv, registry)
    return _PUBLISHER


def shutdown() -> None:
    global _PUBLISHER
    _PUBLISHER = None


# -- the learner side ---------------------------------------------------------


class _PeerState:
    """Aggregator-thread-private view of one peer."""

    __slots__ = (
        "pid", "kind", "last_seen", "last_raw", "totals", "gauges",
        "rate_samples",
    )

    def __init__(self, kind: str) -> None:
        self.pid = 0
        self.kind = kind
        self.last_seen = 0.0
        # raw cumulative counter values of the CURRENT pid (delta base)
        self.last_raw: Dict[str, float] = {}
        # restart-corrected accumulated totals across incarnations
        self.totals: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.rate_samples: deque = deque()   # (t, actor/env_steps total)


class FleetAggregator:
    """Learner-side merge + alert evaluation.

    Thread split (graftlint OWNERSHIP map, lint/ownership.py):

    * ``ingest`` runs on transport READER threads (socket) or the
      learner's consume thread (shm drain) — it only decodes and parks
      the snapshot in ``_inbox`` under ``_lock``;
    * ``tick``/``_merge``/``_rollup`` and every touch of ``_peers`` and
      the alert engine run on THIS aggregator's own thread (``start``),
      at ``interval_s`` cadence — rule state never races the readers;
    * everything the rest of the process reads goes through the
      (thread-safe) telemetry registry, never this object's state.

    Construction alone eager-creates every ``fleet/``+``alerts/`` tier
    key; ``start()`` is only called when a fleet can actually report
    (the learner's external-transport modes, the bench stage)."""

    def __init__(
        self,
        registry: Optional[telemetry.Registry] = None,
        interval_s: Optional[float] = None,
        stale_after_s: Optional[float] = None,
        forget_after_s: float = 300.0,
        emit_event: Optional[Callable[[Dict[str, object]], None]] = None,
        rules: Optional[tuple] = None,
    ) -> None:
        from dotaclient_tpu.utils.alerts import AlertEngine

        self._reg = (
            registry if registry is not None else telemetry.get_registry()
        )
        self.interval_s = max(
            0.05,
            telemetry.fleet_interval_s
            if interval_s is None
            else float(interval_s),
        )
        # silence hysteresis: several missed snapshots, floored so a slow
        # host's jittery publish cadence cannot flap the stale gauge
        self.stale_after_s = (
            max(4.0 * self.interval_s, 6.0)
            if stale_after_s is None
            else float(stale_after_s)
        )
        self.forget_after_s = float(forget_after_s)
        self._lock = threading.Lock()
        self._inbox: List[Tuple[float, Dict[str, Any]]] = []
        self._peers: Dict[str, _PeerState] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # per-tick hooks (the outcome aggregator, ISSUE 15): run after the
        # merge/rollup but BEFORE alert evaluation, so rules watch gauges
        # the hook just refreshed. Registered at construction time (before
        # start()); the hook itself must be thread-safe — the outcome
        # aggregator locks internally because in-process modes tick it
        # from the train thread instead.
        self._tick_hooks: List[Callable[[], None]] = []
        # eager keys (schema tier determinism — the --require-fleet
        # contract holds for ANY learner JSONL, fleet traffic or not)
        for key in ("fleet/snapshots_total", "fleet/bad_snapshots_total"):
            self._reg.counter(key)
        for key in ("fleet/peers", "fleet/peers_stale"):
            self._reg.gauge(key)
        for name in AGG_KEYS:
            self._reg.gauge(f"fleet/agg/{name}")
        self._engine = AlertEngine(
            rules=rules, registry=self._reg, emit=emit_event
        )

    # -- reader-thread surface --------------------------------------------

    def ingest(self, payload: Any, recv_ts: Optional[float] = None) -> bool:
        """Decode one metrics frame and park it for the aggregator thread.
        Runs on whatever thread drained the wire; a malformed payload is
        counted and dropped, never raised."""
        snap = decode_snapshot(payload)
        if snap is None:
            self._reg.counter("fleet/bad_snapshots_total").inc()
            return False
        self._reg.counter("fleet/snapshots_total").inc()
        ts = time.monotonic() if recv_ts is None else recv_ts
        with self._lock:
            self._inbox.append((ts, snap))
        return True

    # -- aggregator-thread surface ----------------------------------------

    def add_tick_hook(self, hook: Callable[[], None]) -> None:
        """Register a callable run every tick between rollup and alert
        evaluation (call BEFORE start(); see ``_tick_hooks``)."""
        self._tick_hooks.append(hook)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="fleet-aggregator", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 - aggregation must not die
                import warnings

                warnings.warn(f"fleet aggregator tick failed: {e}")

    def tick(self, now: Optional[float] = None) -> None:
        """One merge + rollup + alert-evaluation pass (public for tests
        and the bench stage; production calls come from ``_run``)."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            batch, self._inbox = self._inbox, []
        for recv_ts, snap in batch:
            self._merge(recv_ts, snap)
        self._rollup(now)
        for hook in self._tick_hooks:
            hook()
        # counters + gauges only: rules never address timer-stat leaves,
        # and the full registry snapshot() computes every timer's stats —
        # measured ~3 ms on a populated registry vs µs for this view
        counters, gauges = self._reg.counters_and_gauges()
        self._engine.evaluate({**counters, **gauges}, now)

    def _peer_counter(self, key: str, delta: float) -> None:
        self._reg.counter(f"fleet/{key}").inc(delta)

    def _peer_gauge(self, key: str, value: float) -> None:
        self._reg.gauge(f"fleet/{key}").set(value)

    def _merge(self, recv_ts: float, snap: Dict[str, Any]) -> None:
        label = snap["peer"]
        st = self._peers.get(label)
        if st is None:
            st = self._peers[label] = _PeerState(snap["kind"])
        if st.pid != snap["pid"]:
            # restarted incarnation: its cumulative counters start from
            # zero, so the delta base resets — the old pid's totals are
            # already folded in and must NOT be re-added (pinned by test)
            st.pid = snap["pid"]
            st.last_raw = {}
            st.rate_samples.clear()
        st.last_seen = recv_ts
        for name, v in snap["counters"].items():
            prev = st.last_raw.get(name, 0.0)
            delta = v - prev if v >= prev else v   # reset within a pid
            st.last_raw[name] = v
            st.totals[name] = st.totals.get(name, 0.0) + delta
            self._peer_counter(f"{label}/{name}", delta)
        for name, v in snap["gauges"].items():
            st.gauges[name] = v
            self._peer_gauge(f"{label}/{name}", v)
        # derived env-steps/sec over the snapshot stream
        total = st.totals.get("actor/env_steps")
        if total is not None:
            st.rate_samples.append((recv_ts, total))
            while (
                len(st.rate_samples) > 2
                and recv_ts - st.rate_samples[0][0] > 4 * self.interval_s
            ):
                st.rate_samples.popleft()
            t0, v0 = st.rate_samples[0]
            span = recv_ts - t0
            fps = (total - v0) / span if span > 0 else 0.0
            st.gauges["env_fps"] = fps
            self._peer_gauge(f"{label}/env_fps", fps)

    def _peer_metric(self, st: _PeerState, metric: str) -> Optional[float]:
        source, key = AGG_SOURCES[metric]
        if source == "gauge":
            return st.gauges.get(key)
        if source == "counter":
            return st.totals.get(key)
        return st.gauges.get(metric)   # "rate": the derived env_fps gauge

    def _rollup(self, now: float) -> None:
        for label in [
            l for l, st in self._peers.items()
            if now - st.last_seen > self.forget_after_s
        ]:
            del self._peers[label]   # long-gone peer: retire its row
        live = [
            st for st in self._peers.values()
            if now - st.last_seen <= self.stale_after_s
        ]
        self._reg.gauge("fleet/peers").set(float(len(live)))
        self._reg.gauge("fleet/peers_stale").set(
            float(len(self._peers) - len(live))
        )
        for metric in AGG_SOURCES:
            values = [
                v
                for st in live
                if (v := self._peer_metric(st, metric)) is not None
            ]
            stats = (
                (min(values), max(values), sum(values) / len(values))
                if values
                else (0.0, 0.0, 0.0)
            )
            for stat_name, v in zip(AGG_STATS, stats):
                name = f"{metric}/{stat_name}"
                self._reg.gauge(f"fleet/agg/{name}").set(v)
