"""Dependency-free pipeline telemetry: one registry, per-stage spans, sinks.

The actor→transport→buffer→learner pipeline is only as fast as its slowest
stage, and Podracer-style scaling work (PAPERS.md, arXiv:2104.06272) starts
from per-stage throughput accounting; IMPACT (arXiv:1912.00167) adds that
actor-side weight *staleness* must be tracked for async correctness. This
module is the shared instrument panel: every layer records into one process-
wide :class:`Registry`, and the learner's ``MetricsLogger`` (utils/metrics.py,
now a facade over this registry) drains it to pluggable sinks.

Primitives
----------
* :class:`Counter` — monotone count (``inc``); rates are derived by diffing
  consecutive JSONL lines.
* :class:`Gauge` — last-write-wins level (``set``): queue depth, buffer
  occupancy, weight-version staleness.
* :class:`Timer` — duration accumulator with EMA, mean, last, and an
  approximate power-of-two-bucket histogram (``p95_s``).
* ``Registry.span("stage")`` — context manager timing a pipeline stage into
  the timer ``span/<stage>``; spans NEST via a per-thread stack
  (``span("a")`` inside ``span("b")`` records ``span/b/a``).

Everything here is host-side wall clock — recording a span never touches the
device, so the learner's "no host↔device sync except at ``log_every``"
discipline is preserved by construction.

Snapshot key schema (the JSONL contract; see docs/ARCHITECTURE.md
"Observability" and scripts/check_telemetry_schema.py):

* counters / gauges: ``<name>`` → float value
* timers: ``<name>/count``, ``/total_s``, ``/last_s``, ``/mean_s``,
  ``/ema_s``, ``/p95_s``
* spans are timers named ``span/<stage>``

Pipeline stage names wired in this repo: ``actor/step``, ``actor/infer``,
``actor/collect``, ``actor/drain``, ``transport/consume``,
``transport/publish_weights``, ``buffer/stage`` (host-row staging into the
reused ingest lanes), ``buffer/insert``, ``buffer/sample``,
``learner/consume``, ``learner/assemble``, ``learner/dispatch``,
``learner/metrics_fetch``, ``learner/prefetch`` (batch N+1's
drain+stage+scatter+gather, issued behind batch N's in-flight dispatch),
``league/evaluate``. The pipelined data path also reports two gauges:
``learner/prefetch_hit_rate`` (batches served from the prefetch lane /
batches served) and ``learner/overlap_fraction`` (prefetch host time spent
while a dispatch was in flight / all prefetch host time) — see
docs/ARCHITECTURE.md "Pipelined data path".

Zero-stall snapshot engine (ISSUE 5; docs/ARCHITECTURE.md "Zero-stall
snapshots"): ``snapshot/pending`` (engine job slots occupied),
``snapshot/d2h_ms`` (last batched device→host fetch on the snapshot
thread), ``snapshot/<kind>_coalesced`` for kind ∈ publish/checkpoint/
metrics (latest-wins replacements when the thread falls behind),
``snapshot/errors_total`` (jobs that failed without killing the engine),
``learner/publish_stall_ms`` (train-thread time lost to the last publish —
the on-device copy dispatch in async mode, the full fetch+encode+enqueue in
sync mode), and ``learner/stall_fraction`` (cumulative side-effect stall /
train() wall time). The engine records ``span/transport/publish_weights``
and ``span/learner/metrics_fetch`` from its own thread, keeping those keys
stable across modes.

Fault-tolerance counters (ISSUE 4; docs/OPERATIONS.md "Failure modes"):
``transport/frames_corrupt_total`` (CRC-failed wire frames dropped),
``transport/peers_quarantined`` (poison-frame streaks cut),
``transport/conn_idle_drops`` (half-open connections dropped),
``transport/heartbeats_sent``, ``transport/reader_exits``,
``checkpoint/save_failures_total`` (degraded periodic saves), and
``faults/injected_total`` (chaos-harness injections that actually fired).

Sinks: :class:`ConsoleSink` (prints only un-slashed legacy scalar keys, so
log lines stay readable), :class:`JsonlSink` (one JSON object per emit —
``{"ts", "step", "scalars"}`` — for headless/bench runs), and
:class:`TensorBoardSink` (tensorboardX when available; degrades to a
one-line warning when not).
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, TextIO, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Timer",
    "Registry",
    "ConsoleSink",
    "JsonlSink",
    "TensorBoardSink",
    "get_registry",
    "load_jsonl",
    "trace_sample_n",
]

# Pipeline-tracing sample cadence (ISSUE 12; utils/tracing.py): with a
# trace log configured (``--trace-jsonl``), every Nth sampling decision —
# chunk encode, train dispatch, serve request — carries/emits a trace
# record; the rest pay one int test. 1 = trace everything (chaos runs,
# latency hunts); with tracing OFF the knob is never consulted at all
# (``tracing.get() is None`` is the whole hot-path cost). ``--trace-sample``
# overrides per process.
trace_sample_n = 16

# Fleet-health snapshot cadence (ISSUE 13; utils/fleet.py): actors and
# serve processes push one compact metric snapshot (counter totals + gauge
# values) upstream every this many seconds, and the learner-side
# FleetAggregator merges/evaluates at the same cadence. <= 0 disables the
# fanout (the aggregator's keys stay eager-created so schema tiers hold);
# ``--fleet-interval`` overrides per process. A peer silent for several
# intervals is itself a signal (``fleet/peers_stale``).
fleet_interval_s = 5.0


class Counter:
    """Monotone counter. ``inc`` is the only mutator."""

    __slots__ = ("value", "_lock")

    def __init__(self) -> None:
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins level (assignment is atomic under the GIL)."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


# Histogram buckets: powers of two from 1 µs up; 36 buckets reach ~64 s,
# far past any sane stage latency. Bucket i covers [2^i, 2^(i+1)) µs.
_N_BUCKETS = 36
_BUCKET0_S = 1e-6


class Timer:
    """Duration accumulator: count/total/last, EMA, approximate p95.

    The EMA (alpha=0.2) is the responsive per-stage latency signal; the
    histogram answers "was that spike real" without storing samples.
    """

    __slots__ = ("count", "total", "last", "ema", "_buckets", "_lock")

    EMA_ALPHA = 0.2

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.last = 0.0
        self.ema = 0.0
        self._buckets = [0] * _N_BUCKETS
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        seconds = max(float(seconds), 0.0)
        with self._lock:
            self.count += 1
            self.total += seconds
            self.last = seconds
            self.ema = (
                seconds
                if self.count == 1
                else self.EMA_ALPHA * seconds + (1 - self.EMA_ALPHA) * self.ema
            )
            if seconds > 0:
                i = int(math.log2(max(seconds, _BUCKET0_S) / _BUCKET0_S))
                self._buckets[min(max(i, 0), _N_BUCKETS - 1)] += 1
            else:
                self._buckets[0] += 1

    @contextlib.contextmanager
    def time(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0)

    def quantile(self, q: float) -> float:
        """Approximate quantile from the bucket upper bounds (within 2× of
        the true value — enough to separate 1 ms from 100 ms stalls)."""
        with self._lock:
            if not self.count:
                return 0.0
            target = q * self.count
            seen = 0
            for i, c in enumerate(self._buckets):
                seen += c
                if seen >= target:
                    return _BUCKET0_S * (2.0 ** (i + 1))
        return _BUCKET0_S * (2.0 ** _N_BUCKETS)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            count, total, last, ema = self.count, self.total, self.last, self.ema
        return {
            "count": float(count),
            "total_s": total,
            "last_s": last,
            "mean_s": total / count if count else 0.0,
            "ema_s": ema,
            "p95_s": self.quantile(0.95),
        }


class Registry:
    """Named counters/gauges/timers plus the nesting ``span`` timer.

    Create-or-get semantics: ``registry.counter("x")`` is cheap enough for
    call sites to re-resolve by name every time — no handles to thread
    through constructors. All mutation is thread-safe (the overlap-mode
    actor thread and the learner thread share one registry).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._timers: Dict[str, Timer] = {}
        self._span_stack = threading.local()

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            return g

    def timer(self, name: str) -> Timer:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = self._timers[name] = Timer()
            return t

    @contextlib.contextmanager
    def span(self, stage: str) -> Iterator[None]:
        """Time one pipeline stage into ``span/<stage>``.

        A *bare* name (no "/") nests under the enclosing span via a
        per-thread stack — ``span("b")`` inside ``span("a")`` records
        ``span/a/b``. A name containing "/" is absolute: the documented
        pipeline stages ("buffer/insert", "learner/dispatch", ...) keep
        stable keys no matter which outer span the caller holds.
        """
        stack: List[str] = getattr(self._span_stack, "names", None) or []
        if "/" in stage or not stack:
            full = stage
        else:
            # stack entries are already full names — extend the innermost
            full = f"{stack[-1]}/{stage}"
        self._span_stack.names = stack + [full]
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timer(f"span/{full}").observe(time.perf_counter() - t0)
            self._span_stack.names = stack

    def snapshot(self) -> Dict[str, float]:
        """Flatten every metric to ``name → float`` per the key schema."""
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            timers = list(self._timers.items())
        out: Dict[str, float] = {}
        for name, c in counters:
            out[name] = c.value
        for name, g in gauges:
            out[name] = g.value
        for name, t in timers:
            for stat, v in t.stats().items():
                out[f"{name}/{stat}"] = v
        return out

    def counters_and_gauges(self) -> "Tuple[Dict[str, float], Dict[str, float]]":
        """Current counter totals and gauge values as two plain dicts —
        the fleet-health snapshot source (ISSUE 13; utils/fleet.py). Kept
        separate because the two kinds merge differently downstream:
        counters are delta-merged (a restarted pid must not double-count),
        gauges are last-write-wins. Timers are excluded — their stat
        leaves are derived, not mergeable."""
        with self._lock:
            return (
                {n: c.value for n, c in self._counters.items()},
                {n: g.value for n, g in self._gauges.items()},
            )

    def clear(self) -> None:
        """Drop every metric (test isolation)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()


# One process-wide registry: the pipeline layers (actor pools, transports,
# buffer) self-instrument against it so telemetry needs zero constructor
# plumbing; tests that want isolation construct their own Registry.
_GLOBAL = Registry()


def get_registry() -> Registry:
    return _GLOBAL


# -- sinks -------------------------------------------------------------------


class ConsoleSink:
    """The legacy console line: only un-slashed keys print (telemetry keys
    all contain "/"), so per-step log lines stay the familiar short form."""

    def __init__(self, t0: Optional[float] = None) -> None:
        self._t0 = t0 if t0 is not None else time.time()

    def emit(self, step: int, scalars: Dict[str, float]) -> None:
        parts = " ".join(
            f"{k}={v:.4g}" for k, v in sorted(scalars.items()) if "/" not in k
        )
        print(f"[{time.time() - self._t0:8.1f}s] step {step}: {parts}", flush=True)

    def close(self) -> None:
        pass


def _json_safe(v: float) -> Optional[float]:
    # NaN/Inf are not JSON; a diverged loss must not corrupt the stream.
    return v if math.isfinite(v) else None


class JsonlSink:
    """Append one JSON object per emit: ``{"ts": <unix>, "step": <int>,
    "scalars": {name: number|null}}`` — the machine-readable record for
    headless/bench runs (non-finite values become null).

    Durability (ISSUE 12): the stream is line-buffered and every emit is
    ONE ``write`` of a complete line followed by a flush, so a SIGKILL'd
    process (the chaos harness's stock in trade) can tear at most the
    line the OS was mid-writing — never interleave two lines; ``close``
    fsyncs before closing. Readers go through :func:`load_jsonl`, which
    drops an unterminated trailing line instead of choking on it."""

    def __init__(self, path: str) -> None:
        self.path = path
        # Crash-mid-write repair (ISSUE 15 bugfix sweep): a SIGKILL'd
        # writer leaves a torn TRAILING line, which load_jsonl tolerates —
        # but a RESTARTED process appending to the same path (chaos
        # restarts, --restore relaunches reusing --metrics-jsonl) would
        # concatenate its first line onto the fragment, producing a
        # corrupt INTERIOR line no reader drops. Truncate the fragment
        # before appending: it was already unreadable.
        _seal_torn_tail(path)
        self._f: Optional[TextIO] = open(path, "a", buffering=1)
        self._lock = threading.Lock()

    def emit(self, step: int, scalars: Dict[str, float]) -> None:
        line = json.dumps(
            {
                "ts": time.time(),
                "step": int(step),
                "scalars": {k: _json_safe(float(v)) for k, v in scalars.items()},
            },
            sort_keys=True,
        )
        self._write_line(line)

    def emit_event(self, event: Dict[str, object]) -> None:
        """Append one structured event line (``{"ts", "event", ...}``) to
        the same stream as the metrics envelopes — the alert channel
        (ISSUE 13). Rides the SAME durability contract as :meth:`emit`
        (one write of a complete line + flush), so a SIGKILL'd learner's
        last ``ALERT`` events survive for the post-mortem. Readers
        (``scripts/check_telemetry_schema.py``, ``scripts/fleet_status.py``)
        dispatch on the ``event`` key."""
        self._write_line(
            json.dumps({"ts": time.time(), **event}, sort_keys=True)
        )

    def _write_line(self, line: str) -> None:
        with self._lock:
            if self._f is None:
                return
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.flush()
                    os.fsync(self._f.fileno())
                except (OSError, ValueError):
                    pass  # durability is best-effort; close must not raise
                self._f.close()
                self._f = None


def _seal_torn_tail(path: str) -> None:
    """Drop an unterminated trailing fragment from an existing JSONL file
    (see :class:`JsonlSink`). Best-effort: a missing file or an
    unwritable one degrades to the reader-side torn-line tolerance."""
    try:
        with open(path, "rb+") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size == 0:
                return
            f.seek(size - 1)
            if f.read(1) == b"\n":
                return
            pos = size
            while pos > 0:
                step = min(4096, pos)
                f.seek(pos - step)
                data = f.read(step)
                idx = data.rfind(b"\n")
                if idx >= 0:
                    f.truncate(pos - step + idx + 1)
                    return
                pos -= step
            f.truncate(0)
    except FileNotFoundError:
        return
    except OSError:
        return


def load_jsonl(path: str) -> List[str]:
    """Read a JSONL file's COMPLETE lines, tolerating the one torn
    trailing line a SIGKILL can leave (no terminating newline → the
    write was cut mid-line → the line is dropped, never parsed). The
    shared reader for ``scripts/trace_report.py`` and
    ``scripts/check_telemetry_schema.py`` — both must survive a chaos
    harness's corpses (ISSUE 12). ``errors="replace"``: a write torn
    mid-UTF-8-sequence must not raise before the torn-tail drop below
    can even run (ISSUE 15 bugfix sweep)."""
    with open(path, "r", errors="replace") as f:
        text = f.read()
    if not text:
        return []
    complete = text.endswith("\n")
    lines = text.split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    if lines and not complete:
        lines.pop()  # torn trailing line: mid-write at kill time
    return lines


class TensorBoardSink:
    """tensorboardX scalars; construct via :meth:`create`, which degrades to
    ``None`` with a one-line warning when tensorboardX is not installed
    (console/JSONL sinks keep working — the logdir request must never crash
    a training run in a slim image)."""

    def __init__(self, writer) -> None:
        self._writer = writer

    @classmethod
    def create(cls, logdir: str) -> Optional["TensorBoardSink"]:
        try:
            from tensorboardX import SummaryWriter
        except ImportError:
            print(
                f"WARNING: tensorboardX not installed — logdir {logdir!r} "
                f"ignored; continuing with console/JSONL sinks only",
                flush=True,
            )
            return None
        return cls(SummaryWriter(logdir))

    def emit(self, step: int, scalars: Dict[str, float]) -> None:
        for name, v in scalars.items():
            self._writer.add_scalar(name, v, step)

    def close(self) -> None:
        self._writer.close()
