"""Frozen dataclass configuration tree.

The reference spreads configuration across per-entrypoint ``argparse`` flags
plus the ``GameConfig`` proto (SURVEY.md §5.6). Here the whole system is
configured by one immutable tree that is serialized into checkpoints; the
``GameConfig`` proto survives only at the environment boundary.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Mapping, Tuple


@dataclasses.dataclass(frozen=True)
class ObsSpec:
    """Fixed-shape observation layout (TPU-critical: no shape depends on the
    live unit count — SURVEY.md §7 step 2)."""

    max_units: int = 32          # padded unit slots per observation
    unit_features: int = 22      # per-unit feature vector length
    global_features: int = 8     # game-time, team, gold/xp diffs, ...
    max_abilities: int = 4       # ability slots exposed per hero


@dataclasses.dataclass(frozen=True)
class ActionSpec:
    """Discrete multi-head action space (reference head set, SURVEY.md §3.3)."""

    n_action_types: int = 4      # noop / move / attack-unit / cast
    move_bins: int = 9           # discretized move offsets per axis
    max_units: int = 32          # target-unit head size == padded unit slots
    max_abilities: int = 4

    @property
    def head_sizes(self) -> Mapping[str, int]:
        return {
            "action_type": self.n_action_types,
            "move_x": self.move_bins,
            "move_y": self.move_bins,
            "target_unit": self.max_units,
            "ability": self.max_abilities,
        }


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Flax policy hyper-parameters (LSTM(128) core per BASELINE.json:7)."""

    unit_embed_dim: int = 64
    hidden_dim: int = 128        # LSTM hidden size — parity with reference
    n_hero_ids: int = 32         # hero-embedding vocabulary (multi-hero pools)
    hero_embed_dim: int = 16
    core: str = "lstm"           # "lstm" | "transformer"
    # Transformer-core options (scale-out path, SURVEY.md §7 step 8).
    n_layers: int = 2
    n_heads: int = 4
    context_window: int = 16     # rolling KV-cache length (recurrent carry)
    # Mixture-of-experts FFN (expert parallelism; 0 = dense MLP).
    moe_experts: int = 0         # experts per MoE layer, sharded over `model`
    moe_capacity_factor: float = 2.0
    dtype: str = "bfloat16"      # compute dtype; params stay float32
    param_dtype: str = "float32"


# Valid PPOConfig.adv_norm values — the single source of truth for the
# runtime check in train.ppo and any CLI-level validation.
ADV_NORM_MODES = ("batch", "none")

# Valid PPOConfig.advantage estimators.
ADVANTAGE_MODES = ("gae", "vtrace")

# Valid PPOConfig.advantage_dtype storage widths for the one-pass
# advantage plane's staged advantages/returns (train/advantage.py).
ADVANTAGE_STORE_DTYPES = ("bfloat16", "float32")


@dataclasses.dataclass(frozen=True)
class PPOConfig:
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    value_coef: float = 0.5
    entropy_coef: float = 0.01
    learning_rate: float = 3e-4
    max_grad_norm: float = 0.5
    rollout_len: int = 16        # truncated-BPTT chunk length T
    batch_rollouts: int = 32     # rollouts per optimizer step (B)
    epochs_per_batch: int = 1
    minibatches: int = 1         # shuffled minibatch splits per epoch
    max_staleness: int = 4       # drop rollouts older than this many BATCHES
    moe_aux_coef: float = 0.01   # Switch load-balancing loss weight (MoE core)
    # Advantage normalization. "batch" (the standard per-batch whitening) is
    # right for training from scratch, but it amplifies GAE noise to unit
    # scale when the true advantage signal is ~zero — measured to destroy a
    # near-optimal transferred policy within ~1k steps (BASELINE.md, 5v5
    # curriculum). adv_norm_floor puts a lower bound on the divisor so small
    # advantages stay small: floor 0.0 reproduces the standard behavior,
    # floor 1.0 means "whiten only when the batch std exceeds unit scale".
    # adv_norm="none" centers but never rescales.
    adv_norm: str = "batch"      # one of ADV_NORM_MODES
    adv_norm_floor: float = 0.0
    # Advantage estimator. "gae" (reference parity) assumes on-policy
    # batches; "vtrace" (IMPALA) reweights every step by the clipped
    # importance ratio min(ρ̄, π/μ) so STALE rollouts from async actors
    # contribute bias-corrected targets instead of being merely tolerated
    # by the PPO clip — the estimator for the external/overlap topology
    # at high staleness. gae_lambda is unused under vtrace (its trace
    # cutting comes from the c̄-clipped ratios).
    advantage: str = "gae"       # one of ADVANTAGE_MODES
    vtrace_rho_clip: float = 1.0
    vtrace_c_clip: float = 1.0
    # Critic-only warmup: for the first N optimizer steps, train ONLY the
    # value head (policy surrogate + entropy off; all non-value-head grads
    # masked to zero, so the behavior policy is bitwise frozen). The
    # curriculum-transfer lever: after --init-from the transferred critic is
    # calibrated to the SOURCE config's returns (team size, reward weights,
    # gamma), so early advantages are systematically wrong and can destroy a
    # near-optimal policy before the critic adapts (BASELINE.md 5v5
    # fine-tune measurements). 0 disables.
    value_warmup_steps: int = 0
    # KL-adaptive learning rate (trust-region-style auto-stabilizer).
    # When kl_target > 0, every train step measures the POST-update KL
    # (k3 estimator over the batch's taken actions) inside the compiled
    # step and adapts the Adam learning rate carried in the optimizer
    # state: ×kl_lr_down when KL > 2·target, ×kl_lr_up when KL <
    # target/2, clipped to [learning_rate·kl_lr_min_scale,
    # learning_rate·kl_lr_max_scale]. Fully in-graph — no host sync — so
    # it works in fused mode. Motivating measurement: 5v5 fine-tune
    # collapses at lr 3e-4 but ascends at 1e-5 (BASELINE.md); this makes
    # step size self-tuning instead of a per-run guess. 0 disables
    # (plain constant-lr Adam; optimizer-state layout unchanged).
    kl_target: float = 0.0
    # Anchor-KL regularizer (AlphaStar's KL-to-supervised-anchor, adapted):
    # adds anchor_kl_coef · KL(π_θ ‖ π_anchor) to the loss, where π_anchor
    # is the policy AT LEARNER CONSTRUCTION (after --restore/--init-from —
    # i.e. the transferred policy in a curriculum run; a mid-run resume
    # re-anchors at the resumed params). Motivation (BASELINE.md, 5v5
    # fine-tune): the shaped reward's true optimum is a farming attractor,
    # and rate limiters (low lr, KL-adaptive lr) only slow the slide into
    # it — a persistent gradient integrates to the same place. The anchor
    # term changes the optimum instead: drift from the known-good policy
    # now costs loss, so improvement must pay for its distance. 0 disables.
    anchor_kl_coef: float = 0.0
    kl_lr_down: float = 0.7
    kl_lr_up: float = 1.02
    kl_lr_min_scale: float = 0.01
    kl_lr_max_scale: float = 10.0
    # Fused epoch step (train/ppo.make_epoch_step): when a consumed batch
    # needs more than one optimizer step (epochs_per_batch × minibatches >
    # 1), run ALL of them inside one donated XLA program — a lax.scan over
    # minibatch slices of the epoch permutations — instead of the staged
    # host loop's gather+step dispatch pair per minibatch. Same updates on
    # the same data (the permutations come from the same seeded stream as
    # the staged fallback; agreement to XLA-fusion float rounding); the
    # staged path remains for --checkify and as the explicit opt-out.
    # False forces the staged loop.
    fused_epoch: bool = True
    # One-pass advantage plane (train/advantage.py): compute the value
    # forward + GAE scan ONCE per consumed batch — a jitted, mesh-sharded
    # pass at the buffer gather boundary — and train all epochs_per_batch
    # × minibatches optimizer steps on the precomputed advantages/returns
    # instead of re-running the estimator inside every step (HEPPO-GAE's
    # pipeline-stage observation, PAPERS.md). This is the standard PPO
    # regime (advantages fixed for the batch, from the params the batch's
    # first update trains from); the in-step recompute remains for
    # advantage="vtrace" (its importance ratios need the CURRENT policy's
    # logp, which changes every optimizer step), for fused mode (the
    # rollout+update program is strictly on-policy with E×M per-chunk
    # updates of its own), and at steps_per_batch == 1 (the in-step
    # estimator already runs once per batch there — a separate pass would
    # add a forward, not remove one). False forces the per-step recompute
    # everywhere.
    one_pass_advantage: bool = True
    # Storage width for the staged advantages/returns between the pass and
    # the epoch step (the narrow-ring discipline of ISSUE 7 extended to
    # the advantage plane): "bfloat16" halves the staged bytes and the
    # loss upcasts at consume; "float32" opts out (bit-exact staging).
    # The estimator's INPUTS (rewards, behavior_logp, dones, values) keep
    # their pinned-f32 precision either way — only the derived outputs
    # narrow.
    advantage_dtype: str = "bfloat16"

    @property
    def steps_per_batch(self) -> int:
        """Optimizer steps (= version ticks) per consumed batch — the unit
        ``max_staleness`` is denominated in. Shared by the learner's
        counters and the buffer's staleness window so they cannot drift."""
        return self.epochs_per_batch * max(1, self.minibatches)


@dataclasses.dataclass(frozen=True)
class EnvConfig:
    n_envs: int = 8
    ticks_per_observation: int = 6
    max_dota_time: float = 600.0
    hero_pool: Tuple[int, ...] = (1,)   # hero ids agents may draft from
    team_size: int = 1                  # 1 => 1v1, 2 => 2v2, 5 => 5v5
    opponent: str = "scripted_easy"     # scripted_easy | scripted_hard | league
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class RewardConfig:
    """Shaped-reward component weights.

    The reference hardcoded its shaping inside ``agent.py`` (SURVEY.md §2.1);
    here the table is part of the config tree (checkpointed, overridable per
    run). Defaults reproduce ``features/reward.py``'s historical weights.
    The 5v5 pure-self-play experiments in BASELINE.md show why this must be
    tunable: dense farm shaping can dominate the sparse win/tower terms and
    converge to a farming equilibrium that loses the timeout adjudication.
    """

    xp: float = 0.002
    gold: float = 0.006
    hp: float = 2.0            # own-hero hp *fraction* delta
    enemy_hp: float = 1.0      # symmetric harass term
    last_hits: float = 0.16
    denies: float = 0.12
    kills: float = 1.0
    deaths: float = -1.0
    tower_damage: float = 2.0  # enemy tower hp-fraction lost
    own_tower: float = 2.0     # OWN tower hp-fraction lost (defense term)
    win: float = 5.0

    def as_dict(self) -> Mapping[str, float]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device mesh layout. Axes: dcn (multi-slice), data (batch/grad psum),
    model (TP). With ``dcn_slices == 1`` the mesh is 2-D (data, model)."""

    data_axis: str = "data"
    model_axis: str = "model"
    dcn_axis: str = "dcn"
    data_parallel: int = -1      # -1 => all remaining devices
    model_parallel: int = 1
    dcn_slices: int = 1          # ICI-connected slices bridged over DCN


@dataclasses.dataclass(frozen=True)
class BufferConfig:
    capacity_rollouts: int = 256   # ring-buffer slots (sharded over data axis)
    min_fill: int = 32             # rollouts required before first train step
    # Host staging lanes for ingest: decoded rollout rows are copied into
    # one of this many REUSED preallocated numpy buffers (rotating) before
    # the device scatter, instead of a fresh np.stack allocation per ingest.
    # 2 = double buffering: the scatter for ingest N can still be in flight
    # (async dispatch holds the host rows) while ingest N+1 assembles into
    # the other lane. 1 disables the overlap margin but keeps the reuse.
    staging_slots: int = 2
    # Transport-consume poll timeout (seconds) for the learner's ingest
    # drain — how long an empty poll blocks before the loop moves on. A
    # batch already assembled in the prefetch lane is consumed without
    # reaching the drain at all (train/learner.py `_next_batch`).
    consume_poll_timeout_s: float = 0.001
    # Admission control (ISSUE 6): semantic integrity at the buffer door,
    # extending the wire-integrity discipline (CRC + poison-peer
    # quarantine, ISSUE 4) to payload CONTENT.
    #
    # max_weight_staleness: absolute version-delta bound for admission —
    # a frame whose producer version is more than this many optimizer
    # versions behind is rejected and counted
    # (buffer/stale_rejected_total). -1 (default) derives the bound from
    # ppo.max_staleness × steps_per_batch, the historical behavior; >= 0
    # overrides it with a raw version delta (the knob thousand-actor
    # fleets tune directly — IMPACT's soundness argument needs staleness
    # BOUNDED at ingest, not merely observed).
    max_weight_staleness: int = -1
    # reject_nonfinite: scan every float leaf of a host-ingested payload
    # (observations, rewards, behavior logp, carries) and reject frames
    # carrying NaN/Inf (buffer/nonfinite_rejected_total) — one actor with
    # corrupted state must not poison the learner's numerics. Device-path
    # ingest (add_device) skips the scan: those chunks are produced
    # in-process by construction and divergence there is the train-step
    # probe's job (train/health.py).
    reject_nonfinite: bool = True


@dataclasses.dataclass(frozen=True)
class TransportConfig:
    """Cross-process transport tuning (socket/shm lanes; ISSUE 3).

    The weights fanout is non-blocking: ``publish_weights`` is an O(1)
    enqueue per connection and per-connection writer threads do the actual
    sends, coalescing to the latest version (stale intermediate weights are
    worthless — IMPACT licenses bounded staleness, PAPERS.md)."""

    # Wire dtype for the weights fanout: "float32" (bit-exact) or
    # "bfloat16" — float32 params are cast at encode, halving the fanout
    # bytes per publish; the actor upcasts on apply (lossless: every bf16
    # value is exactly representable in f32).
    wire_dtype: str = "float32"
    # Wire dtype for ROLLOUT payloads (ISSUE 7) — the dominant byte stream
    # at scale: "float32" (bit-exact, the default) or "bfloat16". With
    # bfloat16, actors narrow f32 observation/feature leaves to bf16 (and
    # config-bounded integer leaves — action indices, hero ids — to
    # int8/int16, exactly) at encode, the ``__wire_cast__`` marker names
    # what was narrowed, the learner's trajectory buffer STORES the narrow
    # dtypes (≈half the resident HBM ring bytes and per-scatter H2D
    # traffic), and the upcast to f32 happens on-device inside the already
    # jitted consume gather — the train step sees f32 inputs bit-identical
    # to decoding the wire. Precision-critical leaves (behavior_logp,
    # rewards, dones, values, LSTM initial carries) are pinned f32 by
    # serialize.rollout_leaf_pinned and cross the wire byte-identical, so
    # PPO ratios and GAE are untouched. Keep actor and learner values
    # aligned (the buffer tolerates either width at the door, but mixed
    # fleets forfeit the bandwidth win on the f32 side).
    rollout_wire_dtype: str = "float32"
    # A connection whose writer thread is still stuck sending when this
    # many NEWER publishes have been enqueued is declared over-budget and
    # dropped (counted in transport/fanout_conns_dropped) — a stalled actor
    # must never delay the learner or its peers.
    fanout_max_lag: int = 8
    # Shared-memory same-host lane (--transport shm): per-actor SPSC
    # rollout ring size and the seqlock'd weights slab size. The slab must
    # hold one encoded ModelWeights payload; rings drop-newest (counted)
    # when the learner falls behind.
    shm_slots: int = 16
    shm_ring_bytes: int = 8 * 1024 * 1024
    shm_weights_bytes: int = 32 * 1024 * 1024
    # Fault tolerance (ISSUE 4). Every wire frame carries a CRC32 trailer;
    # a peer that ships this many CONSECUTIVE corrupt frames is quarantined
    # (socket: connection cut; shm: slot never drained again until reaped)
    # instead of crashing a reader thread — one bit-flipping actor must not
    # take the learner down, and one flaky NIC must not poison the buffer.
    poison_frame_limit: int = 8
    # TCP-lane liveness, both directions: the learner's per-connection
    # writer interleaves heartbeat frames with the weights fanout at this
    # cadence (actors echo them), and either side drops/declares-dead a
    # connection with no inbound traffic for idle_timeout_s — a half-open
    # TCP connection (peer host died, NAT entry expired) can never wedge
    # the fleet. 0 disables the respective check. Keep idle_timeout_s
    # comfortably above BOTH heartbeat_interval_s and the actor's fixed
    # ~1s echo rate limit (actors echo liveness on inbound frames at most
    # once per second), or healthy peers get dropped as half-open.
    heartbeat_interval_s: float = 5.0
    idle_timeout_s: float = 30.0


@dataclasses.dataclass(frozen=True)
class LearnerConfig:
    """Learner-side execution knobs (ISSUE 5).

    ``async_snapshots`` routes every train-loop side effect that fetches
    device state — the weights publish, the periodic checkpoint, and the
    log-boundary metrics fetch — through the snapshot engine
    (train/snapshot.py): the train thread runs one cheap jitted on-device
    copy and dispatches the next step immediately; a background thread does
    the device→host transfer, the wire cast + encode, the fanout enqueue,
    and the orbax write. Published versions stay monotonic (latest-wins
    coalescing when the thread falls behind), the graceful-stop/forced
    checkpoint drains pending snapshots and lands at the exact stop step
    via the sync path, and async write failures surface through the
    ``checkpoint/save_failures_total`` degrade policy. Disable for
    debugging (``--sync-snapshots``): every side effect runs inline on the
    train thread, stalling it — the pre-ISSUE-5 behavior."""

    async_snapshots: bool = True
    # Upper bound on how long a graceful stop waits for the snapshot
    # thread to finish in-flight work before proceeding with the forced
    # sync checkpoint anyway (a wedged disk must not turn a drain into a
    # hang; the sync save then surfaces the real error loudly).
    snapshot_drain_timeout_s: float = 60.0
    # Compute-stage pipeline overlap (ISSUE 14, the OPPO observation):
    # with the one-pass advantage plane on, run batch N+1's advantage
    # pass on the prefetch lane — dispatch-only work enqueued behind
    # batch N's in-flight donated epoch step — instead of at consume
    # time. advantage/overlap_fraction measures how much of the pass's
    # host time actually hid behind a dispatch. False defers every pass
    # to consume time (the serial one-pass baseline bench.py measures).
    overlap_advantage: bool = True


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Training health guardian (ISSUE 6): detect → contain → recover.

    Detection is a cheap in-graph probe fused into every train-step
    variant (``train/ppo.py`` adds a ``health_ok`` finiteness flag over
    loss and grad-norm to the step metrics; scanned multi-update programs
    AND-fold it), surfaced WITHOUT blocking the train thread: the
    ``HealthMonitor`` (train/health.py) accumulates the per-batch verdict
    scalars host-side and the snapshot engine fetches them in one batched
    transfer per boundary — ordered BEFORE the publish job, so a poisoned
    version can never reach the weights fanout. Containment: unhealthy
    state blocks weight publishes and periodic checkpoints (actors keep
    serving the last good version). Recovery: divergence rolls the
    TrainState back to the ``last_good`` checkpoint slot
    (utils/checkpoint.py) with a distinct minibatch-RNG stream, bounded by
    ``max_rollbacks`` before a loud exit."""

    enabled: bool = True
    # Host-side EMA of the (pre-clip) gradient global norm, updated on
    # healthy verdicts only; a verdict whose grad_norm exceeds
    # explosion_band × the EMA latches divergence even when every value is
    # still finite — the "loss exploded but has not NaN'd yet" band. The
    # EMA arms after warmup_steps healthy verdicts (early training swings
    # legitimately). Band is deliberately wide by default: the finiteness
    # probe is the primary tripwire; the band exists to catch runaway
    # growth before it saturates to inf.
    ema_alpha: float = 0.02
    explosion_band: float = 100.0
    warmup_steps: int = 50
    # Divergence rollbacks attempted (each restores last_good and resumes
    # with a DISTINCT minibatch-shuffle RNG stream) before the guardian
    # declares the run unrecoverable and exits non-zero with the runbook
    # message (docs/OPERATIONS.md "Failure modes").
    max_rollbacks: int = 3


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Low-latency policy-serving plane (ISSUE 11; dotaclient_tpu/serve).

    The serving workload is training inverted: many concurrent games each
    wanting ONE action at tight latency. The continuous-batching engine
    collects per-game step requests into preallocated staging lanes until
    ``batch_window_ms`` elapses or ``max_batch`` requests are staged
    (whichever first), runs ONE jitted dispatch over the padded batch with
    server-resident recurrent carries, and scatters sampled actions back
    per requester. These knobs trade latency (smaller window) against
    throughput (fuller batches) — ``bench.py``'s serve stage measures the
    curve."""

    # Batch-collection deadline in milliseconds. 0 dispatches whatever is
    # pending immediately (minimum latency, worst batching).
    batch_window_ms: float = 2.0
    # Requests per dispatch (the padded batch's static shape — changing it
    # recompiles the serve step). A window closes early when it fills.
    max_batch: int = 64
    # Server-resident carry slots = concurrently attached games. A slot is
    # allocated at client attach, zeroed on release, and reused; clients
    # never ship recurrent state.
    max_slots: int = 256
    # Request wire dtype ("float32" | "bfloat16"): bf16 narrows the
    # request's observation leaves via the rollout cast-plan machinery
    # (ISSUE 7) — the same ``__wire_cast__`` marker discipline, roughly
    # half the request bytes. Replies (a few ints + one float) stay f32.
    request_wire_dtype: str = "float32"
    # Weight-swap subscription cadence: the serve server's weights thread
    # polls its fanout subscription (socket or shm lane) this often; a new
    # version hot-swaps BETWEEN dispatches, never within one.
    weights_poll_s: float = 0.5
    # Base seed of the serve-side sampling RNG stream: dispatch i samples
    # with fold_in(key(seed), i) — the stream the parity digest replays.
    seed: int = 0
    # -- serve-fleet failover (ISSUE 19) ---------------------------------
    # Per-request deadline budget in seconds: every ServeClient.step()
    # resolves to an action or a typed ServeDeadlineError within this
    # budget — reconnects, router redirects, and retries all spend from
    # it. A dead backend is a bounded deadline miss, never a hang.
    request_deadline_s: float = 10.0
    # Bounded resend attempts per request inside the deadline budget (the
    # actor-contract retry discipline: backoff between attempts, SIGTERM
    # honored within one segment via should_abort).
    request_retries: int = 4
    # Router→backend liveness probe cadence: one persistent probe
    # connection per backend (it holds one carry slot), heartbeat frames
    # at this interval — a SIGKILL'd backend surfaces as EOF within one
    # probe turn.
    router_probe_s: float = 1.0
    # Grace window before a probe-lost backend is declared DEAD and its
    # sessions re-home (a transient reconnect inside the window is not a
    # death). Keep > one probe turn to ride out GC/compile pauses.
    router_dead_after_s: float = 3.0
    # Opt-in carry-shadow mode: replies carry the updated recurrent carry
    # row back to the client (narrowed by request_wire_dtype like every
    # other leaf — bit-exact at the default f32 wire), and a re-homed
    # session resends its stashed row so it resumes bit-exact on the new
    # backend. Off: a re-home resets the carry to zeros (the
    # reset_recurrent discipline) and is counted.
    carry_shadow: bool = False


@dataclasses.dataclass(frozen=True)
class LeagueConfig:
    enabled: bool = False
    pool_size: int = 8
    snapshot_every: int = 200      # learner steps between opponent snapshots
    selfplay_prob: float = 0.5     # chance of facing the latest policy
    # Snapshot matchmaking: "uniform" | "pfsp" (prioritized fictitious
    # self-play — weight (1-winrate)^pfsp_power, replay hard opponents).
    matchmaking: str = "pfsp"
    pfsp_power: float = 2.0
    # Optimizer steps a drawn opponent is held before redrawing: episodes
    # span many rollout chunks, so holding keeps most of an episode against
    # ONE opponent — the per-chunk outcome attribution PFSP feeds on stays
    # meaningful, and lanes stop seeing mid-episode opponent swaps.
    opponent_hold: int = 64
    # Scripted-anchor games (AlphaStar-style league exploiters, simplified):
    # this fraction of the device actor's games pins the opponent side to a
    # scripted bot instead of a pool snapshot. Pure self-play pools can
    # converge to metas where nobody pressures towers (BASELINE.md "5v5
    # farming equilibrium"); anchors keep fight/push behavior in the
    # training distribution. Anchor outcomes are excluded from PFSP stats.
    anchor_prob: float = 0.0
    # "scripted_easy" | "scripted_hard" | "mixed". Measured (BASELINE.md 30k
    # league run): anchoring only vs hard improved the hard-bot eval but
    # collapsed the easy-bot eval — the meta only covers strategies in the
    # anchor distribution.
    anchor_opponent: str = "scripted_hard"
    # "mixed" only: fraction of anchor games played vs scripted_easy (the
    # rest vs scripted_hard), easy rounding up. The 10k mixed-anchor run
    # (BASELINE.md) showed 12.5% easy games does not fully offset the shaped
    # reward's farming pull on the easy-bot eval — this is the knob to raise.
    anchor_easy_share: float = 0.5


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """Top-level config tree."""

    obs: ObsSpec = ObsSpec()
    actions: ActionSpec = ActionSpec()
    model: ModelConfig = ModelConfig()
    ppo: PPOConfig = PPOConfig()
    env: EnvConfig = EnvConfig()
    reward: RewardConfig = RewardConfig()
    mesh: MeshConfig = MeshConfig()
    buffer: BufferConfig = BufferConfig()
    transport: TransportConfig = TransportConfig()
    learner: LearnerConfig = LearnerConfig()
    health: HealthConfig = HealthConfig()
    serve: ServeConfig = ServeConfig()
    league: LeagueConfig = LeagueConfig()
    checkpoint_dir: str = "checkpoints"
    checkpoint_every: int = 100
    # Best-model tracking: whenever the windowed win-rate at a log boundary
    # beats the best seen so far (and the window holds at least this many
    # episodes — the noise guard), a weights-only checkpoint is saved to
    # `<checkpoint_dir>/best` (its own max_to_keep=1 rotation). Motivated by
    # the measured 5v5 fine-tune trajectory that peaked at 0.714 mid-run and
    # ended at 0.16 — the peak policy otherwise rotates out of the periodic
    # checkpoints (BASELINE.md). 0 disables.
    checkpoint_best_min_episodes: int = 50
    # Fused-mode dispatch batching: lax.scan this many rollout+update
    # iterations inside the ONE jitted fused program, so each host dispatch
    # advances K optimizer steps. The host↔device round trip is the fused
    # path's floor (~100 ms on a tunneled PJRT link — train/fused.py); K>1
    # amortizes it. Trade-offs: the league opponent draw and all host-side
    # cadences (logging, eval, snapshots, best-model capture) coarsen to
    # K-step granularity. Fused mode only; other actors reject K>1.
    steps_per_dispatch: int = 1
    log_every: int = 10
    seed: int = 0

    def replace(self, **kwargs: Any) -> "RunConfig":
        return dataclasses.replace(self, **kwargs)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "RunConfig":
        raw = json.loads(text)
        return cls(
            obs=ObsSpec(**raw["obs"]),
            actions=ActionSpec(**raw["actions"]),
            model=ModelConfig(**raw["model"]),
            ppo=PPOConfig(**raw["ppo"]),
            env=EnvConfig(**{**raw["env"], "hero_pool": tuple(raw["env"]["hero_pool"])}),
            # absent in checkpoints written before RewardConfig existed
            reward=RewardConfig(**raw.get("reward", {})),
            mesh=MeshConfig(**raw["mesh"]),
            buffer=BufferConfig(**raw["buffer"]),
            # .get: absent in checkpoints written before TransportConfig
            transport=TransportConfig(**raw.get("transport", {})),
            # .get: absent in checkpoints written before LearnerConfig
            learner=LearnerConfig(**raw.get("learner", {})),
            # .get: absent in checkpoints written before HealthConfig
            health=HealthConfig(**raw.get("health", {})),
            # .get: absent in checkpoints written before ServeConfig
            serve=ServeConfig(**raw.get("serve", {})),
            league=LeagueConfig(**raw["league"]),
            # .get: absent in checkpoints written before the field existed
            checkpoint_best_min_episodes=raw.get(
                "checkpoint_best_min_episodes",
                cls.checkpoint_best_min_episodes,
            ),
            steps_per_dispatch=raw.get(
                "steps_per_dispatch", cls.steps_per_dispatch
            ),
            **{k: raw[k] for k in ("checkpoint_dir", "checkpoint_every", "log_every", "seed")},
        )


def default_config() -> RunConfig:
    return RunConfig()
