"""Windowed episode stats shared by the host-driven actor pools.

``DeviceActor`` accumulates its window on-device and drains it in one host
sync; the host pools (``ActorPool``, ``VecActorPool``) already keep their
episode counters on the host, so their window is just a delta against the
counters at the previous drain. Same drain cadence, same ``*_recent`` keys —
which is what lets the learner's best-model checkpointing
(``Learner._maybe_save_best``) work identically across all actor modes.

Outcome attribution (ISSUE 15): the episode-end sites the pools already
own are ALSO where per-opponent game-quality telemetry is born, so this
mixin is the host-actor half of the outcome plane's extraction layer —
``record_episode_outcome`` lands one completed episode's outcome (bucket,
win, length, side) in the process telemetry registry's ``outcome/``
counters, where external actors' fleet snapshots pick it up
(``utils/fleet.py``) and in-process modes feed the learner's
``OutcomeAggregator`` directly. The device path mirrors the same schema
via in-graph reductions (``outcome/ingraph.py``).
"""

from __future__ import annotations

from typing import Dict, Optional

from dotaclient_tpu.outcome import records as outcome_records
from dotaclient_tpu.utils import telemetry


class WindowedStatsMixin:
    """Mixin over a pool exposing ``episodes_done``/``wins`` counters and an
    append-only ``episode_rewards`` list. Provides ``drain_stats()`` and the
    windowed entries merged into ``stats()`` via ``windowed_entries()``,
    plus the outcome-plane episode recording hook."""

    # set lazily so __init__ orders don't matter
    _win_base_eps = 0
    _win_base_wins = 0
    _win_base_ret_idx = 0

    def record_episode_outcome(
        self,
        bucket: str,
        won: bool,
        ep_len_steps: float,
        side: str = "radiant",
        registry: Optional[telemetry.Registry] = None,
    ) -> None:
        """One completed episode → the ``outcome/`` registry counters
        (owner-lane convention: call once per finished game, at the same
        site that bumps ``episodes_done``/``wins``)."""
        outcome_records.record_episode(
            registry if registry is not None else telemetry.get_registry(),
            bucket,
            won,
            ep_len_steps,
            side,
        )

    def drain_stats(self) -> Dict[str, float]:
        """Close the current window (since the previous drain) and return
        ``stats()`` with the fresh window in the ``*_recent`` keys."""
        self._recent_window = {
            "episodes": float(self.episodes_done - self._win_base_eps),
            "wins": float(self.wins - self._win_base_wins),
            "ep_return_sum": float(
                sum(self.episode_rewards[self._win_base_ret_idx:])
            ),
        }
        self._win_base_eps = self.episodes_done
        self._win_base_wins = self.wins
        self._win_base_ret_idx = len(self.episode_rewards)
        return self.stats()

    def windowed_entries(self) -> Dict[str, float]:
        recent = getattr(self, "_recent_window", None) or {}
        r_eps = recent.get("episodes", 0.0)
        return {
            "episodes_recent": r_eps,
            "win_rate_recent": (
                recent.get("wins", 0.0) / r_eps if r_eps else 0.0
            ),
            "ep_reward_recent": (
                recent.get("ep_return_sum", 0.0) / r_eps if r_eps else 0.0
            ),
        }
