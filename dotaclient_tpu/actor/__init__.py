"""Batched actor runtime (scalar gRPC-parity pool + vectorized pool)."""

from dotaclient_tpu.actor.runtime import ActorPool, build_game_config
from dotaclient_tpu.actor.vec_runtime import VecActorPool, make_device_step

__all__ = ["ActorPool", "VecActorPool", "build_game_config", "make_device_step"]
