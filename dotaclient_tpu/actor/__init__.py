"""Batched actor runtime."""

from dotaclient_tpu.actor.runtime import ActorPool, build_game_config

__all__ = ["ActorPool", "build_game_config"]
