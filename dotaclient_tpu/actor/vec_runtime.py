"""Vectorized actor pool: N games × P players stepped as arrays.

Same responsibilities and chunk semantics as ``actor.runtime.ActorPool``
(truncated-BPTT chunks with carry0 + T+1 obs + version tags, SURVEY.md §5.7,
§3.1) but the environment is a ``VecLaneSim`` and featurize / reward / action
translation are single vectorized calls (`features.vec_featurizer`) — the
round-2 fix for the Python-per-lane hot loop (VERDICT round 1, "What's weak"
#1). One jitted device dispatch and one host fetch per step, exactly like the
scalar pool.

Rollout delivery: in-process consumers take *decoded* rollouts (the
``(meta, arrays)`` form ``TrajectoryBuffer.add`` ingests) through
``rollout_sink`` — no proto round-trip on the hot path. The proto wire format
still applies when shipping through a ``Transport`` (cluster topology,
SURVEY.md §2.4).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dotaclient_tpu.actor.window_stats import WindowedStatsMixin
from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.outcome import records as outcome_records
from dotaclient_tpu.utils import faults, fleet, telemetry, tracing, utilization
from dotaclient_tpu.envs.vec_lane_sim import (
    OPPONENT_CONTROL,
    VecLaneSim,
    VecSimSpec,
    apply_anchor_games,
    draft_games,
)
from dotaclient_tpu.features.vec_featurizer import VecFeaturizer, VecRewards
from dotaclient_tpu.models import distributions as D
from dotaclient_tpu.models.policy import Policy
from dotaclient_tpu.protos import dota_pb2 as pb
from dotaclient_tpu.transport import (
    Transport,
    decode_weights,
    encode_rollout,
    encode_rollout_bytes,
)

DecodedRollout = Tuple[Dict[str, Any], Any]


def make_device_step(policy: Policy):
    """The batched actor device step (shared shape with
    ``ActorPool._device_step``): zero reset carries, split key, forward +
    sample; host-bound outputs packed into one fetch."""

    from dotaclient_tpu.models.policy import mask_carry

    def _step(params, obs_batch, carry, key, reset_mask):
        key, sub = jax.random.split(key)
        carry = mask_carry(carry, 1.0 - reset_mask.astype(jnp.float32))
        logits, _, new_carry = policy.apply(params, obs_batch, carry, method="step")
        actions, logp = D.sample(sub, logits, obs_batch)
        packed = jnp.stack([actions[h] for h in D.HEADS], axis=1).astype(jnp.int32)
        carry_f32 = jax.tree.map(lambda t: t.astype(jnp.float32), new_carry)
        return (packed, logp, carry_f32), (new_carry, key)

    return jax.jit(_step)


class VecActorPool(WindowedStatsMixin):
    """Batched actor over a vectorized sim. Public surface matches
    ``ActorPool`` (step/run/stats/set_params/refresh_weights/params/version).
    """

    def __init__(
        self,
        config: RunConfig,
        policy: Policy,
        params: Any,
        transport: Optional[Transport] = None,
        seed: int = 0,
        version: int = 0,
        rollout_sink: Optional[Callable[[List[DecodedRollout]], None]] = None,
    ) -> None:
        self.config = config
        self.policy = policy
        self._weights = (params, version)
        self.transport = transport
        self.rollout_sink = rollout_sink
        env = config.env

        N, P = env.n_envs, 2 * env.team_size
        spec = VecSimSpec(
            n_games=N,
            team_size=env.team_size,
            max_units=config.obs.max_units,
            ticks_per_obs=env.ticks_per_observation,
            max_dota_time=env.max_dota_time,
            move_bins=config.actions.move_bins,
        )
        hero_ids, control = draft_games(
            N, env.team_size, env.hero_pool, env.opponent, seed
        )
        opp_mode = OPPONENT_CONTROL[env.opponent]
        # No per-game attribution mask here (unlike DeviceActor): host-pool
        # league draws never feed PFSP outcome attribution (the learner
        # warns and keeps the uniform prior), so there is nothing for
        # anchor games to contaminate.
        self.n_anchor_games = apply_anchor_games(
            control, env.team_size, env.opponent, config.league
        )
        self.sim = VecLaneSim(spec, hero_ids, control, seed=seed)
        self._reseed_rng = np.random.default_rng(seed ^ 0x5EED)

        # Learner lanes: every CONTROL_AGENT player on the Radiant side plus —
        # in self-play — the Dire side (all lanes ship experience and share
        # the live params; league opponents get frozen params via
        # ``set_opponent`` and never ship).
        if opp_mode == pb.CONTROL_AGENT:
            learner_players = list(range(P)) if env.opponent == "selfplay" else list(range(env.team_size))
            opponent_players = (
                [] if env.opponent == "selfplay" else list(range(env.team_size, P))
            )
        else:
            learner_players = list(range(env.team_size))
            opponent_players = []
        self.feat = VecFeaturizer(self.sim, config.obs, config.actions, learner_players)
        self.rewards = VecRewards(
            self.sim, learner_players, weights=dict(config.reward.as_dict())
        )
        self._opponent: Optional["_OpponentLanes"] = None
        if opponent_players:
            self._opponent = _OpponentLanes(
                self, opponent_players, params, version
            )

        L = self.feat.n_lanes
        self.n_lanes = L
        T = config.ppo.rollout_len

        self._carry_dev = policy.initial_state(L)
        self._key_dev = jax.random.PRNGKey(seed)
        self._reset_mask = np.zeros((L,), np.bool_)
        self._step_fn = make_device_step(policy)

        obs0 = self.feat.featurize_all()
        self._pending_obs = obs0
        self._obs_buf = {
            k: np.zeros((L, T + 1) + v.shape[1:], v.dtype) for k, v in obs0.items()
        }
        self._act_buf = np.zeros((L, T, len(D.HEADS)), np.int32)
        self._logp_buf = np.zeros((L, T), np.float32)
        self._rew_buf = np.zeros((L, T), np.float32)
        self._done_buf = np.zeros((L, T), np.float32)
        self._cursor = np.zeros((L,), np.int64)
        # carry0 snapshots: host pytree mirroring the policy's carry layout
        # (LSTM (h, c) or transformer KV cache), f32
        self._carry0 = jax.tree.map(
            lambda t: np.zeros(t.shape, np.float32), self._carry_dev
        )
        self._version0 = np.full((L,), version, np.int64)
        self._lane_reward = np.zeros((L,), np.float64)

        self._next_rollout_id = 0
        self.env_steps = 0
        self.rollouts_shipped = 0
        self.episodes_done = 0
        self.episode_rewards: List[float] = []
        self.wins = 0
        self._tel = telemetry.get_registry()
        # Outcome plane (ISSUE 15): per-game episode-length accounting +
        # the opponent bucket this pool's games attribute to; counters
        # eager-created so the first fleet snapshot ships the zeroed set.
        outcome_records.ensure_actor_metrics(self._tel)
        self._outcome_bucket = outcome_records.opponent_bucket(env.opponent)
        self._ep_game_steps = np.zeros((N,), np.int64)
        self._faults = faults.get()   # None unless chaos injection is on
        # Fleet-health publisher (ISSUE 13): captured ONCE like the fault
        # registry and the tracer — with the fanout off (in-proc pools,
        # --fleet-interval 0) the run loop pays exactly one `is not None`
        # test per refresh boundary (pinned by test).
        self._fleet = fleet.get()
        # Pipeline tracing (ISSUE 12): the tracer is captured ONCE, like
        # the fault registry — with tracing off the ship path pays exactly
        # one `is not None` test per emit batch (pinned by test). Per-lane
        # chunk-start stamps exist only when tracing is on.
        self._tracer = tracing.get()
        # Utilization plane (ISSUE 16): always-on phase accounting — keys
        # eager-created by the factory, None when the module knob is off
        # (one pointer test per call site, same discipline as faults).
        self._util = utilization.make_actor(self._tel)
        self._actor_tag = seed & 0xFFFF
        self._chunk_start = (
            np.full((L,), tracing.now()) if self._tracer is not None else None
        )
        # Rollout wire narrowing (ISSUE 7): encode-time kwargs derived once
        # from config. In-proc delivery (rollout_sink) ships full-width
        # decoded arrays; the learner's buffer quantizes at its own door
        # per its config.
        from dotaclient_tpu.transport.serialize import rollout_wire_kwargs

        self._wire_kwargs = rollout_wire_kwargs(config)
        # Every distinct weight version this pool has ever APPLIED — the
        # chaos harness's evidence that no poisoned (health-blocked)
        # version reached an actor (scripts/chaos_run.py divergence
        # scenario; bounded by the number of publishes).
        self.versions_applied = {version}

    # -- weights -----------------------------------------------------------

    @property
    def params(self) -> Any:
        return self._weights[0]

    @property
    def version(self) -> int:
        return self._weights[1]

    def set_params(self, params: Any, version: int) -> None:
        # per-actor refresh lag: how many optimizer versions this pool was
        # behind at the moment it caught up (IMPACT-style staleness)
        self._tel.gauge("actor/weight_refresh_lag").set(version - self.version)
        self._weights = (params, version)
        self.versions_applied.add(version)

    def set_opponent(self, params: Any, version: int) -> None:
        """Give the opponent lanes (league mode) their frozen params."""
        if self._opponent is None:
            raise ValueError("no opponent lanes (opponent is scripted or selfplay)")
        self._opponent.set_params(params, version)

    def refresh_weights(self) -> bool:
        if self.transport is None:
            return False
        msg = self.transport.latest_weights()
        if msg is None or msg.version == self.version:
            return False
        self._tel.gauge("actor/weight_refresh_lag").set(
            msg.version - self.version
        )
        version, tree = decode_weights(msg)
        self._weights = (jax.tree.map(jnp.asarray, tree), version)
        self.versions_applied.add(version)
        if self._tracer is not None:
            # staleness attribution (ISSUE 12): the publish-side trace
            # record (when the learner traces too) dates this version's
            # fanout; the apply event closes the publish→apply leg
            from dotaclient_tpu.transport.serialize import weights_trace

            rec = tracing.parse_blob(weights_trace(msg) or b"")
            publish_ts = rec["hops"][0][1] if rec and rec["hops"] else None
            self._tracer.emit(
                "apply", version=int(version), publish_ts=publish_ts
            )
        return True

    # -- stepping ----------------------------------------------------------

    def step(self) -> None:
        """Advance every game one step: one device dispatch, one fetch."""
        with self._tel.span("actor/step"):
            self._step_impl()
        self._tel.counter("actor/env_steps").inc(self.n_lanes)

    def _step_impl(self) -> None:
        cfg = self.config
        T = cfg.ppo.rollout_len
        L = self.n_lanes
        lanes = np.arange(L)
        obs = self._pending_obs
        params, version = self._weights

        # actor/infer = jitted dispatch + the one host fetch. The opponent
        # stays between them (overlapping the device) but its host compute
        # must not be attributed to inference, so time the two segments
        # explicitly instead of spanning the whole block.
        t0 = time.perf_counter()
        host_out, (self._carry_dev, self._key_dev) = self._step_fn(
            params, obs, self._carry_dev, self._key_dev, self._reset_mask
        )
        infer_s = time.perf_counter() - t0
        opp_actions = None
        if self._opponent is not None:
            opp_actions = self._opponent.step()
        t1 = time.perf_counter()
        actions_np, logp_np, carry_np = jax.device_get(host_out)
        infer_s += time.perf_counter() - t1
        self._tel.timer("span/actor/infer").observe(infer_s)
        self._reset_mask[:] = False

        # record pre-action obs + sampled actions at each lane's cursor
        cur = self._cursor
        for k, v in obs.items():
            self._obs_buf[k][lanes, cur] = v
        self._act_buf[lanes, cur] = actions_np
        self._logp_buf[lanes, cur] = logp_np

        sim_actions = self.feat.actions_to_sim(actions_np)
        if opp_actions is not None:
            for k in sim_actions:
                np.copyto(
                    sim_actions[k], opp_actions[k],
                    where=self._opponent.player_mask[None, :],
                )
        t_env = time.perf_counter()
        self.sim.step(sim_actions)

        r = self.rewards.compute()                                 # [L]
        if self._util is not None:
            # env_step = sim advance + reward compute (both host-side
            # simulation work, indivisible from the env's point of view)
            self._util.phase("env_step", time.perf_counter() - t_env)
        # outcome plane: every live game advanced one env step, and the
        # step's weighted per-term reward sums feed the decomposition
        self._ep_game_steps += 1
        outcome_records.add_reward_terms(
            self._tel, self.rewards.last_term_sums
        )
        done_game = self.sim.done                                  # [N]
        A = len(self.feat.agent_players)
        done_lane = np.repeat(done_game, A)                        # [L]
        self._rew_buf[lanes, cur] = r
        self._done_buf[lanes, cur] = done_lane
        self._lane_reward += r
        self._cursor += 1
        self.env_steps += L

        t_feat = time.perf_counter()
        obs_next = self.feat.featurize_all()
        if self._util is not None:
            self._util.phase("featurize", time.perf_counter() - t_feat)
        finished = (self._cursor >= T) | done_lane
        if finished.any():
            self._emit_chunks(np.nonzero(finished)[0], done_lane, obs_next, carry_np, version)

        if done_game.any():
            games = np.nonzero(done_game)[0]
            self._record_episodes(games)
            self.sim.reset(
                games,
                seeds=self._reseed_rng.integers(0, 2**31 - 1, size=len(games)),
            )
            # Terminal→fresh state is not an experienced transition: without a
            # re-snapshot the next compute() would credit the new episode's
            # first action with the (huge, negative) reset delta.
            self.rewards.snapshot()
            if self._opponent is not None:
                self._opponent.on_reset(games)
            self._reset_mask |= done_lane
            t_feat = time.perf_counter()
            obs_next = self.feat.featurize_all()  # fresh-episode observations
            if self._util is not None:
                self._util.phase("featurize", time.perf_counter() - t_feat)
        self._pending_obs = obs_next

    def _emit_chunks(
        self,
        lanes: np.ndarray,
        done_lane: np.ndarray,
        obs_next: Dict[str, np.ndarray],
        carry_np: Tuple[np.ndarray, np.ndarray],
        version: int,
    ) -> None:
        """Ship finished lanes' chunks; reset their accumulators."""
        cfg = self.config
        T = cfg.ppo.rollout_len
        out: List[DecodedRollout] = []
        blobs: List[Optional[bytes]] = []   # wire trace blob per chunk
        t_enc = time.perf_counter()
        for l in lanes:
            n = int(self._cursor[l])
            done = bool(done_lane[l])
            # bootstrap obs at position n; pad the rest by repeating it
            for k, v in obs_next.items():
                self._obs_buf[k][l, n:] = v[l]
            # pad steps beyond n
            self._act_buf[l, n:] = 0
            self._logp_buf[l, n:] = 0.0
            self._rew_buf[l, n:] = 0.0
            self._done_buf[l, n:] = 1.0
            valid = np.zeros((T,), np.float32)
            valid[:n] = 1.0
            arrays = {
                "obs": {k: v[l].copy() for k, v in self._obs_buf.items()},
                "actions": {
                    h: self._act_buf[l, :, j].copy()
                    for j, h in enumerate(D.HEADS)
                },
                "behavior_logp": self._logp_buf[l].copy(),
                "rewards": self._rew_buf[l].copy(),
                "dones": self._done_buf[l].copy(),
                "valid": valid,
                "carry0": jax.tree.map(lambda b: b[l].copy(), self._carry0),
            }
            meta = {
                "model_version": int(self._version0[l]),
                "env_id": int(l) // max(len(self.feat.agent_players), 1),
                "rollout_id": self._next_rollout_id,
                "length": n,
                "total_reward": float(self._rew_buf[l, :n].sum()),
            }
            self._next_rollout_id += 1
            trace_blob = None
            if self._tracer is not None:
                # per-lane chunk window: collect = when this lane's chunk
                # began accumulating (previous emit / pool start)
                collect_ts = float(self._chunk_start[l])
                self._chunk_start[l] = tracing.now()
                if self._tracer.should_sample():
                    rec = tracing.new_record(
                        self._tracer.next_tid(self._actor_tag),
                        self._actor_tag,
                        meta["model_version"],
                    )
                    rec["hops"].append(["collect", collect_ts])
                    tracing.append_hop(rec, "encode")
                    # actor-side partial record (the merge's origin-side
                    # evidence even when this process is later SIGKILLed)
                    self._tracer.emit_chunk(rec)
                    if self.rollout_sink is not None:
                        # in-proc delivery: the host record rides the meta
                        # directly — downstream hops append to it in place
                        meta["trace"] = rec
                    else:
                        trace_blob = tracing.record_to_blob(rec)
            blobs.append(trace_blob)
            if self._faults is not None and self._faults.fire(
                "actor.nonfinite_payload"
            ):
                # semantic-integrity chaos (ISSUE 6): a NaN reward ships in
                # an otherwise well-formed frame — the CRC layer passes it,
                # the learner buffer's admission control must reject it
                arrays["rewards"][0] = np.nan
            out.append((meta, arrays))
            # next chunk state
            self._cursor[l] = 0
            self._version0[l] = version
            if done:
                for buf in jax.tree.leaves(self._carry0):
                    buf[l] = 0.0
            else:
                for buf, src in zip(
                    jax.tree.leaves(self._carry0), jax.tree.leaves(carry_np)
                ):
                    buf[l] = src[l]
        if self._util is not None:
            # encode = chunk assembly (buffer slicing, pad, trace stamps);
            # the publish leg below is ship_wait
            self._util.phase("encode", time.perf_counter() - t_enc)
        self._tel.counter("actor/rollouts_shipped").inc(len(out))
        self._tel.counter("actor/frames_shipped").inc(
            float(sum(m["length"] for m, _ in out))
        )
        t_ship = time.perf_counter()
        if self.rollout_sink is not None:
            self.rollout_sink(out)
        elif self.transport is not None:
            # wire fast path: C encoder straight from the numpy buffers when
            # the transport ships bytes (socket/AMQP); in-proc passes protos
            publish_bytes = getattr(
                self.transport, "publish_rollout_bytes", None
            )
            for (meta, arrays), blob in zip(out, blobs):
                if publish_bytes is not None:
                    publish_bytes(
                        encode_rollout_bytes(
                            arrays, **meta, **self._wire_kwargs, trace=blob
                        )
                    )
                else:
                    self.transport.publish_rollout(
                        encode_rollout(
                            arrays, **meta, **self._wire_kwargs, trace=blob
                        )
                    )
        if self._util is not None:
            self._util.phase("ship_wait", time.perf_counter() - t_ship)
        self.rollouts_shipped += len(out)

    def _record_episodes(self, games: np.ndarray) -> None:
        from dotaclient_tpu.envs.lane_sim import TEAM_RADIANT

        A = len(self.feat.agent_players)
        owner_team = self.sim.player_team(int(self.feat.agent_players[0]))
        side = "radiant" if owner_team == TEAM_RADIANT else "dire"
        for g in games:
            self.episodes_done += 1
            owner_lane = int(g) * A
            self.episode_rewards.append(float(self._lane_reward[owner_lane]))
            won = int(self.sim.winning_team[g]) == owner_team
            if won:
                self.wins += 1
            # anchor games (the first n_anchor_games) played a scripted
            # bot regardless of the pool's nominal opponent mode
            bucket = (
                "vs_scripted"
                if int(g) < self.n_anchor_games
                else self._outcome_bucket
            )
            self.record_episode_outcome(
                bucket,
                won,
                int(self._ep_game_steps[g]),
                side=side,
                registry=self._tel,
            )
            self._ep_game_steps[int(g)] = 0
            self._lane_reward[int(g) * A:(int(g) + 1) * A] = 0.0

    # -- driving -----------------------------------------------------------

    def run(self, n_steps: int, refresh_every: int = 8) -> Dict[str, float]:
        for t in range(n_steps):
            if refresh_every and t % refresh_every == 0:
                self.refresh_weights()
                if self._fleet is not None and self.transport is not None:
                    # cadence-gated inside (one clock compare); send
                    # errors propagate like a failed rollout publish —
                    # the actor's reconnect machinery owns them
                    self._fleet.maybe_publish(self.transport)
                if self._util is not None:
                    # cadence-gated fold (one clock compare) at refresh
                    # boundaries, same rhythm as the fleet publisher
                    self._util.maybe_fold()
            self.step()
        return self.stats()

    def flush_partial(self) -> int:
        """Ship every lane's in-progress (cursor > 0) chunk NOW — the
        graceful-stop path (ISSUE 4): a SIGTERM'd actor flushes the partial
        rollouts it is holding instead of discarding up to
        ``rollout_len - 1`` steps of experience per lane. Chunks go out with
        their true ``length`` and a zero-padded tail exactly like the
        episode-boundary partials ``_emit_chunks`` already ships, so the
        learner's buffer needs nothing new. Returns the chunk count."""
        lanes = np.nonzero(self._cursor > 0)[0]
        if len(lanes) == 0:
            return 0
        carry_np = jax.device_get(self._carry_dev)
        self._emit_chunks(
            lanes,
            np.zeros(self.n_lanes, dtype=bool),
            self._pending_obs,
            carry_np,
            self.version,
        )
        return len(lanes)

    def stats(self) -> Dict[str, float]:
        recent = self.episode_rewards[-20:]
        return {
            "env_steps": float(self.env_steps),
            "rollouts_shipped": float(self.rollouts_shipped),
            "episodes_done": float(self.episodes_done),
            "episode_reward_mean": float(np.mean(recent)) if recent else 0.0,
            "win_rate": (
                self.wins / self.episodes_done if self.episodes_done else 0.0
            ),
            **self.windowed_entries(),
        }


class _OpponentLanes:
    """Opponent-controlled players (league mode): frozen params drive the
    Dire side through a second featurizer + device step; their experience is
    never shipped (SURVEY.md §7 step 7)."""

    def __init__(
        self,
        pool: VecActorPool,
        players: List[int],
        params: Any,
        version: int,
    ) -> None:
        self.pool = pool
        self.players = players
        self.player_mask = np.zeros((pool.sim.spec.n_players,), bool)
        self.player_mask[players] = True
        self.feat = VecFeaturizer(
            pool.sim, pool.config.obs, pool.config.actions, players
        )
        self._weights = (params, version)
        L = self.feat.n_lanes
        self._carry = pool.policy.initial_state(L)
        self._key = jax.random.PRNGKey(hash(tuple(players)) & 0x7FFFFFFF)
        self._reset = np.zeros((L,), np.bool_)

    def set_params(self, params: Any, version: int) -> None:
        self._weights = (params, version)

    def on_reset(self, games: np.ndarray) -> None:
        A = len(self.players)
        for g in games:
            self._reset[int(g) * A:(int(g) + 1) * A] = True

    def step(self) -> Dict[str, np.ndarray]:
        obs = self.feat.featurize_all()
        params, _ = self._weights
        (packed, _, _), (self._carry, self._key) = self.pool._step_fn(
            params, obs, self._carry, self._key, self._reset
        )
        self._reset[:] = False
        return self.feat.actions_to_sim(jax.device_get(packed))
