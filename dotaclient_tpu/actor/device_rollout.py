"""On-device rollout generation: policy + env + reward in ONE XLA program.

The third and fastest actor (after the scalar proto pool and the numpy
vectorized pool): the jittable ``jax_lane_sim`` makes the entire experience
loop a ``lax.scan`` — featurize → policy step → sample → env step → reward →
in-scan episode reset — compiled once and run for a whole T-step chunk per
dispatch. Per-chunk host traffic is ZERO on the experience path (chunks are
consumed device-to-device by the trajectory buffer); only tiny episode stats
are fetched, and only at log boundaries.

This is the Anakin/Podracer architecture (PAPERS.md [P:7]) and the design
answer to SURVEY.md §7 hard-part 2: on this sandbox's tunneled TPU a single
host↔device round trip costs ~100 ms, which bounds any host-driven actor at
~10 chunks/sec regardless of batch size; the on-device loop is bounded by
compute instead.

Chunks SPAN episodes (valid is all-ones; ``dones`` marks boundaries and the
learner's sequence mode resets the carry mid-chunk — ``Policy.sequence``) so
no frame is ever padding: fixed shapes, zero waste.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.envs import jax_lane_sim as sim_mod
from dotaclient_tpu.envs.vec_lane_sim import VecSimSpec, draft_games
from dotaclient_tpu.features.jax_featurizer import (
    JaxFeaturizer,
    shaped_reward_terms,
)
from dotaclient_tpu.features.reward import fold_terms
from dotaclient_tpu.models import distributions as D
from dotaclient_tpu.models.policy import Policy, mask_carry
from dotaclient_tpu.outcome import ingraph as outcome_ingraph
from dotaclient_tpu.outcome import records as outcome_records
from dotaclient_tpu.protos import dota_pb2 as pb
from dotaclient_tpu.utils import telemetry


class DeviceActorState(NamedTuple):
    """Everything the rollout loop carries across chunks, device-resident."""

    sim: sim_mod.SimState
    carry: Tuple[jnp.ndarray, jnp.ndarray]       # learner lanes' LSTM state
    opp_carry: Tuple[jnp.ndarray, jnp.ndarray]   # opponent lanes' (or dummy)
    # f32/u32 [N, 2] per-GAME PRNG keys: each game's lanes sample from that
    # game's key, so action sampling is shard-local when the game axis is
    # partitioned over the mesh (and bitwise independent of the shard count)
    key: jnp.ndarray
    ep_return: jnp.ndarray                       # f32 [L] running episode return
    # i32 [N] env steps into each game's CURRENT episode (outcome plane:
    # episode length at the done site, reset in-scan)
    ep_steps: jnp.ndarray
    # cumulative episode stats, accumulated IN the rollout program as
    # per-game/per-lane PARTIALS (shard-local, no in-program collective);
    # a drain fetches them and reduce_device_stats sums the game axis
    stats: Dict[str, jnp.ndarray]


def actor_state_sharding(state: DeviceActorState, mesh, mesh_config):
    """The lane sharding of one ``DeviceActorState``: a matching tree of
    ``NamedSharding``s, game/lane leading axes partitioned over the
    (dcn×)data mesh axes, true scalars replicated.

    One rule (``parallel.mesh.row_sharding``): a leaf whose leading axis
    divides the batch shard count is data-sharded, anything else is
    replicated. Lane order is game-major (lane = game·A + player), so a
    game count divisible by the shard count keeps every derived lane
    tensor — featurized obs, carries, rewards — local to its games' shard;
    ``make_fused_step`` enforces that divisibility up front. The sim's
    batch-wide PRNG key (creep-wave jitter only) is pinned replicated
    explicitly: its [2] shape must never be mistaken for a 2-row batch.
    """
    from dotaclient_tpu.parallel.mesh import replicated, row_sharding

    repl = replicated(mesh)

    def rows(leaf):
        n = leaf.shape[0] if getattr(leaf, "ndim", 0) else 0
        return row_sharding(mesh, mesh_config, n)

    sim_sh = state.sim._replace(
        **{
            f: (repl if f == "key" else rows(getattr(state.sim, f)))
            for f in sim_mod.SimState._fields
        }
    )
    return DeviceActorState(
        sim=sim_sh,
        carry=jax.tree.map(rows, state.carry),
        opp_carry=jax.tree.map(rows, state.opp_carry),
        key=rows(state.key),
        ep_return=rows(state.ep_return),
        ep_steps=rows(state.ep_steps),
        stats=jax.tree.map(rows, state.stats),
    )


def reduce_device_stats(stats: Dict[str, Any]) -> Dict[str, Any]:
    """Collapse fetched per-game/per-lane stat partials to the scalar dict
    the host surfaces expect (counters → scalars, the per-game episode-
    length histogram ``[N, B]`` → ``[B]``). Pure host numpy — the drain
    reduces AFTER its one batched fetch; scalar-shaped legacy accumulators
    pass through unchanged."""
    out: Dict[str, Any] = {}
    for k, v in stats.items():
        if isinstance(v, dict):
            out[k] = reduce_device_stats(v)
        elif k == "out_ep_len_hist":
            a = np.asarray(v)
            out[k] = a.sum(axis=0) if a.ndim == 2 else a
        else:
            out[k] = np.asarray(v).sum()
    return out


def sample_per_game(
    keys: jnp.ndarray, logits, obs, n_games: int
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """``D.sample`` vmapped over the game axis: lanes are game-major, so
    each game's block of lanes draws from that game's own key ``[N, 2]``.
    Random-bit generation therefore partitions WITH the games when they
    shard over a mesh — a single batch-wide key would make every device
    generate the full lane set's bits — and the sampled actions are
    bitwise independent of the shard count."""
    def split_g(t):
        return t.reshape((n_games, t.shape[0] // n_games) + t.shape[1:])

    def merge_g(t):
        return t.reshape((t.shape[0] * t.shape[1],) + t.shape[2:])

    acts, logp = jax.vmap(D.sample)(
        keys, jax.tree.map(split_g, logits), jax.tree.map(split_g, obs)
    )
    return jax.tree.map(merge_g, acts), merge_g(logp)


def build_spec(config: RunConfig) -> VecSimSpec:
    env = config.env
    return VecSimSpec(
        n_games=env.n_envs,
        team_size=env.team_size,
        max_units=config.obs.max_units,
        ticks_per_obs=env.ticks_per_observation,
        max_dota_time=env.max_dota_time,
        move_bins=config.actions.move_bins,
    )


def lane_split(config: RunConfig) -> Tuple[list, list]:
    """(learner players, opponent players) per the opponent mode — identical
    to ``VecActorPool``'s split."""
    env = config.env
    P = 2 * env.team_size
    if env.opponent == "selfplay":
        return list(range(P)), []
    if env.opponent == "league":
        return list(range(env.team_size)), list(range(env.team_size, P))
    return list(range(env.team_size)), []


class DeviceActor:
    """Owns device-resident env+policy state; emits device chunk batches.

    API parallel to the pools where it makes sense (``stats`` /
    ``drain_stats`` are the host-visible surface); the unit of work is
    ``collect(params, opp_params=...)`` → one chunk batch [L, T, ...],
    already on device, ready for ``TrajectoryBuffer.add_device``. Opponent
    params are per-call (the league pool samples a fresh opponent each
    chunk) rather than stored setter state.
    """

    def __init__(
        self,
        config: RunConfig,
        policy: Policy,
        seed: int = 0,
        registry: Optional[telemetry.Registry] = None,
        mesh=None,
        mesh_config=None,
    ) -> None:
        self.config = config
        self.policy = policy
        self.spec = build_spec(config)
        learner_players, opponent_players = lane_split(config)
        self.learner_players = learner_players
        self.opponent_players = opponent_players
        self.feat = JaxFeaturizer(
            self.spec, config.obs, config.actions, learner_players
        )
        self._opp_feat = (
            JaxFeaturizer(self.spec, config.obs, config.actions, opponent_players)
            if opponent_players
            else None
        )
        self.n_lanes = self.feat.n_lanes

        N, P = self.spec.n_games, self.spec.n_players
        hero_ids, control = draft_games(
            N, config.env.team_size, config.env.hero_pool,
            config.env.opponent, seed,
        )
        # League anchor games: shared scheme with the host vec pool
        # (envs.vec_lane_sim.apply_anchor_games — the sim's control-mode
        # override wins over the snapshot policy's actions there). Keeps
        # fight/push behavior in an otherwise pure self-play meta.
        from dotaclient_tpu.envs.vec_lane_sim import apply_anchor_games

        self.n_anchor_games = apply_anchor_games(
            control, config.env.team_size, config.env.opponent, config.league
        )
        # per-game mask of NON-anchor games: PFSP attribution must not
        # credit/blame a snapshot for games a scripted bot actually played
        self._league_game_mask = jnp.arange(N) >= self.n_anchor_games

        key = jax.random.PRNGKey(seed)
        key, k_init = jax.random.split(key)
        sim0 = sim_mod.init_state(self.spec, hero_ids, control, k_init)
        opp_lanes = max(len(opponent_players) * N, 1)
        self.state = DeviceActorState(
            sim=sim0,
            carry=policy.initial_state(self.n_lanes),
            opp_carry=policy.initial_state(opp_lanes),
            # one independent key per game: sampling stays shard-local (and
            # bitwise shard-count-invariant) when games partition over a mesh
            key=jax.random.split(key, N),
            ep_return=jnp.zeros((self.n_lanes,), jnp.float32),
            ep_steps=jnp.zeros((N,), jnp.int32),
            stats=self._zero_stats(),
        )
        # Pod-scale fused Anakin (ISSUE 18): when a mesh is given, the actor
        # state is COMMITTED lane-sharded at construction — games (and the
        # game-major lanes they own) partition over the (dcn×)data axes, so
        # the fused program's pinned in_shardings are satisfied by layout
        # instead of a first-call reshard, and the buffered device mode's
        # inferred-sharding collect computes on local lanes too.
        self.mesh = mesh
        self.mesh_config = mesh_config if mesh_config is not None else (
            config.mesh if mesh is not None else None
        )
        if mesh is not None:
            from dotaclient_tpu.parallel.mesh import batch_shard_count

            # EFFECTIVE lane shard count: the games (and their game-major
            # lanes) must split evenly over the batch shards, else
            # row_sharding has degraded the layout to replicated and the
            # honest answer is 1 — mirrors train/fused.py's eff_shards.
            n = batch_shard_count(mesh, self.mesh_config)
            self.lane_shards = (
                n if self.n_lanes % n == 0 and N % n == 0 else 1
            )
            self.state = jax.device_put(
                self.state,
                actor_state_sharding(self.state, mesh, self.mesh_config),
            )
        else:
            self.lane_shards = 1
        self.lanes_per_shard = self.n_lanes // self.lane_shards
        # Outcome plane (ISSUE 15): static per-game opponent-bucket masks
        # for the in-graph done-masked reductions, and the owner side the
        # drained stats attribute to.
        self._outcome_masks = outcome_ingraph.bucket_masks(
            N, config.env.opponent, self.n_anchor_games
        )
        self._owner_side = (
            "radiant" if learner_players[0] < config.env.team_size else "dire"
        )
        # Quantized experience plane (ISSUE 7): chunks bound for the
        # trajectory buffer narrow to the wire dtypes INSIDE the jitted
        # collect program (obs→bf16, bounded int leaves→int8; pinned
        # leaves stay f32), so ``add_device`` scatters narrow rows into
        # the narrow ring with no extra dispatch. Fused mode calls
        # ``_rollout_impl`` directly and keeps full width — it trains on
        # the chunk in the same program and never stores it, so
        # quantizing there would cost precision for zero resident bytes.
        self._chunk_cast: Dict[str, Any] = {}
        if config.transport.rollout_wire_dtype != "float32":
            from dotaclient_tpu.train.ppo import example_batch
            from dotaclient_tpu.transport.serialize import (
                flatten_tree,
                rollout_cast_plan,
                rollout_int_bounds,
            )

            flat = flatten_tree(example_batch(config, batch=1))
            self._chunk_cast = rollout_cast_plan(
                {n: np.dtype(a.dtype) for n, a in flat.items()},
                config.transport.rollout_wire_dtype,
                rollout_int_bounds(config),
            )

        def _collect_impl(params, state, opp_params):
            new_state, chunk, stats = self._rollout_impl(
                params, state, opp_params
            )
            if self._chunk_cast:
                from dotaclient_tpu.transport.serialize import (
                    apply_cast_plan,
                    flatten_tree,
                    unflatten_tree,
                )

                chunk = unflatten_tree(
                    apply_cast_plan(flatten_tree(chunk), self._chunk_cast)
                )
            return new_state, chunk, stats

        # No donation: the state is small (the big arrays are the chunk
        # OUTPUTS), and zero-initialized carries can alias the same cached
        # constant buffer, which donation would flag as a double-donate.
        self._rollout = jax.jit(_collect_impl)
        # host-side counters, updated from fetched stats at log boundaries
        self.env_steps = 0
        self.rollouts_shipped = 0
        self.episodes_done = 0
        self.wins = 0
        self._reward_sum = 0.0
        self._ep_count_window = 0.0
        self._tel = registry if registry is not None else telemetry.get_registry()
        outcome_records.ensure_actor_metrics(self._tel)

    def reset_recurrent(self) -> None:
        """Zero every lane's recurrent carry (learner + opponent sides).

        Divergence-rollback hygiene (ISSUE 6): carries were computed by
        the poisoned params and would re-poison the restored policy's
        first forward; the sim worlds themselves stay finite (sampled
        actions are always in-range ints) and keep their episodes."""
        opp_lanes = max(
            len(self.opponent_players) * self.spec.n_games, 1
        )
        state = self.state._replace(
            carry=self.policy.initial_state(self.n_lanes),
            opp_carry=self.policy.initial_state(opp_lanes),
        )
        if self.mesh is not None:
            # fresh zero carries are host constants — re-commit them to the
            # lane sharding so the next dispatch starts layout-clean
            state = jax.device_put(
                state, actor_state_sharding(state, self.mesh, self.mesh_config)
            )
        self.state = state

    def _zero_stats(self) -> Dict[str, jnp.ndarray]:
        """Per-game/per-lane PARTIAL accumulators (ISSUE 18): counters keep
        the game axis, per-term reward sums the lane axis, so accumulation
        inside the sharded rollout program never crosses a shard boundary;
        shapes are mesh-size independent (checkpoints restore 8→1 and 1→8
        unchanged). ``reduce_device_stats`` folds them at drain time."""
        N, L = self.spec.n_games, self.n_lanes
        zg = jnp.zeros((N,), jnp.float32)
        zl = jnp.zeros((L,), jnp.float32)
        out = {
            "episodes": zg, "wins": zg, "reward_sum": zl, "ep_return_sum": zg,
            "league_episodes": zg, "league_wins": zg,
        }
        # outcome plane (ISSUE 15): per-bucket episode outcomes, episode
        # lengths (+ pow2 histogram), and the per-term reward sums
        out.update(outcome_ingraph.zero_outcome_stats(N))
        out["out_reward_terms"] = {
            term: zl for term in outcome_records.REWARD_TERMS
        }
        return out

    # -- the jitted chunk generator ---------------------------------------

    def _rollout_impl(
        self,
        params: Any,
        state: DeviceActorState,
        opp_params: Any,
    ):
        cfg = self.config
        spec = self.spec
        T = cfg.ppo.rollout_len
        A = len(self.learner_players)
        feat = self.feat
        owner_team = (
            sim_mod.TEAM_RADIANT
            if self.learner_players[0] < spec.team_size
            else sim_mod.TEAM_DIRE
        )

        carry0 = jax.tree.map(
            lambda t: t.astype(jnp.float32), state.carry
        )

        def body(c, _):
            sim, lstm, opp_lstm, key, ep_ret, ep_steps = c
            # per-GAME key triple [N, 3, 2]: carry / learner lanes / opp
            # lanes — each game's stream is independent, so the whole split
            # is shard-local under the lane sharding
            ks = jax.vmap(lambda k: jax.random.split(k, 3))(key)
            key2, k_act, k_opp = ks[:, 0], ks[:, 1], ks[:, 2]

            obs = feat.featurize(sim)
            logits, _, lstm2 = self.policy.apply(
                params, obs, lstm, method="step"
            )
            acts, logp = sample_per_game(k_act, logits, obs, spec.n_games)
            packed = jnp.stack(
                [acts[h] for h in D.HEADS], axis=1
            ).astype(jnp.int32)
            sim_acts = feat.actions_to_sim(packed)

            if self._opp_feat is not None:
                oobs = self._opp_feat.featurize(sim)
                ologits, _, opp_lstm2 = self.policy.apply(
                    opp_params, oobs, opp_lstm, method="step"
                )
                oacts, _ = sample_per_game(
                    k_opp, ologits, oobs, spec.n_games
                )
                opacked = jnp.stack(
                    [oacts[h] for h in D.HEADS], axis=1
                ).astype(jnp.int32)
                osim = self._opp_feat.actions_to_sim(opacked)
                opp_mask = jnp.zeros((spec.n_players,), bool).at[
                    jnp.asarray(self.opponent_players)
                ].set(True)
                sim_acts = {
                    k: jnp.where(opp_mask[None, :], osim[k], sim_acts[k])
                    for k in sim_acts
                }
            else:
                opp_lstm2 = opp_lstm

            sim2 = sim_mod.step(
                spec, sim, sim_acts,
                scripted_possible=(
                    self.config.env.opponent not in ("selfplay", "league")
                    or self.n_anchor_games > 0
                ),
            )
            r_terms = shaped_reward_terms(
                spec, self.learner_players, sim, sim2,
                weights=cfg.reward.as_dict(),
            )
            # the single-sourced table-order fold: bit-identical to the
            # historical shaped_rewards sum (features.reward.fold_terms)
            r = fold_terms(r_terms)
            done_g = sim2.done
            win_g = done_g & (sim2.winning_team == owner_team)
            ep_ret = ep_ret + r
            # outcome plane: this step closed the episode at length
            # ep_steps+1 for done games; the counter resets in-scan
            ep_steps2 = ep_steps + 1
            ep_len_g = jnp.where(done_g, ep_steps2, 0)
            ep_steps3 = jnp.where(done_g, 0, ep_steps2)

            sim3 = sim_mod.reset_where(spec, sim2, done_g)
            done_lane = jnp.repeat(done_g, A)
            lstm3 = mask_carry(lstm2, 1.0 - done_lane.astype(jnp.float32))
            if self._opp_feat is not None:
                opp_done = jnp.repeat(done_g, len(self.opponent_players))
                opp_lstm3 = mask_carry(
                    opp_lstm2, 1.0 - opp_done.astype(jnp.float32)
                )
            else:
                opp_lstm3 = opp_lstm2

            # completed-episode returns leave through stats; the accumulator
            # resets on done (owner lane per game, matching the pools)
            owner_ret = ep_ret.reshape(-1, A)[:, 0]
            out = {
                "obs": obs,
                "packed": packed,
                "logp": logp,
                "reward": r,
                "done_lane": done_lane.astype(jnp.float32),
                "ep_done": done_g,
                "win": win_g,
                "ep_len": ep_len_g,
                "ep_return": jnp.where(done_g, owner_ret, 0.0),
                # per-term rewards kept PER-LANE [L]: the post-scan sums
                # reduce only the step axis, so the accumulators stay
                # shard-local partials under the lane sharding
                "rew_terms": r_terms,
            }
            ep_ret = jnp.where(done_lane, 0.0, ep_ret)
            return (sim3, lstm3, opp_lstm3, key2, ep_ret, ep_steps3), out

        (sim_f, lstm_f, opp_f, key_f, ep_ret_f, ep_steps_f), outs = jax.lax.scan(
            body,
            (
                state.sim, state.carry, state.opp_carry, state.key,
                state.ep_return, state.ep_steps,
            ),
            None,
            length=T,
        )

        bootstrap = feat.featurize(sim_f)                        # [L, ...]

        def to_chunk_obs(seq, boot):
            # [T, L, ...] -> [L, T+1, ...]
            seq = jnp.moveaxis(seq, 0, 1)
            return jnp.concatenate([seq, boot[:, None]], axis=1)

        obs_seq = jax.tree.map(to_chunk_obs, outs["obs"], bootstrap)
        packed = jnp.moveaxis(outs["packed"], 0, 1)              # [L, T, 5]
        chunk = {
            "obs": obs_seq,
            "actions": {
                h: packed[:, :, j] for j, h in enumerate(D.HEADS)
            },
            "behavior_logp": jnp.moveaxis(outs["logp"], 0, 1),
            "rewards": jnp.moveaxis(outs["reward"], 0, 1),
            "dones": jnp.moveaxis(outs["done_lane"], 0, 1),
            "valid": jnp.ones((self.n_lanes, T), jnp.float32),
            "carry0": carry0,
        }
        lg = self._league_game_mask[None, :]     # [1, N] non-anchor games
        # Stats are PER-GAME/PER-LANE partials (ISSUE 18): only the step
        # axis reduces here, the game/lane axis survives — under the lane
        # sharding every accumulation is shard-local and the rollout half
        # of the fused program emits NO collective. The host folds the
        # surviving axis at drain time (reduce_device_stats).
        stats = {
            "episodes": outs["ep_done"].sum(0).astype(jnp.float32),
            "wins": outs["win"].sum(0).astype(jnp.float32),
            "reward_sum": outs["reward"].sum(0),
            "ep_return_sum": outs["ep_return"].sum(0),
            # snapshot-attributable outcomes only (anchor games excluded)
            "league_episodes": (outs["ep_done"] & lg).sum(0).astype(jnp.float32),
            "league_wins": (outs["win"] & lg).sum(0).astype(jnp.float32),
        }
        # outcome plane (ISSUE 15): done-masked per-bucket reductions +
        # episode-length histogram + the per-term reward decomposition —
        # all accumulated on device, drained with the existing stats sync
        stats.update(
            outcome_ingraph.chunk_outcome_partials(
                outs["ep_done"], outs["win"], outs["ep_len"],
                self._outcome_masks,
            )
        )
        stats["out_reward_terms"] = {
            term: outs["rew_terms"][term].sum(0)
            for term in outcome_records.REWARD_TERMS
        }
        cum_stats = jax.tree.map(
            lambda a, b: a + b, state.stats, stats
        )
        new_state = DeviceActorState(
            sim=sim_f, carry=lstm_f, opp_carry=opp_f, key=key_f,
            ep_return=ep_ret_f, ep_steps=ep_steps_f, stats=cum_stats,
        )
        return new_state, chunk, stats

    # -- host surface ------------------------------------------------------

    def collect(self, params: Any, opp_params: Any = None):
        """Generate one chunk batch [L, T, ...] (device arrays). Returns
        (chunk, device stats dict) — dispatch-only, no host sync.

        League mode REQUIRES ``opp_params`` (the frozen opponent) — falling
        back to the live params would silently turn the league into mirror
        self-play."""
        if self._opp_feat is not None and opp_params is None:
            raise ValueError(
                "opponent lanes exist (league mode): pass opp_params "
                "(e.g. OpponentPool.sample(...)) to collect()"
            )
        if opp_params is None:
            opp_params = params
        # span measures DISPATCH latency only (the program runs async on the
        # device) — watching it grow is how you spot the device falling
        # behind the host without adding a sync to look
        with self._tel.span("actor/collect"):
            self.state, chunk, stats = self._rollout(
                params, self.state, opp_params
            )
        T = self.config.ppo.rollout_len
        self.env_steps += self.n_lanes * T
        self.rollouts_shipped += self.n_lanes
        self._tel.counter("actor/frames_shipped").inc(self.n_lanes * T)
        self._tel.counter("actor/rollouts_shipped").inc(self.n_lanes)
        return chunk, stats

    def begin_drain(self):
        """Dispatch-only half of :meth:`drain_stats` (async snapshots,
        ISSUE 5): copy the device stat accumulators — a tiny jitted copy,
        so a later donating dispatch (the fused step donates the whole
        actor state) can never invalidate the snapshot — reset them, and
        return ``(device_stats, finish)``. ``finish(host_stats)`` runs the
        host-side accounting and returns the scalar dict; the caller (the
        snapshot thread, or :meth:`drain_stats` inline) feeds it the ONE
        batched fetch of ``device_stats``."""
        if not hasattr(self, "_stats_copy"):
            self._stats_copy = jax.jit(
                lambda t: jax.tree.map(jnp.copy, t)
            )
        dev = self._stats_copy(self.state.stats)
        fresh = self._zero_stats()
        if self.mesh is not None:
            # commit the zeroed accumulators back to the lane sharding —
            # uncommitted host zeros would change the collect program's
            # input layout and force a recompile on the next dispatch
            fresh = jax.device_put(
                fresh,
                actor_state_sharding(
                    self.state, self.mesh, self.mesh_config
                ).stats,
            )
        self.state = self.state._replace(stats=fresh)

        def finish(s) -> Dict[str, float]:
            # the fetched accumulators are per-game/per-lane partials —
            # fold the game/lane axes on the host before any consumer
            s = reduce_device_stats(s)
            self.episodes_done += int(s["episodes"])
            self.wins += int(s["wins"])
            self._reward_sum += float(s["ep_return_sum"])
            self._ep_count_window += float(s["episodes"])
            # outcome plane: the drained window's in-graph reductions land
            # in the same outcome/ counters the host pools increment
            outcome_records.fold_device_stats(
                self._tel, s, owner_side=self._owner_side
            )
            # windowed (since previous drain) — the responsive learning signal
            self._recent = {
                "episodes": float(s["episodes"]),
                "wins": float(s["wins"]),
                "ep_return_sum": float(s["ep_return_sum"]),
            }
            return self.stats()

        return dev, finish

    def drain_stats(self) -> Dict[str, float]:
        """Fetch the device-accumulated episode stats (a few scalars, ONE
        host sync regardless of how many chunks were collected); call at
        log boundaries only (async runs fetch via the snapshot thread —
        see :meth:`begin_drain`)."""
        dev, finish = self.begin_drain()
        with self._tel.span("actor/drain"):
            s = jax.device_get(dev)
        return finish(s)

    def stats(self) -> Dict[str, float]:
        # mean return over COMPLETED episodes (owner-lane convention,
        # matching the host pools' episode_reward_mean)
        mean_ep = (
            self._reward_sum / self._ep_count_window
            if self._ep_count_window
            else 0.0
        )
        recent = getattr(self, "_recent", None) or {}
        r_eps = recent.get("episodes", 0.0)
        return {
            "env_steps": float(self.env_steps),
            "rollouts_shipped": float(self.rollouts_shipped),
            "episodes_done": float(self.episodes_done),
            "episode_reward_mean": mean_ep,
            "win_rate": (
                self.wins / self.episodes_done if self.episodes_done else 0.0
            ),
            "episodes_recent": r_eps,
            "win_rate_recent": recent.get("wins", 0.0) / r_eps if r_eps else 0.0,
            "ep_reward_recent": (
                recent.get("ep_return_sum", 0.0) / r_eps if r_eps else 0.0
            ),
        }
