"""Batched actor runtime: many envs, one jitted policy step.

The reference runs one asyncio ``agent.py`` process per game doing batch-1
CPU inference in its hot loop (SURVEY.md §3.1 — "the #1 throughput sin the
TPU rebuild fixes"). Here a single multiplexer owns N environment *lanes*
(an env × agent-controlled player pair), featurizes all of them, and advances
every lane with ONE batched, jitted ``policy.step`` on the device
(SURVEY.md §7 step 6; Podracer/SEED-style batched inference, PAPERS.md).

Rollout-chunk semantics (parity with the reference's truncated-BPTT
transport, SURVEY.md §5.7, and the learner's ``train.ppo.Batch`` contract):

* a chunk is at most ``ppo.rollout_len`` steps and never spans episodes —
  on episode end it is padded (``valid=0``) and shipped early;
* the chunk carries its initial LSTM state (``carry0``) and ``T+1``
  observations (the trailing one is the learner's bootstrap state);
* each chunk is tagged with the model version that produced it.

Weight refresh follows the reference's hot-swap discipline (SURVEY.md §3.4):
the pool polls the transport for the latest published weights between steps
and bumps its version tag.

Host↔device discipline (the round-1 bottleneck, SURVEY.md §7 hard-part 2):
exactly ONE jitted dispatch and ONE host fetch per env step. Host numpy
arrays are passed straight into the jitted call (the transfer rides the
async dispatch path — orders of magnitude cheaper here than an explicit,
synchronizing ``device_put``), the recurrent carry and the PRNG key stay
device-resident between steps (episode resets are applied inside the step
via a mask; the key is split inside), and everything the host loop needs —
packed actions, log-probs, and an f32 carry copy for ``carry0`` snapshots —
comes back in a single ``jax.device_get``. On latency-dominated links the
per-step cost is one round trip, independent of lane count.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dotaclient_tpu.actor.window_stats import WindowedStatsMixin
from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.outcome import records as outcome_records
from dotaclient_tpu.utils import telemetry, utilization
from dotaclient_tpu.envs.env_api import LocalDotaEnv
from dotaclient_tpu.envs import lane_sim
from dotaclient_tpu.features import (
    Observation,
    decode_action,
    featurize,
    observation_to_dict,
    shaped_reward,
    stack_observations,
)
from dotaclient_tpu.models import distributions as D
from dotaclient_tpu.models.policy import Policy
from dotaclient_tpu.protos import dota_pb2 as pb
from dotaclient_tpu.transport import Transport, decode_weights, encode_rollout


def build_game_config(config: RunConfig, seed: int) -> pb.GameConfig:
    """EnvConfig → GameConfig proto (the env-boundary config the reference
    kept as a proto too, SURVEY.md §5.6)."""
    env = config.env
    picks = []
    pool = env.hero_pool or (1,)
    rng = np.random.default_rng(seed)
    opp_mode = {
        "scripted_easy": pb.CONTROL_SCRIPTED_EASY,
        "scripted_hard": pb.CONTROL_SCRIPTED_HARD,
        "selfplay": pb.CONTROL_AGENT,
        "league": pb.CONTROL_AGENT,
    }[env.opponent]
    for team, mode in (
        (lane_sim.TEAM_RADIANT, pb.CONTROL_AGENT),
        (lane_sim.TEAM_DIRE, opp_mode),
    ):
        for _ in range(env.team_size):
            picks.append(
                pb.HeroPick(
                    team_id=team,
                    hero_id=int(rng.choice(pool)),
                    control_mode=mode,
                )
            )
    return pb.GameConfig(
        ticks_per_observation=env.ticks_per_observation,
        seed=seed,
        max_dota_time=env.max_dota_time,
        hero_picks=picks,
    )


@dataclasses.dataclass
class _Lane:
    """One agent-controlled player inside one environment."""

    env_idx: int
    player_id: int
    team_id: int
    prev_ws: pb.WorldState = None  # type: ignore[assignment]
    obs: Observation = None        # type: ignore[assignment]
    # chunk accumulators
    obs_seq: List[Observation] = dataclasses.field(default_factory=list)
    actions: List[Dict[str, int]] = dataclasses.field(default_factory=list)
    logps: List[float] = dataclasses.field(default_factory=list)
    rewards: List[float] = dataclasses.field(default_factory=list)
    dones: List[float] = dataclasses.field(default_factory=list)
    carry0: Tuple[np.ndarray, np.ndarray] = None  # type: ignore[assignment]
    # model version at chunk start — a mid-chunk weight refresh must not
    # re-label earlier steps as fresh, so the chunk ships with the OLDEST
    # version that contributed to it (conservative for staleness filtering).
    version0: int = 0
    # episode stats
    episode_reward: float = 0.0


class ActorPool(WindowedStatsMixin):
    """N-lane batched actor.

    ``opponent="selfplay"`` makes every hero an agent lane sharing the same
    params (the reference's self-play configs, BASELINE.json:8); scripted
    opponents are driven inside the env. League opponents (frozen past
    params) plug in through ``league.opponents`` (separate pools).
    """

    def __init__(
        self,
        config: RunConfig,
        policy: Policy,
        params: Any,
        transport: Optional[Transport] = None,
        env_factory: Callable[[], LocalDotaEnv] = LocalDotaEnv,
        seed: int = 0,
        version: int = 0,
        rollout_sink: Optional[Callable[[pb.Rollout], None]] = None,
    ) -> None:
        if config.model.core != "lstm":
            raise NotImplementedError(
                "ActorPool (the scalar gRPC-parity path) supports the LSTM "
                "core only; the vec/device actors handle any core"
            )
        self.config = config
        self.policy = policy
        self._reward_weights = dict(config.reward.as_dict())
        self._warn_no_anchor_support()
        # (params, version) swap atomically as one tuple: the learner thread
        # may refresh weights while the actor thread is mid-step, and a chunk
        # must never be tagged with a version newer than the params that
        # produced it (the staleness filter keys on the tag).
        self._weights = (params, version)
        self._chunk_version = version
        self.transport = transport
        self.rollout_sink = rollout_sink
        self._seed = seed
        self._next_rollout_id = 0
        self._next_game_seed = seed * 100_003

        self.envs: List[LocalDotaEnv] = [
            env_factory() for _ in range(config.env.n_envs)
        ]
        # per-env episode length in env steps (outcome plane, ISSUE 15)
        self._ep_env_steps: List[int] = [0] * config.env.n_envs
        self._outcome_bucket = outcome_records.opponent_bucket(
            config.env.opponent
        )
        self.lanes: List[_Lane] = []
        for i, env in enumerate(self.envs):
            self._reset_env(i, env)
        n = len(self.lanes)
        H = config.model.hidden_dim
        # Device-resident recurrent state + PRNG key (never pulled per step).
        self._carry_dev = policy.initial_state(n)
        self._key_dev = jax.random.PRNGKey(seed)
        self._reset_mask = np.zeros((n,), np.bool_)
        zeros_row = np.zeros((H,), np.float32)
        for i, lane in enumerate(self.lanes):
            self._begin_chunk(lane, (zeros_row, zeros_row))

        self._step_fn = jax.jit(self._device_step)
        # Rollout wire narrowing (ISSUE 7): encode kwargs derived once from
        # config, applied when chunks leave through a transport (the
        # in-proc rollout_sink keeps full-width protos for gRPC parity).
        from dotaclient_tpu.transport.serialize import rollout_wire_kwargs

        self._wire_kwargs = rollout_wire_kwargs(config)
        # throughput counters
        self.env_steps = 0
        self.rollouts_shipped = 0
        self.episodes_done = 0
        self.episode_rewards: List[float] = []
        self.wins = 0
        self._tel = telemetry.get_registry()
        # outcome counters exist (zeroed) from the first fleet snapshot on
        outcome_records.ensure_actor_metrics(self._tel)
        # Utilization plane (ISSUE 16): always-on phase accounting — keys
        # eager-created by the factory, None when the module knob is off
        # (one pointer test per call site, same discipline as faults).
        self._util = utilization.make_actor(self._tel)

    # -- env / lane lifecycle ---------------------------------------------

    def _warn_no_anchor_support(self) -> None:
        # same visibility discipline as the host-pool PFSP warning: a knob
        # this pool cannot honor must say so, not silently no-op
        cfg = self.config
        if cfg.env.opponent == "league" and cfg.league.anchor_prob > 0:
            print(
                "WARNING: league.anchor_prob is implemented by the "
                "device/fused/vec actors; this scalar pool runs pure "
                "snapshot self-play (no scripted-anchor games)",
                flush=True,
            )

    def _reset_env(self, env_idx: int, env: LocalDotaEnv) -> None:
        game_cfg = build_game_config(self.config, self._next_game_seed)
        self._next_game_seed += 1
        self._ep_env_steps[env_idx] = 0
        init = env.reset(game_cfg)
        assert init.status == pb.STATUS_OK
        # Lanes for this env: every agent-controlled hero.
        existing = [l for l in self.lanes if l.env_idx == env_idx]
        ws_by_team = {ws.team_id: ws for ws in init.world_states}
        agent_players = self._agent_players(game_cfg)
        if existing:
            assert len(existing) == len(agent_players)
            for lane, (player_id, team_id) in zip(existing, agent_players):
                lane.player_id = player_id
                lane.team_id = team_id
                ws = ws_by_team[team_id]
                lane.prev_ws = ws
                lane.obs = self._featurize(ws, player_id)
                lane.episode_reward = 0.0
        else:
            for player_id, team_id in agent_players:
                ws = ws_by_team[team_id]
                lane = _Lane(env_idx=env_idx, player_id=player_id, team_id=team_id)
                lane.prev_ws = ws
                lane.obs = self._featurize(ws, player_id)
                self.lanes.append(lane)

    @staticmethod
    def _agent_players(game_cfg: pb.GameConfig) -> List[Tuple[int, int]]:
        return [
            (pid, pick.team_id)
            for pid, pick in enumerate(game_cfg.hero_picks)
            if pick.control_mode == pb.CONTROL_AGENT
        ]

    def _featurize(self, ws: pb.WorldState, player_id: int) -> Observation:
        return featurize(ws, player_id, self.config.obs, self.config.actions)

    def _begin_chunk(
        self, lane: _Lane, carry0: Tuple[np.ndarray, np.ndarray]
    ) -> None:
        lane.obs_seq = []
        lane.actions = []
        lane.logps = []
        lane.rewards = []
        lane.dones = []
        lane.carry0 = (
            np.asarray(carry0[0], np.float32).copy(),
            np.asarray(carry0[1], np.float32).copy(),
        )
        lane.version0 = self._chunk_version

    # -- device step -------------------------------------------------------

    def _device_step(self, params, obs_batch, carry, key, reset_mask):
        """One batched actor step, fully on device: zero carry rows for lanes
        whose episode just ended, split the key, forward + sample. Outputs are
        split into a host-bound group (packed actions, logp, f32 carry for
        ``carry0`` snapshots — fetched together as ONE transfer) and the
        device-resident group (carry, key) that never leaves HBM."""
        key, sub = jax.random.split(key)
        keep = jnp.logical_not(reset_mask)[:, None].astype(carry[0].dtype)
        carry = (carry[0] * keep, carry[1] * keep)
        logits, _, new_carry = self.policy.apply(
            params, obs_batch, carry, method="step"
        )
        actions, logp = D.sample(sub, logits, obs_batch)
        packed = jnp.stack(
            [actions[h] for h in D.HEADS], axis=1
        ).astype(jnp.int32)
        carry_f32 = (
            new_carry[0].astype(jnp.float32),
            new_carry[1].astype(jnp.float32),
        )
        return (packed, logp, carry_f32), (new_carry, key)

    # -- public API --------------------------------------------------------

    def refresh_weights(self) -> bool:
        """Hot-swap to the latest published weights, if any (SURVEY.md §3.4)."""
        if self.transport is None:
            return False
        msg = self.transport.latest_weights()
        if msg is None or msg.version == self.version:
            return False
        # how far behind this actor was when it caught up — the per-actor
        # refresh lag (IMPACT-style staleness accounting, PAPERS.md)
        self._tel.gauge("actor/weight_refresh_lag").set(
            msg.version - self.version
        )
        version, tree = decode_weights(msg)
        self._weights = (jax.tree.map(jnp.asarray, tree), version)
        return True

    def set_params(self, params: Any, version: int) -> None:
        """Direct replicated-params refresh (in-process learner path — the
        'actors read replicated JAX params' mode of BASELINE.json:5)."""
        self._tel.gauge("actor/weight_refresh_lag").set(version - self.version)
        self._weights = (params, version)

    @property
    def params(self) -> Any:
        return self._weights[0]

    @property
    def version(self) -> int:
        return self._weights[1]

    def step(self) -> None:
        """Advance every lane by one environment step."""
        with self._tel.span("actor/step"):
            self._step_impl()
        self._tel.counter("actor/env_steps").inc(len(self.lanes))

    def _step_impl(self) -> None:
        obs_batch = stack_observations([l.obs for l in self.lanes])
        # One atomic weights read serves the whole step: dispatch uses these
        # params, and chunks beginning this step are tagged with this version.
        params, self._chunk_version = self._weights
        with self._tel.span("actor/infer"):
            host_out, (self._carry_dev, self._key_dev) = self._step_fn(
                params,
                obs_batch,
                self._carry_dev,
                self._key_dev,
                self._reset_mask,
            )
            # ONE host transfer for everything the host loop needs this step —
            # per-array fetches each pay a full device round trip.
            actions_np, logp_np, carry_np = jax.device_get(host_out)
        self._reset_mask[:] = False

        # Submit actions grouped per (env, team) — env steps once all agent
        # teams have acted (env_api contract). Everything from here to the
        # end of the observe/reward loop is env_step for the utilization
        # plane, EXCEPT the per-lane featurize calls (accumulated apart).
        t_env = time.perf_counter()
        feat_s = 0.0
        by_env_team: Dict[Tuple[int, int], List[pb.Action]] = {}
        for i, lane in enumerate(self.lanes):
            idx = {h: int(actions_np[i, j]) for j, h in enumerate(D.HEADS)}
            lane.actions.append(idx)
            lane.logps.append(float(logp_np[i]))
            lane.obs_seq.append(lane.obs)
            proto = decode_action(
                idx, lane.obs, lane.player_id,
                move_bins=self.config.actions.move_bins,
            )
            by_env_team.setdefault((lane.env_idx, lane.team_id), []).append(proto)
        for (env_idx, team_id), protos in by_env_team.items():
            self.envs[env_idx].act(
                pb.Actions(team_id=team_id, actions=protos)
            )

        # Observe, reward, detect episode/chunk boundaries.
        T = self.config.ppo.rollout_len
        finished: List[Tuple[int, _Lane, bool]] = []
        # every env advances one observation per pool step (episode-length
        # accounting for the outcome plane)
        for e in range(len(self.envs)):
            self._ep_env_steps[e] += 1
        step_terms: Dict[str, float] = {}
        for i, lane in enumerate(self.lanes):
            env = self.envs[lane.env_idx]
            resp = env.observe(lane.team_id)
            ws = resp.world_state
            r, terms = shaped_reward(
                lane.prev_ws, ws, lane.player_id,
                weights=self._reward_weights,
            )
            for term, tv in terms.items():
                step_terms[term] = step_terms.get(term, 0.0) + tv
            done = env.done
            lane.rewards.append(r)
            lane.dones.append(1.0 if done else 0.0)
            lane.episode_reward += r
            lane.prev_ws = ws
            t_f = time.perf_counter()
            lane.obs = self._featurize(ws, lane.player_id)
            feat_s += time.perf_counter() - t_f
            self.env_steps += 1
            if done:
                # Fresh episode ⇒ fresh recurrent state: the device step
                # zeroes this row on the NEXT call, and the new chunk's
                # carry0 snapshot below is zeros to match.
                self._reset_mask[i] = True
            if done or len(lane.actions) >= T:
                finished.append((i, lane, done))
            if done and lane is self._env_owner(lane.env_idx):
                self._on_episode_end(lane.env_idx, ws)
        outcome_records.add_reward_terms(self._tel, step_terms)
        if self._util is not None:
            self._util.phase("featurize", feat_s)
            self._util.phase(
                "env_step", time.perf_counter() - t_env - feat_s
            )

        if finished:
            H = self.config.model.hidden_dim
            zeros_row = np.zeros((H,), np.float32)
            for i, lane, done in finished:
                self._finish_chunk(i, lane)
                carry0 = (
                    (zeros_row, zeros_row)
                    if done
                    else (carry_np[0][i], carry_np[1][i])
                )
                self._begin_chunk(lane, carry0)

        # Reset envs whose episode finished (after all lanes shipped chunks).
        for env_idx, env in enumerate(self.envs):
            if env.done:
                self._reset_env(env_idx, env)

    def _env_owner(self, env_idx: int) -> _Lane:
        """First lane of an env (used to count each episode once)."""
        return next(l for l in self.lanes if l.env_idx == env_idx)

    def _on_episode_end(self, env_idx: int, ws: pb.WorldState) -> None:
        """Episode bookkeeping (carry zeroing happens at the done site in
        ``step``; episode_reward resets in ``_reset_env``)."""
        self.episodes_done += 1
        owner = self._env_owner(env_idx)
        self.episode_rewards.append(owner.episode_reward)
        won = ws.winning_team == owner.team_id
        if won:
            self.wins += 1
        self.record_episode_outcome(
            self._outcome_bucket,
            won,
            self._ep_env_steps[env_idx],
            side=(
                "radiant"
                if owner.team_id == lane_sim.TEAM_RADIANT
                else "dire"
            ),
            registry=self._tel,
        )

    def _finish_chunk(self, lane_idx: int, lane: _Lane) -> None:
        """Pad, pack, and ship one rollout chunk."""
        t_enc = time.perf_counter()
        T = self.config.ppo.rollout_len
        n = len(lane.actions)
        assert 0 < n <= T
        valid = [1.0] * n + [0.0] * (T - n)
        # obs sequence: the n step observations + the current (bootstrap)
        # obs, padded to T+1 by repeating the bootstrap.
        obs_seq = lane.obs_seq + [lane.obs] * (T + 1 - n)
        arrays = {
            "obs": {
                k: np.stack([d[k] for d in map(observation_to_dict, obs_seq)])
                for k in observation_to_dict(obs_seq[0])
            },
            "actions": {
                h: np.asarray(
                    [a[h] for a in lane.actions] + [0] * (T - n), np.int32
                )
                for h in self.config.actions.head_sizes
            },
            "behavior_logp": np.asarray(
                lane.logps + [0.0] * (T - n), np.float32
            ),
            "rewards": np.asarray(lane.rewards + [0.0] * (T - n), np.float32),
            "dones": np.asarray(lane.dones + [1.0] * (T - n), np.float32),
            "valid": np.asarray(valid, np.float32),
            "carry0": (lane.carry0[0], lane.carry0[1]),
        }
        meta = dict(
            model_version=lane.version0,
            env_id=lane.env_idx,
            rollout_id=self._next_rollout_id,
            length=n,
            total_reward=float(np.sum(lane.rewards)),
        )
        self._next_rollout_id += 1
        t_ship = time.perf_counter()
        if self._util is not None:
            # chunk assembly above is encode; the publish leg is ship_wait
            self._util.phase("encode", t_ship - t_enc)
        if self.rollout_sink is not None:
            # in-proc consumers get full-width protos (gRPC-parity path —
            # no wire to save bytes on)
            self.rollout_sink(encode_rollout(arrays, **meta))
        elif self.transport is not None:
            self.transport.publish_rollout(
                encode_rollout(arrays, **meta, **self._wire_kwargs)
            )
        if self._util is not None:
            self._util.phase("ship_wait", time.perf_counter() - t_ship)
        self.rollouts_shipped += 1
        self._tel.counter("actor/rollouts_shipped").inc()
        self._tel.counter("actor/frames_shipped").inc(n)

    def run(self, n_steps: int, refresh_every: int = 8) -> Dict[str, float]:
        """Drive the pool for ``n_steps`` batched steps; returns stats."""
        for t in range(n_steps):
            if refresh_every and t % refresh_every == 0:
                self.refresh_weights()
                if self._util is not None:
                    # cadence-gated fold (one clock compare per boundary)
                    self._util.maybe_fold()
            self.step()
        return self.stats()

    def stats(self) -> Dict[str, float]:
        recent = self.episode_rewards[-20:]
        return {
            "env_steps": float(self.env_steps),
            "rollouts_shipped": float(self.rollouts_shipped),
            "episodes_done": float(self.episodes_done),
            "episode_reward_mean": float(np.mean(recent)) if recent else 0.0,
            "win_rate": (
                self.wins / self.episodes_done if self.episodes_done else 0.0
            ),
            **self.windowed_entries(),
        }
