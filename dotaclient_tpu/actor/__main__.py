"""Standalone actor process — the rebuild's ``agent.py`` counterpart.

The reference scale-out topology is N independent rollout-worker processes
feeding one optimizer through a broker (SURVEY.md §1, §2.3 row 1). One such
worker:

    python -m dotaclient_tpu.actor --connect 127.0.0.1:7777 --n-envs 64

connects to the learner's ``TransportServer`` (``--transport socket`` on the
learner), pulls versioned weights from the fanout, runs the vectorized pool,
and ships protobuf rollouts. ``--amqp host[:port]`` targets a RabbitMQ broker
instead (cluster parity). Actors are stateless: on transport loss the process
exits non-zero for the supervisor to restart (SURVEY.md §5.3).

By default the actor pins JAX to CPU: a TPU chip admits one process, and in
the split topology that process is the learner; set ``--platform tpu`` only
for an actor that owns its own accelerator host.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import random
import signal
import sys
import time


def connect_with_backoff(
    factory,
    max_attempts: int = 6,
    base_delay: float = 0.5,
    max_delay: float = 10.0,
    sleep=time.sleep,
    rng: "random.Random | None" = None,
    should_abort=None,
):
    """Call ``factory()`` until it returns a transport, with exponential
    backoff + full jitter between attempts (bounded — a learner that is
    really gone must still fail fast enough for the supervisor to act).

    Every retry (attempt beyond the first) bumps the
    ``transport/reconnects_total`` counter; the final failure re-raises the
    last connection error. ``should_abort`` (when given) is polled between
    backoff segments: a graceful stop requested mid-reconnect abandons the
    remaining schedule within one sleep segment instead of riding out the
    full backoff — at chaos-scale reconnect budgets the tail of the
    schedule can outlive the supervisor's SIGTERM→SIGKILL grace window,
    turning a clean drain (and its ACTOR_VERSIONS_SEEN audit line) into a
    silent kill.
    """
    from dotaclient_tpu.utils import telemetry

    rng = rng or random.Random()
    tel = telemetry.get_registry()
    last: "BaseException | None" = None
    for attempt in range(max_attempts):
        if attempt:
            tel.counter("transport/reconnects_total").inc()
            # full jitter: uniform in (0, base·2^(k-1)], capped — a restarted
            # learner must not be met by a synchronized thundering herd
            delay = min(max_delay, base_delay * (2 ** (attempt - 1)))
            sleep(rng.uniform(0.0, delay))
        if should_abort is not None and should_abort():
            raise ConnectionError(
                "reconnect abandoned: stop requested"
            ) from last
        try:
            return factory()
        except (ConnectionError, OSError) as e:
            last = e
    raise ConnectionError(
        f"transport unreachable after {max_attempts} attempts"
    ) from last


def _transport_factory(args, transport_config):
    """Build the (re)connect callable for the configured transport.
    ``transport_config`` (a TransportConfig) supplies the actor-side
    liveness/poison knobs so they stay in step with the learner's — the
    wire carries no config handshake."""
    if args.connect and args.connect.startswith("shm://"):
        from dotaclient_tpu.transport.shm_transport import ShmTransport

        name = args.connect[len("shm://"):]
        return lambda: ShmTransport(name)
    if args.connect:
        from dotaclient_tpu.transport.socket_transport import SocketTransport

        host, port = args.connect.rsplit(":", 1)
        idle = (
            args.idle_timeout
            if args.idle_timeout is not None
            else transport_config.idle_timeout_s
        )
        return lambda: SocketTransport(
            host, int(port),
            idle_timeout_s=idle,
            poison_frame_limit=transport_config.poison_frame_limit,
        )
    from dotaclient_tpu.transport.queues import AmqpTransport

    host, _, port = args.amqp.partition(":")
    return lambda: AmqpTransport(host, int(port or 5672))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--connect", type=str, default=None,
                   help="learner TransportServer address host:port, or "
                        "shm://NAME for the same-host shared-memory lane")
    p.add_argument("--amqp", type=str, default=None,
                   help="RabbitMQ broker address host[:port]")
    p.add_argument("--n-envs", type=int, default=64)
    p.add_argument("--opponent", type=str, default="scripted_easy")
    p.add_argument("--team-size", type=int, default=1)
    p.add_argument("--max-dota-time", type=float, default=None,
                   help="episode horizon in game seconds (timeout "
                        "adjudication decides unfinished games); default "
                        "EnvConfig.max_dota_time. Short horizons make "
                        "episode OUTCOMES (the ISSUE 15 plane) arrive at "
                        "the learner quickly — the chaos outcome scenario "
                        "relies on it")
    p.add_argument("--rollout-len", type=int, default=None,
                   help="chunk length T; MUST match the learner's "
                        "ppo.rollout_len (e.g. 8 for a --smoke learner) — "
                        "skewed chunks are dropped at the learner's buffer")
    p.add_argument("--rollout-wire-dtype", type=str, default=None,
                   choices=("float32", "bfloat16"),
                   help="narrow rollout payloads on the wire (overrides "
                        "transport.rollout_wire_dtype); set the SAME value "
                        "as the learner — bfloat16 roughly halves shipped "
                        "bytes, precision-critical leaves stay f32")
    p.add_argument("--seed", type=int, default=None,
                   help="rollout RNG seed; default derives from $POD_NAME "
                        "(unique per k8s replica) or 0 outside k8s")
    p.add_argument("--steps", type=int, default=0,
                   help="stop after N env steps (0 = run forever)")
    p.add_argument("--refresh-every", type=int, default=8,
                   help="poll for new weights every N env steps")
    p.add_argument("--platform", type=str, default="cpu",
                   choices=("cpu", "tpu"),
                   help="JAX platform; cpu by default (the learner owns the TPU)")
    p.add_argument("--max-reconnects", type=int, default=6,
                   help="bounded connect attempts (exponential backoff + "
                        "jitter) before exiting non-zero for the supervisor")
    p.add_argument("--trace-jsonl", type=str, default=None, metavar="PATH",
                   help="pipeline tracing (ISSUE 12): append sampled "
                        "lifecycle events (shipped-chunk trace records, "
                        "weight-apply stamps) as JSON lines to PATH; merge "
                        "with the learner's log via "
                        "scripts/trace_report.py. Off by default")
    p.add_argument("--trace-sample", type=int, default=None, metavar="N",
                   help="with --trace-jsonl: trace every Nth shipped "
                        "chunk (default telemetry.trace_sample_n = 16; "
                        "1 = every chunk)")
    p.add_argument("--fleet-interval", type=float, default=None, metavar="S",
                   help="fleet health plane (ISSUE 13): push one compact "
                        "metric snapshot (counters + gauges) to the learner "
                        "every S seconds over the rollout lane (default "
                        "telemetry.fleet_interval_s = 5; 0 disables). The "
                        "learner's FleetAggregator merges them into the "
                        "fleet/<peer>/* keys and the alert rules")
    p.add_argument("--idle-timeout", type=float, default=None,
                   help="seconds of learner silence (no weights OR "
                        "heartbeats) before declaring the connection "
                        "half-open and reconnecting; default "
                        "transport.idle_timeout_s — keep it above the "
                        "learner's heartbeat_interval_s, or 0 to disable")
    args = p.parse_args(argv)
    if bool(args.connect) == bool(args.amqp):
        p.error("exactly one of --connect or --amqp is required")
    if args.seed is None:
        # Replicated actor fleets must not stream identical experience: the
        # k8s manifest injects POD_NAME, and each replica hashes its unique
        # pod name into its seed — no coordination needed.
        import zlib

        pod = os.environ.get("POD_NAME", "")
        args.seed = zlib.crc32(pod.encode()) & 0x7FFFFFFF if pod else 0

    # Graceful stop (ISSUE 4): the first SIGTERM/SIGINT latches a stop flag
    # — the run loop exits at its next slice boundary, flushes the partial
    # rollouts every lane holds, and exits 0 (a drained actor is a SUCCESS
    # to the supervisor, not a restart candidate). A second signal falls
    # through to the default disposition and kills the process.
    stop_flag = {"stop": False}

    def _graceful(signum, frame):
        stop_flag["stop"] = True
        signal.signal(signum, signal.SIG_DFL)
        print(
            f"actor: {signal.Signals(signum).name} received — flushing "
            f"partial rollouts and exiting (signal again to force)",
            file=sys.stderr, flush=True,
        )

    try:
        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _graceful)
    except ValueError:
        pass  # not the main thread (tests drive main() directly)

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from dotaclient_tpu.actor.vec_runtime import VecActorPool
    from dotaclient_tpu.config import default_config
    from dotaclient_tpu.models import init_params, make_policy
    from dotaclient_tpu.transport import decode_weights
    from dotaclient_tpu.utils import fleet, tracing

    if args.trace_jsonl:
        # before the pool exists: it captures tracing.get() at init
        tracing.configure(args.trace_jsonl, sample_n=args.trace_sample)
    # fleet publisher BEFORE the pool for the same reason (it captures
    # fleet.get() at init); the peer id is the actor seed, so a
    # supervisor-restarted incarnation reports under the SAME fleet row
    # (its fresh pid resets the aggregator's counter-delta base)
    fleet.configure(
        peer_id=args.seed, kind="actor", interval_s=args.fleet_interval
    )

    config = default_config()
    env_over = dict(
        n_envs=args.n_envs, opponent=args.opponent,
        team_size=args.team_size,
    )
    if args.max_dota_time is not None:
        env_over["max_dota_time"] = args.max_dota_time
    config = dataclasses.replace(
        config, env=dataclasses.replace(config.env, **env_over)
    )
    if args.rollout_len is not None:
        config = dataclasses.replace(
            config, ppo=dataclasses.replace(
                config.ppo, rollout_len=args.rollout_len
            )
        )
    if args.rollout_wire_dtype is not None:
        config = dataclasses.replace(
            config, transport=dataclasses.replace(
                config.transport,
                rollout_wire_dtype=args.rollout_wire_dtype,
            )
        )

    factory = _transport_factory(args, config.transport)
    try:
        transport = connect_with_backoff(
            factory, max_attempts=args.max_reconnects,
            rng=random.Random(args.seed),
            should_abort=lambda: stop_flag["stop"],
        )
    except (ConnectionError, OSError) as e:
        print(f"actor: cannot reach learner ({e}); exiting for restart",
              file=sys.stderr, flush=True)
        return 1
    policy = make_policy(config.model, config.obs, config.actions)

    # Wait for the learner's first weights broadcast — rollouts from random
    # init are tagged version 0 and would mix with the learner's counter.
    local_init = init_params(policy, jax.random.PRNGKey(args.seed))
    version = 0
    deadline = time.time() + 60.0
    params = None
    while time.time() < deadline and not stop_flag["stop"]:
        try:
            msg = transport.latest_weights()
        except ConnectionError as e:
            print(f"actor: learner lost while waiting for weights ({e}); "
                  f"exiting for restart", file=sys.stderr, flush=True)
            return 1
        if msg is not None:
            version, tree = decode_weights(msg)
            params = jax.tree.map(jax.numpy.asarray, tree)
            # config-skew guard: the wire carries no config handshake, so a
            # learner running different model/obs shapes must fail HERE with
            # a clear message, not deep inside flax or the learner's buffer
            if jax.tree.structure(params) != jax.tree.structure(local_init):
                print(
                    "actor: learner weight tree structure differs from this "
                    "actor's model config (different core/layers?) — align "
                    "configs", file=sys.stderr, flush=True,
                )
                return 2
            mismatch = [
                f"{jax.tree_util.keystr(path)}: learner {got.shape} vs "
                f"actor {exp.shape}"
                for (path, got), (_, exp) in zip(
                    jax.tree_util.tree_flatten_with_path(params)[0],
                    jax.tree_util.tree_flatten_with_path(local_init)[0],
                )
                if got.shape != exp.shape
            ]
            if mismatch:
                print(
                    "actor: learner weights do not match this actor's model "
                    "config — align configs:\n  " + "\n  ".join(mismatch[:5]),
                    file=sys.stderr, flush=True,
                )
                return 2
            break
        time.sleep(0.1)
    if params is None:
        print("actor: no weights from learner within 60s; starting from init",
              file=sys.stderr, flush=True)
        params = local_init

    pool = VecActorPool(
        config, policy, params, transport=transport,
        seed=args.seed, version=version,
    )
    t0 = time.time()
    steps = 0
    while (not args.steps or steps < args.steps) and not stop_flag["stop"]:
        try:
            pool.run(args.refresh_every, refresh_every=args.refresh_every)
        except (ConnectionError, OSError) as e:
            if stop_flag["stop"]:
                break   # stopping anyway: drain instead of reconnecting
            # transient hiccup (learner restart, broker blip, injected
            # connection drop): bounded backoff+jitter reconnect before
            # giving up to the supervisor
            print(f"actor: transport lost ({e}); reconnecting",
                  file=sys.stderr, flush=True)
            try:
                transport.close()
            except OSError:
                pass
            try:
                transport = connect_with_backoff(
                    factory, max_attempts=args.max_reconnects,
                    rng=random.Random(args.seed ^ steps),
                    should_abort=lambda: stop_flag["stop"],
                )
            except (ConnectionError, OSError) as e2:
                if stop_flag["stop"]:
                    break   # stop requested mid-backoff: clean drain exit
                print(
                    f"actor: reconnect failed ({e2}); exiting for restart",
                    file=sys.stderr, flush=True,
                )
                return 1
            pool.transport = transport   # pool re-resolves per publish/refresh
            continue
        steps += args.refresh_every
        if steps % 256 == 0:
            s = pool.stats()
            print(
                f"[actor {args.seed}] {s['env_steps']:.0f} env steps, "
                f"{s['rollouts_shipped']:.0f} rollouts, "
                f"{s['env_steps'] / max(time.time() - t0, 1e-9):.0f} steps/s, "
                f"version {pool.version}",
                flush=True,
            )
    if stop_flag["stop"]:
        # drain: the partial chunk each lane holds is real experience — up
        # to rollout_len-1 steps per lane — and the learner's buffer
        # accepts short-``length`` chunks natively (episode boundaries ship
        # them all the time). Best-effort: a transport that died in the
        # same failure that stopped us must not turn a clean drain into a
        # non-zero exit.
        try:
            n = pool.flush_partial()
            print(f"actor: graceful stop — flushed {n} partial rollouts",
                  file=sys.stderr, flush=True)
        except (ConnectionError, OSError) as e:
            print(f"actor: graceful stop — flush failed ({e})",
                  file=sys.stderr, flush=True)
    # Machine-readable record of every weight version this actor APPLIED —
    # the chaos divergence scenario's evidence that no health-blocked
    # (poisoned) version ever reached the fleet (scripts/chaos_run.py).
    import json as _json

    print(
        "ACTOR_VERSIONS_SEEN "
        + _json.dumps(sorted(pool.versions_applied)),
        flush=True,
    )
    if args.trace_jsonl:
        tracing.shutdown()   # drain + fsync (a SIGKILL skips this — the
        # writer's per-batch flush + torn-line reader cover that corpse)
    try:
        transport.close()
    except OSError:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
