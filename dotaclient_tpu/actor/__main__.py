"""Standalone actor process — the rebuild's ``agent.py`` counterpart.

The reference scale-out topology is N independent rollout-worker processes
feeding one optimizer through a broker (SURVEY.md §1, §2.3 row 1). One such
worker:

    python -m dotaclient_tpu.actor --connect 127.0.0.1:7777 --n-envs 64

connects to the learner's ``TransportServer`` (``--transport socket`` on the
learner), pulls versioned weights from the fanout, runs the vectorized pool,
and ships protobuf rollouts. ``--amqp host[:port]`` targets a RabbitMQ broker
instead (cluster parity). Actors are stateless: on transport loss the process
exits non-zero for the supervisor to restart (SURVEY.md §5.3).

By default the actor pins JAX to CPU: a TPU chip admits one process, and in
the split topology that process is the learner; set ``--platform tpu`` only
for an actor that owns its own accelerator host.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--connect", type=str, default=None,
                   help="learner TransportServer address host:port")
    p.add_argument("--amqp", type=str, default=None,
                   help="RabbitMQ broker address host[:port]")
    p.add_argument("--n-envs", type=int, default=64)
    p.add_argument("--opponent", type=str, default="scripted_easy")
    p.add_argument("--team-size", type=int, default=1)
    p.add_argument("--rollout-len", type=int, default=None,
                   help="chunk length T; MUST match the learner's "
                        "ppo.rollout_len (e.g. 8 for a --smoke learner) — "
                        "skewed chunks are dropped at the learner's buffer")
    p.add_argument("--seed", type=int, default=None,
                   help="rollout RNG seed; default derives from $POD_NAME "
                        "(unique per k8s replica) or 0 outside k8s")
    p.add_argument("--steps", type=int, default=0,
                   help="stop after N env steps (0 = run forever)")
    p.add_argument("--refresh-every", type=int, default=8,
                   help="poll for new weights every N env steps")
    p.add_argument("--platform", type=str, default="cpu",
                   choices=("cpu", "tpu"),
                   help="JAX platform; cpu by default (the learner owns the TPU)")
    args = p.parse_args(argv)
    if bool(args.connect) == bool(args.amqp):
        p.error("exactly one of --connect or --amqp is required")
    if args.seed is None:
        # Replicated actor fleets must not stream identical experience: the
        # k8s manifest injects POD_NAME, and each replica hashes its unique
        # pod name into its seed — no coordination needed.
        import os
        import zlib

        pod = os.environ.get("POD_NAME", "")
        args.seed = zlib.crc32(pod.encode()) & 0x7FFFFFFF if pod else 0

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from dotaclient_tpu.actor.vec_runtime import VecActorPool
    from dotaclient_tpu.config import default_config
    from dotaclient_tpu.models import init_params, make_policy
    from dotaclient_tpu.transport import decode_weights

    if args.connect:
        from dotaclient_tpu.transport.socket_transport import SocketTransport

        host, port = args.connect.rsplit(":", 1)
        transport = SocketTransport(host, int(port))
    else:
        from dotaclient_tpu.transport.queues import AmqpTransport

        host, _, port = args.amqp.partition(":")
        transport = AmqpTransport(host, int(port or 5672))

    config = default_config()
    config = dataclasses.replace(
        config,
        env=dataclasses.replace(
            config.env, n_envs=args.n_envs, opponent=args.opponent,
            team_size=args.team_size,
        ),
    )
    if args.rollout_len is not None:
        config = dataclasses.replace(
            config, ppo=dataclasses.replace(
                config.ppo, rollout_len=args.rollout_len
            )
        )
    policy = make_policy(config.model, config.obs, config.actions)

    # Wait for the learner's first weights broadcast — rollouts from random
    # init are tagged version 0 and would mix with the learner's counter.
    local_init = init_params(policy, jax.random.PRNGKey(args.seed))
    version = 0
    deadline = time.time() + 60.0
    params = None
    while time.time() < deadline:
        msg = transport.latest_weights()
        if msg is not None:
            version, tree = decode_weights(msg)
            params = jax.tree.map(jax.numpy.asarray, tree)
            # config-skew guard: the wire carries no config handshake, so a
            # learner running different model/obs shapes must fail HERE with
            # a clear message, not deep inside flax or the learner's buffer
            if jax.tree.structure(params) != jax.tree.structure(local_init):
                print(
                    "actor: learner weight tree structure differs from this "
                    "actor's model config (different core/layers?) — align "
                    "configs", file=sys.stderr, flush=True,
                )
                return 2
            mismatch = [
                f"{jax.tree_util.keystr(path)}: learner {got.shape} vs "
                f"actor {exp.shape}"
                for (path, got), (_, exp) in zip(
                    jax.tree_util.tree_flatten_with_path(params)[0],
                    jax.tree_util.tree_flatten_with_path(local_init)[0],
                )
                if got.shape != exp.shape
            ]
            if mismatch:
                print(
                    "actor: learner weights do not match this actor's model "
                    "config — align configs:\n  " + "\n  ".join(mismatch[:5]),
                    file=sys.stderr, flush=True,
                )
                return 2
            break
        time.sleep(0.1)
    if params is None:
        print("actor: no weights from learner within 60s; starting from init",
              file=sys.stderr, flush=True)
        params = local_init

    pool = VecActorPool(
        config, policy, params, transport=transport,
        seed=args.seed, version=version,
    )
    t0 = time.time()
    try:
        steps = 0
        while not args.steps or steps < args.steps:
            pool.run(args.refresh_every, refresh_every=args.refresh_every)
            steps += args.refresh_every
            if steps % 256 == 0:
                s = pool.stats()
                print(
                    f"[actor {args.seed}] {s['env_steps']:.0f} env steps, "
                    f"{s['rollouts_shipped']:.0f} rollouts, "
                    f"{s['env_steps'] / max(time.time() - t0, 1e-9):.0f} steps/s, "
                    f"version {pool.version}",
                    flush=True,
                )
    except ConnectionError as e:
        print(f"actor: transport lost ({e}); exiting for restart",
              file=sys.stderr, flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
