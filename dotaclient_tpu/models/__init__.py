"""Policy models (Flax) and action distributions."""

from dotaclient_tpu.models import distributions
from dotaclient_tpu.models.policy import (
    Policy,
    dummy_obs_batch,
    init_params,
    make_policy,
)

__all__ = [
    "Policy",
    "distributions",
    "dummy_obs_batch",
    "init_params",
    "make_policy",
]
