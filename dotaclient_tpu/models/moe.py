"""Mixture-of-experts MLP with expert parallelism via sharding annotations.

The reference has no MoE (SURVEY.md §2.3 row 6 — EP listed as "not needed
for parity; stub"); the rebuild ships it as a real, first-class option so
the transformer core (SURVEY.md §7 step 8) can widen FFN capacity without
widening per-token FLOPs. Two implementations cover the two idiomatic ways
to do EP on TPU:

* **This module** — the GSPMD path used *inside the policy*: Switch-style
  top-1 gating with capacity, einsum dispatch/combine, expert-major weights
  ``[E, D, F]``. The expert-major parameters are sharded over the mesh's
  ``model`` axis (``parallel.sharding`` path rule: any param path containing
  ``"expert"`` → ``P(model, ...)``); under ``jit`` GSPMD propagates that
  layout through the einsums and emits the all-to-alls itself — no
  hand-written communication (SURVEY.md §5.8 design rule).
* ``dotaclient_tpu.parallel.expert`` — the explicit ``shard_map`` +
  ``all_to_all`` primitive, the library-level EP analogue of the ring/
  Ulysses SP modules, with an oracle equivalence test.

Capacity semantics: each expert processes at most ``C = ceil(tokens/E ·
capacity_factor)`` tokens per call; overflow tokens are *dropped* (their
FFN delta is zero, the residual passes through) — the standard Switch
trade for static shapes, which is exactly what XLA needs (SURVEY.md §7
hard-part 5: fixed-shape discipline).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from dotaclient_tpu.config import ModelConfig
from dotaclient_tpu.parallel.expert import expert_capacity, route_top1


def _dtype(name: str):
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[name]


class MoEMLP(nn.Module):
    """Top-1 (Switch) routed MLP: ``[B, D] -> [B, D]``.

    Parameters are expert-major (``w1 [E, D, F]``, ``w2 [E, F, D]``) so the
    expert axis is shardable; ``dotaclient_tpu.parallel.sharding`` maps any
    parameter path containing ``"expert"`` to ``P(model, ...)``.
    """

    config: ModelConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        dtype, pdtype = _dtype(cfg.dtype), _dtype(cfg.param_dtype)
        B, D = x.shape
        E = cfg.moe_experts
        F = 4 * cfg.hidden_dim
        C = expert_capacity(B, E, cfg.moe_capacity_factor)

        gate_w = self.param(
            "gate", nn.initializers.lecun_normal(), (D, E), pdtype
        )
        w1 = self.param(
            "expert_w1",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (E, D, F),
            pdtype,
        )
        b1 = self.param("expert_b1", nn.initializers.zeros, (E, F), pdtype)
        w2 = self.param(
            "expert_w2",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (E, F, D),
            pdtype,
        )
        b2 = self.param("expert_b2", nn.initializers.zeros, (E, D), pdtype)

        # -- route: top-1 expert per token, capacity-limited ---------------
        # (shared routing math with the explicit shard_map EP form)
        dispatch, combine, probs = route_top1(x, gate_w, E, C)  # [B, E, C]

        # -- dispatch → expert FFN → combine (all einsum: GSPMD partitions
        # the E axis over the model-mesh axis and inserts the all-to-alls)
        xin = jnp.einsum("bec,bd->ecd", dispatch.astype(dtype), x.astype(dtype))
        h = jnp.einsum("ecd,edf->ecf", xin, w1.astype(dtype)) + b1[:, None].astype(dtype)
        h = nn.gelu(h)
        out = jnp.einsum("ecf,efd->ecd", h, w2.astype(dtype)) + b2[:, None].astype(dtype)
        y = jnp.einsum("bec,ecd->bd", combine.astype(dtype), out)

        # Per-token routing statistics for the Switch load-balancing loss
        # (eq. 4: E · Σ_e mean-frac_e · mean-prob_e). Sown raw per token —
        # NOT pre-averaged — so the learner can mask padded/bootstrap steps
        # out of the means exactly like every other loss term (the cell
        # cannot see the batch's valid mask from in here).
        self.sow("losses", "moe_probs", probs)                 # [B, E]
        self.sow("losses", "moe_frac", dispatch.sum(axis=2))   # [B, E] 0/1
        return y.astype(x.dtype)
