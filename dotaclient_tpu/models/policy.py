"""Flax policy: unit encoders → masked reduce → recurrent core → action heads.

Parity target is the reference ``Policy(nn.Module)``: per-unit-type input
encoders, concat (+ hero embedding for multi-hero pools), an LSTM(128) core,
and heads for action-type / move-x / move-y / target-unit (dot-product
attention over unit embeddings) / ability, with invalid-action masking before
softmax (SURVEY.md §3.3, BASELINE.json:5,7,9,10; reconstructed — the reference
checkout was an empty mount).

TPU-first design decisions (SURVEY.md §7 step 3):

* One module serves both the actor's batch-step mode (``method="step"``) and
  the learner's teacher-forced sequence mode (``method="sequence"``), sharing
  parameters — sequence mode drives the core with ``nn.scan`` (compiled
  ``lax.scan``; no Python loop under jit).
* The trunk and heads are written shape-polymorphically (Dense/einsum on the
  last axis) so the same code handles ``[B, ...]`` and ``[B, T, ...]``.
* Compute dtype is configurable bfloat16 with float32 params; logits are cast
  to float32 before masking/softmax for numerical stability.
* Fixed shapes everywhere: the unit axis is always ``ObsSpec.max_units``;
  validity arrives as masks (never shape changes ⇒ never recompiles).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from dotaclient_tpu.config import ActionSpec, ModelConfig, ObsSpec

# Recurrent carry: (h, c) for the LSTM core; (valid, KV caches) for the
# transformer core. Always a pytree whose leaves have leading batch axis —
# mask/zero it with mask_carry, never by unpacking tuples.
Carry = Any


def mask_carry(carry: Carry, keep: jnp.ndarray) -> Carry:
    """Multiply every carry leaf by ``keep`` ([B], 0 ⇒ reset that row) —
    core-agnostic episode-boundary reset."""
    def m(t):
        k = keep.reshape((-1,) + (1,) * (t.ndim - 1)).astype(t.dtype)
        return t * k
    return jax.tree.map(m, carry)


def _dtype(name: str):
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[name]


class UnitEncoder(nn.Module):
    """Per-unit MLP shared across unit slots (the per-unit-type information is
    one-hot in the feature vector, so a single shared encoder replaces the
    reference's per-type encoder stack without losing expressivity)."""

    config: ModelConfig

    @nn.compact
    def __call__(self, units: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        dtype, pdtype = _dtype(cfg.dtype), _dtype(cfg.param_dtype)
        x = nn.Dense(cfg.unit_embed_dim, dtype=dtype, param_dtype=pdtype)(units)
        x = nn.relu(x)
        x = nn.Dense(cfg.unit_embed_dim, dtype=dtype, param_dtype=pdtype)(x)
        return nn.relu(x)


class Policy(nn.Module):
    """Actor-critic policy with a recurrent core.

    ``value_head=False`` is the inference-only path (ISSUE 11, the serving
    plane): the SAME trunk/core/head modules — so logits are bit-identical
    by construction — but no value head is ever created, and the param tree
    is exactly the training tree minus ``head_value``
    (``serve.policy_path.slice_train_params`` produces it from a training
    checkpoint or a published weights frame). The step/sequence signatures
    are unchanged; the value output is a constant-zero placeholder so every
    actor-side consumer (which discards it) works with either variant."""

    model: ModelConfig
    obs_spec: ObsSpec
    action_spec: ActionSpec
    value_head: bool = True

    def setup(self):
        cfg = self.model
        self.unit_encoder = UnitEncoder(cfg)
        self.hero_embed = nn.Embed(
            cfg.n_hero_ids, cfg.hero_embed_dim, param_dtype=_dtype(cfg.param_dtype)
        )
        self.globals_proj = nn.Dense(
            cfg.unit_embed_dim, dtype=_dtype(cfg.dtype),
            param_dtype=_dtype(cfg.param_dtype),
        )
        self.trunk_proj = nn.Dense(
            cfg.hidden_dim, dtype=_dtype(cfg.dtype),
            param_dtype=_dtype(cfg.param_dtype),
        )
        if cfg.core == "lstm":
            self.core = nn.OptimizedLSTMCell(
                cfg.hidden_dim, dtype=_dtype(cfg.dtype),
                param_dtype=_dtype(cfg.param_dtype),
            )
        elif cfg.core == "transformer":
            from dotaclient_tpu.models.transformer import WindowedTransformerCore

            self.core = WindowedTransformerCore(cfg)
        else:
            raise ValueError(f"unknown core {cfg.core!r}")
        hs = self.action_spec.head_sizes
        dtype, pdtype = _dtype(cfg.dtype), _dtype(cfg.param_dtype)
        self.head_action_type = nn.Dense(hs["action_type"], dtype=dtype, param_dtype=pdtype)
        self.head_move_x = nn.Dense(hs["move_x"], dtype=dtype, param_dtype=pdtype)
        self.head_move_y = nn.Dense(hs["move_y"], dtype=dtype, param_dtype=pdtype)
        self.head_ability = nn.Dense(hs["ability"], dtype=dtype, param_dtype=pdtype)
        # Target-unit head: dot-product attention query over unit embeddings.
        self.target_query = nn.Dense(self.model.unit_embed_dim, dtype=dtype, param_dtype=pdtype)
        if self.value_head:
            self.head_value = nn.Dense(1, dtype=jnp.float32, param_dtype=pdtype)

    # -- shared trunk ------------------------------------------------------

    def _trunk(self, obs: Mapping[str, jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """obs arrays with any leading axes → (core input [..., H],
        unit embeddings [..., U, E] for the target-attention head)."""
        dtype = _dtype(self.model.dtype)
        units = obs["units"].astype(dtype)
        unit_mask = obs["unit_mask"][..., None].astype(dtype)   # [..., U, 1]
        unit_emb = self.unit_encoder(units) * unit_mask          # zero padding
        # Masked mean + max pool over the unit axis (padding never leaks).
        n_units = unit_mask.sum(axis=-2)                         # [..., 1]
        mean_pool = unit_emb.sum(axis=-2) / jnp.maximum(n_units, 1.0)
        max_pool = jnp.where(
            unit_mask > 0, unit_emb, jnp.asarray(-1e9, dtype)
        ).max(axis=-2)
        max_pool = jnp.where(n_units > 0, max_pool, 0.0)  # all-padding row
        g = nn.relu(self.globals_proj(obs["globals"].astype(dtype)))
        hero = self.hero_embed(obs["hero_id"].astype(jnp.int32)).astype(dtype)
        x = jnp.concatenate([mean_pool, max_pool, g, hero], axis=-1)
        x = nn.relu(self.trunk_proj(x))
        return x, unit_emb

    def _heads(
        self, y: jnp.ndarray, unit_emb: jnp.ndarray
    ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
        """Core output [..., H] → per-head float32 logits + value [...]."""
        q = self.target_query(y)                                  # [..., E]
        scale = jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
        target_logits = (
            jnp.einsum("...e,...ue->...u", q, unit_emb).astype(jnp.float32) / scale
        )
        logits = {
            "action_type": self.head_action_type(y).astype(jnp.float32),
            "move_x": self.head_move_x(y).astype(jnp.float32),
            "move_y": self.head_move_y(y).astype(jnp.float32),
            "target_unit": target_logits,
            "ability": self.head_ability(y).astype(jnp.float32),
        }
        if self.value_head:
            value = self.head_value(y.astype(jnp.float32))[..., 0]
        else:
            value = jnp.zeros(y.shape[:-1], jnp.float32)
        return logits, value

    # -- public modes ------------------------------------------------------

    def initial_state(self, batch_size: int) -> Carry:
        if self.model.core == "transformer":
            from dotaclient_tpu.models.transformer import (
                transformer_initial_state,
            )

            return transformer_initial_state(self.model, batch_size)
        shape = (batch_size, self.model.hidden_dim)
        dtype = _dtype(self.model.dtype)
        return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    def step(
        self, obs: Mapping[str, jnp.ndarray], carry: Carry
    ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray, Carry]:
        """Single batched step (actor path): obs arrays ``[B, ...]``."""
        with jax.named_scope("policy_trunk"):
            x, unit_emb = self._trunk(obs)
        with jax.named_scope("policy_core"):
            carry, y = self.core(carry, x)
        with jax.named_scope("policy_heads"):
            logits, value = self._heads(y, unit_emb)
        return logits, value, carry

    def sequence(
        self,
        obs: Mapping[str, jnp.ndarray],
        carry: Carry,
        dones: jnp.ndarray | None = None,
    ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray, Carry]:
        """Teacher-forced sequence mode (learner path): obs arrays
        ``[B, T, ...]``, ``carry`` is the stored rollout-initial LSTM state.
        Truncated-BPTT parity with the reference (SURVEY.md §5.7).

        ``dones`` (``[B, T']`` with T' ≤ T, f32/bool, episode ended AT step t)
        enables chunks that *span* episodes (the on-device rollout regime):
        the recurrent state is zeroed before step t+1 whenever step t ended an
        episode — exactly matching the actor-side reset — so step t+1 starts
        its new episode from a fresh carry. Without ``dones`` the behavior is
        unchanged (scalar-pool chunks never span episodes)."""
        x, unit_emb = self._trunk(obs)                            # [B, T, H]
        T = x.shape[1]
        if dones is None:
            resets = jnp.zeros((x.shape[0], T), x.dtype)
        else:
            # step 0 is reset by carry0 itself; step t>0 resets if t-1 done
            resets = jnp.concatenate(
                [
                    jnp.zeros((x.shape[0], 1), x.dtype),
                    dones.astype(x.dtype)[:, : T - 1],
                ],
                axis=1,
            )

        def scan_step(cell, c, inp):
            xt, reset_t = inp
            c = mask_carry(c, 1.0 - reset_t)
            return cell(c, xt)

        scan = nn.scan(
            scan_step,
            variable_broadcast="params",
            # intermediates sown by the core (the MoE load-balancing loss,
            # a scalar per step) stack along a leading time axis; empty for
            # cores that sow nothing
            variable_axes={"losses": 0},
            split_rngs={"params": False},
            in_axes=1,
            out_axes=1,
        )
        with jax.named_scope("policy_core_scan"):
            carry, ys = scan(self.core, carry, (x, resets))       # ys [B, T, H]
        with jax.named_scope("policy_heads"):
            logits, value = self._heads(ys, unit_emb)
        return logits, value, carry

    def __call__(self, obs: Mapping[str, jnp.ndarray], carry: Carry):
        """Default = step mode (used for parameter init)."""
        return self.step(obs, carry)


def make_policy(model: ModelConfig, obs_spec: ObsSpec, action_spec: ActionSpec) -> Policy:
    if model.moe_experts > 0 and model.core != "transformer":
        # only the transformer core routes an MoE FFN; silently training a
        # dense LSTM under an "8-expert" label would mislabel every result
        raise ValueError(
            f"moe_experts={model.moe_experts} requires core='transformer' "
            f"(got core={model.core!r}); the LSTM core has no FFN to route"
        )
    return Policy(model=model, obs_spec=obs_spec, action_spec=action_spec)


def init_params(policy: Policy, rng: jax.Array):
    """Initialize parameters from a dummy batch-1 observation (shapes come
    from the policy's own specs).

    The ``losses`` collection (sown per-call intermediates like the MoE
    load-balancing loss) is transient output, not state — it is stripped so
    it never rides inside the param tree (where the learner's scan would
    mistake it for a scannable variable)."""
    dummy = dummy_obs_batch(1, policy.obs_spec, policy.action_spec)
    carry = policy.initial_state(1)
    variables = policy.init(rng, dummy, carry)
    return {k: v for k, v in variables.items() if k != "losses"}


def dummy_obs_batch(
    batch: int, obs_spec: ObsSpec, action_spec: ActionSpec, time: int | None = None
) -> Dict[str, jnp.ndarray]:
    """Zero observation arrays of the right static shapes (init / AOT tracing)."""
    lead = (batch,) if time is None else (batch, time)
    return {
        "units": jnp.zeros(lead + (obs_spec.max_units, obs_spec.unit_features), jnp.float32),
        "unit_mask": jnp.zeros(lead + (obs_spec.max_units,), bool),
        "unit_handles": jnp.zeros(lead + (obs_spec.max_units,), jnp.int32),
        "globals": jnp.zeros(lead + (obs_spec.global_features,), jnp.float32),
        "hero_id": jnp.zeros(lead, jnp.int32),
        "mask_action_type": jnp.ones(lead + (action_spec.n_action_types,), bool),
        "mask_target_unit": jnp.ones(lead + (action_spec.max_units,), bool),
        "mask_cast_target": jnp.ones(lead + (action_spec.max_units,), bool),
        "mask_ability": jnp.ones(lead + (action_spec.max_abilities,), bool),
    }
