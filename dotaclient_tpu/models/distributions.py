"""Masked multi-head categorical action distribution.

The reference samples one categorical per head, masks invalid actions before
softmax, and sums per-head log-probs (SURVEY.md §3.3; reconstructed — the
reference checkout was an empty mount). Here the joint log-prob is the
*conditional* factorization: sub-heads only contribute when the sampled action
type makes them relevant (move bins for MOVE, target slot for ATTACK/CAST,
ability slot for CAST), so the surrogate ratio in PPO is exact.

The target-unit head's legality is itself conditional on the action type
(ATTACK may hit any enemy or a deniable allied creep; CAST only enemies in
cast range), so it carries two masks and the log-softmax is selected by the
sampled/stored action type — sampled actions are legal by construction, and
the sim never has to silently drop one.

All functions are shape-polymorphic over leading axes — they work for the
actor's ``[B, ...]`` step and the learner's ``[B, T, ...]`` sequences alike —
and are jit/vmap/grad-safe (no Python branching on data).
"""

from __future__ import annotations

from typing import Dict, Mapping, Tuple

import jax
import jax.numpy as jnp

# Action-type enum values — must match protos (dota.proto ActionType).
A_NOOP, A_MOVE, A_ATTACK, A_CAST = 0, 1, 2, 3

# Large negative logit for illegal entries. Finite (not -inf) so that
# fully-masked rows still produce finite softmax output under bf16/f32.
NEG_INF = -1e9

HEADS = ("action_type", "move_x", "move_y", "target_unit", "ability")


def _safe_mask(mask: jnp.ndarray) -> jnp.ndarray:
    """A mask with at least one legal entry per row.

    A head can be entirely illegal (e.g. no attackable target) — it is then
    never *used* (its action type is masked out too), but its log-softmax must
    stay finite so `0 × logp` stays 0, not NaN. Fully-illegal rows fall back
    to all-legal (uniform).
    """
    any_legal = jnp.any(mask, axis=-1, keepdims=True)
    return jnp.where(any_legal, mask, True)


def masked_log_softmax(logits: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    masked = jnp.where(_safe_mask(mask), logits, NEG_INF)
    return jax.nn.log_softmax(masked, axis=-1)


def _head_logps(
    logits: Mapping[str, jnp.ndarray], obs: Mapping[str, jnp.ndarray]
) -> Dict[str, jnp.ndarray]:
    """Masked, normalized log-probs per head. The target head appears twice,
    once per conditioning action type."""
    return {
        "action_type": masked_log_softmax(
            logits["action_type"], obs["mask_action_type"]
        ),
        # Move heads are always fully legal — no mask path needed.
        "move_x": jax.nn.log_softmax(logits["move_x"], axis=-1),
        "move_y": jax.nn.log_softmax(logits["move_y"], axis=-1),
        "target_attack": masked_log_softmax(
            logits["target_unit"], obs["mask_target_unit"]
        ),
        "target_cast": masked_log_softmax(
            logits["target_unit"], obs["mask_cast_target"]
        ),
        "ability": masked_log_softmax(logits["ability"], obs["mask_ability"]),
    }


def _select_target_logps(
    logps: Mapping[str, jnp.ndarray], action_type: jnp.ndarray
) -> jnp.ndarray:
    """Per-row target-head log-softmax conditioned on the action type."""
    is_cast = (action_type == A_CAST)[..., None]
    return jnp.where(is_cast, logps["target_cast"], logps["target_attack"])


def sample(
    rng: jax.Array,
    logits: Mapping[str, jnp.ndarray],
    obs: Mapping[str, jnp.ndarray],
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Sample every head; return (actions, joint conditional log-prob).

    The action type is sampled first; the target head then samples under the
    mask that type implies, so every emitted action is legal by construction.
    """
    logps = _head_logps(logits, obs)
    k_type, k_mx, k_my, k_tgt, k_ab = jax.random.split(rng, 5)
    a_type = jax.random.categorical(k_type, logps["action_type"], axis=-1)
    target_logps = _select_target_logps(logps, a_type)
    actions = {
        "action_type": a_type,
        "move_x": jax.random.categorical(k_mx, logps["move_x"], axis=-1),
        "move_y": jax.random.categorical(k_my, logps["move_y"], axis=-1),
        "target_unit": jax.random.categorical(k_tgt, target_logps, axis=-1),
        "ability": jax.random.categorical(k_ab, logps["ability"], axis=-1),
    }
    return actions, _joint_logp(logps, actions)


def log_prob(
    logits: Mapping[str, jnp.ndarray],
    obs: Mapping[str, jnp.ndarray],
    actions: Mapping[str, jnp.ndarray],
) -> jnp.ndarray:
    """Joint conditional log-prob of stored ``actions`` under ``logits``."""
    return _joint_logp(_head_logps(logits, obs), actions)


def _take(logp: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    return jnp.take_along_axis(logp, idx[..., None].astype(jnp.int32), axis=-1)[..., 0]


def _joint_logp(
    logps: Mapping[str, jnp.ndarray], actions: Mapping[str, jnp.ndarray]
) -> jnp.ndarray:
    a_type = actions["action_type"]
    move = (a_type == A_MOVE).astype(jnp.float32)
    target = ((a_type == A_ATTACK) | (a_type == A_CAST)).astype(jnp.float32)
    cast = (a_type == A_CAST).astype(jnp.float32)
    target_logps = _select_target_logps(logps, a_type)
    return (
        _take(logps["action_type"], a_type)
        + move * (_take(logps["move_x"], actions["move_x"])
                  + _take(logps["move_y"], actions["move_y"]))
        + target * _take(target_logps, actions["target_unit"])
        + cast * _take(logps["ability"], actions["ability"])
    )


def kl(
    logits_p: Mapping[str, jnp.ndarray],
    logits_q: Mapping[str, jnp.ndarray],
    obs: Mapping[str, jnp.ndarray],
) -> jnp.ndarray:
    """Exact KL(P ‖ Q) of the conditional factorization at the same state.

    Mirrors ``entropy``: per-head categorical KLs, with sub-heads weighted
    by P's probability of selecting their conditioning action type. Both
    policies see the same observation, so the legality masks (and therefore
    the supports) coincide — masked entries contribute exp(-1e9)·Δ ≈ 0.
    """
    lp = _head_logps(logits_p, obs)
    lq = _head_logps(logits_q, obs)

    def KLh(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        return jnp.sum(jnp.exp(a) * (a - b), axis=-1)

    p_type = jnp.exp(lp["action_type"])
    return (
        KLh(lp["action_type"], lq["action_type"])
        + p_type[..., A_MOVE]
        * (KLh(lp["move_x"], lq["move_x"]) + KLh(lp["move_y"], lq["move_y"]))
        + p_type[..., A_ATTACK] * KLh(lp["target_attack"], lq["target_attack"])
        + p_type[..., A_CAST]
        * (KLh(lp["target_cast"], lq["target_cast"]) + KLh(lp["ability"], lq["ability"]))
    )


def entropy(
    logits: Mapping[str, jnp.ndarray], obs: Mapping[str, jnp.ndarray]
) -> jnp.ndarray:
    """Exact entropy of the conditional factorization: masked per-head
    entropies with sub-heads weighted by the probability their conditioning
    action type is selected."""
    logps = _head_logps(logits, obs)
    p_type = jnp.exp(logps["action_type"])

    def H(lp: jnp.ndarray) -> jnp.ndarray:
        return -jnp.sum(jnp.exp(lp) * lp, axis=-1)

    p_move = p_type[..., A_MOVE]
    p_attack = p_type[..., A_ATTACK]
    p_cast = p_type[..., A_CAST]
    return (
        H(logps["action_type"])
        + p_move * (H(logps["move_x"]) + H(logps["move_y"]))
        + p_attack * H(logps["target_attack"])
        + p_cast * (H(logps["target_cast"]) + H(logps["ability"]))
    )
