"""Windowed-attention transformer core with a recurrent KV-cache carry.

The scale-out core option of SURVEY.md §7 step 8 (`ModelConfig.core =
"transformer"`). Design constraint: it must be a drop-in RECURRENT cell —
``(carry, x) -> (carry, y)`` — because the whole framework (actor pools,
on-device rollout scan, chunk wire format, truncated-BPTT learner) is built
on carried state (SURVEY.md §5.7). The carry is a Transformer-XL-style
rolling window: per layer a K/V cache of the last ``context_window`` steps,
plus a validity mask; episode resets zero the carry exactly like the LSTM
path (an all-zero cache attends to nothing thanks to the mask).

Sequence mode reuses the same cell under ``nn.scan``, so step-vs-sequence
parity is structural, not approximate — the property the LSTM core's tests
pin, inherited for free.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp

from dotaclient_tpu.config import ModelConfig
from dotaclient_tpu.models.moe import MoEMLP


def _dtype(name: str):
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[name]


class _GRUGate(nn.Module):
    """GTrXL gated residual (Parisotto et al. 2019): replaces ``x + y`` with
    a GRU-style gate whose bias initializes the gate nearly closed, so each
    block starts as (close to) the identity on the stream. This is the
    standard stabilizer for transformers under RL gradients — the plain
    residual form measurably collapses mid-training on the lane sim
    (reward +6 → −1 at ~13k optimizer steps, BASELINE.md), exactly the
    failure mode the gating was designed for.
    """

    config: ModelConfig
    bias_init: float = 2.0

    @nn.compact
    def __call__(self, x, y):
        cfg = self.config
        dtype, pdtype = _dtype(cfg.dtype), _dtype(cfg.param_dtype)
        H = cfg.hidden_dim

        def dense(name):
            return nn.Dense(
                H, use_bias=False, dtype=dtype, param_dtype=pdtype, name=name
            )

        r = nn.sigmoid(dense("wr")(y) + dense("ur")(x))
        bg = self.param(
            "bg", nn.initializers.constant(self.bias_init), (H,), pdtype
        )
        z = nn.sigmoid(dense("wz")(y) + dense("uz")(x) - bg.astype(dtype))
        h_hat = nn.tanh(dense("wg")(y) + dense("ug")(r * x))
        return (1.0 - z) * x + z * h_hat


class _Block(nn.Module):
    """Pre-LN attention block operating on one timestep + its KV window."""

    config: ModelConfig

    @nn.compact
    def __call__(self, kv_cache, valid, h):
        cfg = self.config
        dtype, pdtype = _dtype(cfg.dtype), _dtype(cfg.param_dtype)
        H, nh = cfg.hidden_dim, cfg.n_heads
        dh = H // nh
        kc, vc = kv_cache                                   # [B, W, H]
        B, W = valid.shape

        hn = nn.LayerNorm(dtype=dtype, param_dtype=pdtype)(h)
        q = nn.Dense(H, dtype=dtype, param_dtype=pdtype, name="q")(hn)
        k = nn.Dense(H, dtype=dtype, param_dtype=pdtype, name="k")(hn)
        v = nn.Dense(H, dtype=dtype, param_dtype=pdtype, name="v")(hn)

        keys = jnp.concatenate([kc.astype(dtype), k[:, None]], axis=1)
        vals = jnp.concatenate([vc.astype(dtype), v[:, None]], axis=1)
        mask = jnp.concatenate(
            [valid, jnp.ones((B, 1), valid.dtype)], axis=1
        )                                                   # [B, W+1]

        qh = q.reshape(B, nh, dh)
        kh = keys.reshape(B, W + 1, nh, dh)
        vh = vals.reshape(B, W + 1, nh, dh)
        logits = jnp.einsum("bhd,bkhd->bhk", qh, kh).astype(jnp.float32)
        logits = logits / jnp.sqrt(jnp.asarray(dh, jnp.float32))
        # Learned relative-position bias per head: window slot k has a fixed
        # age (W-k steps back), so one [nh, W+1] table IS the full relative
        # encoding — without it the window is an unordered bag and the core
        # cannot tell last step from W steps ago. Zero-init: parity with the
        # bias-free form at initialization.
        pos_bias = self.param(
            "pos_bias", nn.initializers.zeros, (nh, W + 1), pdtype
        )
        logits = logits + pos_bias[None].astype(jnp.float32)
        logits = jnp.where(mask[:, None, :] > 0, logits, -1e9)
        w = nn.softmax(logits, axis=-1).astype(dtype)
        out = jnp.einsum("bhk,bkhd->bhd", w, vh).reshape(B, H)
        attn = nn.Dense(H, dtype=dtype, param_dtype=pdtype, name="o")(out)
        h = _GRUGate(cfg, name="gate_attn")(h, attn)

        hm = nn.LayerNorm(dtype=dtype, param_dtype=pdtype)(h)
        if cfg.moe_experts > 0:
            # routed-FFN option: per-token top-1 expert, expert weights
            # sharded over the `model` mesh axis (models/moe.py)
            ffn = MoEMLP(cfg, name="moe")(hm)
        else:
            hm = nn.Dense(4 * H, dtype=dtype, param_dtype=pdtype)(hm)
            hm = nn.gelu(hm)
            ffn = nn.Dense(H, dtype=dtype, param_dtype=pdtype)(hm)
        h = _GRUGate(cfg, name="gate_ffn")(h, ffn)

        # roll the window: drop oldest, append this step (f32 cache — the
        # carry crosses the wire/buffer in f32 like the LSTM state)
        new_kc = jnp.concatenate([kc[:, 1:], k.astype(jnp.float32)[:, None]], 1)
        new_vc = jnp.concatenate([vc[:, 1:], v.astype(jnp.float32)[:, None]], 1)
        return (new_kc, new_vc), h


class WindowedTransformerCore(nn.Module):
    """Recurrent-cell interface: ``(carry, x) -> (carry, y)``.

    carry = (valid [B, W] f32, ((k, v) per layer, each [B, W, H] f32)).
    """

    config: ModelConfig

    @nn.compact
    def __call__(self, carry, x):
        cfg = self.config
        valid, caches = carry
        h = x.astype(_dtype(cfg.dtype))
        new_caches = []
        for l in range(cfg.n_layers):
            new_kv, h = _Block(cfg, name=f"block_{l}")(caches[l], valid, h)
            new_caches.append(new_kv)
        # Final pre-head LayerNorm: the pre-LN residual stream is unbounded
        # (norms grow with depth/training), and the action/value heads
        # consume this output directly — without normalization the head
        # logit scale drifts, collapsing policy entropy early (the LSTM
        # core's tanh output is bounded by construction).
        h = nn.LayerNorm(
            dtype=_dtype(cfg.dtype), param_dtype=_dtype(cfg.param_dtype),
            name="out_ln",
        )(h)
        B = valid.shape[0]
        new_valid = jnp.concatenate(
            [valid[:, 1:], jnp.ones((B, 1), valid.dtype)], axis=1
        )
        return (new_valid, tuple(new_caches)), h


def transformer_initial_state(config: ModelConfig, batch_size: int):
    W, H = config.context_window, config.hidden_dim
    zeros = jnp.zeros((batch_size, W, H), jnp.float32)
    return (
        jnp.zeros((batch_size, W), jnp.float32),
        tuple((zeros, zeros) for _ in range(config.n_layers)),
    )
