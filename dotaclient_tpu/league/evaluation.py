"""Win-rate evaluation harness.

BASELINE.json's second headline metric is *win-rate vs the hard scripted
bot*; the reference measured it by watching TensorBoard against live games
(SURVEY.md §4). Here it is a first-class function: play N complete
evaluation games on the on-device sim — no training, no experience shipping
— and report the result. Also used league-side to check whether the current
policy beats its own frozen past (SURVEY.md §7 step 7).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import numpy as np

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.models.policy import Policy
from dotaclient_tpu.utils import telemetry


def evaluate(
    config: RunConfig,
    policy: Policy,
    params: Any,
    opponent: str = "scripted_hard",
    opponent_params: Optional[Any] = None,
    n_games: int = 64,
    seed: int = 0,
    max_chunks: Optional[int] = None,
) -> Dict[str, float]:
    """Play ``n_games`` full games of ``params`` vs ``opponent``.

    ``opponent`` is any EnvConfig opponent mode; ``"league"`` plays against
    ``opponent_params`` (frozen policy). Returns win_rate / episodes /
    mean episode return. Games run on the on-device rollout loop; this
    function is the only host sync.
    """
    from dotaclient_tpu.actor.device_rollout import DeviceActor

    tel = telemetry.get_registry()
    eval_cfg = dataclasses.replace(
        config,
        env=dataclasses.replace(config.env, n_envs=n_games, opponent=opponent),
        # an eval measures ONE opponent: anchor games (a training-time
        # distribution lever) would silently swap a fraction of the games
        # to the scripted bot and contaminate the reported win_rate
        league=dataclasses.replace(config.league, anchor_prob=0.0),
        # eval chunks are drained for stats and DROPPED — never stored or
        # shipped — so the rollout wire narrowing would be pure wasted
        # in-program casts per collect
        transport=dataclasses.replace(
            config.transport, rollout_wire_dtype="float32"
        ),
    )
    # the eval actor records into a PRIVATE registry: its frames/collect
    # latencies (different config, different cadence) must not contaminate
    # the training pipeline's counters and EMAs in the global registry
    actor = DeviceActor(eval_cfg, policy, seed=seed, registry=telemetry.Registry())
    steps_per_episode = eval_cfg.env.max_dota_time / (
        eval_cfg.env.ticks_per_observation / 30.0
    )
    # enough chunks for every game to finish at least once, plus slack
    max_chunks = max_chunks or int(
        2 * steps_per_episode / config.ppo.rollout_len + 2
    )
    done = 0.0
    with tel.span("league/evaluate"):
        for _ in range(max_chunks):
            actor.collect(params, opp_params=opponent_params)
            if _ % 8 == 7:
                done = actor.drain_stats()["episodes_done"]
                if done >= n_games:
                    break
        stats = actor.drain_stats()
    # evaluation outcomes ride the shared registry so an attached sink
    # (JSONL/tensorboard) records them next to the pipeline telemetry
    tel.gauge("league/eval_win_rate").set(stats["win_rate"])
    tel.gauge("league/eval_episodes").set(stats["episodes_done"])
    tel.gauge("league/eval_reward_mean").set(stats["episode_reward_mean"])
    return {
        "win_rate": stats["win_rate"],
        "episodes": stats["episodes_done"],
        "episode_reward_mean": stats["episode_reward_mean"],
    }
