"""Win-rate evaluation harness.

BASELINE.json's second headline metric is *win-rate vs the hard scripted
bot*; the reference measured it by watching TensorBoard against live games
(SURVEY.md §4). Here it is a first-class function: play N complete
evaluation games on the on-device sim — no training, no experience shipping
— and report the result. Also used league-side to check whether the current
policy beats its own frozen past (SURVEY.md §7 step 7).

Both eval modes run the **inference-only policy path** (ISSUE 11,
dotaclient_tpu/serve): the same trunk/core/head modules with the value head
sliced out of the param tree — eval discards values, so results are
bit-identical to the training-shaped policy (pinned by
tests/test_serve.py) and the eval actor never materializes critic params.
``evaluate`` plays on the fused on-device rollout loop; ``evaluate_served``
plays the SAME games through a live serve server — the serving plane's
first real client and its end-to-end correctness probe.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.models.policy import Policy
from dotaclient_tpu.utils import telemetry


def evaluate(
    config: RunConfig,
    policy: Policy,
    params: Any,
    opponent: str = "scripted_hard",
    opponent_params: Optional[Any] = None,
    n_games: int = 64,
    seed: int = 0,
    max_chunks: Optional[int] = None,
) -> Dict[str, float]:
    """Play ``n_games`` full games of ``params`` vs ``opponent``.

    ``opponent`` is any EnvConfig opponent mode; ``"league"`` plays against
    ``opponent_params`` (frozen policy). Returns win_rate / episodes /
    mean episode return. Games run on the on-device rollout loop; this
    function is the only host sync.

    ``policy`` may be either the training-shaped module or an
    inference-only one — the eval actor always runs the inference-only
    path: the value head (which eval discards) is sliced out of ``params``
    and a ``value_head=False`` module applies the slim tree. Sampling is
    untouched, so results are bit-identical either way.
    """
    from dotaclient_tpu.actor.device_rollout import DeviceActor
    from dotaclient_tpu.serve.policy_path import slice_train_params

    tel = telemetry.get_registry()
    eval_cfg = dataclasses.replace(
        config,
        env=dataclasses.replace(config.env, n_envs=n_games, opponent=opponent),
        # an eval measures ONE opponent: anchor games (a training-time
        # distribution lever) would silently swap a fraction of the games
        # to the scripted bot and contaminate the reported win_rate
        league=dataclasses.replace(config.league, anchor_prob=0.0),
        # eval chunks are drained for stats and DROPPED — never stored or
        # shipped — so the rollout wire narrowing would be pure wasted
        # in-program casts per collect
        transport=dataclasses.replace(
            config.transport, rollout_wire_dtype="float32"
        ),
    )
    # the eval actor records into a PRIVATE registry: its frames/collect
    # latencies (different config, different cadence) must not contaminate
    # the training pipeline's counters and EMAs in the global registry.
    # Inference-only path (ISSUE 11): the CALLER's module (its
    # architecture is authoritative — a checkpoint's config may diverge
    # from `config`) cloned without the value head, over sliced trees —
    # no critic params ride into the eval program.
    slim_policy = (
        policy if not policy.value_head else policy.clone(value_head=False)
    )
    actor = DeviceActor(
        eval_cfg, slim_policy, seed=seed, registry=telemetry.Registry()
    )
    params = slice_train_params(params)
    if opponent_params is not None:
        opponent_params = slice_train_params(opponent_params)
    steps_per_episode = eval_cfg.env.max_dota_time / (
        eval_cfg.env.ticks_per_observation / 30.0
    )
    # enough chunks for every game to finish at least once, plus slack
    max_chunks = max_chunks or int(
        2 * steps_per_episode / config.ppo.rollout_len + 2
    )
    done = 0.0
    with tel.span("league/evaluate"):
        for _ in range(max_chunks):
            actor.collect(params, opp_params=opponent_params)
            if _ % 8 == 7:
                done = actor.drain_stats()["episodes_done"]
                if done >= n_games:
                    break
        stats = actor.drain_stats()
    # evaluation outcomes ride the shared registry so an attached sink
    # (JSONL/tensorboard) records them next to the pipeline telemetry
    tel.gauge("league/eval_win_rate").set(stats["win_rate"])
    tel.gauge("league/eval_episodes").set(stats["episodes_done"])
    tel.gauge("league/eval_reward_mean").set(stats["episode_reward_mean"])
    return {
        "win_rate": stats["win_rate"],
        "episodes": stats["episodes_done"],
        "episode_reward_mean": stats["episode_reward_mean"],
    }


def evaluate_served(
    config: RunConfig,
    address: Tuple[str, int],
    opponent: str = "scripted_hard",
    n_games: int = 8,
    seed: int = 0,
    max_steps: Optional[int] = None,
) -> Dict[str, float]:
    """Play ``n_games`` full games THROUGH a live serve server (ISSUE 11).

    The serving plane's first client: games run on the host scalar sim
    (the gRPC-parity env), and every action comes back over the
    request/reply wire — one :class:`serve.ServeClient` (one carry slot)
    per agent-controlled hero, ``reset=True`` on each episode's first
    step. Games run concurrently, so the server's continuous batching has
    real work to coalesce. Same result surface as :func:`evaluate`.
    """
    from dotaclient_tpu.actor.runtime import build_game_config
    from dotaclient_tpu.envs.env_api import LocalDotaEnv
    from dotaclient_tpu.features import (
        decode_action,
        featurize,
        observation_to_dict,
        shaped_reward,
    )
    from dotaclient_tpu.protos import dota_pb2 as pb
    from dotaclient_tpu.serve.client import ServeClient

    host, port = address
    eval_cfg = dataclasses.replace(
        config, env=dataclasses.replace(config.env, opponent=opponent)
    )
    tel = telemetry.get_registry()
    steps_per_episode = eval_cfg.env.max_dota_time / (
        eval_cfg.env.ticks_per_observation / 30.0
    )
    max_steps = max_steps or int(2 * steps_per_episode * n_games + 16)
    next_seed = seed

    class _Game:
        def __init__(self) -> None:
            nonlocal next_seed
            self.env = LocalDotaEnv()
            self.game_cfg = build_game_config(eval_cfg, next_seed)
            next_seed += 1
            self.lanes = []  # (client, player_id, team_id) per agent hero
            self.pending_actions: Dict[int, list] = {}
            self.reset()

        def reset(self) -> None:
            nonlocal next_seed
            init = self.env.reset(self.game_cfg)
            ws_by_team = {ws.team_id: ws for ws in init.world_states}
            agent_players = [
                (pid, pick.team_id)
                for pid, pick in enumerate(self.game_cfg.hero_picks)
                if pick.control_mode == pb.CONTROL_AGENT
            ]
            if not self.lanes:
                self.lanes = [
                    {"client": ServeClient(host, port, config)}
                    for _ in agent_players
                ]
            for lane, (player_id, team_id) in zip(self.lanes, agent_players):
                lane.update(
                    player_id=player_id, team_id=team_id,
                    ws=ws_by_team[team_id], reset=True,
                )
            self.episode_reward = 0.0
            # the next episode on this env gets a fresh draw
            self.game_cfg = build_game_config(eval_cfg, next_seed)
            next_seed += 1

        def close(self) -> None:
            for lane in self.lanes:
                lane["client"].close()

    from concurrent.futures import ThreadPoolExecutor

    n_concurrent = max(1, min(n_games, 16))
    games = [_Game() for _ in range(n_concurrent)]
    all_lanes = [(game, lane) for game in games for lane in game.lanes]
    episodes = wins = 0
    episode_rewards = []

    def request_action(pair):
        """One lane's featurize + wire round trip — runs on the pool so
        every concurrent game's request is in flight AT ONCE and the
        server's batch window has real work to coalesce (a serial client
        loop would hand the batcher one lonely request per deadline)."""
        game, lane = pair
        obs = featurize(
            lane["ws"], lane["player_id"], eval_cfg.obs, eval_cfg.actions
        )
        idx = lane["client"].step(
            observation_to_dict(obs), reset=lane["reset"]
        )
        lane["reset"] = False
        lane["obs"] = obs
        return idx

    try:
        with tel.span("league/evaluate"), ThreadPoolExecutor(
            max_workers=len(all_lanes)
        ) as pool:
            for _ in range(max_steps):
                if episodes >= n_games:
                    break
                actions = list(pool.map(request_action, all_lanes))
                for (game, lane), idx in zip(all_lanes, actions):
                    by_team = game.pending_actions
                    by_team.setdefault(lane["team_id"], []).append(
                        decode_action(
                            idx, lane["obs"], lane["player_id"],
                            move_bins=eval_cfg.actions.move_bins,
                        )
                    )
                for game in games:
                    for team_id, protos in game.pending_actions.items():
                        game.env.act(
                            pb.Actions(team_id=team_id, actions=protos)
                        )
                    game.pending_actions = {}
                    owner = game.lanes[0]
                    done = False
                    for lane in game.lanes:
                        resp = game.env.observe(lane["team_id"])
                        ws = resp.world_state
                        if lane is owner:
                            r, _ = shaped_reward(
                                lane["ws"], ws, lane["player_id"],
                                weights=eval_cfg.reward.as_dict(),
                            )
                            game.episode_reward += r
                        lane["ws"] = ws
                        done = done or game.env.done
                    if done:
                        episodes += 1
                        if owner["ws"].winning_team == owner["team_id"]:
                            wins += 1
                        episode_rewards.append(game.episode_reward)
                        game.reset()
    finally:
        for game in games:
            game.close()
    win_rate = wins / episodes if episodes else 0.0
    reward_mean = float(np.mean(episode_rewards)) if episode_rewards else 0.0
    tel.gauge("league/eval_win_rate").set(win_rate)
    tel.gauge("league/eval_episodes").set(float(episodes))
    tel.gauge("league/eval_reward_mean").set(reward_mean)
    return {
        "win_rate": win_rate,
        "episodes": float(episodes),
        "episode_reward_mean": reward_mean,
    }
