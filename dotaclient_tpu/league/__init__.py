"""League self-play: frozen-opponent pools and win-rate evaluation.

SURVEY.md §7 step 7 / BASELINE.json:9-12: opponent pools with periodic
snapshots, mixed self-play vs frozen-past sampling, and the win-rate eval
harness the headline metric is measured with.
"""

from dotaclient_tpu.league.evaluation import evaluate, evaluate_served
from dotaclient_tpu.league.pool import OpponentPool, Snapshot

__all__ = ["OpponentPool", "Snapshot", "evaluate", "evaluate_served"]
