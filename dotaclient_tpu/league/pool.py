"""Frozen-opponent pool for league self-play.

The reference's league configs pit the live policy against frozen past
versions of itself (SURVEY.md §7 step 7; BASELINE.json:12 "5v5 ... league
opponents"). Mechanics here:

* every ``snapshot_every`` learner steps the current params are snapshotted
  (device-to-device copy — snapshots never touch the host) into a bounded
  ring of ``pool_size`` frozen opponents;
* each opponent draw plays the LATEST policy (mirror self-play) with
  probability ``selfplay_prob``, otherwise a frozen snapshot;
* snapshot selection is governed by ``LeagueConfig.matchmaking``:
  - ``"uniform"`` — the classic uniform draw;
  - ``"pfsp"`` — prioritized fictitious self-play: the pool tracks the
    learner's win-rate against each snapshot (callers attribute outcomes
    via :meth:`report`) and weights draws by ``f(w) = (1-w)^p`` — hard
    opponents are replayed until beaten, which is the standard cure for
    the uniform-league failure mode where the learner over-trains on easy
    past selves, then collapses when a strong snapshot enters the pool.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dotaclient_tpu.config import LeagueConfig

LIVE = -1  # sentinel opponent id for the live (mirror self-play) draw


@dataclasses.dataclass
class Snapshot:
    params: Any
    version: int
    step: int
    uid: int = 0               # stable id — survives ring eviction shifts
    # PFSP bookkeeping: learner outcomes vs this snapshot (EMA-free counts;
    # the win-rate estimate is games-weighted so early noise washes out)
    games: float = 0.0
    wins: float = 0.0

    @property
    def win_rate(self) -> float:
        """Learner's win-rate vs this snapshot (0.5 prior until played)."""
        return self.wins / self.games if self.games > 0 else 0.5


class OpponentPool:
    """Bounded ring of frozen policy snapshots + opponent sampling."""

    def __init__(self, config: LeagueConfig, seed: int = 0) -> None:
        if config.matchmaking not in ("uniform", "pfsp"):
            raise ValueError(
                f"unknown matchmaking {config.matchmaking!r} "
                "(expected 'uniform' or 'pfsp')"
            )
        self.config = config
        self.snapshots: List[Snapshot] = []
        self._rng = np.random.default_rng(seed)
        self._last_snapshot_step: Optional[int] = None
        self._next_uid = 0

    def __len__(self) -> int:
        return len(self.snapshots)

    def maybe_snapshot(self, params: Any, version: int, step: int) -> bool:
        """Snapshot ``params`` if ``snapshot_every`` steps have passed since
        the last snapshot (always snapshots on the first call). The params
        are copied on device — the caller may donate its own buffers later.
        """
        if (
            self._last_snapshot_step is not None
            and step - self._last_snapshot_step < self.config.snapshot_every
        ):
            return False
        frozen = jax.tree.map(jnp.copy, params)
        self.snapshots.append(Snapshot(frozen, version, step, uid=self._next_uid))
        self._next_uid += 1
        if len(self.snapshots) > self.config.pool_size:
            self.snapshots.pop(0)
        self._last_snapshot_step = step
        return True

    def _pfsp_weights(self) -> np.ndarray:
        """(1 - win_rate)^power per snapshot, floored so no opponent is
        starved (a beaten opponent must stay in rotation to detect
        forgetting)."""
        w = np.asarray(
            [(1.0 - s.win_rate) ** self.config.pfsp_power for s in self.snapshots]
        )
        w = np.maximum(w, 0.05)
        return w / w.sum()

    def sample_indexed(
        self, live_params: Any, live_version: int
    ) -> Tuple[Any, int, int]:
        """Draw the opponent for the next rollout batch → (params, version,
        uid). ``uid`` is ``LIVE`` for the mirror self-play draw, else the
        snapshot's STABLE id for outcome attribution via :meth:`report`
        (stable: ring eviction shifts list positions, never uids).
        """
        if not self.snapshots or self._rng.random() < self.config.selfplay_prob:
            return live_params, live_version, LIVE
        if self.config.matchmaking == "pfsp":
            idx = int(self._rng.choice(len(self.snapshots), p=self._pfsp_weights()))
        else:
            idx = int(self._rng.integers(len(self.snapshots)))
        snap = self.snapshots[idx]
        return snap.params, snap.version, snap.uid

    def sample(self, live_params: Any, live_version: int) -> Tuple[Any, int]:
        params, version, _ = self.sample_indexed(live_params, live_version)
        return params, version

    def report(self, uid: int, wins: float, games: float) -> None:
        """Attribute ``games`` learner-vs-snapshot outcomes (``wins`` won by
        the learner) to the snapshot with stable id ``uid``. No-op for
        ``LIVE`` draws and for snapshots evicted since the draw."""
        if uid == LIVE or games <= 0:
            return
        for s in self.snapshots:
            if s.uid == uid:
                s.games += games
                s.wins += wins
                return

    def win_rates(self) -> List[float]:
        """Learner win-rate per snapshot (diagnostics / metrics)."""
        return [s.win_rate for s in self.snapshots]
