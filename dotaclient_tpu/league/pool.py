"""Frozen-opponent pool for league self-play.

The reference's league configs pit the live policy against frozen past
versions of itself (SURVEY.md §7 step 7; BASELINE.json:12 "5v5 ... league
opponents"). Mechanics here:

* every ``snapshot_every`` learner steps the current params are snapshotted
  (device-to-device copy — snapshots never touch the host) into a bounded
  ring of ``pool_size`` frozen opponents;
* each opponent draw plays the LATEST policy (mirror self-play) with
  probability ``selfplay_prob``, otherwise a uniformly random frozen
  snapshot — the standard league mix that stops strategy collapse while
  keeping most experience near on-policy.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dotaclient_tpu.config import LeagueConfig


@dataclasses.dataclass
class Snapshot:
    params: Any
    version: int
    step: int


class OpponentPool:
    """Bounded ring of frozen policy snapshots + opponent sampling."""

    def __init__(self, config: LeagueConfig, seed: int = 0) -> None:
        self.config = config
        self.snapshots: List[Snapshot] = []
        self._rng = np.random.default_rng(seed)
        self._last_snapshot_step: Optional[int] = None

    def __len__(self) -> int:
        return len(self.snapshots)

    def maybe_snapshot(self, params: Any, version: int, step: int) -> bool:
        """Snapshot ``params`` if ``snapshot_every`` steps have passed since
        the last snapshot (always snapshots on the first call). The params
        are copied on device — the caller may donate its own buffers later.
        """
        if (
            self._last_snapshot_step is not None
            and step - self._last_snapshot_step < self.config.snapshot_every
        ):
            return False
        frozen = jax.tree.map(jnp.copy, params)
        self.snapshots.append(Snapshot(frozen, version, step))
        if len(self.snapshots) > self.config.pool_size:
            self.snapshots.pop(0)
        self._last_snapshot_step = step
        return True

    def sample(self, live_params: Any, live_version: int) -> Tuple[Any, int]:
        """Draw the opponent for the next rollout batch: the live policy with
        probability ``selfplay_prob``, else a uniform frozen snapshot."""
        if not self.snapshots or self._rng.random() < self.config.selfplay_prob:
            return live_params, live_version
        snap = self.snapshots[self._rng.integers(len(self.snapshots))]
        return snap.params, snap.version
