"""Standalone win-rate evaluation from a checkpoint.

BASELINE.json's second headline metric is win-rate vs the hard scripted bot;
the reference's de-facto eval was watching TensorBoard curves during live
games (SURVEY.md §4). Here it is one command against a saved run:

    python -m dotaclient_tpu.league --checkpoint runs/ckpt
    python -m dotaclient_tpu.league --checkpoint runs/ckpt \
        --opponent scripted_easy --games 128
    python -m dotaclient_tpu.league --checkpoint runs/A --vs runs/B

``--vs`` plays checkpoint-vs-checkpoint (league mode): A controls the
learner side, B is the frozen opponent. Each checkpoint's own stored config
governs its model tree; the first checkpoint's env config (team size, hero
pool) hosts the match. Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys


def _load(directory: str):
    from dotaclient_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(directory)
    config = mgr.restore_config()
    state, config = mgr.restore(config)
    mgr.close()
    return config, state


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--checkpoint", type=str, required=True,
                   help="checkpoint directory (orbax run dir)")
    p.add_argument("--vs", type=str, default=None,
                   help="second checkpoint directory: play league mode "
                        "against its (frozen) policy instead of a bot")
    p.add_argument("--opponent", type=str, default="scripted_hard",
                   help="scripted opponent mode when --vs is absent")
    p.add_argument("--games", type=int, default=64)
    p.add_argument("--seed", type=int, default=7)
    args = p.parse_args(argv)

    from dotaclient_tpu.league import evaluate
    from dotaclient_tpu.models import make_policy

    config, state = _load(args.checkpoint)
    policy = make_policy(config.model, config.obs, config.actions)

    if args.vs is not None:
        opp_config, opp_state = _load(args.vs)
        if (config.model, config.obs, config.actions) != (
            opp_config.model, opp_config.obs, opp_config.actions
        ):
            print(
                "league eval: --vs checkpoint has a different model/obs "
                "config; both sides must share one policy architecture "
                "(the sim hosts one observation/action space per match)",
                file=sys.stderr, flush=True,
            )
            return 2
        result = evaluate(
            config, policy, state.params, "league",
            opponent_params=opp_state.params,
            n_games=args.games, seed=args.seed,
        )
        opponent = f"checkpoint:{args.vs}@step{int(opp_state.step)}"
    else:
        result = evaluate(
            config, policy, state.params, args.opponent,
            n_games=args.games, seed=args.seed,
        )
        opponent = args.opponent

    print(json.dumps({
        "checkpoint": args.checkpoint,
        "step": int(state.step),
        "opponent": opponent,
        "games": int(result["episodes"]),
        "win_rate": round(float(result["win_rate"]), 4),
        "episode_reward_mean": round(float(result["episode_reward_mean"]), 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
