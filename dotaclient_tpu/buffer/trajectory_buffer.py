"""Sharded HBM-resident trajectory ring buffer.

The reference's learner blocked on RabbitMQ and stacked rollouts in host
memory each step (SURVEY.md §3.2). The TPU-native design keeps the trajectory
store *on device*, batch-sharded over the mesh's data axis — the north-star
architecture of BASELINE.json:5 — so a train step consumes its batch without
any host↔device copy beyond the initial staged ingest (SURVEY.md §7 step 5).

Shape contract: one slot holds one rollout chunk laid out exactly like a
``train.ppo.Batch`` row (obs ``[T+1, ...]``, actions/rewards/... ``[T]``,
``carry0`` ``([H],[H])``); a consumed batch of B slots IS a train batch.

Concurrency: host-side bookkeeping (cursor, versions) is plain Python driven
by the single learner thread; actors never touch the buffer — they hand
protos to the transport, and the learner's ingest drains it (same
single-writer discipline the reference gets from its one blocking consumer).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.train.ppo import example_batch


class TrajectoryBuffer:
    """FIFO ring of rollout chunks in device memory.

    PPO is (nearly) on-policy: rollouts are consumed oldest-first, exactly
    once, with version-based staleness filtering at ingest (SURVEY.md §2.3
    "Async off-policy DP").
    """

    def __init__(self, config: RunConfig, mesh: Mesh) -> None:
        self.config = config
        self.mesh = mesh
        n_data = mesh.shape[config.mesh.data_axis]
        cap = config.buffer.capacity_rollouts
        if cap % n_data:
            raise ValueError(
                f"buffer capacity {cap} not divisible by data-parallel size {n_data}"
            )
        if config.ppo.batch_rollouts % n_data:
            raise ValueError(
                f"batch_rollouts {config.ppo.batch_rollouts} not divisible by "
                f"data-parallel size {n_data} (batches are data-sharded)"
            )
        self.capacity = cap
        self._sharding = NamedSharding(mesh, P(config.mesh.data_axis))
        template = example_batch(config, batch=cap)
        self._store = jax.tree.map(
            lambda x: jax.device_put(x, self._sharding), template
        )
        # Host-side ring bookkeeping.
        self._write = 0            # next slot to write
        self._read = 0             # next slot to consume
        self._size = 0             # filled, unconsumed slots
        self._warmed = False       # min_fill reached at least once
        self.dropped_stale = 0
        self.dropped_overflow = 0
        self.ingested = 0

        self._scatter = jax.jit(
            lambda store, rows, idx: jax.tree.map(
                lambda s, r: s.at[idx].set(r), store, rows
            ),
            donate_argnums=(0,),
            out_shardings=jax.tree.map(lambda _: self._sharding, template),
        )
        self._gather = jax.jit(
            lambda store, idx: jax.tree.map(lambda s: s[idx], store),
            out_shardings=jax.tree.map(lambda _: self._sharding, template),
        )

    # -- properties --------------------------------------------------------

    @property
    def size(self) -> int:
        return self._size

    @property
    def ready(self) -> bool:
        return self._size >= max(
            self.config.buffer.min_fill, self.config.ppo.batch_rollouts
        )

    # -- ingest ------------------------------------------------------------

    def add(
        self,
        rollouts: List[Tuple[Dict[str, Any], Any]],
        current_version: int,
    ) -> int:
        """Ingest decoded rollouts ``(meta, arrays)``; returns number kept.

        Stale rollouts (older than ``ppo.max_staleness`` versions) are
        dropped here — the reference's version-tag discipline (SURVEY.md
        §3.4) applied at the buffer door.
        """
        fresh = []
        for meta, arrays in rollouts:
            if current_version - meta["model_version"] > self.config.ppo.max_staleness:
                self.dropped_stale += 1
                continue
            fresh.append((meta, arrays))
        if len(fresh) > self.capacity:
            # A single scatter must not contain duplicate slot indices (the
            # winning write would be undefined); keep only the newest.
            self.dropped_overflow += len(fresh) - self.capacity
            fresh = fresh[-self.capacity:]
        if not fresh:
            return 0

        rows = jax.tree.map(
            lambda *xs: np.stack(xs), *[arrays for _, arrays in fresh]
        )
        idx = np.array(
            [(self._write + i) % self.capacity for i in range(len(fresh))],
            dtype=np.int32,
        )
        self._store = self._scatter(self._store, rows, jnp.asarray(idx))
        self._write = int((self._write + len(fresh)) % self.capacity)
        overflow = max(0, self._size + len(fresh) - self.capacity)
        if overflow:  # ring overwrote oldest unconsumed slots
            self._read = int((self._read + overflow) % self.capacity)
        self._size = min(self._size + len(fresh), self.capacity)
        self.ingested += len(fresh)
        return len(fresh)

    # -- consume -----------------------------------------------------------

    def take(self, batch_size: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Consume the oldest ``batch_size`` rollouts as a train batch
        (device arrays, batch-sharded). Returns None if underfilled, or
        before ``min_fill`` has been reached for the first time (warmup
        diversity guard)."""
        b = batch_size or self.config.ppo.batch_rollouts
        if not self._warmed:
            if not self.ready:
                return None
            self._warmed = True
        if self._size < b:
            return None
        idx = np.array(
            [(self._read + i) % self.capacity for i in range(b)], dtype=np.int32
        )
        batch = self._gather(self._store, jnp.asarray(idx))
        self._read = int((self._read + b) % self.capacity)
        self._size -= b
        return batch

    def metrics(self) -> Dict[str, float]:
        return {
            "buffer_size": float(self._size),
            "buffer_ingested": float(self.ingested),
            "buffer_dropped_stale": float(self.dropped_stale),
            "buffer_dropped_overflow": float(self.dropped_overflow),
        }
