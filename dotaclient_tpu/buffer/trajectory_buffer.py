"""Sharded HBM-resident trajectory ring buffer.

The reference's learner blocked on RabbitMQ and stacked rollouts in host
memory each step (SURVEY.md §3.2). The TPU-native design keeps the trajectory
store *on device*, batch-sharded over the mesh's data axis — the north-star
architecture of BASELINE.json:5 — so a train step consumes its batch without
any host↔device copy beyond the initial staged ingest (SURVEY.md §7 step 5).

Shape contract: one slot holds one rollout chunk laid out exactly like a
``train.ppo.Batch`` row (obs ``[T+1, ...]``, actions/rewards/... ``[T]``,
``carry0`` ``([H],[H])``); a consumed batch of B slots IS a train batch.

Concurrency: host-side bookkeeping (cursor, versions) is plain Python driven
by the single learner thread; actors never touch the buffer — they hand
protos to the transport, and the learner's ingest drains it (same
single-writer discipline the reference gets from its one blocking consumer).
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.train.ppo import example_batch
from dotaclient_tpu.utils import telemetry, tracing

logger = logging.getLogger(__name__)


def _pow2ceil(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << (max(1, n) - 1).bit_length()


try:  # the narrow wire dtype the admission scan must treat as float
    import ml_dtypes

    _WIRE_BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _WIRE_BF16 = np.dtype(np.void)   # matches no real leaf


class TrajectoryBuffer:
    """FIFO ring of rollout chunks in device memory.

    PPO is (nearly) on-policy: rollouts are consumed oldest-first, exactly
    once, with version-based staleness filtering at ingest (SURVEY.md §2.3
    "Async off-policy DP").
    """

    def __init__(
        self,
        config: RunConfig,
        mesh: Mesh,
        registry: Optional[telemetry.Registry] = None,
    ) -> None:
        self.config = config
        self.mesh = mesh
        self._tel = registry if registry is not None else telemetry.get_registry()
        from dotaclient_tpu.parallel.mesh import (
            batch_axes,
            batch_shard_count,
            data_sharding,
            replicated,
        )

        axes = batch_axes(mesh, config.mesh)
        n_data = batch_shard_count(mesh, config.mesh)
        self._n_shards = n_data
        desc = "×".join(f"{a}={mesh.shape[a]}" for a in axes)
        cap = config.buffer.capacity_rollouts
        if cap % n_data:
            raise ValueError(
                f"buffer capacity {cap} not divisible by the batch shard "
                f"count {n_data} ({desc})"
            )
        if config.ppo.batch_rollouts % n_data:
            raise ValueError(
                f"batch_rollouts {config.ppo.batch_rollouts} not divisible "
                f"by the batch shard count {n_data} ({desc}; batches are "
                f"sharded over these axes)"
            )
        self.capacity = cap
        # Staleness is denominated in CONSUMED BATCHES (the cadence actors
        # can actually refresh at), while the version counter ticks once per
        # optimizer step — epochs_per_batch × minibatches ticks per batch.
        # Scale the threshold so max_staleness keeps meaning "batches
        # behind" regardless of the multi-epoch/minibatch configuration.
        # buffer.max_weight_staleness >= 0 overrides with a RAW version
        # delta — the admission-control knob (ISSUE 6) fleets bound
        # staleness with directly.
        self._staleness_limit = (
            config.buffer.max_weight_staleness
            if config.buffer.max_weight_staleness >= 0
            else config.ppo.max_staleness * config.ppo.steps_per_batch
        )
        # Admission control (ISSUE 6): semantic integrity at the buffer
        # door. Counters are eager-created — a clean run reports zeros
        # (check_telemetry_schema.py --require-health pins
        # buffer/stale_rejected_total).
        self._reject_nonfinite = config.buffer.reject_nonfinite
        self.dropped_nonfinite = 0
        self._tel.counter("buffer/stale_rejected_total")
        self._tel.counter("buffer/nonfinite_rejected_total")
        self._tel.counter("buffer/poison_dropped_total")
        self._sharding = data_sharding(mesh, config.mesh)
        template = example_batch(config, batch=cap)
        # Quantized experience plane (ISSUE 7): with
        # transport.rollout_wire_dtype narrow, the ring STORES the wire
        # dtypes — ≈half the resident HBM bytes and per-scatter H2D traffic
        # — and the upcast to the train dtypes happens on-device inside the
        # already-jitted consume gather, so `take()` hands the train step
        # f32 inputs bit-identical to decoding the wire (bf16→f32 and
        # int8→int32 are exact). The f32 template's dtypes are kept as the
        # consume-time upcast targets; the narrow template drives the
        # staging lanes, the skew check, and the scatter.
        from dotaclient_tpu.transport.serialize import (
            apply_cast_plan,
            flatten_tree,
            rollout_cast_plan,
            rollout_int_bounds,
            unflatten_tree,
        )

        self._consume_dtypes = jax.tree.map(
            lambda x: np.dtype(x.dtype), template
        )
        wire_dtype = config.transport.rollout_wire_dtype
        flat_tmpl = flatten_tree(template)
        int_bounds = rollout_int_bounds(config)
        self._wire_plan = rollout_cast_plan(
            {n: np.dtype(a.dtype) for n, a in flat_tmpl.items()},
            wire_dtype,
            int_bounds,
        )
        # Per-leaf admission dtypes: the stored dtype plus every width the
        # same leaf may legitimately arrive at — the original full width
        # (an in-proc actor or an f32-knob fleet member) and the narrow
        # wire width (a bf16-knob actor shipping to an f32 learner). The
        # staging copy casts on assignment either way; genuinely skewed
        # dtypes (wrong kind/meaning) still drop at the door.
        accept_flat: Dict[str, frozenset] = {}
        if self._wire_plan:   # a narrow config's plan IS the bf16 plan
            alt_plan = self._wire_plan
        else:
            try:
                alt_plan = rollout_cast_plan(
                    {n: np.dtype(a.dtype) for n, a in flat_tmpl.items()},
                    "bfloat16",
                    int_bounds,
                )
            except ValueError:   # ml_dtypes unavailable: full-width only
                alt_plan = {}
        for n, a in flat_tmpl.items():
            widths = {np.dtype(a.dtype)}
            if n in alt_plan:
                widths.add(np.dtype(alt_plan[n]))
            accept_flat[n] = frozenset(widths)
        self._accept_dtypes = jax.tree.leaves(unflatten_tree(accept_flat))
        # Bound guards for the mixed-fleet door (review round 2): a
        # FULL-WIDTH int row admitted into a narrow ring is cast by the
        # staging copy / in-program astype with no range check, which
        # would WRAP silently — the exact failure the encode path's
        # exactness guard fails loudly on. Guard the buffer door the same
        # way: np.iinfo of the narrow target per int-narrowed leaf, in
        # template leaf order (same discipline as ``_accept_dtypes``);
        # the scan runs only on rows arriving wider than the store.
        guard_flat = {
            n: (
                np.iinfo(self._wire_plan[n])
                if n in self._wire_plan
                and np.dtype(self._wire_plan[n]).kind == "i"
                else 0
            )
            for n in flat_tmpl
        }
        self._int_guards = jax.tree.leaves(unflatten_tree(guard_flat))
        self._has_int_guards = any(g != 0 for g in self._int_guards)
        self._tel.counter("buffer/intbound_rejected_total")
        if self._wire_plan:
            template = unflatten_tree(
                apply_cast_plan(flat_tmpl, self._wire_plan)
            )
        self._store_dtypes = jax.tree.map(lambda x: np.dtype(x.dtype), template)
        self._store = jax.tree.map(
            lambda x: jax.device_put(x, self._sharding), template
        )
        # Multi-chip residency accounting (ISSUE 10): the ring is
        # batch-sharded, so each device holds 1/n_data of every leaf —
        # `buffer/shard_bytes` is the PER-DEVICE resident HBM cost of the
        # ring (the number an operator sizes capacity_rollouts against).
        total_bytes = sum(
            x.size * np.dtype(x.dtype).itemsize
            for x in jax.tree.leaves(template)
        )
        self._tel.gauge("buffer/shard_bytes").set(
            float(total_bytes // n_data)
        )
        # Host-side bookkeeping: consumption order is an explicit deque of
        # slot ids (oldest first) plus a free list — NOT ring-cursor
        # arithmetic. Chunk versions are not monotone in ship order (an
        # episode-end chunk ships early with a newer version than a longer
        # chunk still in flight), so consume-time staleness drops must be
        # able to remove arbitrary slots, not just the head.
        self._order: Deque[int] = deque()
        self._free: List[int] = list(range(cap - 1, -1, -1))
        # Held batches (prefetch lane): slots taken with ``hold=True`` are
        # parked here — out of ``_order`` (cannot be re-taken or evicted by
        # an interleaved ingest) and out of ``_free`` (cannot be
        # overwritten) — until the consumer either ``release``s them
        # (batch trained on) or ``requeue``s them (end-of-run flush: the
        # experience returns to the front of the ring untrained, so a
        # checkpoint loses nothing).
        self._held: Dict[int, List[int]] = {}
        self._next_ticket = 0
        self._warmed = False       # min_fill reached at least once
        # Per-slot producer version, host-side: staleness is re-checked at
        # consume time too — a rollout that was fresh at ingest can go stale
        # sitting in the ring while the learner trains (ADVICE round 1).
        self._slot_version = np.zeros((cap,), np.int64)
        self.dropped_stale = 0
        self.dropped_overflow = 0
        self.dropped_skew = 0
        self.dropped_bounds = 0
        self.ingested = 0
        # Per-slot leaf spec for the ingest-door shape guard: a rollout from
        # a config-skewed actor (different rollout_len / obs shapes / model
        # core) must be dropped like any other malformed payload — actors
        # are disposable, the learner is not (SURVEY.md §5.3).
        self._tmpl_struct = jax.tree.structure(template)
        self._tmpl_leaves = [
            (x.shape[1:], np.dtype(x.dtype)) for x in jax.tree.leaves(template)
        ]
        self._skew_warned = False
        self._bounds_warned = False
        # Host staging lanes (BufferConfig.staging_slots): the ingest path
        # copies decoded rows into one of these REUSED preallocated numpy
        # buffers instead of np.stack-allocating per call, rotating lanes so
        # the scatter issued for ingest N (async dispatch may still read the
        # host rows) never shares a lane with ingest N+1's assembly.
        # Allocated lazily at first host-path ingest — the device-rollout
        # path scatters device chunks and never stages host rows.
        self._staging_lanes = max(1, config.buffer.staging_slots)
        self._staging: Optional[List[Any]] = None
        self._staging_idx = 0
        # Host ingest pads to shard-divisible power-of-two buckets (see
        # _pad_rows), so the lanes must hold the padded form of a
        # full-capacity ingest (monotone in n, so the cap is the max).
        self._staging_rows = self._pad_rows(cap)

        # Pipeline tracing (ISSUE 12): captured once, the faults/tracer
        # discipline — with tracing off every ingest/consume pays one
        # `is not None` test. Traced slots remember their host record
        # across ring residency so gather/dispatch hops can close the
        # chunk's timeline (the learner emits at dispatch).
        self._tracer = tracing.get()
        self._slot_trace: Optional[List[Optional[dict]]] = (
            [None] * cap if self._tracer is not None else None
        )
        self._pending_traces: List[dict] = []

        # Retrace accounting (ADVICE round 1): every distinct rows leading
        # dim compiles one XLA program. Host ingest pads to shard-divisible
        # pow2 buckets and the device path scatters pow2 chunks, so the
        # program set per path is bounded at log2(capacity)+1 —
        # `scatter_traces` proves it.
        self.scatter_traces = 0

        def _scatter_impl(store, rows, idx):
            self.scatter_traces += 1   # runs at trace time only
            # dtype-aware: rows arriving wider than the store (the
            # device-rollout path's f32 chunks into a narrow ring, or an
            # f32-knob actor at a narrow learner) are cast in-program; a
            # same-dtype astype is free in XLA
            return jax.tree.map(
                lambda s, r: s.at[idx].set(r.astype(s.dtype)), store, rows
            )

        store_shardings = jax.tree.map(lambda _: self._sharding, template)
        # HOST ingest path: rows are numpy staging-lane views, and the
        # explicit data-sharded in_shardings makes the H2D transfer land
        # DIRECTLY in each device's shard — 1/n_data of the group's bytes
        # per device. Without it the compiler replicates uncommitted host
        # inputs: every device received a FULL copy of every ingest group
        # (n_devices × the bytes; measured via compiled input shardings) —
        # the single-device-memory scatter ISSUE 10 exists to fix.
        # _pad_rows guarantees the leading dim divides by n_data.
        # instrument_jit (ISSUE 12): compile/retrace accounting per
        # program; transparent to dispatch AND to the donation lint
        # (lint/donation.py unwraps it) and to `.lower(...)` introspection
        self._scatter = tracing.instrument_jit(
            jax.jit(
                _scatter_impl,
                donate_argnums=(0,),
                in_shardings=(
                    store_shardings,
                    jax.tree.map(lambda _: self._sharding, template),
                    replicated(mesh),
                ),
                out_shardings=store_shardings,
            ),
            "buffer_scatter",
        )
        # DEVICE ingest path (add_device): rows are committed slices of an
        # in-process chunk (whatever sharding the producing program left
        # them with — explicit in_shardings would REJECT them, jax refuses
        # committed args whose sharding mismatches); no H2D happens here,
        # the program reshards in HBM. Separate jit so the two paths'
        # programs never mix; same impl, same trace bound.
        self._scatter_dev = tracing.instrument_jit(
            jax.jit(
                _scatter_impl,
                donate_argnums=(0,),
                out_shardings=store_shardings,
            ),
            "buffer_scatter_dev",
        )
        # Consume-time upcast (ISSUE 7): the gather restores the train
        # dtypes in the same jitted program — the only place narrow rows
        # widen, and it runs on-device (no host copy ever sees f32).
        consume_dtypes = self._consume_dtypes
        self._gather = tracing.instrument_jit(
            jax.jit(
                lambda store, idx: jax.tree.map(
                    lambda s, d: s[idx].astype(d), store, consume_dtypes
                ),
                out_shardings=jax.tree.map(
                    lambda _: self._sharding, template
                ),
            ),
            "buffer_gather",
        )

    def _pad_rows(self, n: int) -> int:
        """Padded row count for a host ingest group of ``n`` rows: the
        smallest power-of-two-per-shard multiple of the batch shard count
        that covers ``n``. With one shard this is exactly the historical
        pow2 bucket; with n_data shards it additionally guarantees the
        sharded scatter's leading dim divides evenly (jax rejects a
        NamedSharding whose axis does not divide). Distinct values stay
        bounded at log2(capacity/n_data)+1, so the retrace bound holds."""
        per_shard = -(-max(1, n) // self._n_shards)
        return _pow2ceil(per_shard) * self._n_shards

    # -- properties --------------------------------------------------------

    @property
    def size(self) -> int:
        return len(self._order)

    @property
    def ready(self) -> bool:
        return self.size >= max(
            self.config.buffer.min_fill, self.config.ppo.batch_rollouts
        )

    # -- ingest ------------------------------------------------------------

    def add(
        self,
        rollouts: List[Tuple[Dict[str, Any], Any]],
        current_version: int,
    ) -> int:
        """Ingest decoded rollouts ``(meta, arrays)``; returns number kept.

        Stale rollouts (older than ``ppo.max_staleness`` versions) are
        dropped here — the reference's version-tag discipline (SURVEY.md
        §3.4) applied at the buffer door.
        """
        fresh = []
        for meta, arrays in rollouts:
            if current_version - meta["model_version"] > self._staleness_limit:
                self.dropped_stale += 1
                self._tel.counter("buffer/stale_rejected_total").inc()
                continue
            if not self._matches_slot(arrays):
                self.dropped_skew += 1
                # Counted (rates come from diffing JSONL lines) AND logged —
                # never a bare print: headless runs must see the skew in
                # both the log stream and the telemetry record.
                self._tel.counter("buffer/skew_drops_total").inc()
                if not self._skew_warned:
                    self._skew_warned = True
                    logger.warning(
                        "trajectory_buffer: dropping rollout whose shapes do "
                        "not match this learner's config (actor running a "
                        "different rollout_len/obs/model config?) — align "
                        "actor and learner configs"
                    )
                continue
            if self._has_int_guards and not self._payload_in_bounds(arrays):
                # Mixed-fleet bound guard (ISSUE 7): a FULL-WIDTH int row
                # headed into a narrow ring would wrap silently at the
                # staging/scatter cast — the exact corruption the encode
                # path fails loudly on. Same door policy as nonfinite:
                # counted, never fatal.
                self.dropped_bounds += 1
                self._tel.counter("buffer/intbound_rejected_total").inc()
                if not self._bounds_warned:
                    self._bounds_warned = True
                    logger.warning(
                        "trajectory_buffer: dropping full-width rollout "
                        "whose integer leaves exceed this learner's "
                        "narrow-ring bounds (rollout_int_bounds promise "
                        "violated by an f32-wire actor?) — fix the actor "
                        "or widen rollout_int_bounds"
                    )
                continue
            if self._reject_nonfinite and not self._payload_finite(arrays):
                # Semantic admission control (ISSUE 6): a NaN/Inf anywhere
                # in a payload's float leaves (observations, rewards,
                # behavior logp, carries) would flow straight into the loss
                # and poison the params — reject at the door, like the wire
                # layer rejects CRC failures. Counted, never fatal: actors
                # are disposable, the learner is not.
                self.dropped_nonfinite += 1
                self._tel.counter("buffer/nonfinite_rejected_total").inc()
                continue
            fresh.append((meta, arrays))
        if len(fresh) > self.capacity:
            # A single scatter must not contain duplicate slot indices (the
            # winning write would be undefined); keep only the newest.
            self.dropped_overflow += len(fresh) - self.capacity
            fresh = fresh[-self.capacity:]
        if not fresh:
            self._publish_telemetry()
            return 0

        with self._tel.span("buffer/insert"):
            slots = self._alloc_slots(len(fresh))
            if len(slots) < len(fresh):
                fresh = fresh[: len(slots)]
                if not fresh:
                    self._publish_telemetry()
                    return 0
            n = len(fresh)
            # Pad the ingest group to a shard-divisible power-of-two bucket
            # and scatter ONCE (ADVICE round 1): a varying leading dim
            # would compile one XLA program per distinct count — up to
            # `capacity` of them. Pad rows are copies of the LAST REAL ROW
            # and their indices duplicate its slot, so the duplicate writes
            # are identical (order-independent) and the pad never enters
            # the slot bookkeeping below. Bounds the program set at
            # log2(capacity/n_data)+1 (asserted via `scatter_traces` in
            # tests). numpy rows transfer on the dispatch path, sharded —
            # each device receives only its slice (see _scatter).
            n_pad = self._pad_rows(n)
            rows = self._stage_rows(
                [arrays for _, arrays in fresh], pad_to=n_pad
            )
            idx = np.empty((n_pad,), np.int32)
            idx[:n] = slots   # host-sync-ok: host ints
            idx[n:] = slots[-1]
            self._store = self._scatter(self._store, rows, idx)
            self._slot_version[idx[:n]] = [
                m["model_version"] for m, _ in fresh
            ]
            if self._tracer is not None:
                # admission hop: the row passed the door and owns a slot.
                # Untraced rows CLEAR the slot's record — a reused slot
                # must never inherit an evicted chunk's timeline.
                ts = tracing.now()
                for (m, _), s in zip(fresh, slots):
                    rec = m.get("trace")
                    if rec is not None:
                        rec["hops"].append(["admit", ts])
                    self._slot_trace[s] = rec
            self._order.extend(slots)
            self.ingested += n
        self._publish_telemetry()
        return len(fresh)

    def _payload_finite(self, arrays: Any) -> bool:
        """True iff every float leaf of a host payload is finite. One
        vectorized pass per leaf — the staging copy touches the same bytes
        anyway, so the scan rides the ingest's existing memory traffic.

        Narrow-dtype rows (ISSUE 7) are scanned DIRECTLY: ml_dtypes
        registers a native ``np.isfinite`` loop for bfloat16 (a bf16 NaN
        is still a NaN), so the pass never materializes an f32 upcast
        copy — pinned by a test. Note bf16's numpy ``dtype.kind`` is
        ``'V'``, not ``'f'``: the kind check alone would silently skip
        exactly the leaves the narrow wire carries."""
        for leaf in jax.tree.leaves(arrays):
            a = np.asarray(leaf)
            if (
                a.dtype.kind == "f" or a.dtype == _WIRE_BF16
            ) and not np.isfinite(a).all():
                return False
        return True

    def _payload_in_bounds(self, arrays: Any) -> bool:
        """True iff every int leaf arriving WIDER than its narrow store
        dtype fits that dtype's range. Only the mixed-fleet path pays the
        min/max pass (a row already at the narrow width fits by dtype;
        a full-width ring has no guards at all)."""
        for leaf, guard in zip(jax.tree.leaves(arrays), self._int_guards):
            if guard == 0:
                continue
            a = np.asarray(leaf)
            if (
                a.dtype.kind == "i"
                and a.dtype.itemsize > guard.dtype.itemsize
                and a.size
                and (a.min() < guard.min or a.max() > guard.max)
            ):
                return False
        return True

    def _matches_slot(self, arrays: Any) -> bool:
        """True iff ``arrays`` has exactly the slot pytree/shapes, with
        every leaf at one of its admissible widths (the stored dtype, the
        original full width, or the narrow wire width — see
        ``_accept_dtypes``; the staging copy casts on assignment). Any
        other dtype is config skew and drops at the door."""
        try:
            if jax.tree.structure(arrays) != self._tmpl_struct:
                return False
            return all(
                np.shape(leaf) == shape and np.asarray(leaf).dtype in accept
                for leaf, (shape, _), accept in zip(
                    jax.tree.leaves(arrays),
                    self._tmpl_leaves,
                    self._accept_dtypes,
                )
            )
        except (TypeError, ValueError, AttributeError):
            return False

    def _alloc_slots(self, n: int) -> List[int]:
        """Allocate up to ``n`` writable slots for an ingest scatter: free
        slots first, then evict oldest unconsumed (counted in
        ``dropped_overflow``). Held (in-flight prefetched) slots are in
        neither pool — they can be neither evicted nor overwritten — so
        when everything else is exhausted the remainder is dropped
        (counted) rather than corrupting a batch mid-consumption. The
        returned list may be shorter than ``n``."""
        slots: List[int] = []
        for k in range(n):
            if self._free:
                slots.append(self._free.pop())
            elif self._order:
                slots.append(self._order.popleft())
                self.dropped_overflow += 1
            else:
                self.dropped_overflow += n - k
                break
        return slots

    def _stage_rows(self, arrays_list: List[Any], pad_to: int = 0) -> Any:
        """Copy decoded rollout rows into the next staging lane and return
        per-leaf views of the first ``max(len(arrays_list), pad_to)`` rows,
        with rows beyond ``len(arrays_list)`` filled with copies of the
        last real row (the pow2 scatter pad — see :meth:`add`).

        The lanes are preallocated at (pow2-padded) ring capacity (the most
        one ``add`` can ingest) and REUSED round-robin: no per-ingest
        allocation, and the ``staging_slots``-deep rotation guarantees the
        rows a possibly still-in-flight previous scatter reads are never
        overwritten by the current assembly — the double-buffering that
        lets the learner issue batch N+1's ingest while batch N's epoch
        step runs.
        """
        if self._staging is None:
            leaves_per_lane = [
                [
                    np.empty((self._staging_rows,) + shape, dtype)
                    for shape, dtype in self._tmpl_leaves
                ]
                for _ in range(self._staging_lanes)
            ]
            self._staging = [
                jax.tree.unflatten(self._tmpl_struct, leaves)
                for leaves in leaves_per_lane
            ]
        lane = self._staging[self._staging_idx]
        self._staging_idx = (self._staging_idx + 1) % self._staging_lanes
        n = len(arrays_list)
        n_out = max(n, pad_to)
        with self._tel.span("buffer/stage"):
            dst_leaves = jax.tree.leaves(lane)
            for i, arrays in enumerate(arrays_list):
                # leaf order matches the template: _matches_slot already
                # verified the pytree structure at the ingest door
                for dst, src in zip(dst_leaves, jax.tree.leaves(arrays)):
                    dst[i] = src
            for dst in dst_leaves:
                # pad rows mirror the last real row — their scatter indices
                # duplicate its slot, so the writes must be bit-identical
                dst[n:n_out] = dst[n - 1]
        return jax.tree.map(lambda dst: dst[:n_out], lane)

    def add_device(self, chunk: Dict[str, Any], version: int) -> int:
        """Ingest a device-resident chunk batch (arrays ``[L, T, ...]``, the
        on-device rollout path) — device-to-device scatter, no host copy of
        the experience tensors.

        Freshness: these chunks are produced with the current params by
        construction, so no staleness filter runs here; the slots are still
        version-tagged for consume-time re-checks.
        """
        with self._tel.span("buffer/insert"):
            L = chunk["valid"].shape[0]
            take = min(L, self.capacity)
            if take < L:
                self.dropped_overflow += L - take
            slots = self._alloc_slots(take)
            take = len(slots)
            if not take:
                self._publish_telemetry()
                return 0
            idx = np.asarray(slots, dtype=np.int32)   # host-sync-ok: host ints
            pos = 0
            remaining = take
            while remaining:
                n = 1 << (remaining.bit_length() - 1)
                rows = jax.tree.map(lambda r: r[pos:pos + n], chunk)
                # device-path scatter: rows keep their producer's sharding
                self._store = self._scatter_dev(
                    self._store, rows, idx[pos:pos + n]
                )
                pos += n
                remaining -= n
            if self._slot_trace is not None:
                # device chunks are untraced, but the slots they claim may
                # have been evicted from under a traced host row — a
                # reused slot must never inherit that chunk's timeline
                # (same invariant the host-path assignment keeps)
                for s in slots:
                    self._slot_trace[s] = None
            self._slot_version[idx] = version
            self._order.extend(slots)
            self.ingested += take
        self._publish_telemetry()
        return take

    # -- consume -----------------------------------------------------------

    def take(
        self,
        batch_size: Optional[int] = None,
        current_version: Optional[int] = None,
        hold: bool = False,
    ) -> Optional[Any]:
        """Consume the oldest ``batch_size`` rollouts as a train batch
        (device arrays, batch-sharded). Returns None if underfilled, or
        before ``min_fill`` has been reached for the first time (warmup
        diversity guard).

        This gather is the CONSUME BOUNDARY of the one-pass advantage
        plane (ISSUE 14): the learner runs its jitted advantage pass over
        the batch returned here — once per batch, not per optimizer step
        — and stages the narrow advantages/returns ON the batch dict, not
        in the ring (slots hold wire-shaped experience only, so requeue/
        rollback hygiene never has to invalidate derived tensors: they
        die with the batch dict — see train/learner.py).

        When ``current_version`` is given, staleness is re-enforced here:
        every unconsumed slot whose producer version has fallen more than
        ``max_staleness`` behind is dropped (slots are scanned, not just the
        head — ship order does not imply version order).

        With ``hold=True`` (the prefetch lane) the return is ``(batch,
        ticket)`` and the slots are PARKED instead of freed: an interleaved
        ingest can neither evict nor overwrite them while the batch is in
        flight. The consumer must then call :meth:`release` (trained on) or
        :meth:`requeue` (flushed untrained — the rows go back to the front
        of the ring, so checkpoints lose nothing).
        """
        b = batch_size or self.config.ppo.batch_rollouts
        if current_version is not None:
            max_st = self._staleness_limit
            stale = [
                s for s in self._order
                if current_version - self._slot_version[s] > max_st
            ]
            if stale:
                stale_set = set(stale)
                self._order = deque(
                    s for s in self._order if s not in stale_set
                )
                self._free.extend(stale)
                self.dropped_stale += len(stale)
                self._tel.counter("buffer/stale_rejected_total").inc(
                    len(stale)
                )
        if not self._warmed:
            if not self.ready:
                return None
            self._warmed = True
        if self.size < b:
            return None
        with self._tel.span("buffer/sample"):
            idx = np.asarray(   # host-sync-ok: host ints
                [self._order.popleft() for _ in range(b)], np.int32
            )
            batch = self._gather(self._store, idx)
            if hold:
                ticket = self._next_ticket
                self._next_ticket += 1
                self._held[ticket] = [int(s) for s in idx]
            else:
                self._free.extend(int(s) for s in idx)
            if self._tracer is not None:
                # consume-gather hop: the slot left the ring in this batch
                # (ring residency = gather − admit). The records park in
                # _pending_traces until the learner stamps `dispatch` on
                # the batch they ride (drain_traces) — a requeued batch's
                # records attribute to the NEXT dispatch, a documented
                # end-of-run approximation.
                ts = tracing.now()
                for s in idx:
                    rec = self._slot_trace[int(s)]
                    if rec is not None:
                        self._slot_trace[int(s)] = None
                        rec["hops"].append(["gather", ts])
                        self._pending_traces.append(rec)
        if current_version is not None:
            # host-side ints: how far behind the optimizer the experience in
            # this batch is, in optimizer steps (the IMPACT-style staleness
            # signal the --overlap path needs; 0 on the on-device path)
            self._tel.gauge("buffer/batch_staleness").set(
                float(current_version - self._slot_version[idx].mean())   # host-sync-ok: host ints
            )
        self._publish_telemetry()
        return (batch, ticket) if hold else batch

    def drain_traces(self) -> List[dict]:
        """Hand off the trace records of every batch gathered since the
        last call (ISSUE 12) — the learner stamps ``dispatch`` and emits
        them. Empty (and allocation-free) when tracing is off."""
        if not self._pending_traces:
            return self._pending_traces
        out, self._pending_traces = self._pending_traces, []
        return out

    def release(self, ticket: int) -> None:
        """The held batch was consumed — its slots become reusable.
        Tolerates an already-cleared ticket (a ``state_dict`` snapshot may
        have folded held slots back via :meth:`requeue_all_held`)."""
        self._free.extend(self._held.pop(ticket, ()))

    def requeue(self, ticket: int) -> None:
        """The held batch was NOT consumed (end-of-run flush): its slots
        return to the FRONT of the consumption order, in their original
        relative order — the next ``take`` re-gathers the same rows."""
        self._order.extendleft(reversed(self._held.pop(ticket, ())))

    def drop_newer_than(self, version: int) -> int:
        """Divergence-rollback hygiene (ISSUE 6): drop every unconsumed
        slot whose producer version is NEWER than ``version`` — experience
        generated by the poisoned policy of the abandoned timeline must
        not train the restored state. Counted in
        ``buffer/poison_dropped_total``; held (prefetch) slots must be
        requeued by the caller first (the learner's rollback flushes its
        prefetch lane before calling this)."""
        bad = [s for s in self._order if self._slot_version[s] > version]
        if bad:
            bad_set = set(bad)
            self._order = deque(s for s in self._order if s not in bad_set)
            self._free.extend(bad)
            self._tel.counter("buffer/poison_dropped_total").inc(len(bad))
            self._publish_telemetry()
        return len(bad)

    def requeue_all_held(self) -> None:
        """Defensive checkpoint hook: park nothing across a state_dict —
        newest tickets first, so the oldest held batch ends up at the very
        front and global FIFO order is preserved."""
        for ticket in sorted(self._held, reverse=True):
            self.requeue(ticket)

    # -- checkpointing -----------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        """Full buffer state for checkpointing: the HBM ring contents plus
        the host bookkeeping, as host arrays (SURVEY.md §5.4 — a restore
        must not lose in-flight experience)."""
        def padded(vals) -> np.ndarray:
            # orbax rejects zero-size arrays: fixed capacity, -1 fill
            out = np.full((self.capacity,), -1, np.int64)
            out[: len(vals)] = list(vals)
            return out

        # in-flight held batches are unconsumed experience: fold them back
        # into the order so the snapshot is self-contained
        self.requeue_all_held()
        return {
            "store": jax.tree.map(np.asarray, self._store),
            "order": padded(self._order),
            "free": padded(self._free),
            "slot_version": self._slot_version.copy(),
            "counters": np.asarray(
                [
                    int(self._warmed), self.dropped_stale,
                    self.dropped_overflow, self.ingested,
                    self.dropped_skew, self.dropped_nonfinite,
                    self.dropped_bounds,
                ],
                np.int64,
            ),
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        def _put(x, dtype):
            a = np.asarray(x)   # host-sync-ok: checkpoint-restore host arrays
            if a.dtype != dtype:
                # snapshot written under a different rollout_wire_dtype
                # (f32 ring restored into a narrow config, or vice versa):
                # cast to THIS config's storage width — exact upward,
                # quantizing floats downward like a fresh ingest; int
                # slots that would WRAP are freed below instead
                a = a.astype(dtype)
            return jax.device_put(a, self._sharding)

        # Same bound guard the ingest door runs (`_payload_in_bounds`): a
        # full-width snapshot restored into a narrow ring would WRAP any
        # out-of-range int slot at the astype below — scan per slot first
        # and free the offenders instead (counted, never fatal, exactly
        # the fresh-ingest policy for the same rows).
        bad_slots = np.zeros((self.capacity,), bool)
        if self._has_int_guards:
            for leaf, guard in zip(
                jax.tree.leaves(state["store"]), self._int_guards
            ):
                if guard == 0:
                    continue
                a = np.asarray(leaf)   # host-sync-ok: checkpoint-restore
                if (
                    a.dtype.kind == "i"
                    and a.dtype.itemsize > guard.dtype.itemsize
                    and a.shape[:1] == (self.capacity,)
                ):
                    over = (a < guard.min) | (a > guard.max)
                    bad_slots |= over.reshape(self.capacity, -1).any(axis=1)

        self._store = jax.tree.map(_put, state["store"], self._store_dtypes)
        self._order = deque(
            int(s) for s in np.asarray(state["order"]) if s >= 0
        )
        self._free = [int(s) for s in np.asarray(state["free"]) if s >= 0]
        self._held = {}   # snapshots never carry in-flight holds
        if self._slot_trace is not None:
            # restored slots carry no live trace timeline
            self._slot_trace = [None] * self.capacity
            self._pending_traces = []
        self._slot_version = np.asarray(state["slot_version"]).copy()
        counters = [int(v) for v in np.asarray(state["counters"])]
        # snapshots written before dropped_skew/dropped_nonfinite/
        # dropped_bounds joined the array carry fewer entries; missing
        # counters resume at 0
        counters += [0] * (7 - len(counters))
        (warmed, stale, overflow, ingested, skew, nonfinite,
         bounds) = counters[:7]
        self._warmed = bool(warmed)
        self.dropped_stale = stale
        self.dropped_overflow = overflow
        self.ingested = ingested
        self.dropped_skew = skew
        self.dropped_nonfinite = nonfinite
        self.dropped_bounds = bounds
        dropped = (
            [s for s in self._order if bad_slots[s]]
            if bad_slots.any()
            else []
        )
        if dropped:
            self._order = deque(s for s in self._order if not bad_slots[s])
            self._free.extend(dropped)
            self.dropped_bounds += len(dropped)
            self._tel.counter("buffer/intbound_rejected_total").inc(
                len(dropped)
            )
            logger.warning(
                "trajectory_buffer: freed %d restored slot(s) whose int "
                "values exceed this config's narrow wire bounds (snapshot "
                "written under a wider rollout_wire_dtype?) — casting "
                "them would wrap silently",
                len(dropped),
            )

    def _publish_telemetry(self) -> None:
        """Mirror the host-side bookkeeping into the registry (gauges are
        cheap host writes; called at ingest/consume, never mid-dispatch)."""
        self._tel.gauge("buffer/occupancy").set(float(self.size))
        self._tel.gauge("buffer/capacity").set(float(self.capacity))
        self._tel.gauge("buffer/ingested").set(float(self.ingested))
        self._tel.gauge("buffer/dropped_stale").set(float(self.dropped_stale))
        self._tel.gauge("buffer/dropped_overflow").set(
            float(self.dropped_overflow)
        )
        self._tel.gauge("buffer/dropped_skew").set(float(self.dropped_skew))
        self._tel.gauge("buffer/dropped_nonfinite").set(
            float(self.dropped_nonfinite)
        )
        self._tel.gauge("buffer/dropped_bounds").set(
            float(self.dropped_bounds)
        )

    def metrics(self) -> Dict[str, float]:
        return {
            "buffer_size": float(self.size),
            "buffer_ingested": float(self.ingested),
            "buffer_dropped_stale": float(self.dropped_stale),
            "buffer_dropped_overflow": float(self.dropped_overflow),
            "buffer_dropped_skew": float(self.dropped_skew),
            "buffer_dropped_nonfinite": float(self.dropped_nonfinite),
            "buffer_dropped_bounds": float(self.dropped_bounds),
        }
