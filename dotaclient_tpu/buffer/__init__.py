"""Sharded HBM-resident trajectory storage."""

from dotaclient_tpu.buffer.trajectory_buffer import TrajectoryBuffer

__all__ = ["TrajectoryBuffer"]
