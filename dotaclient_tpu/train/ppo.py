"""PPO learner: loss, optimizer, and the single pjit'd train step.

Parity target is the reference learner loop — collect N rollouts, re-run the
policy over sequences teacher-forced from stored initial LSTM states, GAE,
clipped-surrogate PPO loss with entropy bonus and value loss, grad-clip, Adam
(SURVEY.md §3.2, BASELINE.json:5; reconstructed — the reference checkout was
an empty mount).

TPU-first shape (SURVEY.md §7 step 4): the whole loop body — sequence
forward, loss, gradient, ``psum`` over the data axis, Adam update — is
ONE jitted function with donated train-state buffers, compiled once against a
``(data, model)`` mesh. The gradient all-reduce is emitted by XLA from the
sharding annotations (batch sharded over ``data``, params replicated); there
is no hand-written collective.

Advantage estimation is its own pipeline stage (the one-pass advantage
plane, ``train/advantage.py``): a batch arriving with precomputed
``advantages``/``returns`` leaves trains all ``epochs_per_batch ×
minibatches`` updates on them over a T-step forward. Batches without the
leaves (fused mode, vtrace, ``one_pass_advantage=false``, and every direct
caller of :func:`make_train_step`) keep the in-step estimator over the
full T+1 chunk — the historical behavior, bitwise.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Mapping, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dotaclient_tpu.config import (
    ADV_NORM_MODES, ADVANTAGE_MODES, PPOConfig, RunConfig,
)
from dotaclient_tpu.models import distributions as D
from dotaclient_tpu.models.policy import Policy
from dotaclient_tpu.train.gae import gae, vtrace


@flax.struct.dataclass
class TrainState:
    """Learner state. ``version`` is the model-version counter the actors tag
    rollouts with (staleness filtering, SURVEY.md §3.4)."""

    step: jnp.ndarray          # i32 []
    version: jnp.ndarray       # i32 []
    params: Any
    opt_state: Any


# A training batch of rollout chunks. Time layout (SURVEY.md §5.7):
#   obs arrays            [B, T+1, ...]  — includes the bootstrap observation
#   actions/logp/...      [B, T]
#   carry0                ([B, H], [B, H]) — stored rollout-initial LSTM state
#   valid                 [B, T] — False on padding after an episode's end
Batch = Dict[str, Any]


def make_optimizer(cfg: PPOConfig) -> optax.GradientTransformation:
    if cfg.kl_target > 0:
        # inject_hyperparams materializes the learning rate as an array in
        # the optimizer state so the KL-adaptive controller in _train_step
        # can rescale it in-graph (state layout gains one scalar leaf).
        return optax.chain(
            optax.clip_by_global_norm(cfg.max_grad_norm),
            optax.inject_hyperparams(optax.adam)(
                learning_rate=cfg.learning_rate
            ),
        )
    return optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adam(cfg.learning_rate),
    )


def init_train_state(policy_params: Any, cfg: PPOConfig) -> TrainState:
    """Build a fresh TrainState.

    The params are copied: the train step donates the whole state (its
    buffers die on every step), while callers — the actor's inference path in
    particular — keep using their own copy.
    """
    opt = make_optimizer(cfg)
    params = jax.tree.map(jnp.copy, policy_params)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        version=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=opt.init(params),
    )


def _moe_aux_loss(losses_col: Any, valid: jnp.ndarray) -> jnp.ndarray:
    """Switch load-balancing loss from the routing stats an MoE core sows.

    Leaves arrive as ``[T+1, B, E]`` (the learner scan stacks one ``[B, E]``
    sow per step on axis 0); padded steps and the trailing bootstrap slot
    are masked out of the means exactly like every other loss term. Zero
    for dense cores (empty collection).
    """
    if not losses_col:
        return jnp.zeros(())
    B, T = valid.shape
    keystr = jax.tree_util.keystr
    flat, _ = jax.tree_util.tree_flatten_with_path(losses_col)
    probs = {keystr(p[:-2]): l for p, l in flat if "moe_probs" in keystr(p)}
    fracs = {keystr(p[:-2]): l for p, l in flat if "moe_frac" in keystr(p)}
    w = valid.T[..., None]                       # [T, B, 1]
    denom = jnp.maximum(valid.sum(), 1.0)
    aux = jnp.zeros(())
    for key, pr in probs.items():
        fr = fracs[key]
        E = pr.shape[-1]
        mean_p = (pr[:T] * w).sum((0, 1)) / denom   # [E] masked importance
        mean_f = (fr[:T] * w).sum((0, 1)) / denom   # [E] masked load
        aux = aux + E * jnp.sum(mean_p * mean_f)
    return aux


def ppo_loss(
    policy: Policy,
    params: Any,
    batch: Batch,
    cfg: PPOConfig,
    step: Any = None,
    anchor_params: Any = None,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Clipped-surrogate PPO loss over a batch of rollout chunks.

    ``step`` (the optimizer-step counter) enables the critic-only warmup
    window: while ``step < cfg.value_warmup_steps`` the policy surrogate,
    entropy bonus, and MoE aux terms are switched off so only the value
    loss trains (see PPOConfig.value_warmup_steps; the matching gradient
    mask in ``_train_step`` keeps the rest of the network bitwise frozen).

    ``anchor_params`` (with ``cfg.anchor_kl_coef > 0``) adds the anchor-KL
    regularizer: one extra frozen-policy forward over the batch, exact
    conditional KL(π_θ ‖ π_anchor) per frame (PPOConfig.anchor_kl_coef).

    A batch carrying precomputed ``advantages``/``returns`` leaves (the
    one-pass advantage plane, ``train/advantage.py``) skips the in-step
    estimator entirely and shortens the forward to the T transition steps
    — the bootstrap slot existed solely to seed the estimator, so every
    forward AND backward in the epoch drops one timestep.
    """
    obs = batch["obs"]
    T = batch["rewards"].shape[1]
    valid = batch["valid"].astype(jnp.float32)
    n_valid = jnp.maximum(valid.sum(), 1.0)
    precomputed = "advantages" in batch
    if precomputed:
        obs = {k: v[:, :T] for k, v in obs.items()}

    (logits, values, _), mutated = policy.apply(
        params, obs, batch["carry0"], batch["dones"], method="sequence",
        mutable=["losses"],
    )
    moe_aux = _moe_aux_loss(mutated.get("losses", {}), valid)
    # Trailing slot is the bootstrap step: value used, policy outputs unused.
    logits_t = {k: v[:, :T] for k, v in logits.items()}
    obs_t = {k: v[:, :T] for k, v in obs.items()}
    values_t = values[:, :T]

    logp = D.log_prob(logits_t, obs_t, batch["actions"])

    if precomputed:
        # Consume-time advantages (train/advantage.py): upcast from the
        # bf16 staging dtype; both are constants to the optimizer (the
        # pass ran on stop-gradient values), exactly like the in-step
        # estimator's outputs below.
        adv = batch["advantages"].astype(jnp.float32)
        returns = batch["returns"].astype(jnp.float32)
    elif cfg.advantage == "gae":
        adv, returns = gae(
            batch["rewards"],
            jax.lax.stop_gradient(values),
            batch["dones"],
            cfg.gamma,
            cfg.gae_lambda,
        )
    elif cfg.advantage == "vtrace":
        # Importance weights are constants to the optimizer (stop-grad on
        # the target logp): the surrogate's gradient flows through the
        # ratio below, not through the advantage estimate.
        adv, returns = vtrace(
            batch["rewards"],
            jax.lax.stop_gradient(values),
            batch["dones"],
            batch["behavior_logp"],
            jax.lax.stop_gradient(logp),
            cfg.gamma,
            cfg.vtrace_rho_clip,
            cfg.vtrace_c_clip,
        )
    else:
        raise ValueError(
            f"unknown advantage {cfg.advantage!r} (one of {ADVANTAGE_MODES})"
        )
    # Advantage normalization over the (valid) batch. Always centered;
    # rescaled per cfg.adv_norm — the floor keeps near-zero advantage
    # batches from being blown up to unit scale (cfg comment, BASELINE.md
    # 5v5 fine-tune measurement).
    adv_mean = (adv * valid).sum() / n_valid
    adv = adv - adv_mean
    if cfg.adv_norm == "batch":
        adv_var = (jnp.square(adv) * valid).sum() / n_valid
        adv_std = jnp.sqrt(adv_var + 1e-8)
        adv = adv / jnp.maximum(adv_std, cfg.adv_norm_floor)
    elif cfg.adv_norm not in ADV_NORM_MODES:
        raise ValueError(
            f"unknown adv_norm {cfg.adv_norm!r} (one of {ADV_NORM_MODES})"
        )
    ratio = jnp.exp(logp - batch["behavior_logp"])
    clipped = jnp.clip(ratio, 1.0 - cfg.clip_eps, 1.0 + cfg.clip_eps)
    policy_loss = -(jnp.minimum(ratio * adv, clipped * adv) * valid).sum() / n_valid

    value_loss = 0.5 * (jnp.square(values_t - returns) * valid).sum() / n_valid
    ent = (D.entropy(logits_t, obs_t) * valid).sum() / n_valid

    anchor_kl = jnp.zeros(())
    if cfg.anchor_kl_coef > 0 and anchor_params is not None:
        # Frozen-anchor forward (no gradient: anchor_params is not the
        # differentiated argument). Same states, same masks — the exact
        # conditional KL is well-defined per frame.
        def _anchor_kl(_):
            (anchor_logits, _, _), _ = policy.apply(
                anchor_params, obs, batch["carry0"], batch["dones"],
                method="sequence", mutable=["losses"],
            )
            a_t = {k: v[:, :T] for k, v in anchor_logits.items()}
            return (D.kl(logits_t, a_t, obs_t) * valid).sum() / n_valid

        if cfg.value_warmup_steps and step is not None:
            # The warmup window zeroes the whole policy group, so the
            # anchor forward would be dead compute (~a full extra policy
            # pass per step) — skip it until the policy trains.
            anchor_kl = jax.lax.cond(
                step >= cfg.value_warmup_steps,
                _anchor_kl,
                lambda _: jnp.zeros(()),
                None,
            )
        else:
            anchor_kl = _anchor_kl(None)

    if cfg.value_warmup_steps and step is not None:
        policy_on = (step >= cfg.value_warmup_steps).astype(jnp.float32)
    else:
        policy_on = 1.0
    loss = (
        policy_on
        * (
            policy_loss
            - cfg.entropy_coef * ent
            + cfg.moe_aux_coef * moe_aux
            + cfg.anchor_kl_coef * anchor_kl
        )
        + cfg.value_coef * value_loss
    )
    metrics = {
        "loss": loss,
        "moe_aux": moe_aux,
        **(
            {"anchor_kl": anchor_kl}
            if cfg.anchor_kl_coef > 0 and anchor_params is not None
            else {}
        ),
        # Stashed for _train_step's post-update KL measurement (popped
        # there — never reaches the logger). Only when the KL-adaptive lr
        # is on, to avoid carrying a [B, T] array through aux otherwise.
        **({"_logp": logp} if cfg.kl_target > 0 else {}),
        "policy_loss": policy_loss,
        "value_loss": value_loss,
        "entropy": ent,
        "approx_kl": ((batch["behavior_logp"] - logp) * valid).sum() / n_valid,
        "clip_frac": (
            (jnp.abs(ratio - 1.0) > cfg.clip_eps).astype(jnp.float32) * valid
        ).sum() / n_valid,
        "value_mean": (values_t * valid).sum() / n_valid,
        "reward_mean": (batch["rewards"] * valid).sum() / n_valid,
    }
    return loss, metrics


def _train_step(
    policy: Policy,
    cfg: PPOConfig,
    state: TrainState,
    batch: Batch,
    anchor_params: Any = None,
    probe: bool = True,
) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
    grad_fn = jax.value_and_grad(
        lambda p: ppo_loss(
            policy, p, batch, cfg, step=state.step,
            anchor_params=anchor_params,
        ),
        has_aux=True,
    )
    (_, metrics), grads = grad_fn(state.params)
    if cfg.value_warmup_steps:
        # Critic-only warmup: zero every gradient outside the value head so
        # the behavior policy is EXACTLY frozen (value-loss gradients still
        # flow through the shared trunk otherwise). The head itself keeps
        # its full gradient and recalibrates to this config's returns.
        policy_on = (state.step >= cfg.value_warmup_steps).astype(jnp.float32)

        def _mask(path, g):
            in_value_head = any(
                getattr(k, "key", None) == "head_value" for k in path
            )
            # astype(g.dtype): a float32 scalar would silently promote
            # bfloat16 grads (and with them Adam's moments) to float32,
            # retracing the donated step and skewing checkpoint templates.
            return g if in_value_head else g * policy_on.astype(g.dtype)

        grads = jax.tree_util.tree_map_with_path(_mask, grads)
    opt = make_optimizer(cfg)
    opt_state_in = state.opt_state
    if cfg.value_warmup_steps:
        # At the warmup boundary, re-init the optimizer state: the frozen
        # params sat out the warmup with zero moments while Adam's shared
        # step count advanced, so their bias correction is desynchronized —
        # the first post-warmup update would be ~(1-b1)/sqrt(1-b2) ≈ 3×
        # oversized across every policy param at once, exactly the
        # destroy-the-transferred-policy kick this feature exists to
        # prevent. A fresh opt_state makes the first live step behave like
        # a fresh optimizer's first step. (The value head's moments reset
        # too — harmless, it has converged toward this config's returns by
        # then.) jnp.where keeps the opt_state structure unchanged, so
        # checkpoints stay layout-compatible.
        at_boundary = state.step == cfg.value_warmup_steps
        fresh = opt.init(state.params)
        opt_state_in = jax.tree.map(
            lambda f, cur: jnp.where(at_boundary, f, cur),
            fresh, opt_state_in,
        )
    updates, opt_state = opt.update(grads, opt_state_in, state.params)
    params = optax.apply_updates(state.params, updates)
    if cfg.kl_target > 0:
        # KL-adaptive lr: measure the POST-update policy shift on this
        # batch's taken actions (k3 estimator, E_old[r − 1 − log r] ≥ 0)
        # and rescale the lr carried in the optimizer state for the NEXT
        # step. All in-graph: no host sync, fused-mode compatible.
        logp_pre = metrics.pop("_logp")

        def _measure_kl(operand):
            params_new, lp_pre = operand
            T = batch["rewards"].shape[1]
            obs = batch["obs"]
            if "advantages" in batch:
                # one-pass batches train on a T-step forward (the
                # bootstrap slot only fed the estimator) — measure the
                # post-update KL over the same window
                obs = {k: v[:, :T] for k, v in obs.items()}
            (logits_post, _, _), _ = policy.apply(
                params_new, obs, batch["carry0"], batch["dones"],
                method="sequence", mutable=["losses"],
            )
            logits_t = {k: v[:, :T] for k, v in logits_post.items()}
            obs_t = {k: v[:, :T] for k, v in obs.items()}
            logp_post = D.log_prob(logits_t, obs_t, batch["actions"])
            d = logp_post - lp_pre
            valid = batch["valid"].astype(jnp.float32)
            n_valid = jnp.maximum(valid.sum(), 1.0)
            return (((jnp.exp(d) - 1.0) - d) * valid).sum() / n_valid

        if cfg.value_warmup_steps:
            # The frozen-policy window has post-KL ≡ 0 by construction;
            # skip the measurement forward (~a full policy pass) there.
            post_kl = jax.lax.cond(
                state.step >= cfg.value_warmup_steps,
                _measure_kl,
                lambda _: jnp.zeros(()),
                (params, logp_pre),
            )
        else:
            post_kl = _measure_kl((params, logp_pre))

        inj = opt_state[1]
        lr = inj.hyperparams["learning_rate"]
        t = cfg.kl_target
        factor = jnp.where(
            post_kl > 2.0 * t,
            cfg.kl_lr_down,
            jnp.where(post_kl < 0.5 * t, cfg.kl_lr_up, 1.0),
        )
        if cfg.value_warmup_steps:
            # The frozen-policy window measures KL ≡ 0; don't let the
            # controller ratchet the lr up against a flat signal (the
            # boundary reset would restore it anyway, but the value head
            # trains through the warmup at whatever lr this leaves).
            factor = jnp.where(
                state.step < cfg.value_warmup_steps, 1.0, factor
            )
        new_lr = jnp.clip(
            lr * factor,
            cfg.learning_rate * cfg.kl_lr_min_scale,
            cfg.learning_rate * cfg.kl_lr_max_scale,
        )
        hp = dict(inj.hyperparams)
        hp["learning_rate"] = new_lr
        opt_state = (opt_state[0], inj._replace(hyperparams=hp))
        metrics["post_kl"] = post_kl
        metrics["lr"] = lr
    metrics["grad_norm"] = optax.global_norm(grads)
    if probe:
        # Training-health probe (ISSUE 6, train/health.py): one scalar AND
        # over the two values every step already computes. loss covers
        # NaN/Inf anywhere in the forward/returns path (non-finite params
        # from a previous step included); the PRE-clip gradient global
        # norm covers a backward pass that NaN'd after a finite loss.
        # Scanned multi-update programs AND-fold this flag
        # (fold_scan_metrics), so one poisoned update taints the whole
        # program's verdict.
        metrics["health_ok"] = (
            jnp.isfinite(metrics["loss"]) & jnp.isfinite(metrics["grad_norm"])
        ).astype(jnp.float32)
    new_state = dataclasses.replace(
        state,
        step=state.step + 1,
        version=state.version + 1,
        params=params,
        opt_state=opt_state,
    )
    return new_state, metrics


def fold_scan_metrics(metric_seq: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    """Reduce a ``lax.scan``'s stacked per-update metrics to one report:
    the LAST update's values (the state reflects it — the historical
    contract of every scanned train path), except ``health_ok``, which
    AND-folds (min) across the scan — a single poisoned update inside a
    fused multi-update program must taint the program's verdict even when
    later updates happen to report finite values again."""
    out = jax.tree.map(lambda m: m[-1], metric_seq)
    if "health_ok" in metric_seq:
        out["health_ok"] = metric_seq["health_ok"].min()
    return out


def train_state_sharding(policy: Policy, config: RunConfig, mesh: Mesh):
    """The TrainState sharding tree (TP partition rules applied to params
    and the Adam mirrors, scalars replicated) — the single source of truth
    shared by ``make_train_step`` and the fused step."""
    from dotaclient_tpu.models import init_params
    from dotaclient_tpu.parallel.sharding import state_shardings

    state_shape = jax.eval_shape(
        lambda: init_train_state(
            init_params(policy, jax.random.PRNGKey(0)), config.ppo
        )
    )
    return state_shardings(state_shape, mesh, config.mesh)


def make_train_step(
    policy: Policy,
    config: RunConfig,
    mesh: Mesh,
    debug_checkify: bool = False,
    anchor_params: Any = None,
):
    """Compile the train step against ``mesh``.

    Batch arrays are sharded over the data axis (leading/batch dim); the
    train state follows the tensor-parallel rules of
    ``parallel.sharding.state_shardings`` — replicated when
    ``model_parallel == 1``, last-axis-sharded kernels over the model axis
    otherwise. XLA inserts the gradient all-reduce (data axis) and the TP
    collectives (model axis) over ICI. The train state is donated —
    params/opt-state update in place in HBM.

    ``anchor_params`` (required iff ``ppo.anchor_kl_coef > 0``) is
    closure-captured: the anchor is fixed for the compiled step's lifetime,
    so it rides along as a jit constant (replicated; at policy scale the
    memory is noise).
    """
    if (config.ppo.anchor_kl_coef > 0) != (anchor_params is not None):
        raise ValueError(
            "anchor_params must be passed exactly when ppo.anchor_kl_coef > 0"
        )
    from dotaclient_tpu.parallel.mesh import data_sharding as _data_sharding

    # (dcn, data) when the mesh is multi-slice, else just (data,): the
    # gradient all-reduce then lowers hierarchically — ICI inside each
    # slice, one slice-level all-reduce over DCN
    data_sharding = _data_sharding(mesh, config.mesh)
    repl = NamedSharding(mesh, P())
    # a bare sharding broadcasts over the whole batch pytree, so the
    # compiled contract is structure-agnostic: a batch may carry the
    # optional precomputed-advantage leaves (train/advantage.py) or not
    batch_shardings = data_sharding
    state_sharding = train_state_sharding(policy, config, mesh)
    metrics_repl = repl
    if debug_checkify:
        # Debug numerics mode (SURVEY.md §5.2): checkify float checks guard
        # every op and RAISE on the first NaN/Inf instead of letting it
        # propagate into the params. No donation, no sharding constraints —
        # this is the hunt-the-NaN path, not the production path.
        from jax.experimental import checkify

        inner = checkify.checkify(
            lambda state, batch: _train_step(
                policy, config.ppo, state, batch, anchor_params=anchor_params,
                probe=config.health.enabled,
            ),
            errors=checkify.float_checks,
        )
        jitted = jax.jit(inner)

        def checked_step(state, batch):
            err, out = jitted(state, batch)
            checkify.check_error(err)
            return out

        return checked_step
    step_fn = jax.jit(
        lambda state, batch: _train_step(
            policy, config.ppo, state, batch, anchor_params=anchor_params,
            probe=config.health.enabled,
        ),
        in_shardings=(state_sharding, batch_shardings),
        out_shardings=(state_sharding, metrics_repl),
        donate_argnums=(0,),
    )
    return step_fn


def make_epoch_step(
    policy: Policy,
    config: RunConfig,
    mesh: Mesh,
    anchor_params: Any = None,
):
    """Compile the fused epoch step: ``(state, batch, perms) → (state',
    last_metrics)`` — all ``epochs_per_batch × minibatches`` optimizer
    updates over one consumed batch inside ONE donated XLA program.

    The staged loop in ``Learner._optimize`` pays a jitted-gather dispatch
    plus a train-step dispatch per minibatch (2·E·M host→device round trips
    per batch); here a ``lax.scan`` walks minibatch slices of the epoch
    permutations in-program, so one batch costs one dispatch regardless of
    the epoch/minibatch configuration (the OPPO/Podracer observation —
    PAPERS.md — that PPO's inner loop belongs inside the compiled program).

    ``perms`` is ``[E, B] int32`` — one shuffled row order per epoch, drawn
    host-side from the SAME seeded stream as the staged fallback. Taking
    the permutations as an input (rather than folding a PRNG key in-graph)
    is deliberate: on identical seeds the two paths run the same updates
    on the same data (agreement to float-ulp XLA-fusion rounding — tested)
    and the checkpointed ``mb_draws`` counter reconstructs the stream
    exactly on resume, for either path. The array is E·B int32 — its
    transfer rides the dispatch and is noise next to the batch itself.
    With ``minibatches == 1`` the scan trains on the whole batch per epoch
    and ``perms`` is ignored (matching the staged path, which never
    shuffles an unsplit batch).

    The train state is donated and updates in place in HBM; each minibatch
    slice is re-constrained to the batch sharding so the update runs
    exactly as it would on a staged minibatch. Metrics are the last
    update's (device-resident), like the staged loop's.
    """
    if (config.ppo.anchor_kl_coef > 0) != (anchor_params is not None):
        raise ValueError(
            "anchor_params must be passed exactly when ppo.anchor_kl_coef > 0"
        )
    from dotaclient_tpu.parallel.mesh import data_sharding as _data_sharding

    cfg = config.ppo
    E = cfg.epochs_per_batch
    M = max(1, cfg.minibatches)
    B = cfg.batch_rollouts
    if B % M:
        raise ValueError(
            f"batch_rollouts {B} not divisible by minibatches {M}"
        )
    mb = B // M
    ds = _data_sharding(mesh, config.mesh)
    repl = NamedSharding(mesh, P())
    # bare sharding = structure-agnostic contract (see make_train_step):
    # one-pass batches add advantages/returns leaves, sliced per
    # minibatch by the same in-program jnp.take as every other leaf
    batch_shardings = ds
    state_sharding = train_state_sharding(policy, config, mesh)

    def epoch_step(state, batch, perms):
        def body(st, idx_mb):
            if M == 1:
                sub = batch
            else:
                sub = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(
                        jnp.take(x, idx_mb, axis=0), ds
                    ),
                    batch,
                )
            return _train_step(
                policy, cfg, st, sub, anchor_params=anchor_params,
                probe=config.health.enabled,
            )

        # [E, B] → [E·M, mb]: scan one optimizer step per slice; epoch e's
        # minibatches are rows e·M..(e+1)·M of the reshape, exactly the
        # slices the staged loop gathers. health_ok AND-folds across the
        # scan (fold_scan_metrics) so one poisoned update taints the batch.
        idx = perms.reshape(E * M, mb)
        state, metric_seq = jax.lax.scan(body, state, idx)
        return state, fold_scan_metrics(metric_seq)

    return jax.jit(
        epoch_step,
        in_shardings=(state_sharding, batch_shardings, repl),
        out_shardings=(state_sharding, repl),
        donate_argnums=(0,),
    )


def example_batch(config: RunConfig, batch: int, as_struct: bool = False) -> Batch:
    """A correctly-shaped zero batch (compile warm-up, tests, AOT)."""
    from dotaclient_tpu.models.policy import dummy_obs_batch, make_policy

    T = config.ppo.rollout_len
    obs = dummy_obs_batch(batch, config.obs, config.actions, time=T + 1)
    # carry0 layout comes from the policy's own core (LSTM (h, c) or a
    # transformer KV cache); the wire/buffer representation is always f32
    carry0 = jax.tree.map(
        lambda t: jnp.zeros(t.shape, jnp.float32),
        make_policy(config.model, config.obs, config.actions).initial_state(batch),
    )
    out: Batch = {
        "obs": obs,
        "actions": {
            h: jnp.zeros((batch, T), jnp.int32)
            for h in config.actions.head_sizes
        },
        "behavior_logp": jnp.zeros((batch, T), jnp.float32),
        "rewards": jnp.zeros((batch, T), jnp.float32),
        "dones": jnp.zeros((batch, T), jnp.float32),
        "valid": jnp.ones((batch, T), jnp.float32),
        "carry0": carry0,
    }
    if as_struct:
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), out
        )
    return out
