"""Training health monitor: divergence verdicts off the hot path (ISSUE 6).

A single NaN gradient silently poisons the params; every subsequent weight
publish then fans the poison out to the whole actor fleet, and the rolling
checkpoint retention eventually overwrites the last healthy save — the
failure mode multi-day self-play runs hit in practice (PAPER.md §0). This
module is the *detect* stage of the guardian's detect → contain → recover
loop:

* **Probe (in-graph, train/ppo.py):** every train-step variant computes a
  ``health_ok`` flag — ``isfinite(loss) & isfinite(grad_norm)`` — as one
  scalar AND inside the compiled program; scanned multi-update programs
  (the fused epoch step, the fused rollout+update program, dispatch
  batching) AND-fold it across their updates. Cost: two scalar ops per
  program — the bench ``health`` stage pins the overhead ≤ 2%.
* **Submit (train thread, zero sync):** :meth:`HealthMonitor.submit`
  appends the step's tiny verdict scalars (device arrays — program
  outputs, never donated) to a host-side pending deque. No fetch, no
  lock contention beyond one mutex append.
* **Fold (snapshot thread, one batched fetch per boundary):** the learner
  flushes the pending deque through the snapshot engine's never-coalesced
  stats backlog at boundary cadence; the engine fetches the whole batch in
  ONE transfer and calls :meth:`fold_batch`. Because the engine processes
  stats jobs BEFORE the same cycle's publish/checkpoint jobs
  (train/snapshot.py ordering contract), every verdict for steps ≤ V has
  landed by the time version V's publish job runs — the publish gate is
  sound without the train thread ever blocking on a verdict. In
  ``--sync-snapshots`` mode the learner folds the already-fetched boundary
  scalars via :meth:`fold_host` instead — zero extra transfers, verdicts
  at log cadence.

The verdict LATCHES: once unhealthy, the monitor stays unhealthy (and the
publish/checkpoint gates stay closed) until the learner's rollback clears
it. ``clear()`` bumps a generation counter so verdict entries submitted
before the rollback — steps of the abandoned timeline — are discarded
instead of re-latching the fresh state.

Telemetry (eager-created so ``check_telemetry_schema.py --require-health``
is deterministic): ``health/nonfinite_steps_total``,
``health/rollbacks_total``, ``health/last_good_step``,
``health/publish_blocked_total``, ``health/checkpoints_blocked_total``,
``health/ema_breaches_total``, and (owned by the buffer but pinned here for
bufferless fused runs) ``buffer/stale_rejected_total``.
"""

from __future__ import annotations

import logging
import math
import threading
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax

from dotaclient_tpu.config import HealthConfig
from dotaclient_tpu.utils import telemetry

logger = logging.getLogger(__name__)

# Verdict scalars the probe ships per optimizer batch. grad_norm is the
# PRE-clip global norm (train/ppo.py) — the explosion band must see the
# raw magnitude, not the clipped one.
VERDICT_KEYS = ("loss", "grad_norm", "health_ok")

# Pending-verdict cap: between boundaries the deque holds one entry of
# three device scalars per consumed batch. A run configured with no
# boundaries in range (log_every=inf benches) must not grow it unboundedly;
# dropping the OLDEST entries is safe because non-finite params persist —
# every later verdict re-detects them (a transient EMA breach can be lost,
# which only delays band detection by one window).
_PENDING_CAP = 2048


class HealthEvent(NamedTuple):
    step: int
    version: int
    reason: str     # "nonfinite" | "explosion"
    value: float    # the offending scalar (loss or grad_norm)


class HealthMonitor:
    """Latching divergence detector fed by the in-graph probe."""

    def __init__(
        self,
        cfg: HealthConfig,
        registry: Optional[telemetry.Registry] = None,
    ) -> None:
        self.cfg = cfg
        self._tel = registry if registry is not None else telemetry.get_registry()
        self._lock = threading.Lock()
        self._pending: deque = deque(maxlen=_PENDING_CAP)
        self._gen = 0
        self._ema_grad: Optional[float] = None
        self._healthy_folds = 0
        self._unhealthy: Optional[HealthEvent] = None
        self._unrecoverable_warned = False
        # eager-create the full HEALTH_KEYS tier (+ the gate counters): a
        # clean run reports zeros — check_telemetry_schema.py
        # --require-health pins presence, not events
        self._tel.counter("health/nonfinite_steps_total")
        self._tel.counter("health/rollbacks_total")
        self._tel.counter("health/ema_breaches_total")
        self._tel.counter("health/publish_blocked_total")
        self._tel.counter("health/checkpoints_blocked_total")
        self._tel.gauge("health/last_good_step")
        # owned by TrajectoryBuffer, but fused-mode runs have no buffer —
        # pin it here so the HEALTH_KEYS tier validates on any health-
        # enabled learner run
        self._tel.counter("buffer/stale_rejected_total")

    # -- train thread (no device traffic) -----------------------------------

    def submit(self, step: int, version: int, metrics: Any) -> None:
        """Queue one optimizer batch's verdict scalars (device arrays —
        program outputs; holding them is donation-safe). No fetch."""
        tree = {k: metrics[k] for k in VERDICT_KEYS if k in metrics}
        with self._lock:
            self._pending.append((self._gen, step, version, tree))

    def take_pending(self) -> List[Tuple[int, int, int, Any]]:
        """Drain the pending entries for one batched boundary fetch."""
        with self._lock:
            out = list(self._pending)
            self._pending.clear()
        return out

    @property
    def unhealthy(self) -> Optional[HealthEvent]:
        # lint-ok: thread-ownership(lock-free latch read: the event only
        # transitions None->set under _lock and is immutable until clear();
        # a stale None merely delays gate closure to the next fold)
        return self._unhealthy

    def note_unrecoverable(self) -> bool:
        """First-call latch for the no-checkpoint degrade warning (a run
        without a checkpoint dir can contain — publishes stay blocked —
        but never recover). True exactly once."""
        if self._unrecoverable_warned:
            return False
        self._unrecoverable_warned = True
        return True

    def clear(self) -> None:
        """Rollback epilogue: unlatch and discard verdicts of the
        abandoned timeline (generation bump — folds of entries submitted
        before this call become no-ops). The EMA restarts its warmup: the
        restored run's gradient scale is re-learned, not inherited from
        the diverged one."""
        with self._lock:
            self._gen += 1
            self._pending.clear()
            self._unhealthy = None
            self._ema_grad = None
            self._healthy_folds = 0

    # -- fold side (snapshot thread, or train thread in sync mode) ----------

    def fold_batch(self, host_entries: List[Tuple[int, int, int, Any]]) -> None:
        """Fold one fetched batch of (gen, step, version, scalars) entries
        in submission order — the snapshot engine's stats-job entry point
        (the engine already did the one batched ``jax.device_get``)."""
        for gen, step, version, tree in host_entries:
            self._fold_one(gen, step, version, tree)

    def fold_host(self, step: int, version: int, scalars: Dict[str, Any]) -> None:
        """Fold already-fetched host scalars (the --sync-snapshots path:
        the boundary metrics fetch carries the verdict keys — no second
        transfer). Always folds with the CURRENT generation (``gen=None``
        below) — reading ``self._gen`` here would race ``clear()``, and
        sync-mode callers are by definition post-rollback callers of the
        live timeline (train/learner.py clears ``_last_verdict_m`` at
        rollback so no stale verdict can reach this path)."""
        if all(k in scalars for k in ("loss", "grad_norm")):
            self._fold_one(None, step, version, scalars)

    def _fold_one(
        self, gen: Optional[int], step: int, version: int, tree: Any
    ) -> None:
        """``gen=None`` means "the current generation" (the fold_host
        path); a concrete gen is compared against the latest clear()."""
        with self._lock:
            if (
                gen is not None and gen != self._gen
            ) or self._unhealthy is not None:
                return  # abandoned timeline, or already latched
            loss = float(tree["loss"])   # host-sync-ok: fetched host scalars
            gn = float(tree["grad_norm"])   # host-sync-ok: fetched host scalars
            ok = float(tree.get("health_ok", 1.0)) >= 0.5   # host-sync-ok: fetched host scalars
            if not ok or not math.isfinite(loss) or not math.isfinite(gn):
                self._tel.counter("health/nonfinite_steps_total").inc()
                self._unhealthy = HealthEvent(
                    step, version, "nonfinite",
                    gn if not math.isfinite(gn) else loss,
                )
            elif (
                self._ema_grad is not None
                and self._healthy_folds >= self.cfg.warmup_steps
                and gn > self.cfg.explosion_band * max(self._ema_grad, 1e-8)
            ):
                self._tel.counter("health/ema_breaches_total").inc()
                self._unhealthy = HealthEvent(step, version, "explosion", gn)
            else:
                a = self.cfg.ema_alpha
                self._ema_grad = (
                    gn if self._ema_grad is None
                    else (1.0 - a) * self._ema_grad + a * gn
                )
                self._healthy_folds += 1
                return
        logger.warning(
            "health: divergence latched at step %d (version %d): %s "
            "(value %r) — weight publishes and periodic checkpoints are "
            "blocked until rollback",
            # lint-ok: thread-ownership(only reached by the thread that just
            # latched the event; latched values are immutable until clear)
            step, version, self._unhealthy.reason, self._unhealthy.value,
        )
