"""Consume-time advantage plane: one value forward + GAE per consumed batch.

With ``epochs_per_batch × minibatches > 1`` the historical train step
re-ran the value forward and the GAE scan inside every optimizer step —
E×M redundant passes over work that is FIXED for the batch (the estimator
consumes stop-gradient values, so nothing it produces depends on the
update being taken). HEPPO-GAE (PAPERS.md) makes advantage estimation its
own pipeline stage; this module is that stage: :func:`make_advantage_pass`
compiles a jitted, mesh-sharded pass that runs the sequence forward + GAE
ONCE over a just-gathered batch and hands back ``(advantages, returns)``
at the narrow staging dtype (``ppo.advantage_dtype``, bf16 by default —
the quantized-plane discipline of ISSUE 7 extended to the advantage
leaves; the estimator's f32-pinned inputs are untouched, only the derived
outputs narrow).

The learner attaches the pair to the batch dict at the buffer gather
boundary (``train/learner.py`` ``_next_batch``/``_prefetch_next``);
``train/ppo.ppo_loss`` sees the ``advantages`` leaf, skips its in-step
estimator, and shortens the loss forward to the T transition steps (the
bootstrap slot existed solely to seed the estimator). With
``learner.overlap_advantage`` (the default) the pass for batch N+1 is
dispatch-only work enqueued behind batch N's in-flight donated epoch step
— OPPO's phase overlap (PAPERS.md), extending the prefetch lane from
"stage bytes" to "stage compute".

Scope: GAE only. V-trace's importance ratios need the CURRENT policy's
logp, which changes every optimizer step — precomputing would freeze the
off-policy correction it exists to provide — so ``advantage="vtrace"``
keeps the in-step recompute, as does fused mode (its rollout+update
program is strictly on-policy and already amortizes per chunk).

Discipline: the pass is dispatch-only (no host↔device sync — guarded by
``lint/host_sync.py``, which scans this module) and donates nothing (the
params are the live train state's and the batch is consumed by the very
next epoch step).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dotaclient_tpu.config import ADVANTAGE_STORE_DTYPES, RunConfig
from dotaclient_tpu.models.policy import Policy
from dotaclient_tpu.train.gae import gae


def one_pass_enabled(config: RunConfig) -> bool:
    """True iff the consume-time advantage plane applies to this config:
    ``ppo.one_pass_advantage`` is on, the estimator is GAE (see module
    docstring for why vtrace keeps the in-step recompute), AND the batch
    is consumed more than once (``steps_per_batch > 1``). At E×M = 1 the
    in-step estimator already runs exactly once per batch — a separate
    pass would ADD a redundant value forward instead of removing E×M−1
    of them, measurably slowing the default config."""
    return (
        config.ppo.one_pass_advantage
        and config.ppo.advantage == "gae"
        and config.ppo.steps_per_batch > 1
    )


def store_dtype(config: RunConfig):
    """Staging dtype for the precomputed advantages/returns."""
    name = config.ppo.advantage_dtype
    if name not in ADVANTAGE_STORE_DTYPES:
        raise ValueError(
            f"unknown advantage_dtype {name!r} "
            f"(one of {ADVANTAGE_STORE_DTYPES})"
        )
    return jnp.bfloat16 if name == "bfloat16" else jnp.float32


def advantages_and_returns(
    policy: Policy,
    params: Any,
    batch: Any,
    cfg: Any,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The in-step recompute's exact ops, as a standalone stage: sequence
    forward over the full ``[B, T+1]`` chunk (the trailing slot is the
    bootstrap value), then the GAE reverse scan. The logits heads the
    shared ``sequence`` apply also produces are unused here and XLA
    dead-code-eliminates them — the pass compiles to the value trunk +
    scan. Bitwise agreement with the recompute branch of
    ``train/ppo.ppo_loss`` is pinned by tests/test_advantage.py."""
    (_, values, _), _ = policy.apply(
        params, batch["obs"], batch["carry0"], batch["dones"],
        method="sequence", mutable=["losses"],
    )
    return gae(
        batch["rewards"],
        jax.lax.stop_gradient(values),
        batch["dones"],
        cfg.gamma,
        cfg.gae_lambda,
    )


def make_advantage_pass(policy: Policy, config: RunConfig, mesh: Mesh):
    """Compile the advantage pass against ``mesh``: ``(params, batch) →
    (advantages, returns)`` at the staging dtype, batch-sharded over the
    data axis like every other ``[B, ...]`` tensor in the pipeline.

    No donation: the params are the live train state's (the next epoch
    step donates them) and the batch is consumed by that same step. No
    in_shardings pin: both inputs arrive committed (the state to its
    state_shardings, the batch from the buffer's sharded gather)."""
    if config.ppo.advantage != "gae":
        raise ValueError(
            "the one-pass advantage plane precomputes GAE only — "
            "advantage='vtrace' needs the current policy's logp per "
            "optimizer step and keeps the in-step recompute"
        )
    from dotaclient_tpu.parallel.mesh import data_sharding

    ds = data_sharding(mesh, config.mesh)
    dt = store_dtype(config)
    cfg = config.ppo

    def _pass(params, batch):
        adv, ret = advantages_and_returns(policy, params, batch, cfg)
        return adv.astype(dt), ret.astype(dt)

    return jax.jit(_pass, out_shardings=(ds, ds))
