"""Fully-fused synchronous iteration: rollout + PPO update, ONE XLA program.

The reference's loop crosses process and device boundaries every iteration
(actor → RMQ → learner → GPU, SURVEY.md §3.1–3.2). The device actor already
collapsed the actor side into a single program; this module goes the rest of
the way for the synchronous on-policy regime: the whole iteration —
T-step rollout scan (featurize, policy, sample, env step, reward, episode
reset), then the PPO update on the chunk it just produced — is one jitted,
donated call. One dispatch per optimizer step, zero host round-trips,
nothing staged through the trajectory buffer.

This is the Anakin architecture (PAPERS.md [P:7]) taken to its endpoint, and
it matters here concretely: the sandbox's tunneled TPU charges ~100 ms per
host↔device sync, so the buffered device loop (collect + scatter + gather +
train ≈ 4–5 dispatches) is dispatch-dominated at small batch.

Trade-offs vs the buffered path (why both exist):
  * strictly on-policy — every chunk is trained on exactly once, by the
    params that generated it (behavior_logp ratio ≡ 1 at epoch 1); the
    staleness/version machinery has nothing to do;
  * the train batch IS the lane set (``n_lanes`` rollouts of length T) —
    ``ppo.batch_rollouts`` does not apply;
  * ``epochs_per_batch`` > 1 runs as a ``lax.scan`` of update steps over
    the same chunk INSIDE the program (epoch 2+ are the standard PPO
    re-uses, ratio clipped against the rollout's behavior_logp);
    ``minibatches`` > 1 shuffles IN-PROGRAM: each epoch draws a fresh
    lane permutation (keyed on ``config.seed`` and the optimizer step, so
    it is deterministic and needs no host shuffle point or carried RNG),
    splits the chunk into M equal lane groups, and scans an optimizer
    step per group — the standard PPO minibatch pass, fully fused;
  * ``RunConfig.steps_per_dispatch`` > 1 scans K whole rollout+update
    iterations per dispatch, amortizing the host↔device round trip K× at
    the cost of K-step granularity for everything host-side (opponent
    draws, logging, best-model capture);
  * no cross-process experience — single-host self-play only.

The learner exposes it as ``actor="fused"``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.models.policy import Policy
from dotaclient_tpu.parallel.mesh import data_sharding, replicated
from dotaclient_tpu.train.ppo import (
    _train_step,
    fold_scan_metrics,
    train_state_sharding,
)


def make_fused_step(
    policy: Policy, config: RunConfig, mesh, actor, anchor_params=None
):
    """Compile (state, actor_state, opp_params) → (state', actor_state',
    metrics, stats) against ``mesh``.

    The train state keeps the TP/DP shardings of ``make_train_step``; the
    chunk produced mid-program is constrained to the batch sharding so the
    PPO update runs exactly as it would on a buffered batch; the actor's
    sim/carry state is replicated (its arrays are small and the rollout
    math is elementwise over lanes). ``opp_params`` must always be passed —
    self-play callers pass the live params (the jitted program has one
    signature for both modes).
    """
    if (config.ppo.anchor_kl_coef > 0) != (anchor_params is not None):
        raise ValueError(
            "anchor_params must be passed exactly when ppo.anchor_kl_coef > 0"
        )
    ds = data_sharding(mesh, config.mesh)
    repl = replicated(mesh)
    st_sh = train_state_sharding(policy, config, mesh)

    n_epochs = config.ppo.epochs_per_batch
    n_mb = max(1, config.ppo.minibatches)
    n_iters = config.steps_per_dispatch
    L = actor.n_lanes
    if L % n_mb:
        raise ValueError(
            f"fused minibatching splits the {L}-lane chunk along lanes: "
            f"n_lanes must be divisible by minibatches ({n_mb})"
        )

    probe = config.health.enabled

    def update_on_chunk(state, chunk):
        if n_epochs == 1 and n_mb == 1:
            return _train_step(
                policy, config.ppo, state, chunk,
                anchor_params=anchor_params, probe=probe,
            )

        def epoch(st, _):
            if n_mb == 1:
                return _train_step(
                    policy, config.ppo, st, chunk,
                    anchor_params=anchor_params, probe=probe,
                )
            # In-program shuffle: the permutation is keyed on the run seed
            # and the optimizer step at epoch entry (strictly increasing,
            # so every epoch of every iteration draws fresh) — no host
            # shuffle point, no extra carried RNG state.
            key = jax.random.fold_in(
                jax.random.PRNGKey(config.seed), st.step
            )
            perm = jax.random.permutation(key, L)
            mbs = jax.tree.map(
                lambda x: jnp.take(x, perm, axis=0).reshape(
                    (n_mb, L // n_mb) + x.shape[1:]
                ),
                chunk,
            )

            def mb_step(s, mb):
                mb = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, ds), mb
                )
                return _train_step(
                    policy, config.ppo, s, mb,
                    anchor_params=anchor_params, probe=probe,
                )

            st, mseq = jax.lax.scan(mb_step, st, mbs)
            return st, fold_scan_metrics(mseq)

        new_state, metric_seq = jax.lax.scan(
            epoch, state, None, length=n_epochs
        )
        # report the final update (the state reflects it), like the
        # buffered loop's last logged step of a multi-epoch pass;
        # health_ok AND-folds across every scan level (fold_scan_metrics)
        return new_state, fold_scan_metrics(metric_seq)

    def one_iter(state, actor_state, opp_params):
        actor_state, chunk, stats = actor._rollout_impl(
            state.params, actor_state, opp_params
        )
        chunk = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, ds), chunk
        )
        new_state, metrics = update_on_chunk(state, chunk)
        return new_state, actor_state, metrics, stats

    if n_iters == 1:
        fused = one_iter
    else:
        # Dispatch batching (RunConfig.steps_per_dispatch): scan K whole
        # rollout+update iterations, so ONE host dispatch advances K
        # optimizer steps. The opponent is fixed for the dispatch (the
        # learner rejects league configs whose opponent_hold is shorter
        # than the dispatch stride); per-chunk
        # episode stats are additive scalars, summed over the scan so
        # league attribution sees the dispatch's true totals.
        def fused(state, actor_state, opp_params):
            def it(c, _):
                st, ast = c
                st, ast, metrics, stats = one_iter(st, ast, opp_params)
                return (st, ast), (metrics, stats)

            (state, actor_state), (metric_seq, stat_seq) = jax.lax.scan(
                it, (state, actor_state), None, length=n_iters
            )
            metrics = fold_scan_metrics(metric_seq)
            stats = jax.tree.map(lambda s: s.sum(axis=0), stat_seq)
            return state, actor_state, metrics, stats

    # No donation: in self-play the caller passes state.params AS
    # opp_params (one signature for both modes), so donating the state
    # would alias a donated buffer with a live input; the actor state's
    # zero carries can likewise alias a cached constant on the first call.
    # The state is LSTM(128)-scale — the copy cost is noise next to the
    # dispatch savings this path exists for.
    # opp_params shards like the live params (st_sh's params subtree): under
    # TP, pinning it replicated would all-gather the full param set every
    # step — on the one-dispatch hot path this module exists to shorten.
    return jax.jit(
        fused,
        in_shardings=(st_sh, repl, st_sh.params),
        out_shardings=(st_sh, repl, repl, repl),
    )
