"""Fully-fused synchronous iteration: rollout + PPO update, ONE XLA program.

The reference's loop crosses process and device boundaries every iteration
(actor → RMQ → learner → GPU, SURVEY.md §3.1–3.2). The device actor already
collapsed the actor side into a single program; this module goes the rest of
the way for the synchronous on-policy regime: the whole iteration —
T-step rollout scan (featurize, policy, sample, env step, reward, episode
reset), then the PPO update on the chunk it just produced — is one jitted,
donated call. One dispatch per optimizer step, zero host round-trips,
nothing staged through the trajectory buffer.

This is the Anakin architecture (PAPERS.md [P:7]) taken to its endpoint, and
it matters here concretely: the sandbox's tunneled TPU charges ~100 ms per
host↔device sync, so the buffered device loop (collect + scatter + gather +
train ≈ 4–5 dispatches) is dispatch-dominated at small batch.

Trade-offs vs the buffered path (why both exist):
  * strictly on-policy — every chunk is trained on exactly once, by the
    params that generated it (behavior_logp ratio ≡ 1 at epoch 1); the
    staleness/version machinery has nothing to do;
  * the train batch IS the lane set (``n_lanes`` rollouts of length T) —
    ``ppo.batch_rollouts`` does not apply;
  * ``epochs_per_batch`` > 1 runs as a ``lax.scan`` of update steps over
    the same chunk INSIDE the program (epoch 2+ are the standard PPO
    re-uses, ratio clipped against the rollout's behavior_logp);
    ``minibatches`` > 1 shuffles IN-PROGRAM and SHARD-LOCALLY
    (``lane_minibatches``): each epoch every mesh shard draws a fresh
    permutation of its own lanes (keyed on ``config.seed`` and the
    optimizer step, so it is deterministic and needs no host shuffle
    point or carried RNG) and contributes its m-th local group to
    minibatch m — the standard PPO minibatch pass, fully fused, with no
    cross-device gather;
  * ``RunConfig.steps_per_dispatch`` > 1 scans K whole rollout+update
    iterations per dispatch, amortizing the host↔device round trip K× at
    the cost of K-step granularity for everything host-side (opponent
    draws, logging, best-model capture);
  * no cross-process experience — single-host self-play only.

The learner exposes it as ``actor="fused"``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from dotaclient_tpu.actor.device_rollout import actor_state_sharding
from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.models.policy import Policy
from dotaclient_tpu.parallel.mesh import (
    batch_shard_count,
    data_sharding,
    replicated,
)
from dotaclient_tpu.train.ppo import (
    _train_step,
    fold_scan_metrics,
    train_state_sharding,
)


def lane_minibatches(chunk, step, seed: int, n_lanes: int, n_shards: int,
                     n_mb: int):
    """Shard-LOCAL in-program minibatch shuffle: permute lanes within each
    mesh shard, never across — the gather stays on the local axis, so
    minibatching adds NO collective to the hot loop (the only one left per
    update is ``_train_step``'s gradient psum).

    Each shard draws its own permutation of its ``n_lanes // n_shards``
    local lanes (keyed on the run seed and the optimizer step at epoch
    entry — strictly increasing, so every epoch of every iteration draws
    fresh with no host shuffle point or carried RNG). Minibatch ``m`` is
    the concatenation of every shard's ``m``-th local group, so each
    minibatch is itself an evenly lane-sharded batch and the downstream
    sharding constraint is a no-op assertion. The permutation stream is
    shard-count DEPENDENT by design (the blocks are the shards); cross-
    shard-count parity probes run with ``minibatches=1``, where the math
    is shard-count invariant.

    Returns the chunk reshaped to ``[n_mb, n_lanes // n_mb, ...]`` leaves.
    """
    S, Ls = n_shards, n_lanes // n_shards
    key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
    perm = jax.vmap(
        lambda k: jax.random.permutation(k, Ls)
    )(jax.random.split(key, S))                     # [S, Ls] per-shard perms

    def shuffle(x):
        xs = x.reshape((S, Ls) + x.shape[1:])
        idx = perm.reshape((S, Ls) + (1,) * (x.ndim - 1))
        xs = jnp.take_along_axis(xs, idx, axis=1)   # local-axis gather
        xs = xs.reshape((S, n_mb, Ls // n_mb) + x.shape[1:])
        # [S, M, Ls/M] → [M, S·Ls/M]: minibatch m owns every shard's m-th
        # group; the sharded axis stays outermost of the merged dim, so the
        # result is born lane-sharded
        return jnp.moveaxis(xs, 0, 1).reshape(
            (n_mb, S * (Ls // n_mb)) + x.shape[1:]
        )

    return jax.tree.map(shuffle, chunk)


def make_fused_step(
    policy: Policy, config: RunConfig, mesh, actor, anchor_params=None
):
    """Compile (state, actor_state, opp_params) → (state', actor_state',
    metrics, stats) against ``mesh``.

    The train state keeps the TP/DP shardings of ``make_train_step``; the
    actor state is pinned LANE-SHARDED (``actor_state_sharding``): games —
    and the game-major lanes they own — partition over the (dcn×)data axes,
    so sim stepping, featurize, the policy forward, sampling, and the
    in-graph outcome partials all compute on local lanes only and the chunk
    is BORN data-sharded; the mid-program sharding constraints are no-op
    assertions, not reshards. ``opp_params`` must always be passed —
    self-play callers pass the live params (the jitted program has one
    signature for both modes).
    """
    if (config.ppo.anchor_kl_coef > 0) != (anchor_params is not None):
        raise ValueError(
            "anchor_params must be passed exactly when ppo.anchor_kl_coef > 0"
        )
    ds = data_sharding(mesh, config.mesh)
    repl = replicated(mesh)
    st_sh = train_state_sharding(policy, config, mesh)
    st_act_sh = actor_state_sharding(actor.state, mesh, config.mesh)

    n_epochs = config.ppo.epochs_per_batch
    n_mb = max(1, config.ppo.minibatches)
    n_iters = config.steps_per_dispatch
    n_shards = batch_shard_count(mesh, config.mesh)
    L = actor.n_lanes
    N = actor.spec.n_games
    # Lane sharding engages when the games (and their game-major lanes)
    # split evenly over the batch shards; otherwise the per-leaf
    # divisibility rule in actor_state_sharding has already degraded the
    # layout to replicated (tiny debug configs — e.g. 4 games on an
    # 8-device mesh) and the minibatch split treats the chunk as one
    # shard, exactly the pre-sharding behavior.
    lane_sharded = N % n_shards == 0 and L % n_shards == 0
    eff_shards = n_shards if lane_sharded else 1
    if L % (eff_shards * n_mb):
        raise ValueError(
            f"fused minibatching splits the {L}-lane chunk along lanes "
            f"WITHIN each of the {eff_shards} lane shard(s): n_lanes must "
            f"be divisible by data_parallel x minibatches "
            f"({eff_shards} x {n_mb} = {eff_shards * n_mb}) so every shard "
            f"contributes equal lane groups to each of the {n_mb} "
            f"minibatch(es)"
        )

    probe = config.health.enabled

    def update_on_chunk(state, chunk):
        if n_epochs == 1 and n_mb == 1:
            return _train_step(
                policy, config.ppo, state, chunk,
                anchor_params=anchor_params, probe=probe,
            )

        def epoch(st, _):
            if n_mb == 1:
                return _train_step(
                    policy, config.ppo, st, chunk,
                    anchor_params=anchor_params, probe=probe,
                )
            # In-program shuffle, shard-local (lane_minibatches): each mesh
            # shard permutes its own lanes and contributes its m-th group
            # to minibatch m — no cross-device gather enters the hot loop.
            mbs = lane_minibatches(
                chunk, st.step, config.seed, L, eff_shards, n_mb
            )

            def mb_step(s, mb):
                # no-op assertion under the lane-sharded layout (each
                # minibatch is born evenly lane-sharded); kept as the
                # contract pin rather than trusting propagation
                mb = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, ds), mb
                )
                return _train_step(
                    policy, config.ppo, s, mb,
                    anchor_params=anchor_params, probe=probe,
                )

            st, mseq = jax.lax.scan(mb_step, st, mbs)
            return st, fold_scan_metrics(mseq)

        new_state, metric_seq = jax.lax.scan(
            epoch, state, None, length=n_epochs
        )
        # report the final update (the state reflects it), like the
        # buffered loop's last logged step of a multi-epoch pass;
        # health_ok AND-folds across every scan level (fold_scan_metrics)
        return new_state, fold_scan_metrics(metric_seq)

    def one_iter(state, actor_state, opp_params):
        actor_state, chunk, stats = actor._rollout_impl(
            state.params, actor_state, opp_params
        )
        # no-op assertion: the chunk is BORN data-sharded (its lanes
        # inherit the actor state's lane sharding); this pin turns a
        # layout regression into a visible reshard instead of silence
        chunk = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, ds), chunk
        )
        new_state, metrics = update_on_chunk(state, chunk)
        return new_state, actor_state, metrics, stats

    if n_iters == 1:
        fused = one_iter
    else:
        # Dispatch batching (RunConfig.steps_per_dispatch): scan K whole
        # rollout+update iterations, so ONE host dispatch advances K
        # optimizer steps. The opponent is fixed for the dispatch (the
        # learner rejects league configs whose opponent_hold is shorter
        # than the dispatch stride); per-chunk
        # episode stats are additive scalars, summed over the scan so
        # league attribution sees the dispatch's true totals.
        def fused(state, actor_state, opp_params):
            def it(c, _):
                st, ast = c
                st, ast, metrics, stats = one_iter(st, ast, opp_params)
                return (st, ast), (metrics, stats)

            (state, actor_state), (metric_seq, stat_seq) = jax.lax.scan(
                it, (state, actor_state), None, length=n_iters
            )
            metrics = fold_scan_metrics(metric_seq)
            stats = jax.tree.map(lambda s: s.sum(axis=0), stat_seq)
            return state, actor_state, metrics, stats

    # No donation: in self-play the caller passes state.params AS
    # opp_params (one signature for both modes), so donating the state
    # would alias a donated buffer with a live input; the actor state's
    # zero carries can likewise alias a cached constant on the first call.
    # The state is LSTM(128)-scale — the copy cost is noise next to the
    # dispatch savings this path exists for.
    # opp_params shards like the live params (st_sh's params subtree): under
    # TP, pinning it replicated would all-gather the full param set every
    # step — on the one-dispatch hot path this module exists to shorten.
    # The actor state is pinned lane-sharded in AND out (st_act_sh): the
    # sim worlds, carries, per-game keys, and stat partials live
    # partitioned in HBM across dispatches; the per-chunk stats output
    # keeps the same partial layout (its game/lane axes are the sharded
    # ones), so emitting it is collective-free too.
    return jax.jit(
        fused,
        in_shardings=(st_sh, st_act_sh, st_sh.params),
        out_shardings=(st_sh, st_act_sh, repl, st_act_sh.stats),
    )
