"""Generalized Advantage Estimation as a reverse ``lax.scan``.

The reference computes GAE over the time axis inside its learner
(SURVEY.md §3.2, BASELINE.json:5; reconstructed — the reference checkout was
an empty mount). A sequential Python/torch loop there; here a single
``lax.scan`` over time, batched over rollouts, fully inside jit so XLA fuses
it with the surrounding loss computation (HEPPO-GAE, PAPERS.md, covers the
hardware-friendly formulation space — a scan is already bandwidth-bound
optimal at these sizes).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def gae(
    rewards: jnp.ndarray,      # f32 [B, T]
    values: jnp.ndarray,       # f32 [B, T+1] — includes bootstrap value
    dones: jnp.ndarray,        # bool/f32 [B, T] — episode ended AT step t
    gamma: float,
    lam: float,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (advantages [B, T], returns [B, T]).

    ``values[:, t]`` is V(s_t) under the *current* policy; ``values[:, T]`` is
    the bootstrap for the state following the last transition. ``dones[:, t]``
    cuts both the TD target and the accumulation, so chunks that straddle
    episode boundaries (the truncated-BPTT regime of SURVEY.md §5.7) are
    handled exactly.
    """
    not_done = 1.0 - dones.astype(jnp.float32)
    deltas = rewards + gamma * not_done * values[:, 1:] - values[:, :-1]

    def backward(carry, xs):
        delta_t, nd_t = xs
        carry = delta_t + gamma * lam * nd_t * carry
        return carry, carry

    # scan over time, reversed; batch axis rides along.
    _, adv_rev = jax.lax.scan(
        backward,
        jnp.zeros_like(deltas[:, 0]),
        (deltas.T, not_done.T),
        reverse=True,
    )
    advantages = adv_rev.T
    returns = advantages + values[:, :-1]
    return advantages, returns


def gae_reference(rewards, values, dones, gamma, lam):
    """Plain NumPy reference implementation (test oracle, SURVEY.md §4)."""
    import numpy as np

    rewards, values, dones = map(np.asarray, (rewards, values, dones))
    B, T = rewards.shape
    adv = np.zeros((B, T), dtype=np.float64)
    for b in range(B):
        acc = 0.0
        for t in reversed(range(T)):
            nd = 1.0 - float(dones[b, t])
            delta = rewards[b, t] + gamma * nd * values[b, t + 1] - values[b, t]
            acc = delta + gamma * lam * nd * acc
            adv[b, t] = acc
    return adv.astype(np.float32), (adv + values[:, :-1]).astype(np.float32)
