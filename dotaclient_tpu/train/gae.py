"""Generalized Advantage Estimation as a reverse ``lax.scan``.

The reference computed GAE as a sequential Python/torch loop in its learner
(SURVEY.md §3.2, BASELINE.json:5; reconstructed — the reference checkout was
an empty mount). Here GAE always runs ON DEVICE, in one of two jitted
homes, and there is no host-side GAE pass anywhere in the pipeline:

* **Consume-time advantage pass** (the default buffered-learner path,
  ``train/advantage.py``): the value forward + the reverse scan run ONCE
  per consumed batch at the buffer gather boundary, and every
  ``epochs_per_batch × minibatches`` optimizer step trains on the staged
  result — HEPPO-GAE's (PAPERS.md) advantage-estimation-as-pipeline-stage
  idea, with the pass overlapped behind the in-flight epoch step.
* **In-step recompute** (fused mode, vtrace, ``one_pass_advantage=false``):
  the loss function calls :func:`gae`/:func:`vtrace` directly inside the
  jitted train step, so the scan compiles into the same XLA program as
  the forward, loss, and gradient — the historical shape, still the
  right one wherever the estimator's inputs change per step.

Either way values come from the policy's sequence forward in the same
program (a scan is already bandwidth-bound optimal at these sizes).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def gae(
    rewards: jnp.ndarray,      # f32 [B, T]
    values: jnp.ndarray,       # f32 [B, T+1] — includes bootstrap value
    dones: jnp.ndarray,        # bool/f32 [B, T] — episode ended AT step t
    gamma: float,
    lam: float,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (advantages [B, T], returns [B, T]).

    ``values[:, t]`` is V(s_t) under the *current* policy; ``values[:, T]`` is
    the bootstrap for the state following the last transition. ``dones[:, t]``
    cuts both the TD target and the accumulation, so chunks that straddle
    episode boundaries (the truncated-BPTT regime of SURVEY.md §5.7) are
    handled exactly.
    """
    not_done = 1.0 - dones.astype(jnp.float32)
    deltas = rewards + gamma * not_done * values[:, 1:] - values[:, :-1]

    def backward(carry, xs):
        delta_t, nd_t = xs
        carry = delta_t + gamma * lam * nd_t * carry
        return carry, carry

    # scan over time, reversed; batch axis rides along.
    _, adv_rev = jax.lax.scan(
        backward,
        jnp.zeros_like(deltas[:, 0]),
        (deltas.T, not_done.T),
        reverse=True,
    )
    advantages = adv_rev.T
    returns = advantages + values[:, :-1]
    return advantages, returns


def vtrace(
    rewards: jnp.ndarray,        # f32 [B, T]
    values: jnp.ndarray,         # f32 [B, T+1] — includes bootstrap value
    dones: jnp.ndarray,          # bool/f32 [B, T]
    behavior_logp: jnp.ndarray,  # f32 [B, T] — μ(a|s) at collection time
    target_logp: jnp.ndarray,    # f32 [B, T] — π(a|s) under current params
    gamma: float,
    rho_clip: float = 1.0,
    c_clip: float = 1.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """V-trace targets and policy-gradient advantages (IMPALA, Espeholt et
    al. 2018 — the off-policy correction IMPACT [P:9] builds on).

    Where GAE assumes the batch is on-policy, V-trace reweights each step
    by the clipped importance ratio ρ_t = min(ρ̄, π/μ), so stale rollouts
    from async actors contribute a bias-corrected value target instead of
    being merely tolerated by the PPO clip. Returns ``(pg_advantages,
    vs)``: feed ``pg_advantages`` to the surrogate and regress the value
    head onto ``vs``. On-policy (π ≡ μ) with ρ̄ = c̄ ≥ 1 this reduces
    exactly to GAE(λ=1) — pinned by a test.

    ``dones`` cuts the recursion exactly like :func:`gae`; importance
    weights are consumed as constants (callers pass stop-gradient logps).
    """
    not_done = 1.0 - dones.astype(jnp.float32)
    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), rho_clip)
    c = jnp.minimum(jnp.exp(target_logp - behavior_logp), c_clip)
    deltas = rho * (
        rewards + gamma * not_done * values[:, 1:] - values[:, :-1]
    )

    def backward(carry, xs):
        delta_t, c_t, nd_t = xs
        carry = delta_t + gamma * c_t * nd_t * carry
        return carry, carry

    _, corr_rev = jax.lax.scan(
        backward,
        jnp.zeros_like(deltas[:, 0]),
        (deltas.T, c.T, not_done.T),
        reverse=True,
    )
    corr = corr_rev.T                       # vs_t − V(s_t)
    vs = corr + values[:, :-1]
    # vs_{t+1}: the next step's target, bootstrap V(s_T) at the chunk end.
    vs_next = jnp.concatenate([vs[:, 1:], values[:, -1:]], axis=1)
    pg_adv = rho * (
        rewards + gamma * not_done * vs_next - values[:, :-1]
    )
    return pg_adv, vs


def vtrace_reference(
    rewards, values, dones, behavior_logp, target_logp, gamma,
    rho_clip=1.0, c_clip=1.0,
):
    """Plain NumPy reference implementation (test oracle)."""
    import numpy as np

    rewards, values, dones, blp, tlp = map(
        np.asarray, (rewards, values, dones, behavior_logp, target_logp)
    )
    B, T = rewards.shape
    vs = np.zeros((B, T), dtype=np.float64)
    for b in range(B):
        acc = 0.0
        for t in reversed(range(T)):
            nd = 1.0 - float(dones[b, t])
            w = float(np.exp(tlp[b, t] - blp[b, t]))
            rho = min(rho_clip, w)
            cc = min(c_clip, w)
            delta = rho * (
                rewards[b, t] + gamma * nd * values[b, t + 1] - values[b, t]
            )
            acc = delta + gamma * cc * nd * acc
            vs[b, t] = values[b, t] + acc
    pg = np.zeros((B, T), dtype=np.float64)
    for b in range(B):
        for t in range(T):
            nd = 1.0 - float(dones[b, t])
            rho = min(rho_clip, float(np.exp(tlp[b, t] - blp[b, t])))
            nxt = vs[b, t + 1] if t + 1 < T else values[b, T]
            pg[b, t] = rho * (
                rewards[b, t] + gamma * nd * nxt - values[b, t]
            )
    return pg.astype(np.float32), vs.astype(np.float32)


def gae_reference(rewards, values, dones, gamma, lam):
    """Plain NumPy reference implementation (test oracle, SURVEY.md §4)."""
    import numpy as np

    rewards, values, dones = map(np.asarray, (rewards, values, dones))
    B, T = rewards.shape
    adv = np.zeros((B, T), dtype=np.float64)
    for b in range(B):
        acc = 0.0
        for t in reversed(range(T)):
            nd = 1.0 - float(dones[b, t])
            delta = rewards[b, t] + gamma * nd * values[b, t + 1] - values[b, t]
            acc = delta + gamma * lam * nd * acc
            adv[b, t] = acc
    return adv.astype(np.float32), (adv + values[:, :-1]).astype(np.float32)
