"""The learner: end-to-end training loop and CLI entrypoint.

Counterpart of the reference's ``optimizer.py`` main loop — consume rollouts,
train, publish versioned weights, checkpoint, log scalars (SURVEY.md §3.2;
reconstructed — the reference checkout was an empty mount) — wired TPU-first:
the actor pool batches env inference on-device, experience flows through the
transport into the sharded HBM buffer, and each optimization is one donated
pjit step (SURVEY.md §7 "Minimum end-to-end slice").

Single-process mode interleaves actor and learner phases (the deterministic
test path) or overlaps them (``--overlap``: the actor pool runs in its own
thread feeding the transport while the learner trains — the async
actor-learner topology of SURVEY.md §1, in one process). The same components
run split across processes with an AMQP transport on a cluster
(``--transport amqp``).

Sync discipline (SURVEY.md §7 hard-part 2): the optimizer loop never reads a
device value per step — step/version counters are host-side mirrors, the
donated train step is dispatch-only, and metrics are fetched (one transfer)
only at ``log_every`` boundaries. On hardware where a host↔device round trip
is expensive this is the difference between dispatch-rate and sync-rate
training. ``scripts/check_host_sync.py`` guards the discipline statically.

Zero-stall snapshots (ISSUE 5, docs/ARCHITECTURE.md "Zero-stall snapshots"):
with ``learner.async_snapshots`` (the default) even the boundary-cadence
side effects leave the train thread. At a publish/checkpoint/log boundary
the loop runs one cheap jitted on-device copy of the needed state into
fresh HBM snapshot buffers and dispatches the next step immediately; the
background snapshot thread (train/snapshot.py) does the batched device→host
fetch, the bf16 wire cast + encode, the non-blocking fanout enqueue, and
the orbax write. Published versions stay monotonic under latest-wins
coalescing, graceful stop drains the engine and lands the forced checkpoint
at the EXACT stop step via the sync path, and async write failures surface
through ``checkpoint/save_failures_total``. ``--sync-snapshots`` opts out.

Pipelined data path (ISSUE 2, docs/ARCHITECTURE.md "Pipelined data path"):
multi-epoch/minibatch batches train through the fused epoch step — ONE
donated dispatch for all ``epochs × minibatches`` updates
(``ppo.fused_epoch``; ``train/ppo.make_epoch_step``) — and the loop
prefetches batch N+1 (transport drain → staged host rows → ring scatter →
batch gather, all dispatch) behind batch N's in-flight step, with hit-rate
and overlap-fraction gauges proving the overlap.

Usage:
    python -m dotaclient_tpu.train.learner --smoke       # tiny sanity run
    python -m dotaclient_tpu.train.learner --steps 1000 --logdir runs/x
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from collections import deque

from dotaclient_tpu.buffer import TrajectoryBuffer
from dotaclient_tpu.league import pool as league_pool
from dotaclient_tpu.config import RunConfig, default_config
from dotaclient_tpu.actor import ActorPool, VecActorPool
from dotaclient_tpu.models import init_params, make_policy
from dotaclient_tpu.parallel import make_mesh
from dotaclient_tpu.train.ppo import (
    init_train_state,
    make_epoch_step,
    make_train_step,
)
from dotaclient_tpu.transport import (
    InProcTransport,
    Transport,
    decode_rollout,
    encode_weights,
)
from dotaclient_tpu.utils import faults, telemetry, tracing, utilization
from dotaclient_tpu.utils.checkpoint import CheckpointManager, shape_mismatches
from dotaclient_tpu.utils.metrics import MetricsLogger


class Learner:
    """Owns the full training stack for single-host runs."""

    def __init__(
        self,
        config: RunConfig,
        transport: Optional[Transport] = None,
        logdir: Optional[str] = None,
        checkpoint_dir: Optional[str] = None,
        restore: bool = False,
        init_from: Optional[str] = None,
        seed: int = 0,
        vec: bool = True,
        actor: Optional[str] = None,
        debug_checkify: bool = False,
        metrics_jsonl: Optional[str] = None,
    ) -> None:
        # actor mode: "device" (on-device rollout scan feeding the buffered
        # learner), "fused" (rollout + PPO update in ONE XLA program — the
        # fastest synchronous path; train batch = lane set, strictly
        # on-policy, see train/fused.py), "vec" (numpy vectorized sim,
        # host-driven), "scalar" (proto/gRPC-parity pool), "external" (no
        # in-process actors — N standalone `python -m dotaclient_tpu.actor`
        # processes feed the transport, the reference's scale-out topology,
        # SURVEY.md §1). `vec` kept for backward compatibility.
        mode = actor or ("vec" if vec else "scalar")
        if mode not in ("device", "fused", "vec", "scalar", "external"):
            raise ValueError(f"unknown actor mode {mode!r}")
        # Fused mode shuffles/splits in-program along lanes (train/fused.py
        # validates n_lanes % minibatches); the buffered paths split the
        # optimizer batch host-side, so batch_rollouts must divide.
        if (
            mode != "fused"
            and config.ppo.minibatches > 1
            and config.ppo.batch_rollouts % config.ppo.minibatches
        ):
            raise ValueError(
                f"batch_rollouts {config.ppo.batch_rollouts} not "
                f"divisible by minibatches {config.ppo.minibatches}"
            )
        if config.steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got "
                f"{config.steps_per_dispatch}"
            )
        if config.steps_per_dispatch > 1 and mode != "fused":
            raise ValueError(
                "steps_per_dispatch > 1 batches iterations inside the fused "
                "program — it has no meaning for the staged actor modes; "
                "use actor='fused' or leave it at 1"
            )
        if (
            # the pool itself is gated on env.opponent (below), so the
            # guard must be too — league.enabled alone can be stale
            (config.league.enabled or config.env.opponent == "league")
            and mode in ("fused", "device")
            and config.steps_per_dispatch * config.ppo.steps_per_batch
            > config.league.opponent_hold
        ):
            # opponent redraws can only happen at dispatch boundaries, so a
            # hold shorter than the stride is silently stretched to it —
            # PFSP mixing would degrade below its configured cadence.
            raise ValueError(
                f"league.opponent_hold ({config.league.opponent_hold}) is "
                f"shorter than one dispatch stride "
                f"(steps_per_dispatch × steps_per_batch = "
                f"{config.steps_per_dispatch * config.ppo.steps_per_batch}) "
                f"— raise opponent_hold or lower steps_per_dispatch"
            )
        if mode == "fused" and debug_checkify:
            raise ValueError(
                "checkify instruments the buffered train step, which fused "
                "mode never calls — use actor='device' to hunt NaNs"
            )
        if mode == "external" and transport is None:
            raise ValueError(
                "external actor mode needs a transport (TransportServer or "
                "AmqpTransport) for the actor processes to reach"
            )
        self.actor_mode = mode
        self.config = config
        self.mesh = make_mesh(config.mesh)
        # Multi-chip telemetry (ISSUE 10): mesh geometry gauges plus a
        # ONE-TIME startup probe of the mesh's all-reduce round trip
        # (`learner/psum_ms`) — the per-step gradient psum is fused into
        # the dispatched program and never separably observable, so the
        # probe is the documented stand-in. All eager-created here so any
        # learner run's JSONL validates
        # `check_telemetry_schema.py --require-multichip`
        # deterministically (`buffer/shard_bytes` stays 0 for bufferless
        # fused runs; the ring overwrites it when it allocates).
        from dotaclient_tpu.parallel.mesh import (
            batch_shard_count,
            collective_probe_ms,
        )

        reg = telemetry.get_registry()
        # Pipeline tracing + device hooks (ISSUE 12): the tracer is
        # captured ONCE (faults.get() discipline — configure before
        # constructing the learner); the trace/compile/mem keys are
        # eager-created so `check_telemetry_schema.py --require-trace`
        # validates ANY learner JSONL deterministically.
        tracing.ensure_metrics(reg)
        self._tracer = tracing.get()
        reg.gauge("mesh/n_devices").set(float(self.mesh.devices.size))
        reg.gauge("mesh/data_shards").set(
            float(batch_shard_count(self.mesh, config.mesh))
        )
        reg.gauge("buffer/shard_bytes")
        reg.gauge("learner/psum_ms").set(
            collective_probe_ms(self.mesh, config.mesh)
        )
        # Lane-sharded actor geometry (ISSUE 18): eager-created so any
        # learner JSONL validates --require-multichip; they stay 0 for
        # modes without a device-resident actor and are set to the real
        # lane split when the DeviceActor is constructed below.
        reg.gauge("mesh/lane_shards")
        reg.gauge("fused/lanes_per_shard")
        if config.ppo.minibatches > 1:
            # each minibatch is itself a data-sharded train batch. In fused
            # mode the chunk IS the lane set, split along lanes in-program
            # (train/fused.py); the buffered paths split batch_rollouts.
            shards = batch_shard_count(self.mesh, config.mesh)
            if mode == "fused":
                from dotaclient_tpu.actor.device_rollout import lane_split

                total = config.env.n_envs * len(lane_split(config)[0])
                what = f"lane count {total}"
            else:
                total = config.ppo.batch_rollouts
                what = f"batch_rollouts {total}"
            mb = total // config.ppo.minibatches
            if total % config.ppo.minibatches or mb % shards:
                raise ValueError(
                    f"{what} must split into minibatches "
                    f"({config.ppo.minibatches}) of a size divisible by the "
                    f"batch shard count {shards} (minibatches are "
                    f"data-sharded batches); got minibatch size {mb}"
                )
        self.policy = make_policy(config.model, config.obs, config.actions)
        params = init_params(self.policy, jax.random.PRNGKey(config.seed))
        self.state = init_train_state(params, config.ppo)
        # The TrainState's sharding tree (params + Adam mirrors replicated
        # under pure DP, TP-partitioned under model_parallel > 1; counters
        # replicated) — the SAME tree make_train_step/make_epoch_step pin
        # as in/out shardings, computed once and reused by every restore
        # path so a checkpoint written at a different device count is
        # re-committed to THIS mesh before its first dispatch (ISSUE 10).
        from dotaclient_tpu.train.ppo import train_state_sharding

        self.state_shardings = train_state_sharding(
            self.policy, config, self.mesh
        )
        self.ckpt: Optional[CheckpointManager] = None
        self._want_restore = restore
        self._init_from_step = 0   # source step when seeded via init_from
        if init_from:
            if restore:
                raise ValueError(
                    "init_from seeds a FRESH run from a source checkpoint; "
                    "restore resumes this run's own checkpoint_dir — "
                    "they are mutually exclusive"
                )
            if checkpoint_dir and (
                os.path.realpath(init_from) == os.path.realpath(checkpoint_dir)
            ):
                raise ValueError(
                    "init_from must point at a SEPARATE source directory: "
                    "seeding resets the step counter to 0, so writing into "
                    "the source dir would decline every periodic save "
                    "(step <= latest) and the end-of-run save would destroy "
                    "the source snapshot"
                )
            # Weights-only seed from a SEPARATE source directory: the run's
            # own checkpoint_dir stays the destination, so its rolling
            # garbage collection can never eat the source snapshot (the
            # failure mode of resuming curriculum stages in one directory).
            # Optimizer moments and counters start FRESH: restored Adam
            # second moments are calibrated to the SOURCE config's gradient
            # scales and can catastrophically over-step the transferred
            # policy in the first updates. (The source's opt_state is read
            # and discarded — a few MB at these model sizes; not worth a
            # partial-restore template.)
            if not os.path.isdir(init_from):
                # Constructing the manager would CREATE the missing dir
                # (orbax create=True) — a mistyped path must fail cleanly,
                # not leave a stray empty checkpoint tree masking the typo.
                raise FileNotFoundError(
                    f"init_from directory does not exist: {init_from!r}"
                )
            src = CheckpointManager(init_from)
            try:
                # Weights-only (template-free) restore: init_from must work
                # across optimizer configs — a plain-Adam source seeding a
                # KL-adaptive run has a different opt_state layout, and the
                # moments are discarded here anyway.
                seeded_params, seeded_step = src.restore_weights()
            except (KeyError, ValueError, TypeError) as e:
                raise ValueError(
                    f"init_from checkpoint at {init_from!r} does not match "
                    f"this run's model structure (different core?): {e}"
                ) from e
            finally:
                src.close()
            want = jax.eval_shape(lambda: self.state.params)
            bad = shape_mismatches(seeded_params, want)
            if bad:
                raise ValueError(
                    f"init_from checkpoint is incompatible with this run's "
                    f"model config (param shape {bad[0]}, +{len(bad) - 1} "
                    f"more mismatches) — was it trained with a different "
                    f"core/width?"
                )
            self.state = init_train_state(seeded_params, config.ppo)
            self._init_from_step = seeded_step
        self.ckpt_best: Optional[CheckpointManager] = None
        self._best_dir: Optional[str] = None
        self._best_win = -1.0
        if checkpoint_dir:
            self.ckpt = CheckpointManager(checkpoint_dir)
            if restore and self.ckpt.latest_step() is not None:
                try:
                    self.state, _ = self.ckpt.restore(config, self.state)
                except ValueError as e:
                    # The only layout-changing PPO knob today is kl_target
                    # (inject_hyperparams adds an lr leaf to opt_state) —
                    # translate orbax's raw tree diff into the fix.
                    raise ValueError(
                        f"checkpoint at {checkpoint_dir!r} does not match "
                        f"this run's OPTIMIZER layout — toggling "
                        f"ppo.kl_target between a run and its --restore "
                        f"changes the opt_state structure. Restore with "
                        f"the original setting, or re-seed weights-only "
                        f"via --init-from. ({e})"
                    ) from e
            if config.checkpoint_best_min_episodes > 0:
                # Best-model rotation (see RunConfig.checkpoint_best_min_
                # episodes): the mid-run peak survives even when training
                # later slides off it. The manager is created lazily at the
                # first qualifying save (actor modes without windowed
                # win-rate stats would otherwise leave a stray empty tree),
                # but the best-so-far value must load EAGERLY: a resumed
                # run that reset it to -1 would let its first (possibly
                # collapsed) window overwrite the captured peak.
                self._best_dir = os.path.join(checkpoint_dir, "best")
                meta = os.path.join(self._best_dir, "best_meta.json")
                if os.path.exists(meta):
                    try:
                        with open(meta) as f:
                            self._best_win = float(
                                json.load(f)["win_rate_recent"]
                            )
                    except (OSError, ValueError, KeyError):
                        # Unreadable meta + a resumed collapsed run would
                        # let the first window displace the captured peak;
                        # +inf freezes the rotation until the operator
                        # inspects/removes best/ (loud, not silent).
                        print(
                            f"WARNING: {meta} unreadable — best-model "
                            f"rotation FROZEN to protect the existing "
                            f"best/ checkpoint; delete the dir to reset",
                            flush=True,
                        )
                        self._best_win = float("inf")
        # Commit the state to the mesh NOW (one device_put against
        # state_shardings), whatever path built it — fresh init, init_from
        # seed, or a --restore of a checkpoint written at ANY device count
        # (restores hand back host-layout arrays; this is the re-shard).
        # Committing before the first dispatch also means the first donated
        # step donates correctly-sharded buffers instead of paying a
        # layout change mid-program. A 1-device mesh is the degenerate
        # case of the same call.
        self.state = jax.device_put(self.state, self.state_shardings)
        # Anchor-KL regularizer (PPOConfig.anchor_kl_coef): the anchor is
        # the policy AS CONSTRUCTED — after --init-from/--restore — i.e.
        # the transferred policy in a curriculum fine-tune. Copied: the
        # train step donates/updates the live params.
        self.anchor_params = (
            jax.tree.map(jnp.copy, self.state.params)
            if config.ppo.anchor_kl_coef > 0
            else None
        )
        # instrument_jit (ISSUE 12): per-program compile/retrace counters
        # + cost analysis once per compile; transparent to dispatch and
        # to the donation lint (lint/donation.py unwraps the call)
        self.train_step = tracing.instrument_jit(
            make_train_step(
                self.policy, config, self.mesh,
                debug_checkify=debug_checkify,
                anchor_params=self.anchor_params,
            ),
            "train_step",
        )
        # Fused epoch step (ppo.fused_epoch): when one consumed batch needs
        # E×M > 1 optimizer steps, run them all in ONE donated program
        # instead of the staged gather+step dispatch pair per minibatch.
        # The staged loop stays compiled-on-demand as the fallback
        # (--checkify instruments per-step; fused_epoch=false opts out).
        self.epoch_step = None
        if (
            config.ppo.fused_epoch
            and config.ppo.steps_per_batch > 1
            and mode != "fused"
            and not debug_checkify
        ):
            self.epoch_step = tracing.instrument_jit(
                make_epoch_step(
                    self.policy, config, self.mesh,
                    anchor_params=self.anchor_params,
                ),
                "epoch_step",
            )
        # One-pass advantage plane (ISSUE 14, train/advantage.py): a
        # jitted, mesh-sharded value-forward + GAE pass runs ONCE per
        # consumed batch at the buffer gather boundary, and the epoch
        # step consumes the staged (bf16-narrow) advantages/returns
        # across all E×M updates instead of recomputing them per step.
        # Fused mode trains in-program on-policy and vtrace needs the
        # current policy's per-step logp — both keep the in-step
        # recompute (one_pass_enabled gates on the estimator).
        from dotaclient_tpu.train.advantage import (
            make_advantage_pass,
            one_pass_enabled,
        )

        self.advantage_pass = None
        self._adv_overlap = config.learner.overlap_advantage
        self._adv_overlapped_s = 0.0
        self._adv_serial_s = 0.0
        self._adv_first = True   # first pass pays compile: not accounted
        if mode != "fused" and one_pass_enabled(config):
            self.advantage_pass = tracing.instrument_jit(
                make_advantage_pass(self.policy, config, self.mesh),
                "advantage_pass",
            )
        # eager-created so ANY learner JSONL validates
        # `check_telemetry_schema.py --require-advantage` (a recompute run
        # reports one_pass=0 and zeros, never missing keys)
        reg.gauge("advantage/one_pass").set(
            1.0 if self.advantage_pass is not None else 0.0
        )
        reg.gauge("advantage/pass_ms")
        reg.gauge("advantage/overlap_fraction")
        reg.counter("advantage/passes_total")
        # Fused mode trains each chunk inside its one program and never
        # stages experience: allocating the HBM ring there would pin
        # capacity_rollouts chunks of dead device memory.
        self.buffer = (
            None if mode == "fused" else TrajectoryBuffer(config, self.mesh)
        )
        self.transport = transport or InProcTransport()
        # Zero-stall snapshot engine (ISSUE 5, docs/ARCHITECTURE.md
        # "Zero-stall snapshots"): weight publishes, periodic checkpoints,
        # and log-boundary metrics fetches run on a background thread; at a
        # boundary the train thread only runs one cheap jitted on-device
        # copy (`_snap_copy`, dispatched BEFORE the next donating train
        # step, so device-stream ordering protects the snapshot) and keeps
        # dispatching. learner.async_snapshots=false (--sync-snapshots)
        # restores the inline behavior for debugging.
        self._snap_engine = None
        self._snap_copy = None
        # Training health guardian (ISSUE 6, train/health.py): the
        # in-graph probe's verdict scalars accumulate host-side per
        # consumed batch (zero device traffic) and are flushed as ONE
        # batched fetch through the snapshot engine at boundary cadence —
        # ordered before the publish job, so the publish gate is sound
        # without the train thread ever blocking on a verdict. On a
        # latched divergence the loop rolls the TrainState back to the
        # last_good checkpoint slot (bounded retries, distinct minibatch
        # RNG, loud exit when exhausted).
        self._health = None
        if config.health.enabled:
            from dotaclient_tpu.train.health import HealthMonitor

            self._health = HealthMonitor(config.health)
        self._rollback_count = 0
        # Device references to the LAST batch's verdict scalars (sync-mode
        # checkpoint/tail folds — see _sync_fold_latest).
        self._last_verdict_m = None
        # Highest version actually handed to the fanout on the SYNC path
        # (async mode asks the engine); the rollback audit line reports
        # whichever is live as its published-floor evidence.
        self._published_version = -1
        # Deferred best-model candidate, written by the snapshot thread's
        # metrics continuation and consumed on the train thread; the lock
        # makes the read-and-clear swap atomic against a concurrent write
        # (an unsynchronized swap could silently drop a qualifying peak).
        self._pending_best: Optional[Dict[str, float]] = None
        self._pending_best_lock = threading.Lock()
        self._stall_s = 0.0   # train-thread seconds lost to side effects
        if config.learner.async_snapshots:
            from dotaclient_tpu.train.snapshot import SnapshotEngine

            self._snap_engine = SnapshotEngine(
                transport=self.transport,
                wire_dtype=config.transport.wire_dtype,
                ckpt=self.ckpt,
                health=self._health,
            )
            self._snap_copy = tracing.instrument_jit(
                jax.jit(lambda t: jax.tree.map(jnp.copy, t)), "snap_copy"
            )
        # eager-create the stall gauges (and, sync mode, the snapshot keys
        # the engine would have created): a clean run reports zeros —
        # check_telemetry_schema.py --require-snapshot pins all four
        for key in (
            "learner/publish_stall_ms",
            "learner/stall_fraction",
            "snapshot/pending",
            "snapshot/d2h_ms",
        ):
            telemetry.get_registry().gauge(key)
        # Vectorized mode ships decoded rollouts through an in-proc deque
        # (thread-safe append/drain) — no proto round-trip on the hot path;
        # the scalar pool keeps proto/gRPC parity coverage. Bounded with
        # drop-oldest, like InProcTransport: in overlap mode the actor thread
        # free-runs while the learner compiles/checkpoints.
        self._sink: Optional[deque] = (
            deque(maxlen=4 * config.buffer.capacity_rollouts)
            if mode == "vec" else None
        )
        self.device_actor = None
        self.fused_step = None
        if mode == "external":
            self.pool = None
        elif mode in ("device", "fused"):
            from dotaclient_tpu.actor.device_rollout import DeviceActor

            # the actor state is committed lane-sharded over the learner's
            # mesh (ISSUE 18): games partition over the (dcn×)data axes, so
            # the fused program's pinned shardings are satisfied by layout
            self.device_actor = DeviceActor(
                config, self.policy, seed=seed,
                mesh=self.mesh, mesh_config=config.mesh,
            )
            self.pool: Any = self.device_actor  # shared stats() surface
            reg = telemetry.get_registry()
            reg.gauge("mesh/lane_shards").set(
                float(self.device_actor.lane_shards)
            )
            reg.gauge("fused/lanes_per_shard").set(
                float(self.device_actor.lanes_per_shard)
            )
            if mode == "fused":
                from dotaclient_tpu.train.fused import make_fused_step

                self.fused_step = tracing.instrument_jit(
                    make_fused_step(
                        self.policy, config, self.mesh, self.device_actor,
                        anchor_params=self.anchor_params,
                    ),
                    "fused_step",
                )
        elif mode == "vec":
            self.pool = VecActorPool(
                config,
                self.policy,
                self.state.params,
                seed=seed,
                version=int(self.state.version),
                rollout_sink=self._sink.extend,
            )
        else:
            self.pool = ActorPool(
                config,
                self.policy,
                self.state.params,
                transport=self.transport,
                seed=seed,
                version=int(self.state.version),
            )
        # League: frozen-opponent pool driving the Dire side (SURVEY.md §7
        # step 7). Seeded from the initial params so opponent lanes are
        # frozen from step 0, never silently mirroring the live policy.
        self.league = None
        self._league_pending: List[Any] = []
        self._held_opponent = None      # (params|None, uid) held draw
        self._held_until = -1
        if config.env.opponent == "league":
            if mode == "scalar":
                raise NotImplementedError(
                    "league mode needs frozen-opponent lanes; the scalar "
                    "gRPC-parity pool has none — use actor='device' or 'vec'"
                )
            from dotaclient_tpu.league import OpponentPool

            self.league = OpponentPool(config.league, seed=seed)
            self.league.maybe_snapshot(
                self.state.params, int(self.state.version), 0
            )
            if mode == "vec":
                # live-params draws must be copies: the train step donates
                # the learner state, killing any buffer the pool holds
                self.pool.set_opponent(
                    *self.league.sample(self._actor_params_copy(), 0)
                )
                if config.league.matchmaking == "pfsp":
                    print(
                        "WARNING: PFSP matchmaking needs per-draw outcome "
                        "attribution, which only the device/fused loops "
                        "provide; host-pool league draws keep the 0.5 "
                        "prior and behave as uniform",
                        flush=True,
                    )
        self.telemetry = telemetry.get_registry()
        self.metrics = MetricsLogger(logdir, jsonl=metrics_jsonl)
        # Fleet health plane (ISSUE 13): the aggregator is ALWAYS
        # constructed — that alone eager-creates every fleet/ + alerts/
        # key, so `check_telemetry_schema.py --require-fleet` validates
        # any learner JSONL deterministically. Its merge/alert thread
        # only STARTS when a fleet can actually report (the external
        # transports); transport reader threads hand it kind-5 metric
        # snapshot frames via `metrics_handler`, and ALERT events ride
        # the metrics JSONL's flush-per-emit durability.
        from dotaclient_tpu.utils.fleet import FleetAggregator

        self.fleet = FleetAggregator(
            registry=self.telemetry, emit_event=self.metrics.emit_event
        )
        if transport is not None and hasattr(transport, "metrics_handler"):
            transport.metrics_handler = self.fleet.ingest
        # Outcome attribution plane (ISSUE 15): eager-create BOTH halves
        # of the outcome key schema — the actor-side counters (so
        # `--require-outcome` validates an external learner's JSONL that
        # only ever sees fleet mirrors) and the aggregator's curve gauges.
        # The aggregator has no thread of its own: the fleet aggregator's
        # tick hook drives it at fleet cadence in external modes (wall
        # clock — outcome staleness evaluates even when training stalls),
        # and _publish_pipeline_gauges ticks it at log boundaries in the
        # in-process modes.
        from dotaclient_tpu.outcome import OutcomeAggregator
        from dotaclient_tpu.outcome import records as outcome_records

        outcome_records.ensure_actor_metrics(self.telemetry)
        self.outcome = OutcomeAggregator(registry=self.telemetry)
        self.fleet.add_tick_hook(self.outcome.tick)
        self._fleet_started = False
        if mode == "external" and telemetry.fleet_interval_s > 0:
            self.fleet.start()
            self._fleet_started = True
        self.frames_per_rollout = config.ppo.rollout_len
        # Minibatch machinery: one jitted gather (a tree of row-gathers is
        # otherwise a dispatch per leaf), host RNG for the shuffles, and the
        # optimizer-steps-per-consumed-batch stride used by counters and
        # log/checkpoint gating.
        from dotaclient_tpu.parallel import data_sharding

        self._minibatch_gather = tracing.instrument_jit(
            jax.jit(
                lambda batch, idx: jax.tree.map(lambda x: x[idx], batch),
                # minibatches must arrive at the train step in its batch
                # sharding (the donated step pins its in_shardings)
                out_shardings=data_sharding(self.mesh, config.mesh),
            ),
            "minibatch_gather",
        )
        self._mb_rng = np.random.default_rng(config.seed + 1)
        self._mb_draws = 0          # permutations consumed (for exact resume)
        self._steps_per_batch = config.ppo.steps_per_batch
        self._last_metrics: Dict[str, float] = {}
        # Prefetch lane: batch N+1, already drained/scattered/gathered while
        # batch N's (dispatch-only) optimizer step runs on the device. Hit
        # and overlap accounting feed the learner/prefetch_hit_rate and
        # learner/overlap_fraction gauges — host floats, no device traffic.
        self._prefetched = None
        self._prefetch_ticket: Optional[int] = None
        self._prefetch_hits = 0
        self._prefetch_misses = 0
        self._prefetch_overlapped_s = 0.0
        self._prefetch_serial_s = 0.0
        # True between an optimizer dispatch and the next blocking fetch:
        # host work done in that window overlaps device compute.
        self._dispatch_inflight = False
        self._poll_timeout = config.buffer.consume_poll_timeout_s
        # Host-side mirrors of state.step/state.version: reading the device
        # scalars costs a full sync per read, so the loop never does.
        self._host_step = int(np.asarray(self.state.step))   # host-sync-ok: one-time init
        self._host_version = int(np.asarray(self.state.version))   # host-sync-ok: one-time init
        # Graceful-stop latch (ISSUE 4): request_stop() — typically from a
        # SIGTERM handler — makes every train loop exit at its next step
        # boundary, after which the normal end-of-run tail runs: the
        # prefetch lane requeues its held batch, the full-pipeline
        # checkpoint is taken, final weights publish, transports close.
        self._stop_requested = False
        self._faults = faults.get()   # None unless chaos injection is on
        # Pipeline utilization plane (ISSUE 16, utils/utilization.py):
        # always-on phase accountant attributing every train-thread
        # wall-clock second to a closed phase set at the boundaries the
        # loop already has. The factory eager-creates every util/* gauge
        # (so `check_telemetry_schema.py --require-utilization` validates
        # ANY learner JSONL) and returns None when the module knob is off
        # — the faults.get() one-pointer-test discipline.
        self._util = utilization.make_learner(self.telemetry)
        # Pipeline restore (buffer contents + device-actor state) happens
        # after those components exist; weights/opt-state restored above.
        if (
            self._want_restore
            and self.ckpt is not None
            and self.ckpt.latest_step() is not None
        ):
            self._restore_pipeline()

    # -- loop --------------------------------------------------------------

    def request_stop(self) -> None:
        """Ask the running train() to drain and return at its next step
        boundary (signal-handler safe: one flag write, no locks). The
        end-of-run tail then checkpoints the FULL pipeline — a stopped run
        resumes at the exact step with no experience loss."""
        self._stop_requested = True

    @property
    def stop_requested(self) -> bool:
        return self._stop_requested

    def ingest(self) -> int:
        with self.telemetry.span("learner/consume"):
            return self._ingest_impl()

    def _ingest_impl(self) -> int:
        if self._sink is not None:
            rollouts = []
            cap = self.config.buffer.capacity_rollouts
            while self._sink and len(rollouts) < cap:
                rollouts.append(self._sink.popleft())
            if not rollouts:
                return 0
            return self.buffer.add(rollouts, self._host_version)
        # Poll budget (buffer.consume_poll_timeout_s): how long an EMPTY
        # drain may block. A ready prefetched batch never waits on this —
        # _next_batch serves the lane without reaching the drain at all.
        timeout = self._poll_timeout
        if hasattr(self.transport, "consume_decoded"):
            # socket path: raw bytes → native wire parser → zero-copy views
            rollouts = self.transport.consume_decoded(
                self.config.buffer.capacity_rollouts, timeout=timeout
            )
            if not rollouts:
                return 0
            return self.buffer.add(rollouts, self._host_version)
        protos = self.transport.consume_rollouts(
            self.config.buffer.capacity_rollouts, timeout=timeout
        )
        if not protos:
            return 0
        return self.buffer.add(
            [decode_rollout(p) for p in protos], self._host_version
        )

    def _optimize(self, batch) -> Dict[str, jnp.ndarray]:
        """Run ``epochs_per_batch`` passes over one batch, each split into
        ``minibatches`` shuffled slices (the standard PPO regime; with the
        defaults of 1×1 this is a single donated step). Dispatch-only.
        Returns the last pass's (device-resident) metrics.

        With ``ppo.fused_epoch`` (the default) and E×M > 1 this is ONE
        donated dispatch: the epoch permutations are drawn host-side from
        the same ``_mb_rng`` stream the staged loop uses (same updates on
        the same data, and ``_mb_draws`` keeps its exact-resume meaning —
        one draw per epoch), then the whole update loop runs in-program
        (``make_epoch_step``).
        The staged loop below is the fallback for --checkify and
        ``fused_epoch=false``.
        """
        if self._faults is not None and self._faults.fire(
            "learner.fail_train_step"
        ):
            raise RuntimeError(
                "injected fault: learner.fail_train_step (chaos harness)"
            )
        if self._faults is not None and self._faults.fire("learner.nan_grad"):
            # Divergence injection (ISSUE 6 chaos): one NaN reward poisons
            # the loss and the whole backward pass — the realistic NaN-
            # gradient shape — placed on the dispatch path (a tiny jitted
            # scatter, no host↔device sync). The health probe must flag
            # the step, the publish gate must hold the version back, and
            # rollback must restore last_good.
            batch = dict(batch)
            batch["rewards"] = batch["rewards"].at[0, 0].set(jnp.nan)
            if "advantages" in batch:
                # one-pass batches: the poisoned reward would have flowed
                # through the consume-time pass — mirror it into the
                # staged advantages or the loss never sees the NaN
                batch["advantages"] = (
                    batch["advantages"].at[0, 0].set(jnp.nan)
                )
        cfg = self.config.ppo
        M = max(1, cfg.minibatches)
        E = cfg.epochs_per_batch
        if self.epoch_step is not None:
            B = cfg.batch_rollouts
            if M > 1:
                perms = np.stack(
                    [self._mb_rng.permutation(B) for _ in range(E)]
                )
                self._mb_draws += E
            else:
                # unsplit batches are never shuffled (matches the staged
                # path); the in-program scan ignores this placeholder
                perms = np.broadcast_to(np.arange(B), (E, B))
            t0 = time.perf_counter()
            with self.telemetry.span("learner/dispatch"):
                self.state, m = self.epoch_step(
                    self.state, batch, perms.astype(np.int32)
                )
            if self._util is not None:
                # the dispatch call's host time: in a throughput-bound
                # loop it blocks on donation back-pressure — the
                # host-observable proxy for device busy time (the
                # accounting contract, docs/ARCHITECTURE.md)
                self._util.phase(
                    "dispatch_inflight", time.perf_counter() - t0
                )
            self._dispatch_inflight = True
            self._host_step += E * M
            self._host_version += E * M
            self._submit_health(m)
            if self._tracer is not None:
                self._emit_dispatch_traces()
            return m
        for _ in range(E):
            if M == 1:
                t0 = time.perf_counter()
                with self.telemetry.span("learner/dispatch"):
                    self.state, m = self.train_step(self.state, batch)
                if self._util is not None:
                    self._util.phase(
                        "dispatch_inflight", time.perf_counter() - t0
                    )
                self._dispatch_inflight = True
                self._host_step += 1
                self._host_version += 1
                continue
            B = cfg.batch_rollouts
            mb = B // M
            perm = self._mb_rng.permutation(B)
            self._mb_draws += 1
            for i in range(M):
                t0 = time.perf_counter()
                with self.telemetry.span("learner/assemble"):
                    idx = jnp.asarray(perm[i * mb:(i + 1) * mb], jnp.int32)
                    sub = self._minibatch_gather(batch, idx)
                t1 = time.perf_counter()
                with self.telemetry.span("learner/dispatch"):
                    self.state, m = self.train_step(self.state, sub)
                if self._util is not None:
                    self._util.phase("gather", t1 - t0)
                    self._util.phase(
                        "dispatch_inflight", time.perf_counter() - t1
                    )
                self._dispatch_inflight = True
                self._host_step += 1
                self._host_version += 1
        self._submit_health(m)
        if self._tracer is not None:
            self._emit_dispatch_traces()
        return m

    def _emit_dispatch_traces(self) -> None:
        """Terminal hop of the chunk timeline (ISSUE 12): the batch the
        just-issued dispatch consumes carries the records its ``take``
        parked in the buffer — stamp ``dispatch`` and emit them, plus the
        sampled per-dispatch lifecycle event. Host dict appends only;
        caller guards on ``self._tracer``."""
        tracer = self._tracer
        ts = tracing.now()
        if self.buffer is not None:
            for rec in self.buffer.drain_traces():
                rec["hops"].append(["dispatch", ts])
                tracer.emit_chunk(rec)
        if tracer.should_sample():
            tracer.emit("dispatch", step=self._host_step)

    def _next_batch(self, drain_transport: bool = True):
        """The consume side of the prefetch lane: hand back the batch
        staged behind the previous dispatch if there is one, else do the
        (serial) ingest+take now. Dispatch-only either way."""
        batch, self._prefetched = self._prefetched, None
        if batch is not None:
            # consuming the held batch: its ring slots become reusable
            self.buffer.release(self._prefetch_ticket)
            self._prefetch_ticket = None
            self._prefetch_hits += 1
            # overlap_advantage=false stages the batch bare — the pass
            # runs here, at consume time (no-op when already attached)
            return self._attach_advantages(batch)
        t0 = time.perf_counter()
        if drain_transport:
            self.ingest()
        batch = self.buffer.take(current_version=self._host_version)
        dt = time.perf_counter() - t0
        if self._util is not None:
            # a productive take is batch assembly; an empty one is the
            # buffer below min consumable — starvation, not staging
            self._util.phase(
                "gather" if batch is not None else "ingest_wait", dt
            )
        if batch is not None:
            # only productive staging counts toward the overlap accounting
            # — empty polls while starved are idle waiting, not assemble
            # cost (same rule the transport/consume span applies)
            self._prefetch_serial_s += dt
            self._prefetch_misses += 1
            batch = self._attach_advantages(batch)
        return batch

    def _prefetch_next(self, drain_transport: bool = True) -> None:
        """Stage batch N+1 while batch N's optimizer step is still in
        flight: the loop is dispatch-only, so the host returns from
        ``_optimize`` immediately and the transport drain, host-row
        staging, ring scatter, and batch gather issued here all overlap
        the device's epoch-step compute. Single-writer discipline holds —
        this runs on the learner thread, same as every other buffer op."""
        if self._prefetched is not None or self.buffer is None:
            return
        t0 = time.perf_counter()
        if drain_transport:
            self.ingest()
        # hold=True parks the slots: an ingest racing this in-flight
        # batch can neither evict nor overwrite them
        taken = self.buffer.take(
            current_version=self._host_version, hold=True
        )
        if taken is None:
            if self._util is not None:
                self._util.phase(
                    "ingest_wait", time.perf_counter() - t0
                )
            return   # nothing staged: idle waiting, not assemble cost
        self._prefetched, self._prefetch_ticket = taken
        dt = time.perf_counter() - t0
        if self._util is not None:
            self._util.phase("gather", dt)
        # recorded only when a batch was actually staged, like the
        # transport/consume span — empty attempts would dilute both the
        # span stats and the overlap fraction toward meaninglessness
        self.telemetry.timer("span/learner/prefetch").observe(dt)
        if self._dispatch_inflight:
            self._prefetch_overlapped_s += dt
        else:
            self._prefetch_serial_s += dt
        if self._adv_overlap:
            # stage compute, not just bytes (ISSUE 14): batch N+1's
            # advantage pass dispatches behind batch N's in-flight epoch
            # step — device-stream ordering runs it on the step's OUTPUT
            # params, exactly the params the staged batch's first update
            # will train from
            self._prefetched = self._attach_advantages(
                self._prefetched, overlapped=self._dispatch_inflight
            )

    def _flush_prefetch(self) -> None:
        """Return an unconsumed prefetched batch to the ring (front of the
        order) before anything that snapshots or ends the run — prefetching
        must never turn into experience loss. Advantages staged on the
        batch (``_attach_advantages``) die with it: only the ring slots
        survive, so the next take re-runs the pass with whatever params
        are live then — the invariant the divergence rollback leans on."""
        if self._prefetched is not None:
            self.buffer.requeue(self._prefetch_ticket)
            self._prefetched = None
            self._prefetch_ticket = None

    def _attach_advantages(self, batch, overlapped: bool = False):
        """Consume-time advantage plane (ISSUE 14, train/advantage.py):
        run the jitted value-forward + GAE pass over a just-gathered
        batch and attach the narrow ``advantages``/``returns`` leaves the
        epoch step consumes across all E×M updates. Dispatch-only: the
        host enqueues one program (behind the in-flight donated epoch
        step when called from the prefetch lane) and appends two array
        futures to the batch dict — no sync anywhere.

        ``overlapped`` is the CALLER's classification: only the prefetch
        lane stages the pass behind an in-flight dispatch; consume-time
        passes count serial. (``_dispatch_inflight`` alone cannot
        classify — the dispatch-only loop never clears it between
        batches in async-snapshot mode, so it would peg the fraction at
        1.0 even with ``overlap_advantage=false``.)"""
        if (
            self.advantage_pass is None
            or batch is None
            or "advantages" in batch
        ):
            return batch
        t0 = time.perf_counter()
        adv, ret = self.advantage_pass(self.state.params, batch)
        batch = dict(batch)
        batch["advantages"] = adv
        batch["returns"] = ret
        dt = time.perf_counter() - t0
        self.telemetry.gauge("advantage/pass_ms").set(dt * 1e3)
        self.telemetry.counter("advantage/passes_total").inc()
        if self._util is not None:
            self._util.phase("advantage_pass", dt)
        if self._adv_first:
            # the first call pays the pass's XLA compile — steady-state
            # dispatch is sub-ms, so folding seconds of compile into the
            # serial bucket would flatten overlap_fraction to noise
            self._adv_first = False
        elif overlapped:
            self._adv_overlapped_s += dt
        else:
            self._adv_serial_s += dt
        return batch

    def _actor_params_copy(self):
        """Device-to-device copy of the current params for the actor pool:
        the train step donates the state, so actors must never hold the
        learner's own buffers (they die on the next step)."""
        return jax.tree.map(jnp.copy, self.state.params)

    def _pipeline_state(self) -> Dict[str, Any]:
        """Everything beyond the TrainState a restore needs to resume the
        exact pipeline: buffer ring + cursors, and (device mode) the actor's
        full device state — sim worlds, recurrent carries, PRNG, episode
        accumulators — as flat leaves (checkpoint-format-stable regardless
        of the NamedTuple nesting)."""
        # an in-flight prefetched batch goes back to the ring first: the
        # snapshot must carry every unconsumed rollout
        self._flush_prefetch()
        out: Dict[str, Any] = (
            {"buffer": self.buffer.state_dict()} if self.buffer else {}
        )
        if self.device_actor is not None:
            leaves = jax.tree.leaves(jax.device_get(self.device_actor.state))
            out["actor_leaves"] = {f"{i:04d}": leaf for i, leaf in enumerate(leaves)}
        # minibatch-shuffle RNG position: the stream is seeded, so the count
        # of consumed permutations reconstructs it exactly on restore
        out["mb_draws"] = np.asarray(self._mb_draws, np.int64)
        return out

    def _restore_pipeline(self) -> None:
        restored, reason = self.ckpt.restore_pipeline(self._pipeline_state())
        if restored is None:
            if reason:  # mismatch is loud; a pipeline-less checkpoint is not
                print(
                    f"WARNING: checkpoint pipeline state not restored "
                    f"({reason}); resuming weights-only — in-flight "
                    f"experience and actor state are lost",
                    flush=True,
                )
            return
        if self.buffer is not None and "buffer" in restored:
            self.buffer.load_state_dict(restored["buffer"])
        if self.device_actor is not None and "actor_leaves" in restored:
            from dotaclient_tpu.actor.device_rollout import (
                actor_state_sharding,
            )

            treedef = jax.tree.structure(self.device_actor.state)
            state = jax.tree.unflatten(
                treedef,
                [
                    np.asarray(restored["actor_leaves"][k])
                    for k in sorted(restored["actor_leaves"])
                ],
            )
            # re-commit through THIS mesh's lane sharding (ISSUE 18): the
            # saved host leaves are layout-free, so a checkpoint written at
            # a different device count lands partitioned — not replicated —
            # before the first fused dispatch (the train-state analogue is
            # state_shardings re-commit above / in the rollback path)
            self.device_actor.state = jax.device_put(
                state,
                actor_state_sharding(state, self.mesh, self.config.mesh),
            )
        if "mb_draws" in restored:
            # fast-forward the seeded shuffle stream to its saved position
            self._mb_draws = int(np.asarray(restored["mb_draws"]))
            self._mb_rng = np.random.default_rng(self.config.seed + 1)
            for _ in range(self._mb_draws):
                self._mb_rng.permutation(self.config.ppo.batch_rollouts)

    def _submit_health(self, m) -> None:
        """Queue this batch's verdict scalars with the health monitor —
        a host-side append of three device scalars (program outputs, never
        donated); the boundary flush ships the whole backlog to the
        snapshot engine in ONE batched fetch. In sync-snapshots mode the
        boundary metrics fetch folds the verdicts instead (``fold_host``,
        zero extra transfers); the last batch's verdict leaves are kept
        either way so sync checkpoint boundaries and the end-of-run tail
        can close their coverage gap (``_sync_fold_latest``)."""
        if self._health is None:
            return
        from dotaclient_tpu.train.health import VERDICT_KEYS

        self._last_verdict_m = {k: m[k] for k in VERDICT_KEYS if k in m}
        if self._snap_engine is not None:
            self._health.submit(self._host_step, self._host_version, m)

    def _sync_fold_latest(self) -> None:
        """--sync-snapshots gap-closer: verdicts normally fold from the
        log-boundary metrics fetch, but a checkpoint boundary (or the
        end-of-run forced save) that is NOT a log boundary must not mark a
        state ``last_good`` on stale knowledge — fold the LAST batch's
        verdict scalars first (one tiny fetch at checkpoint/tail cadence;
        sync mode stalls by design)."""
        if (
            self._health is None
            or self._snap_engine is not None
            or self._last_verdict_m is None
        ):
            return
        host = jax.device_get(self._last_verdict_m)  # host-sync-ok: sync-snapshots checkpoint/tail cadence, three scalars
        self._health.fold_host(self._host_step, self._host_version, host)

    def _flush_health(self) -> None:
        """Hand every pending verdict to the snapshot engine's
        never-coalesced stats backlog. The engine processes stats jobs
        BEFORE the same cycle's publish/checkpoint jobs, so a publish
        submitted after this flush can only run once every verdict for
        steps ≤ its version has been folded — the ordering that makes the
        publish gate sound."""
        if self._health is None or self._snap_engine is None:
            return
        pending = self._health.take_pending()
        if pending:
            self._snap_engine.submit_stats(pending, self._health.fold_batch)

    def _maybe_rollback(self) -> int:
        """Recover from a latched divergence: restore the last_good
        checkpoint, abandon the poisoned timeline (its checkpoints, its
        buffered experience, its recurrent actor carries), resume with a
        DISTINCT minibatch-RNG stream, and return how many optimizer steps
        were rewound (0 when healthy) so the caller's step budget covers
        the retraining. Bounded by ``health.max_rollbacks``; exhaustion —
        or a run with no checkpoint manager to restore from — exits loudly
        with the runbook pointer (docs/OPERATIONS.md "Failure modes")."""
        if self._health is None or self._health.unhealthy is None:
            return 0
        ev = self._health.unhealthy
        if self.ckpt is None:
            # contain-only degrade: without a checkpoint dir there is no
            # restore point — publishes stay blocked (actors keep the last
            # good version) and the operator is told once, loudly.
            if self._health.note_unrecoverable():
                print(
                    f"WARNING: training health latched unhealthy "
                    f"({ev.reason} at step {ev.step}) but no "
                    f"--checkpoint-dir is configured — cannot roll back; "
                    f"weight publishes stay BLOCKED (see docs/OPERATIONS.md "
                    f"'Failure modes')",
                    flush=True,
                )
            return 0
        runbook = (
            "see docs/OPERATIONS.md 'Failure modes' (divergence runbook): "
            "inspect the batch data and learning rate, consider "
            "--ppo kl_target/max_grad_norm, and restart from "
            "<checkpoint_dir>/last_good"
        )
        # exhaustion check BEFORE counting: the give-up path performs no
        # restore, so it must not inflate health/rollbacks_total
        if self._rollback_count >= self.config.health.max_rollbacks:
            raise RuntimeError(
                f"training health guardian: divergence persisted after "
                f"{self.config.health.max_rollbacks} rollback(s) "
                f"({ev.reason} at step {ev.step}, value {ev.value!r}) — "
                f"giving up; {runbook}"
            )
        self._rollback_count += 1
        self.telemetry.counter("health/rollbacks_total").inc()
        # Drain the engine FIRST, with the monitor still latched: any
        # pending publish/checkpoint job of the poisoned timeline hits the
        # engine-side health gate and is refused — clearing the latch
        # before the drain would let one slip through.
        self._drain_snapshots()
        published_floor = (
            self._snap_engine.last_published
            if self._snap_engine is not None
            else self._published_version
        )
        restored = self.ckpt.restore_last_good(self.config, self.state)
        if restored is None:
            # no verified slot yet (divergence before the first healthy
            # checkpoint): fall back to the newest manifest-valid main
            # save — every main save was itself health-gated
            try:
                restored = self.ckpt.restore(self.config, self.state)
            except (FileNotFoundError, ValueError, RuntimeError) as e:
                raise RuntimeError(
                    f"training health guardian: divergence at step "
                    f"{ev.step} ({ev.reason}) and no restorable checkpoint "
                    f"to roll back to ({type(e).__name__}: {e}) — {runbook}"
                ) from e
        state, _ = restored
        from_step, from_version = self._host_step, self._host_version
        restored_version = int(np.asarray(state.version))  # host-sync-ok: rollback cadence, host-bound restore
        # The VERSION counter stays monotone across the rollback AND skips
        # past the poisoned range entirely: the restored state resumes at
        # from_version + 1, so every version the poisoned steps produced —
        # (restored_version, from_version] — is never reused on the wire
        # and "no actor ever applied a poisoned version" becomes a
        # checkable set invariant (chaos divergence scenario); the
        # engine's monotonic-publish floor needs no rewind. Steps DO
        # rewind (the retraining re-earns them); step and version diverge
        # from here on, which nothing downstream assumes away.
        resumed_version = from_version + 1
        # re-commit to the mesh (restores return host-layout arrays; the
        # next donated step expects its state_shardings — same re-shard
        # the constructor applies)
        self.state = jax.device_put(
            dataclasses.replace(
                state, version=jnp.asarray(resumed_version, jnp.int32)
            ),
            self.state_shardings,
        )
        self._host_step = int(np.asarray(state.step))      # host-sync-ok: rollback cadence
        self._host_version = resumed_version
        rewound = from_step - self._host_step
        # the abandoned timeline's saves must not be restorable (and the
        # retrained timeline re-reaches their step numbers)
        self.ckpt.discard_steps_above(self._host_step)
        # experience produced by the poisoned policy is dropped (slots
        # tagged with a version inside the poisoned range); the prefetch
        # lane is flushed first so held slots fold back in — and with it
        # die any STAGED ADVANTAGES computed by the poisoned params (they
        # ride the flushed batch dict, never the ring): the retrained
        # timeline's takes re-run the pass with the restored params,
        # pinned by tests/test_advantage.py
        if self.buffer is not None:
            self._flush_prefetch()
            self.buffer.drop_newer_than(restored_version)
        # recurrent carries computed by poisoned params must not leak into
        # the restored run (the sim worlds themselves stay finite)
        if self.device_actor is not None:
            self.device_actor.reset_recurrent()
        elif self.pool is not None and hasattr(self.pool, "set_params"):
            self.pool.set_params(self._actor_params_copy(), self._host_version)
        # distinct RNG resume: the retry must not replay the exact
        # minibatch permutation stream that diverged
        self._mb_rng = np.random.default_rng(
            self.config.seed + 1 + 7919 * self._rollback_count
        )
        self._mb_draws = 0
        # the poisoned batch's verdict scalars must not be re-folded into
        # the cleared monitor by the next sync-mode boundary (fold_host
        # folds with the CURRENT generation — clear() alone doesn't shield)
        self._last_verdict_m = None
        self._health.clear()
        self.telemetry.gauge("health/last_good_step").set(
            float(self._host_step)   # host-sync-ok: host int mirror
        )
        # machine-readable audit line (scripts/chaos_run.py divergence
        # scenario): published_floor ≤ to_version proves no poisoned
        # version ever reached the actor fleet
        print(
            "HEALTH_ROLLBACK " + json.dumps(
                {
                    "reason": ev.reason,
                    "detected_step": ev.step,
                    # the first version the flagged update produced: the
                    # POISONED range is [detected_version, resumed_version)
                    # — versions between the restore point and detection
                    # were produced by verdict-clean steps and may have
                    # been legitimately published before the latch
                    "detected_version": ev.version,
                    "from_step": from_step,
                    "to_step": self._host_step,
                    "restored_version": restored_version,
                    "resumed_version": resumed_version,
                    "published_floor": published_floor,
                    "rollback": self._rollback_count,
                },
                sort_keys=True,
            ),
            flush=True,
        )
        return rewound

    def _push_pool_params(self, params) -> None:
        """In-process weight refresh (``pool.set_params``) behind the same
        health gate as the transport publish paths: a latched-unhealthy
        monitor blocks the push (counted in ``health/publish_blocked_total``)
        so in-proc actors keep serving the last good params too — the
        contain promise must hold whether actors are across a wire or in
        this process. The rollback path pushes restored params directly
        (the monitor is cleared by then)."""
        if self._health is not None:
            if self._snap_engine is None:
                self._sync_fold_latest()
            if self._health.unhealthy is not None:
                self.telemetry.counter("health/publish_blocked_total").inc()
                return
        self.pool.set_params(params, self._host_version)

    def _publish_weights(self) -> None:
        """Hand the current params to the weights fanout (call at refresh
        cadence, not per step). Async (the default): one jitted on-device
        copy into fresh HBM snapshot buffers, then the snapshot thread does
        the device→host fetch, the ``transport.wire_dtype`` cast + encode,
        and the non-blocking fanout enqueue — the train thread never waits
        on the host. Sync (``--sync-snapshots``): everything inline, with
        ONE batched fetch inside :func:`encode_weights`. Either way the
        fanout itself never blocks on a stalled actor (socket_transport.py),
        and a latched-unhealthy monitor blocks the publish entirely — the
        contain stage of the health guardian (ISSUE 6): actors keep
        serving the last good version."""
        t0 = time.perf_counter()
        if self._snap_engine is not None:
            # verdicts for every step ≤ this version reach the engine
            # before the publish job (stats-before-jobs ordering): the
            # engine-side gate sees a current latch, never a stale one
            self._flush_health()
            self._snap_engine.submit_publish(
                self._snap_copy(self.state.params), self._host_version
            )
        else:
            # sync mode folds verdicts at LOG cadence, but the gate below
            # must see the last batch's verdict even when the refresh
            # boundary isn't a log boundary — same gap-closer the sync
            # checkpoint branch uses (a poisoned publish is exactly the
            # fanout this gate exists to stop)
            self._sync_fold_latest()
            if self._health is not None and self._health.unhealthy is not None:
                self.telemetry.counter("health/publish_blocked_total").inc()
            else:
                trace_blob = None
                if self._tracer is not None:
                    rec = tracing.weights_record(self._host_version)
                    trace_blob = tracing.record_to_blob(rec, pad=False)
                    self._tracer.emit(
                        "publish", version=self._host_version
                    )
                with self.telemetry.span("transport/publish_weights"):
                    self.transport.publish_weights(
                        encode_weights(
                            self.state.params,   # one batched fetch inside
                            self._host_version,
                            wire_dtype=self.config.transport.wire_dtype,
                            trace=trace_blob,
                        )
                    )
                self._published_version = max(
                    self._published_version, self._host_version
                )
        stall = time.perf_counter() - t0
        self._stall_s += stall
        if self._util is not None:
            self._util.phase("publish_stall", stall)
        self.telemetry.gauge("learner/publish_stall_ms").set(stall * 1e3)

    def _drain_snapshots(self) -> None:
        """Wait out the snapshot thread (graceful stop / end-of-run tail /
        crash rescue): pending publishes reach the wire and pending async
        saves land BEFORE the forced sync checkpoint, so the final save
        still lands at the exact stop step with no writer overlap. Applies
        any best-model save the async metrics path deferred to this
        thread."""
        if self._snap_engine is None:
            return
        if not self._snap_engine.drain(
            timeout=self.config.learner.snapshot_drain_timeout_s
        ):
            print(
                "WARNING: snapshot engine did not drain within "
                f"{self.config.learner.snapshot_drain_timeout_s:.0f}s — "
                "proceeding with the forced sync checkpoint (its error, "
                "if any, will be the loud one)",
                flush=True,
            )
        self._apply_pending_best()

    def _apply_pending_best(self) -> None:
        """Consume the best-model candidate the async metrics continuation
        deferred to this thread (atomic swap — a concurrent write from the
        snapshot thread must never be lost)."""
        with self._pending_best_lock:
            best, self._pending_best = self._pending_best, None
        if best is not None:
            self._maybe_save_best(best)

    def _league_opponent(self):
        """Snapshot-if-due and return the current frozen opponent for the
        device/fused loops → (params | None, snapshot uid). Draws are HELD
        for ``league.opponent_hold`` optimizer steps: episodes span many
        chunks, so holding keeps (most of) each episode against one
        opponent — without it the per-chunk outcome attribution PFSP feeds
        on dilutes toward the pool average. Residual bias: episodes that
        straddle a redraw credit their final opponent."""
        if self.league is None:
            return None, league_pool.LIVE
        self.league.maybe_snapshot(
            self.state.params, self._host_version, self._host_step
        )
        if (
            self._held_opponent is None
            or self._host_step >= self._held_until
        ):
            params, _, uid = self.league.sample_indexed(
                self.state.params, self._host_version
            )
            # LIVE draws are never cached: the buffered path donates the
            # train state every step, so held live params would be dead
            # buffers by the next iteration — re-resolve them per call.
            self._held_opponent = (
                None if uid == league_pool.LIVE else params, uid
            )
            self._held_until = (
                self._host_step + self.config.league.opponent_hold
            )
        params, uid = self._held_opponent
        if uid == league_pool.LIVE:
            params = self.state.params
        return params, uid

    def _report_league(self, idx: int, chunk_stats) -> None:
        """Queue one chunk's (device-resident) episode outcomes against the
        snapshot that produced them; resolved in batches at log boundaries
        so the hot loop never syncs."""
        if self.league is None or idx == league_pool.LIVE:
            return
        self._league_pending.append((idx, chunk_stats))
        if len(self._league_pending) >= 64:
            self._flush_league_reports()

    def _flush_league_reports(self) -> None:
        if not self._league_pending:
            return
        pending, self._league_pending = self._league_pending, []
        fetched = jax.device_get([st for _, st in pending])  # one sync
        for (idx, _), st in zip(pending, fetched):
            # anchor games (scripted-bot opponents) are excluded from the
            # snapshot's PFSP record — it never played them. Chunk stats
            # are per-game partials (ISSUE 18) — fold the game axis here.
            self.league.report(
                idx,
                float(np.sum(st.get("league_wins", st["wins"]))),
                float(np.sum(st.get("league_episodes", st["episodes"]))),
            )

    def _refresh_league_opponent(self) -> None:
        """Snapshot-if-due and re-draw the frozen opponent (host-pool modes;
        the device actor samples per collect instead)."""
        if self.league is None or self.device_actor is not None:
            return
        self.league.maybe_snapshot(
            self.state.params, self._host_version, self._host_step
        )
        params, version = self.league.sample(
            self._actor_params_copy(), self._host_version
        )
        self.pool.set_opponent(params, version)

    def _maybe_save_best(self, scalars: Dict[str, float]) -> None:
        """Best-model rotation: save weights to ``<checkpoint_dir>/best``
        when the windowed win-rate beats the best seen, with the
        ``checkpoint_best_min_episodes`` noise guard (RunConfig comment)."""
        if self._best_dir is None:
            return
        wr = scalars.get("win_rate_recent")
        eps = scalars.get("episodes_recent", 0.0)
        if (
            wr is None
            or eps < self.config.checkpoint_best_min_episodes
            or wr <= self._best_win
        ):
            return
        if self.ckpt_best is None:
            self.ckpt_best = CheckpointManager(self._best_dir, max_to_keep=1)
        # An orbax-declined save (resumed run whose step counter sits below
        # the captured peak's step) must not advance the best marker.
        if self.ckpt_best.save(self.state, self.config):
            self._best_win = wr
            # temp+rename: the orbax save is atomic, the sidecar must be
            # too — a kill mid-write would otherwise reset the marker on
            # resume and let a collapsed window rotate out the peak.
            meta = os.path.join(self._best_dir, "best_meta.json")
            tmp = meta + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {"win_rate_recent": wr, "step": int(self.state.step)}, f
                )
            os.replace(tmp, meta)

    def _make_metrics_finish(
        self,
        step: int,
        host_extra: Dict[str, float],
        stats_source,
    ):
        """Build the host-side continuation of one async log boundary. It
        runs ON the snapshot thread after that thread's one batched fetch
        of the train metrics dict and must never touch ``self.state``
        (in-flight dispatches donate its buffers) — a qualifying best-model
        save is deferred to the train thread via ``_pending_best`` instead.
        ``stats_source`` is a HOST-ONLY callable (the actor's ``stats()``)
        — the actual device stat drain rides the engine's never-coalesced
        ``submit_stats`` backlog, so a coalesced log line can never lose an
        episode window."""
        # captured HERE, on the train thread: _best_win is train-owned
        # (lint/ownership.py) and reading it from the snapshot thread was
        # an unsynchronized race — the submit-time value is also the more
        # honest log field (the save that could move it is itself deferred
        # back to the train thread and lands after this boundary)
        best_win = self._best_win

        def _finish_metrics(host) -> None:
            scalars = {k: float(v) for k, v in host["m"].items()}   # host-sync-ok: snapshot thread, fetched host arrays
            if stats_source is not None:
                # host-only read: every stat drain submitted up to this
                # boundary was folded by the engine BEFORE this job ran
                # (submit_stats ordering), so the accumulators are current
                scalars.update(stats_source())
            # outcome curves (ISSUE 15): tick AFTER the stat drain above
            # folded this window's episodes into the outcome counters, so
            # the line logged below carries curves consistent with its
            # own counters (tick is lock-guarded — safe on this thread)
            if not self._fleet_started:
                self.outcome.tick()
            scalars.update(host_extra)
            if self._best_dir is not None:
                # the save itself happens on the train thread at the next
                # boundary (or the end-of-run drain) — see _drain_snapshots
                with self._pending_best_lock:
                    self._pending_best = dict(scalars)
                scalars["best_win_rate"] = best_win
            # lint-ok: thread-ownership(handoff, not shared state: train()
            # reads _last_metrics only after the _drain_snapshots barrier
            # has joined every pending engine job)
            self._last_metrics = self.metrics.log(step, scalars)

        return _finish_metrics

    def _publish_pipeline_gauges(self) -> None:
        """Refresh the cross-stage gauges at a log boundary: actor weight
        staleness (host version mirror minus the actor pool's in-use
        version — 0 for the on-policy device/fused paths, which have no
        separate actor copy) and the transport's experience-queue depth.
        Host integers only — no device traffic."""
        pool_version = getattr(self.pool, "version", None)
        self.telemetry.gauge("actor/weight_staleness").set(
            float(self._host_version - pool_version)
            if pool_version is not None
            else 0.0
        )
        pending = getattr(self.transport, "pending_rollouts", None)
        if pending is not None:
            # absent attribute ≠ empty queue: a transport that can't report
            # its backlog must not masquerade as a healthy one
            self.telemetry.gauge("transport/queue_depth").set(float(pending))
        # Prefetch-lane health: hit rate (batches served from the lane /
        # batches served at all) and overlap fraction (prefetch host time
        # spent while a dispatch was in flight / all prefetch host time) —
        # the proof the data path actually pipelines.
        served = self._prefetch_hits + self._prefetch_misses
        if served:
            self.telemetry.gauge("learner/prefetch_hit_rate").set(
                self._prefetch_hits / served
            )
        staged = self._prefetch_overlapped_s + self._prefetch_serial_s
        if staged > 0:
            self.telemetry.gauge("learner/overlap_fraction").set(
                self._prefetch_overlapped_s / staged
            )
        # advantage-plane overlap (ISSUE 14): pass host time staged from
        # the prefetch lane behind an in-flight dispatch / all pass host
        # time (consume-time passes count serial) — the proof the
        # compute stage pipelines, reported next to the byte-staging one
        adv_staged = self._adv_overlapped_s + self._adv_serial_s
        if adv_staged > 0:
            self.telemetry.gauge("advantage/overlap_fraction").set(
                self._adv_overlapped_s / adv_staged
            )
        # device-memory watermark (ISSUE 12): host-only allocator metadata,
        # refreshed at log cadence; CPU backends report none → stays 0
        tracing.update_memory_gauges(self.telemetry)
        # outcome curves (ISSUE 15): in-process modes tick the windowed
        # aggregation at log cadence (host counter arithmetic only);
        # external modes tick from the fleet aggregator thread instead.
        # This tick keeps the tail/log_files_only snapshot fresh; the
        # boundary-cadence ticks that feed the JSONL curves run AFTER the
        # stats drain folds the window's episodes (the async metrics
        # continuation / the sync branch) — ticking only here would lag
        # the device/fused curves one full boundary behind the counters
        # logged on the same line (review finding).
        if not self._fleet_started:
            self.outcome.tick()
        # utilization fold (ISSUE 16): close the accounting window at the
        # same host-sync boundary — host arithmetic only, arms
        # util/duty_cycle and advances the steps/s EMA + the
        # warmup-armed baseline the throughput sentinel compares against
        if self._util is not None:
            self._util.fold(self._host_step)

    def train(
        self,
        num_steps: int,
        actor_steps_per_iter: Optional[int] = None,
        overlap: bool = False,
        refresh_every: int = 1,
    ) -> Dict[str, float]:
        """Run until ``num_steps`` optimizer steps have completed.

        ``overlap=False``: strictly alternating actor/learner phases
        (deterministic; the test path). ``overlap=True``: the actor pool runs
        in its own thread feeding the transport while this thread trains —
        the staleness filter and version tags do real work here.
        """
        cfg = self.config
        epochs = self._steps_per_batch
        # host-visible counter stride per loop iteration: fused dispatch
        # batching advances K×epochs steps per call, so the log/checkpoint
        # boundary windows must widen with it or boundaries get stepped over
        stride = epochs * (
            cfg.steps_per_dispatch if self.fused_step is not None else 1
        )
        actor_steps = actor_steps_per_iter or cfg.ppo.rollout_len
        t_start = time.time()
        frames_trained = 0
        steps_done = 0
        self._stall_s = 0.0   # per-call: stall_fraction is per train() call
        # Mid-run weights publish for the device/fused loops (ISSUE 5):
        # they never refresh an in-process pool, so consumers on a real
        # transport (same-host eval actors on the shm lane, socket
        # listeners) would only ever see the end-of-run weights. In-proc
        # transports skip it — nobody is listening.
        publish_midrun = self.device_actor is not None and not isinstance(
            self.transport, InProcTransport
        )

        def after_step(m, frames: Optional[int] = None) -> int:
            """Boundary side effects for one loop iteration. Returns the
            number of optimizer steps a divergence rollback rewound (0 on
            the healthy path) — callers subtract it from their step budget
            so the run still completes to its target step."""
            nonlocal frames_trained
            frames_trained += (
                frames
                if frames is not None
                else cfg.ppo.batch_rollouts * cfg.ppo.rollout_len
            )
            step = self._host_step
            if step % cfg.log_every < stride or (
                self.ckpt and step % cfg.checkpoint_every < stride
            ):
                # ship pending health verdicts ahead of this boundary's
                # jobs (one batched fetch on the snapshot thread); the
                # publish branch flushes inside _publish_weights itself
                self._flush_health()
            if step % cfg.log_every < stride:
                t0 = time.perf_counter()
                # a best-model save the async metrics continuation deferred
                # here: self.state must never be read from the snapshot
                # thread — in-flight dispatches donate its buffers
                self._apply_pending_best()
                host_extra: Dict[str, float] = {}
                if self.league is not None:
                    self._flush_league_reports()
                    wrs = self.league.win_rates()
                    host_extra["league_snapshots"] = float(len(wrs))   # host-sync-ok: host ints
                    if wrs:
                        host_extra["league_winrate_mean"] = float(np.mean(wrs))   # host-sync-ok: host floats
                if self.buffer is not None:
                    host_extra.update(self.buffer.metrics())
                elapsed = time.time() - t_start
                host_extra["frames_per_sec"] = frames_trained / max(elapsed, 1e-9)
                self._publish_pipeline_gauges()
                if self._snap_engine is not None:
                    # async (default): the device values leave through the
                    # snapshot thread's batched fetches; this thread only
                    # dispatches the tiny stats copy and keeps training.
                    # The stat drain rides the never-coalesced backlog (its
                    # accumulators were just reset — dropping it would lose
                    # the window); the log job itself is latest-wins.
                    stats_source = None
                    if self.device_actor is not None:
                        s_dev, s_fin = self.device_actor.begin_drain()
                        self._snap_engine.submit_stats(s_dev, s_fin)
                        stats_source = self.device_actor.stats
                    elif self.pool is not None:
                        # host pools: windowed stats are host floats already
                        host_extra.update(self.pool.drain_stats())
                    self._snap_engine.submit_metrics(
                        {"m": m},
                        self._make_metrics_finish(
                            step, host_extra, stats_source
                        ),
                    )
                else:
                    # sync-snapshots mode: ONE transfer for the whole
                    # metrics dict — the only host↔device sync this loop
                    # performs (spans and gauges above are host values).
                    with self.telemetry.span("learner/metrics_fetch"):
                        scalars = {
                            k: float(v) for k, v in jax.device_get(m).items()   # host-sync-ok: log_every boundary (sync-snapshots mode)
                        }
                        if self.device_actor is not None:
                            scalars.update(self.device_actor.drain_stats())
                        elif self.pool is not None:
                            scalars.update(self.pool.drain_stats())
                    # the fetch blocked on the dispatched step — overlap
                    # window for prefetch accounting closes here
                    self._dispatch_inflight = False
                    if self._health is not None:
                        # sync-mode health verdicts fold from the boundary
                        # scalars just fetched — zero extra transfers,
                        # detection at log cadence
                        self._health.fold_host(
                            step, self._host_version, scalars
                        )
                    scalars.update(host_extra)
                    self._maybe_save_best(scalars)
                    if self._best_dir is not None:
                        scalars["best_win_rate"] = self._best_win
                    # outcome curves (ISSUE 15): tick after the drain
                    # above folded this window's episodes — same-line
                    # consistency as the async continuation
                    if not self._fleet_started:
                        self.outcome.tick()
                    self._last_metrics = self.metrics.log(step, scalars)
                self._stall_s += time.perf_counter() - t0
                self.telemetry.gauge("learner/stall_fraction").set(
                    self._stall_s / max(elapsed, 1e-9)
                )
            # `< stride` (not `== 0`): the counter advances in strides of
            # epochs_per_batch × steps_per_dispatch, which may step over
            # exact multiples.
            if self.ckpt and step % cfg.checkpoint_every < stride:
                # periodic saves are weights-only: the pipeline extras cost a
                # full buffer+actor device fetch (review finding — on the
                # tunneled link that stalls the loop for seconds); the forced
                # end-of-run save below captures the complete pipeline
                t0 = time.perf_counter()
                if self._snap_engine is not None:
                    # one cheap on-device copy of the WHOLE TrainState; the
                    # snapshot thread fetches it (one transfer), health-
                    # gates it (verdicts ≤ this step land first — flushed
                    # above), and writes
                    self._snap_engine.submit_checkpoint(
                        self._snap_copy(self.state), cfg
                    )
                else:
                    # sync mode: log-boundary folds may not cover THIS
                    # step (checkpoint_every and log_every need not align)
                    # — fold the latest verdict before gating, or a
                    # poisoned state could earn the last_good mark
                    self._sync_fold_latest()
                    if (
                        self._health is not None
                        and self._health.unhealthy is not None
                    ):
                        # contain (sync mode): a poisoned state never
                        # enters the rolling retention
                        self.telemetry.counter(
                            "health/checkpoints_blocked_total"
                        ).inc()
                    else:
                        self.ckpt.save(
                            self.state, cfg,
                            mark_good=self._health is not None,
                        )
                ckpt_dt = time.perf_counter() - t0
                self._stall_s += ckpt_dt
                if self._util is not None:
                    self._util.phase("checkpoint_stall", ckpt_dt)
            if (
                publish_midrun
                and refresh_every
                and step % (refresh_every * stride) < stride
            ):
                self._publish_weights()
            return self._maybe_rollback()

        def _run_mode_loop() -> None:
            """One pass of the mode-specific training loop, until
            ``steps_done`` reaches ``num_steps`` or a stop is
            requested. Factored so the tail's divergence-rollback
            check (ISSUE 6) can re-enter it: a health verdict that
            folds only after the loop hits its target must still be
            able to roll back AND retrain to the exact target step."""
            nonlocal steps_done
            if self.fused_step is not None:
                # Fused mode: rollout + update is ONE program; each dispatch
                # runs steps_per_dispatch iterations of epochs_per_batch
                # optimizer steps (train/fused.py). Train batch = the lane set.
                da = self.device_actor
                k_iters = cfg.steps_per_dispatch
                frames_per = da.n_lanes * cfg.ppo.rollout_len * k_iters
                while steps_done < num_steps and not self._stop_requested:
                    opp_params, opp_idx = self._league_opponent()
                    if opp_params is None:       # self-play / scripted: one
                        opp_params = self.state.params   # signature for all modes
                    t0 = time.perf_counter()
                    self.state, da.state, m, chunk_stats = self.fused_step(
                        self.state, da.state, opp_params
                    )
                    if self._util is not None:
                        self._util.phase(
                            "dispatch_inflight", time.perf_counter() - t0
                        )
                    self._report_league(opp_idx, chunk_stats)
                    # the program ran `stride` optimizer steps over K chunks —
                    # keep the host mirrors in lockstep with the device counters
                    self._host_step += stride
                    self._host_version += stride
                    da.env_steps += frames_per
                    da.rollouts_shipped += da.n_lanes * k_iters
                    self._submit_health(m)
                    if self._tracer is not None:
                        self._emit_dispatch_traces()
                    steps_done += stride
                    steps_done -= after_step(m, frames=frames_per)
            elif self.device_actor is not None:
                # On-device rollout mode: collect→ingest→train is all dispatch
                # (the device serializes rollout and train programs back-to-back,
                # so a host thread would add nothing; `overlap` is a no-op here).
                # The prefetch lane still earns its keep: batch N+1's gather is
                # issued behind batch N's epoch step, so the host-side take/
                # bookkeeping cost never sits between two dispatches.
                da = self.device_actor
                while steps_done < num_steps and not self._stop_requested:
                    opp_params, opp_idx = self._league_opponent()
                    chunk, chunk_stats = da.collect(
                        self.state.params, opp_params=opp_params
                    )
                    self._report_league(opp_idx, chunk_stats)
                    self.buffer.add_device(chunk, self._host_version)
                    while (
                        batch := self._next_batch(drain_transport=False)
                    ) is not None:
                        m = self._optimize(batch)
                        if steps_done + epochs < num_steps:
                            # there is a next step to feed; a batch staged
                            # behind the FINAL dispatch could never be consumed
                            # and would only be requeued at the flush below
                            self._prefetch_next(drain_transport=False)
                        steps_done += epochs
                        steps_done -= after_step(m)
                        if steps_done >= num_steps or self._stop_requested:
                            break
            elif self.actor_mode == "external":
                # Experience arrives from standalone actor processes over the
                # transport; this loop only trains and publishes weights. The
                # transport drain + host-row staging + scatter + gather for
                # batch N+1 run behind batch N's dispatched step (prefetch).
                self._publish_weights()
                while steps_done < num_steps and not self._stop_requested:
                    batch = self._next_batch()
                    if batch is None:
                        time.sleep(0.005)
                        if self._util is not None:
                            self._util.phase("ingest_wait", 0.005)
                        continue
                    m = self._optimize(batch)
                    if steps_done + epochs < num_steps:   # see device loop
                        self._prefetch_next()
                    steps_done += epochs
                    steps_done -= after_step(m)
                    if refresh_every and (steps_done // epochs) % refresh_every == 0:
                        self._publish_weights()
            elif overlap:
                stop = threading.Event()
                actor_error: List[BaseException] = []

                def actor_loop() -> None:
                    try:
                        while not stop.is_set():
                            self.pool.step()
                    except BaseException as e:  # surface, never swallow
                        actor_error.append(e)

                self.pool.set_params(self._actor_params_copy(), self._host_version)
                actor_thread = threading.Thread(
                    target=actor_loop, name="actor", daemon=True
                )
                actor_thread.start()
                try:
                    while steps_done < num_steps and not self._stop_requested:
                        if actor_error:
                            raise RuntimeError(
                                "actor thread died; learner cannot make progress"
                            ) from actor_error[0]
                        batch = self._next_batch()
                        if batch is None:
                            time.sleep(0.002)
                            if self._util is not None:
                                self._util.phase("ingest_wait", 0.002)
                            continue
                        m = self._optimize(batch)
                        if steps_done + epochs < num_steps:   # see device loop
                            self._prefetch_next()
                        steps_done += epochs
                        steps_done -= after_step(m)
                        if refresh_every and (steps_done // epochs) % refresh_every == 0:
                            self._push_pool_params(self._actor_params_copy())
                            self._refresh_league_opponent()
                finally:
                    stop.set()
                    actor_thread.join(timeout=30.0)
            else:
                while steps_done < num_steps and not self._stop_requested:
                    # Actor phase: generate experience with the current weights.
                    self._push_pool_params(self.state.params)
                    self._refresh_league_opponent()
                    self.pool.run(actor_steps, refresh_every=0)
                    self.ingest()
                    # Learner phase: drain full batches; each iteration stages
                    # the next batch behind the in-flight dispatch.
                    while (batch := self._next_batch()) is not None:
                        m = self._optimize(batch)
                        if steps_done + epochs < num_steps:   # see device loop
                            self._prefetch_next()
                        steps_done += epochs
                        steps_done -= after_step(m)
                        if steps_done >= num_steps or self._stop_requested:
                            break
        _run_mode_loop()
        while True:
            # End-of-call prefetch flush: a batch staged behind the final
            # dispatch was never trained on — return it to the ring so the
            # final checkpoint (and the next train() call) see it.
            if self.buffer is not None:
                self._flush_prefetch()
            self._dispatch_inflight = False
            # Async boundary jobs still in flight must land before the tail
            # reads/mutates the shared stats below (and any deferred
            # best-model save applies); the snapshot thread is idle
            # afterwards. Pending health verdicts flush first so the
            # tail's publish/save gates see the final steps' verdicts.
            self._flush_health()
            self._drain_snapshots()
            # Tail rollback check (ISSUE 6): on a fast run the engine can
            # fold the poisoned verdict only AFTER the loop hit its step
            # target — containment already held (the gates were latched
            # before anything left the learner), but the run must not be
            # SEALED on poisoned params: roll back and re-enter the loop
            # so it still completes to the exact target step. Bounded by
            # health.max_rollbacks like every rollback.
            rewound = self._maybe_rollback()
            if not rewound or self._stop_requested:
                break
            steps_done -= rewound
            _run_mode_loop()
        if self.device_actor is not None:
            # End-of-call drain: the windowed stats cover this train() call
            # (the demo's block cadence) — the second best-model hook, so
            # peak capture works even when log_every never fires mid-call.
            self._maybe_save_best(self.device_actor.drain_stats())
        elif self.pool is not None:
            self._maybe_save_best(self.pool.drain_stats())
        if self.league is not None:
            self._flush_league_reports()
        # Publish final weights for out-of-process actors (cluster parity);
        # drain so they reach the wire before the caller closes transports.
        self._publish_weights()
        self._drain_snapshots()
        if self.ckpt:
            # The forced end-of-run/drain save stays SYNC (the snapshot
            # thread is drained and idle): it lands at the EXACT stop step
            # and an I/O failure here raises loudly (ISSUE 4 policy). It is
            # NEVER health-blocked — exact-step resume outranks hygiene —
            # but only a verdict-clean state earns the last_good mark (a
            # divergence detected in the final steps restores through the
            # guardian on the next --restore instead). Sync mode folds the
            # final batch's verdict first — its last log boundary may
            # predate the final steps.
            self._sync_fold_latest()
            self.ckpt.save(
                self.state, cfg, force=True,
                pipeline=self._pipeline_state(),
                mark_good=(
                    self._health is not None
                    and self._health.unhealthy is None
                ),
            )
            self.ckpt.wait()
        elapsed = time.time() - t_start
        actor_stats = self.pool.stats() if self.pool is not None else {}
        out = {
            **self._last_metrics,
            **{f"actor_{k}": v for k, v in actor_stats.items()},
            # Fresh end-of-run figures last so they win over logged snapshots.
            "optimizer_steps": float(steps_done),     # host-sync-ok: host ints
            "frames_trained": float(frames_trained),  # host-sync-ok: host ints
            "frames_per_sec": frames_trained / max(elapsed, 1e-9),
            "elapsed_sec": elapsed,
        }
        self._publish_pipeline_gauges()
        # Close the machine-readable record with a final full snapshot (the
        # end-of-run publish/checkpoint spans land here); console is spared.
        self.metrics.log_files_only(self._host_step, out)
        return out


def main(argv=None) -> Dict[str, float]:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--smoke", action="store_true", help="tiny fast config")
    p.add_argument("--logdir", type=str, default=None)
    p.add_argument(
        "--metrics-jsonl", type=str, default=None, metavar="PATH",
        help="append every log-boundary metrics snapshot (training scalars "
        "+ pipeline telemetry: per-stage spans, queue depth, staleness, "
        "buffer occupancy) as JSON lines to PATH — the headless/bench "
        "record; schema in docs/ARCHITECTURE.md 'Observability'",
    )
    p.add_argument("--checkpoint-dir", type=str, default=None)
    p.add_argument(
        "--checkpoint-every", type=int, default=None,
        help="optimizer steps between periodic checkpoints (default "
        "RunConfig.checkpoint_every); the chaos divergence scenario "
        "tightens this so a last_good restore point exists early",
    )
    p.add_argument("--restore", action="store_true")
    p.add_argument("--init-from", type=str, default=None, metavar="DIR",
                   help="seed a fresh run with the params of the latest "
                   "checkpoint in DIR (source stays untouched; mutually "
                   "exclusive with --restore)")
    p.add_argument("--n-envs", type=int, default=None)
    p.add_argument("--opponent", type=str, default=None)
    p.add_argument("--team-size", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--core", type=str, default=None,
                   choices=("lstm", "transformer"),
                   help="policy core: nn.scan LSTM(128) (reference parity, "
                   "default) or the GTrXL-gated windowed-attention "
                   "transformer (scale-out option)")
    p.add_argument("--moe-experts", type=int, default=None,
                   help="with --core transformer: experts per MoE FFN "
                   "layer (0 = dense FFN)")
    p.add_argument(
        "--ppo", type=str, default=None, metavar="K=V,...",
        help="comma-separated PPOConfig overrides, e.g. "
        "'learning_rate=1e-4,entropy_coef=0.001,anchor_kl_coef=0.05'",
    )
    p.add_argument(
        "--reward", type=str, default=None, metavar="K=V,...",
        help="comma-separated RewardConfig overrides, e.g. "
        "'win=25,tower_damage=20'",
    )
    p.add_argument(
        "--league", type=str, default=None, metavar="K=V,...",
        help="comma-separated LeagueConfig overrides (with --opponent "
        "league), e.g. 'anchor_prob=0.25,snapshot_every=200'",
    )
    p.add_argument(
        "--buffer", type=str, default=None, metavar="K=V,...",
        help="comma-separated BufferConfig overrides, e.g. "
        "'capacity_rollouts=64,min_fill=8'",
    )
    p.add_argument(
        "--health", type=str, default=None, metavar="K=V,...",
        help="comma-separated HealthConfig overrides (training health "
        "guardian, ISSUE 6), e.g. 'explosion_band=50,max_rollbacks=2' or "
        "'enabled=false'",
    )
    p.add_argument(
        "--learner", type=str, default=None, metavar="K=V,...",
        help="comma-separated LearnerConfig overrides (snapshot-engine "
        "knobs, ISSUE 5), e.g. 'snapshot_drain_timeout_s=120' or "
        "'async_snapshots=false' (the long form of --sync-snapshots)",
    )
    p.add_argument(
        "--mesh", type=str, default=None, metavar="K=V,...",
        help="comma-separated MeshConfig overrides (device-mesh layout, "
        "ISSUE 10), e.g. 'data_parallel=4,model_parallel=2' or "
        "'dcn_slices=2'; data_parallel=-1 (default) takes every remaining "
        "device. --model-parallel/--dcn-slices are shorthands for the "
        "same fields; an explicit layout smaller than the visible device "
        "set uses the first dcn×data×model devices (a 1-device mesh is "
        "the degenerate case of the one sharded code path)",
    )
    p.add_argument(
        "--serve", type=str, default=None, metavar="K=V,...",
        help="comma-separated ServeConfig overrides (policy-serving "
        "plane, ISSUE 11), e.g. 'batch_window_ms=4,max_batch=128'. The "
        "learner itself never serves — the knobs ride the config tree "
        "into checkpoints, so a serve server restored from this run "
        "(`python -m dotaclient_tpu.serve --checkpoint DIR`) starts with "
        "them; its own --serve flag overrides at serve time",
    )
    p.add_argument(
        "--sync-snapshots", action="store_true",
        help="debug opt-out of the async snapshot engine (ISSUE 5): run "
        "the weights publish, periodic checkpoints, and log-boundary "
        "metrics fetch inline on the train thread (stalling it) instead "
        "of on the background snapshot thread",
    )
    p.add_argument(
        "--on-crash-checkpoint", action="store_true",
        help="on an unexpected exception, attempt a best-effort weights-"
        "only checkpoint before re-raising (needs --checkpoint-dir); the "
        "graceful path — SIGTERM/SIGINT — always drains and saves the full "
        "pipeline regardless of this flag",
    )
    p.add_argument(
        "--steps-per-dispatch", type=int, default=None,
        help="with --actor fused: scan this many rollout+update iterations "
        "inside the one compiled program per host dispatch (amortizes the "
        "host-device round trip; host-side cadences coarsen to this stride)",
    )
    p.add_argument(
        "--overlap", action="store_true",
        help="run the actor pool in a background thread (async actor-learner)",
    )
    p.add_argument(
        "--no-vec", action="store_true",
        help="use the scalar (proto/gRPC-parity) actor pool instead of the "
        "vectorized sim",
    )
    p.add_argument(
        "--actor", type=str, default=None,
        choices=("device", "fused", "vec", "scalar", "external"),
        help="actor implementation: on-device rollout scan (default), "
        "fused single-program rollout+update (fastest synchronous path), "
        "numpy vectorized sim, scalar proto pool, or external "
        "(standalone `python -m dotaclient_tpu.actor` processes)",
    )
    p.add_argument(
        "--transport", type=str, default="inproc",
        choices=("inproc", "socket", "shm", "amqp"),
        help="experience/weights transport; socket listens for actor "
        "processes, shm serves same-host actors over shared memory "
        "(zero syscalls/copies on the wire), amqp targets a RabbitMQ broker",
    )
    p.add_argument(
        "--listen", type=str, default="127.0.0.1:7777",
        help="host:port for --transport socket",
    )
    p.add_argument(
        "--shm-name", type=str, default=None,
        help="shared-memory lane name for --transport shm (default "
        "tpu-dota-<pid>; actors connect with --connect shm://NAME)",
    )
    p.add_argument(
        "--wire-dtype", type=str, default=None,
        choices=("float32", "bfloat16"),
        help="weights fanout wire dtype (overrides transport.wire_dtype); "
        "bfloat16 halves fanout bytes, actors upcast on apply",
    )
    p.add_argument(
        "--rollout-wire-dtype", type=str, default=None,
        choices=("float32", "bfloat16"),
        help="rollout payload wire dtype (overrides "
        "transport.rollout_wire_dtype); bfloat16 roughly halves experience "
        "wire bytes AND the resident trajectory-ring bytes (the ring "
        "stores the narrow dtypes; the upcast to f32 runs on-device at "
        "consume). Precision-critical leaves (behavior_logp, rewards, "
        "dones, carries) stay f32 on the wire. Set the SAME value on "
        "actors (docs/OPERATIONS.md)",
    )
    p.add_argument(
        "--amqp-host", type=str, default="localhost",
        help="broker address for --transport amqp",
    )
    p.add_argument(
        "--refresh-every", type=int, default=10,
        help="publish weights to actors every N optimizer steps",
    )
    p.add_argument(
        "--profile", "--profile-dir", dest="profile", type=str, default=None,
        metavar="DIR",
        help="capture a jax.profiler device trace of the run to DIR "
        "(utils/profiling.trace; view with tensorboard + "
        "tensorboard-plugin-profile). --profile-dir is the long spelling",
    )
    p.add_argument(
        "--trace-jsonl", type=str, default=None, metavar="PATH",
        help="pipeline tracing (ISSUE 12): append sampled lifecycle "
        "events (chunk hop timelines, publish/apply, per-compile cost "
        "analysis, dispatches) as JSON lines to PATH; merge a "
        "learner+actors run's logs with scripts/trace_report.py. Off by "
        "default — the hot paths then pay one pointer test",
    )
    p.add_argument(
        "--trace-sample", type=int, default=None, metavar="N",
        help="with --trace-jsonl: trace every Nth sampling decision "
        "(default telemetry.trace_sample_n = 16; 1 = every chunk, the "
        "chaos-harness setting)",
    )
    p.add_argument(
        "--fleet-interval", type=float, default=None, metavar="S",
        help="fleet health plane (ISSUE 13): aggregate actor/serve metric "
        "snapshots and evaluate the alert rules every S seconds (default "
        "telemetry.fleet_interval_s = 5; 0 disables the fanout — the "
        "fleet/ and alerts/ keys stay eager-created). External-transport "
        "modes only; read the merged table with scripts/fleet_status.py",
    )
    p.add_argument(
        "--checkify", action="store_true",
        help="debug numerics: checkify-instrumented train step that raises "
        "on the first NaN/Inf (slow; never for production runs)",
    )
    p.add_argument(
        "--multihost", action="store_true",
        help="join the job-wide JAX distributed runtime before any device "
        "op (TPU pods/GKE auto-detect coordinator); required on every host "
        "of a multi-host or multi-slice (--dcn-slices > 1) job",
    )
    p.add_argument("--dcn-slices", type=int, default=None,
                   help="ICI-connected slices bridged over DCN (mesh axis)")
    p.add_argument("--model-parallel", type=int, default=None,
                   help="tensor-parallel width (model mesh axis)")
    p.add_argument("--compile-cache", type=str, default=None, metavar="DIR",
                   help="persistent XLA compilation cache directory: the "
                   "fused/train programs compile once per machine instead "
                   "of once per process (~20-40s saved on restart)")
    args = p.parse_args(argv)
    if args.transport != "inproc" and args.actor is None:
        args.actor = "external"

    if args.multihost:
        # must precede every jax op in this process
        from dotaclient_tpu.parallel import initialize_runtime, process_info

        initialize_runtime()
        print(f"learner: distributed runtime up: {process_info()}", flush=True)
    if args.compile_cache:
        jax.config.update("jax_compilation_cache_dir", args.compile_cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    config = default_config()
    model_over = {}
    if args.core is not None:
        model_over["core"] = args.core
    if args.moe_experts is not None:
        model_over["moe_experts"] = args.moe_experts
    if model_over:
        config = dataclasses.replace(
            config, model=dataclasses.replace(config.model, **model_over)
        )
    mesh_over = {}
    if args.dcn_slices is not None:
        mesh_over["dcn_slices"] = args.dcn_slices
    if args.model_parallel is not None:
        mesh_over["model_parallel"] = args.model_parallel
    if mesh_over:
        config = dataclasses.replace(
            config, mesh=dataclasses.replace(config.mesh, **mesh_over)
        )
    if args.smoke:
        config = dataclasses.replace(
            config,
            env=dataclasses.replace(config.env, n_envs=4, max_dota_time=60.0),
            ppo=dataclasses.replace(
                config.ppo, rollout_len=8, batch_rollouts=8
            ),
            buffer=dataclasses.replace(
                config.buffer, capacity_rollouts=32, min_fill=8
            ),
            log_every=1,
        )
        args.steps = min(args.steps, 5)
    if args.steps_per_dispatch is not None:
        config = dataclasses.replace(
            config, steps_per_dispatch=args.steps_per_dispatch
        )
    if args.checkpoint_every is not None:
        config = dataclasses.replace(
            config, checkpoint_every=args.checkpoint_every
        )
    from dotaclient_tpu.config import (
        BufferConfig,
        HealthConfig,
        LeagueConfig,
        LearnerConfig,
        MeshConfig,
        PPOConfig,
        RewardConfig,
        ServeConfig,
    )
    from dotaclient_tpu.utils.overrides import parse_dataclass_overrides

    if args.league and args.opponent != "league":
        p.error("--league overrides need --opponent league")
    parsed: Dict[str, dict] = {}
    for flag, text, sub, cls in (
        ("--ppo", args.ppo, "ppo", PPOConfig),
        ("--reward", args.reward, "reward", RewardConfig),
        ("--league", args.league, "league", LeagueConfig),
        ("--buffer", args.buffer, "buffer", BufferConfig),
        ("--health", args.health, "health", HealthConfig),
        ("--learner", args.learner, "learner", LearnerConfig),
        # serving-plane knobs checkpoint with the run (a serve server
        # restored from this checkpoint starts with them)
        ("--serve", args.serve, "serve", ServeConfig),
        # --mesh composes with the --dcn-slices/--model-parallel
        # shorthands (applied above); explicit --mesh keys win
        ("--mesh", args.mesh, "mesh", MeshConfig),
    ):
        if not text:
            continue
        try:
            parsed[sub] = parse_dataclass_overrides(cls, text, flag)
        except ValueError as e:
            p.error(str(e))
    if args.opponent == "league":
        # same glue as the demo: a league run DEFAULTS to a live league
        # config (so the enabled-gated validations apply and the
        # checkpointed config says what ran); an explicit enabled=false
        # override is respected
        parsed.setdefault("league", {}).setdefault("enabled", True)
    for sub, over in parsed.items():
        config = dataclasses.replace(
            config, **{sub: dataclasses.replace(getattr(config, sub), **over)}
        )
    env_over = {}
    if args.n_envs is not None:
        env_over["n_envs"] = args.n_envs
    if args.opponent is not None:
        env_over["opponent"] = args.opponent
    if args.team_size is not None:
        env_over["team_size"] = args.team_size
    if env_over:
        config = dataclasses.replace(
            config, env=dataclasses.replace(config.env, **env_over)
        )

    if args.wire_dtype is not None:
        config = dataclasses.replace(
            config, transport=dataclasses.replace(
                config.transport, wire_dtype=args.wire_dtype
            )
        )
    if args.rollout_wire_dtype is not None:
        config = dataclasses.replace(
            config, transport=dataclasses.replace(
                config.transport,
                rollout_wire_dtype=args.rollout_wire_dtype,
            )
        )
    if args.sync_snapshots:
        config = dataclasses.replace(
            config, learner=dataclasses.replace(
                config.learner, async_snapshots=False
            )
        )

    # tracer BEFORE any pipeline object: pools/buffers/learner capture
    # tracing.get() at construction (the faults.get() discipline)
    if args.trace_jsonl:
        tracing.configure(args.trace_jsonl, sample_n=args.trace_sample)
    if args.fleet_interval is not None:
        # before the Learner exists: its FleetAggregator reads the knob
        # at construction (telemetry.fleet_interval_s is the one source)
        telemetry.fleet_interval_s = args.fleet_interval

    transport = None
    if args.transport == "socket":
        from dotaclient_tpu.transport.socket_transport import TransportServer

        host, port = args.listen.rsplit(":", 1)
        transport = TransportServer(
            host, int(port),
            fanout_max_lag=config.transport.fanout_max_lag,
            poison_frame_limit=config.transport.poison_frame_limit,
            heartbeat_interval_s=config.transport.heartbeat_interval_s,
            idle_timeout_s=config.transport.idle_timeout_s,
        )
        print(f"learner: listening for actors on {transport.address}", flush=True)
    elif args.transport == "shm":
        from dotaclient_tpu.transport.shm_transport import ShmTransportServer

        transport = ShmTransportServer(
            name=args.shm_name,
            slots=config.transport.shm_slots,
            ring_bytes=config.transport.shm_ring_bytes,
            weights_bytes=config.transport.shm_weights_bytes,
            poison_frame_limit=config.transport.poison_frame_limit,
        )
        print(
            f"learner: shm lane {transport.address!r} "
            f"({transport.slots} actor slots; actors: "
            f"--connect shm://{transport.address})",
            flush=True,
        )
    elif args.transport == "amqp":
        from dotaclient_tpu.transport.queues import AmqpTransport

        host, _, port = args.amqp_host.partition(":")
        transport = AmqpTransport(host, int(port or 5672))

    learner = Learner(
        config,
        transport=transport,
        logdir=args.logdir,
        checkpoint_dir=args.checkpoint_dir,
        restore=args.restore,
        init_from=args.init_from,
        seed=args.seed,
        actor=args.actor or ("scalar" if args.no_vec else "device"),
        debug_checkify=args.checkify,
        metrics_jsonl=args.metrics_jsonl,
    )
    from dotaclient_tpu.utils.profiling import trace

    # Graceful stop (ISSUE 4): the FIRST SIGTERM/SIGINT converts to a drain
    # — the train loop exits at its next step boundary and the end-of-run
    # tail requeues held batches, takes the full-pipeline checkpoint, and
    # closes transports (the finally below). A SECOND signal forces exit:
    # the handler restores the default disposition and re-raises it, so a
    # wedged drain can still be killed with the same signal.
    import signal as _signal

    def _graceful(signum, frame):
        learner.request_stop()
        name = _signal.Signals(signum).name
        print(
            f"learner: {name} received — draining (checkpoint + clean "
            f"shutdown); send {name} again to force exit",
            flush=True,
        )
        _signal.signal(signum, _signal.SIG_DFL)

    try:
        _signal.signal(_signal.SIGTERM, _graceful)
        _signal.signal(_signal.SIGINT, _graceful)
    except ValueError:
        pass  # not the main thread (embedded use): signals stay external

    try:
        with trace(args.profile):
            stats = learner.train(
                args.steps, overlap=args.overlap,
                refresh_every=args.refresh_every,
            )
    except BaseException as e:
        if (
            args.on_crash_checkpoint
            and not isinstance(e, (KeyboardInterrupt, SystemExit))
            and learner.ckpt is not None
        ):
            # Best-effort weights-only save: the state may be mid-donation
            # or the disk may be the very thing that failed — never let the
            # rescue attempt mask the original exception. The crash save is
            # SYNC by contract (ISSUE 5): drain the snapshot thread first so
            # a pending async write can't race the rescue write.
            try:
                learner._drain_snapshots()
            except Exception:  # noqa: BLE001 - rescue path, keep going
                pass
            try:
                # force=True: failures raise instead of degrading to the
                # periodic-save counter — success must not be claimed below
                # when the disk is the very thing that broke
                saved = learner.ckpt.save(
                    learner.state, learner.config, force=True
                )
                learner.ckpt.wait()
                print(
                    f"learner: crash checkpoint "
                    f"{'saved to ' + learner.ckpt.directory if saved else 'declined (step already checkpointed)'}"
                    f" before re-raising",
                    flush=True,
                )
            except Exception as save_err:  # noqa: BLE001 - reported, not masked
                print(
                    f"learner: crash checkpoint failed too "
                    f"({type(save_err).__name__}: {save_err})",
                    flush=True,
                )
        raise
    finally:
        if args.trace_jsonl:
            # drain + fsync the trace log (clean exits; a SIGKILL relies
            # on the writer thread's per-batch flush and the torn-line-
            # tolerant reader)
            tracing.shutdown()
        # the fleet aggregator thread outlives train() by design (the
        # tail still merges late snapshots); main is its owner
        learner.fleet.stop()
        if transport is not None and hasattr(transport, "close"):
            # deterministic teardown even when train() raises: the shm
            # server unlinks its segments (the resource tracker would
            # otherwise warn "leaked" at exit), the socket server closes
            # its listener and connections
            transport.close()
    print(
        f"done: {stats['optimizer_steps']:.0f} steps, "
        f"{stats['frames_trained']:.0f} frames, "
        f"{stats['frames_per_sec']:.0f} frames/sec",
        flush=True,
    )
    return stats


if __name__ == "__main__":
    main()
