"""The learner: end-to-end training loop and CLI entrypoint.

Counterpart of the reference's ``optimizer.py`` main loop — consume rollouts,
train, publish versioned weights, checkpoint, log scalars (SURVEY.md §3.2;
reconstructed — the reference checkout was an empty mount) — wired TPU-first:
the actor pool batches env inference on-device, experience flows through the
transport into the sharded HBM buffer, and each optimization is one donated
pjit step (SURVEY.md §7 "Minimum end-to-end slice").

Single-process mode interleaves actor and learner phases (the sandbox path);
the same components run split across processes with an AMQP transport on a
cluster (``--transport amqp``).

Usage:
    python -m dotaclient_tpu.train.learner --smoke       # tiny sanity run
    python -m dotaclient_tpu.train.learner --steps 1000 --logdir runs/x
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Dict, Optional

import jax
import numpy as np

from dotaclient_tpu.buffer import TrajectoryBuffer
from dotaclient_tpu.config import RunConfig, default_config
from dotaclient_tpu.actor import ActorPool
from dotaclient_tpu.models import init_params, make_policy
from dotaclient_tpu.parallel import make_mesh
from dotaclient_tpu.train.ppo import init_train_state, make_train_step
from dotaclient_tpu.transport import (
    InProcTransport,
    Transport,
    decode_rollout,
    encode_weights,
)
from dotaclient_tpu.utils.checkpoint import CheckpointManager
from dotaclient_tpu.utils.metrics import MetricsLogger


class Learner:
    """Owns the full training stack for single-host runs."""

    def __init__(
        self,
        config: RunConfig,
        transport: Optional[Transport] = None,
        logdir: Optional[str] = None,
        checkpoint_dir: Optional[str] = None,
        restore: bool = False,
        seed: int = 0,
    ) -> None:
        self.config = config
        self.mesh = make_mesh(config.mesh)
        self.policy = make_policy(config.model, config.obs, config.actions)
        params = init_params(self.policy, jax.random.PRNGKey(config.seed))
        self.state = init_train_state(params, config.ppo)
        self.ckpt: Optional[CheckpointManager] = None
        if checkpoint_dir:
            self.ckpt = CheckpointManager(checkpoint_dir)
            if restore and self.ckpt.latest_step() is not None:
                self.state, _ = self.ckpt.restore(config, self.state)
        self.train_step = make_train_step(self.policy, config, self.mesh)
        self.buffer = TrajectoryBuffer(config, self.mesh)
        self.transport = transport or InProcTransport()
        self.pool = ActorPool(
            config,
            self.policy,
            self.state.params,
            transport=self.transport,
            seed=seed,
            version=int(self.state.version),
        )
        self.metrics = MetricsLogger(logdir)
        self.frames_per_rollout = config.ppo.rollout_len
        self._last_metrics: Dict[str, float] = {}

    # -- loop --------------------------------------------------------------

    def ingest(self) -> int:
        protos = self.transport.consume_rollouts(
            self.config.buffer.capacity_rollouts, timeout=0.001
        )
        if not protos:
            return 0
        return self.buffer.add(
            [decode_rollout(p) for p in protos], int(self.state.version)
        )

    def train(self, num_steps: int, actor_steps_per_iter: Optional[int] = None) -> Dict[str, float]:
        """Run until ``num_steps`` optimizer steps have completed."""
        cfg = self.config
        actor_steps = actor_steps_per_iter or cfg.ppo.rollout_len
        t_start = time.time()
        frames_trained = 0
        steps_done = 0
        while steps_done < num_steps:
            # Actor phase: generate experience with the current weights.
            self.pool.set_params(self.state.params, int(self.state.version))
            self.pool.run(actor_steps, refresh_every=0)
            self.ingest()
            # Learner phase: drain full batches.
            while (batch := self.buffer.take()) is not None:
                self.state, m = self.train_step(self.state, batch)
                steps_done += 1
                frames_trained += cfg.ppo.batch_rollouts * cfg.ppo.rollout_len
                step = int(self.state.step)
                if step % cfg.log_every == 0:
                    scalars = {k: float(np.asarray(v)) for k, v in m.items()}
                    scalars.update(self.pool.stats())
                    scalars.update(self.buffer.metrics())
                    elapsed = time.time() - t_start
                    scalars["frames_per_sec"] = frames_trained / max(elapsed, 1e-9)
                    self._last_metrics = scalars
                    self.metrics.log(step, scalars)
                if self.ckpt and step % cfg.checkpoint_every == 0:
                    self.ckpt.save(self.state, cfg)
                if steps_done >= num_steps:
                    break
        # Publish final weights for out-of-process actors (cluster parity).
        self.transport.publish_weights(
            encode_weights(
                jax.tree.map(np.asarray, self.state.params),
                int(self.state.version),
            )
        )
        if self.ckpt:
            self.ckpt.save(self.state, cfg, force=True)
            self.ckpt.wait()
        elapsed = time.time() - t_start
        return {
            **self._last_metrics,
            **{f"actor_{k}": v for k, v in self.pool.stats().items()},
            # Fresh end-of-run figures last so they win over logged snapshots.
            "optimizer_steps": float(steps_done),
            "frames_trained": float(frames_trained),
            "frames_per_sec": frames_trained / max(elapsed, 1e-9),
            "elapsed_sec": elapsed,
        }


def main(argv=None) -> Dict[str, float]:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--smoke", action="store_true", help="tiny fast config")
    p.add_argument("--logdir", type=str, default=None)
    p.add_argument("--checkpoint-dir", type=str, default=None)
    p.add_argument("--restore", action="store_true")
    p.add_argument("--n-envs", type=int, default=None)
    p.add_argument("--opponent", type=str, default=None)
    p.add_argument("--team-size", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    config = default_config()
    if args.smoke:
        config = dataclasses.replace(
            config,
            env=dataclasses.replace(config.env, n_envs=4, max_dota_time=60.0),
            ppo=dataclasses.replace(
                config.ppo, rollout_len=8, batch_rollouts=8
            ),
            buffer=dataclasses.replace(
                config.buffer, capacity_rollouts=32, min_fill=8
            ),
            log_every=1,
        )
        args.steps = min(args.steps, 5)
    env_over = {}
    if args.n_envs is not None:
        env_over["n_envs"] = args.n_envs
    if args.opponent is not None:
        env_over["opponent"] = args.opponent
    if args.team_size is not None:
        env_over["team_size"] = args.team_size
    if env_over:
        config = dataclasses.replace(
            config, env=dataclasses.replace(config.env, **env_over)
        )

    learner = Learner(
        config,
        logdir=args.logdir,
        checkpoint_dir=args.checkpoint_dir,
        restore=args.restore,
        seed=args.seed,
    )
    stats = learner.train(args.steps)
    print(
        f"done: {stats['optimizer_steps']:.0f} steps, "
        f"{stats['frames_trained']:.0f} frames, "
        f"{stats['frames_per_sec']:.0f} frames/sec",
        flush=True,
    )
    return stats


if __name__ == "__main__":
    main()
