"""PPO learner: advantage plane, loss, pjit train step, training loop."""

from dotaclient_tpu.train.advantage import (
    advantages_and_returns,
    make_advantage_pass,
    one_pass_enabled,
)
from dotaclient_tpu.train.gae import gae, gae_reference
from dotaclient_tpu.train.ppo import (
    Batch,
    TrainState,
    example_batch,
    init_train_state,
    make_epoch_step,
    make_optimizer,
    make_train_step,
    ppo_loss,
)

__all__ = [
    "Batch",
    "TrainState",
    "advantages_and_returns",
    "example_batch",
    "gae",
    "gae_reference",
    "init_train_state",
    "make_advantage_pass",
    "make_epoch_step",
    "make_optimizer",
    "make_train_step",
    "one_pass_enabled",
    "ppo_loss",
]
