"""PPO learner: GAE, loss, pjit train step, training loop."""

from dotaclient_tpu.train.gae import gae, gae_reference
from dotaclient_tpu.train.ppo import (
    Batch,
    TrainState,
    example_batch,
    init_train_state,
    make_epoch_step,
    make_optimizer,
    make_train_step,
    ppo_loss,
)

__all__ = [
    "Batch",
    "TrainState",
    "example_batch",
    "gae",
    "gae_reference",
    "init_train_state",
    "make_epoch_step",
    "make_optimizer",
    "make_train_step",
    "ppo_loss",
]
