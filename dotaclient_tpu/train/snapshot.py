"""Async snapshot engine: learner side effects off the train thread (ISSUE 5).

The learner's throughput discipline says the train loop is dispatch-only —
yet until this module every side effect broke it: ``_publish_weights`` did a
full device→host param fetch plus serialization inline, ``CheckpointManager.
save`` synchronously fetched params + opt state before a blocking orbax
write, and the log-boundary metrics fetch parked the train thread on the
in-flight step. Keeping the optimizer busy by overlapping those host phases
with device compute is the pipeline-overlap win OPPO demonstrates for PPO,
and it finishes the Podracer "device never waits on the host" discipline
(PAPERS.md) that the actor half already applies.

Division of labor:

* **train thread** — at a publish/checkpoint/log boundary it runs ONE cheap
  jitted on-device copy of the needed state (params / TrainState / the tiny
  stat accumulators) into fresh HBM snapshot buffers and submits the copy
  here. The copy program is enqueued on the device stream *before* the next
  (donating) train step, so the snapshot can never read donated buffers;
  the thread returns to dispatching immediately.
* **snapshot thread** (one per engine) — drains the job slots: the batched
  ``jax.device_get`` (the one transfer per job), the bf16 wire cast +
  ``encode_weights``, the non-blocking ``transport.publish_weights``
  enqueue, the orbax write via ``CheckpointManager.save_host``, and the
  host-side metrics continuation.

Semantics preserved, not relaxed (the contract tests/test_snapshot.py pins):

* one latest-wins slot per job kind — when the thread falls behind, unsent
  work coalesces to the newest submission (counted in
  ``snapshot/<kind>_coalesced``; the PR3 fanout-slot pattern) and published
  versions stay MONOTONIC (an engine-side guard skips anything at or below
  the last published version). Coalescing only ever drops IDEMPOTENT work
  (an older weights version, an older checkpoint, an older log line):
  actor stat drains — whose device accumulators are destructively reset at
  submit time — go through :meth:`submit_stats`, a backlog that is ALWAYS
  fully processed (before the same cycle's log job, so the surviving log
  sees every fold) and never coalesced;
* ``drain()`` blocks until every pending job has landed — the graceful-stop
  path drains before its forced sync checkpoint, so the final save still
  lands at the EXACT stop step;
* failures never kill the engine: checkpoint I/O errors degrade through the
  existing ``checkpoint/save_failures_total`` policy inside ``save_host``;
  anything else is counted in ``snapshot/errors_total`` + a warning, and
  the next job proceeds.

HBM budget: at most two snapshots per kind are alive at once (one pending
slot + one being fetched) — for the checkpoint kind that is ~2× the
TrainState, freed as soon as the fetch completes.

Telemetry: ``snapshot/pending`` (job slots occupied), ``snapshot/d2h_ms``
(last batched device→host fetch), ``span/transport/publish_weights`` and
``span/learner/metrics_fetch`` keep their documented keys — they are simply
recorded from this thread now.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from dotaclient_tpu.utils import telemetry, tracing

logger = logging.getLogger(__name__)

_KINDS = ("publish", "checkpoint", "metrics")


class SnapshotEngine:
    """One background thread + three latest-wins job slots."""

    def __init__(
        self,
        transport: Any = None,
        wire_dtype: str = "float32",
        ckpt: Any = None,
        registry: Optional[telemetry.Registry] = None,
        health: Any = None,
    ) -> None:
        self._transport = transport
        self._wire_dtype = wire_dtype
        self._ckpt = ckpt
        # Training-health gate (ISSUE 6, train/health.py): verdicts ride
        # the never-coalesced stats backlog, which this thread processes
        # BEFORE the same cycle's publish/checkpoint jobs — so by the time
        # a version-V publish job runs, every verdict for steps <= V has
        # been folded. An unhealthy latch refuses the publish (actors keep
        # serving the last good version) and the periodic checkpoint (the
        # retention loop must not rotate good saves out for poisoned ones).
        self._health = health
        # Pipeline tracing (ISSUE 12): captured once, like the learner's —
        # with tracing off the publish path pays one pointer test
        self._tracer = tracing.get()
        self._tel = registry if registry is not None else telemetry.get_registry()
        self._cond = threading.Condition()
        self._jobs: Dict[str, Optional[Tuple]] = {k: None for k in _KINDS}
        # Never-coalesced backlog of (device_stats, finish) actor stat
        # drains: each entry's device accumulators were already reset at
        # submit, so dropping one would lose those episodes forever.
        # Entries are a few scalars each and arrive at boundary cadence —
        # the backlog stays tiny unless the thread is fully wedged.
        self._stats_jobs: list = []
        self._busy = False
        self._stopped = False
        # Monotonic-publish floor: the train thread submits strictly
        # increasing versions and the slot keeps only the newest, but a
        # drain/tail re-submit of an already-published version must be a
        # no-op, never a duplicate or regression on the wire.
        self._last_published = -1
        # eager-create: a run whose engine never falls behind still reports
        # zeros (check_telemetry_schema.py --require-snapshot pins these)
        self._tel.gauge("snapshot/pending")
        self._tel.gauge("snapshot/d2h_ms")
        self._tel.counter("snapshot/errors_total")
        for k in _KINDS:
            self._tel.counter(f"snapshot/{k}_coalesced")
        self._thread = threading.Thread(
            target=self._run, name="snapshot", daemon=True
        )
        self._thread.start()

    # -- submission (train thread) -----------------------------------------

    def _submit(self, kind: str, job: Tuple) -> None:
        with self._cond:
            if self._stopped:
                raise RuntimeError("snapshot engine is stopped")
            if self._jobs[kind] is not None:
                # an unprocessed older snapshot just became worthless:
                # latest wins (the PR3 fanout-slot rule)
                self._tel.counter(f"snapshot/{kind}_coalesced").inc()
            self._jobs[kind] = job
            self._tel.gauge("snapshot/pending").set(float(self._pending_locked()))   # host-sync-ok: host ints
            self._cond.notify_all()

    def _pending_locked(self) -> int:
        """Jobs not yet fully processed (slot jobs + stats backlog + the
        batch currently in flight). Caller holds ``_cond``."""
        return (
            sum(j is not None for j in self._jobs.values())
            + len(self._stats_jobs)
            + (1 if self._busy else 0)
        )

    def submit_publish(self, params: Any, version: int) -> None:
        """``params`` must be an on-device COPY (the train step donates the
        live state; a jitted ``jnp.copy`` tree dispatched before the next
        step is the cheap, ordering-safe way to get one)."""
        self._submit("publish", (params, version))

    def submit_checkpoint(self, state: Any, config: Any) -> None:
        """``state`` is an on-device TrainState copy (same donation rule)."""
        self._submit("checkpoint", (state, config))

    def submit_metrics(
        self, device_tree: Any, finish: Callable[[Any], None]
    ) -> None:
        """Fetch ``device_tree`` (one transfer) and hand the host result to
        ``finish`` on the snapshot thread. ``device_tree`` leaves must be
        program OUTPUTS or copies — never buffers a later step donates.
        Latest-wins: only the newest unprocessed log boundary survives a
        backlog — put anything non-idempotent in :meth:`submit_stats`."""
        self._submit("metrics", (device_tree, finish))

    def submit_stats(
        self, device_stats: Any, finish: Callable[[Any], Any]
    ) -> None:
        """Queue one actor stat drain: ``finish(fetched)`` folds the window
        into the host accumulators. NEVER coalesced — the device
        accumulators were reset when this drain began, so this entry is the
        only copy of its window — and always processed BEFORE the same
        cycle's metrics job, so the surviving log line reflects every
        fold."""
        with self._cond:
            if self._stopped:
                raise RuntimeError("snapshot engine is stopped")
            self._stats_jobs.append((device_stats, finish))
            self._tel.gauge("snapshot/pending").set(float(self._pending_locked()))   # host-sync-ok: host ints
            self._cond.notify_all()

    # -- lifecycle ----------------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every pending job has been processed (False on
        timeout). The graceful-stop/forced-checkpoint path calls this so
        the sync save that follows cannot race an in-flight async write."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._pending_locked():
                wait = 1.0
                if deadline is not None:
                    wait = deadline - time.monotonic()
                    if wait <= 0:
                        return False
                self._cond.wait(min(wait, 1.0))
        return True

    def stop(self, timeout: float = 30.0) -> None:
        """Process whatever is pending, then stop the thread (tests and
        bench teardown; production engines live for the process)."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout)

    @property
    def pending(self) -> int:
        with self._cond:
            return self._pending_locked()

    # -- snapshot thread -----------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while (
                    not self._stopped
                    and all(j is None for j in self._jobs.values())
                    and not self._stats_jobs
                ):
                    self._cond.wait()
                batch = {k: j for k, j in self._jobs.items() if j is not None}
                stats_batch, self._stats_jobs = self._stats_jobs, []
                if not batch and not stats_batch:
                    return  # stopped with nothing left
                for k in batch:
                    self._jobs[k] = None
                self._busy = True
                # the in-flight batch still counts as pending: an operator
                # reading the last metrics line of a crashed run must see
                # that work was outstanding (OPERATIONS.md runbook)
                self._tel.gauge("snapshot/pending").set(float(self._pending_locked()))   # host-sync-ok: host ints
            try:
                # stat drains first (their fold must land before the log
                # job that reports it), then publish (actors get fresh
                # weights at fanout latency), then the slower orbax write
                for dev, finish in stats_batch:
                    try:
                        finish(jax.device_get(dev))  # host-sync-ok: snapshot thread, tiny stat scalars
                    except Exception as e:  # noqa: BLE001 - engine must outlive any job
                        self._tel.counter("snapshot/errors_total").inc()
                        logger.warning(
                            "snapshot stats fold failed (%s: %s)",
                            type(e).__name__, e,
                        )
                for kind in _KINDS:
                    job = batch.get(kind)
                    if job is None:
                        continue
                    try:
                        getattr(self, f"_do_{kind}")(*job)
                    except Exception as e:  # noqa: BLE001 - engine must outlive any job
                        self._tel.counter("snapshot/errors_total").inc()
                        logger.warning(
                            "snapshot %s job failed (%s: %s) — engine "
                            "continues; the next boundary retries",
                            kind, type(e).__name__, e,
                        )
            finally:
                with self._cond:
                    self._busy = False
                    self._tel.gauge("snapshot/pending").set(float(self._pending_locked()))   # host-sync-ok: host ints
                    self._cond.notify_all()

    def _fetch(self, tree: Any) -> Any:
        """The ONE batched device→host transfer per job.

        Mesh-sharded snapshots (ISSUE 10) need no special casing here:
        the submitted copies keep the live state's shardings, and
        ``device_get`` assembles replicated leaves from shard 0 (and
        gathers TP-partitioned ones) — ON THIS THREAD, so the train
        thread's boundary stays dispatch-only at every device count
        (pinned by tests/test_multichip.py's zero-fetch test)."""
        t0 = time.perf_counter()
        host = jax.device_get(tree)  # host-sync-ok: snapshot thread — the transfer this engine exists to absorb
        self._tel.gauge("snapshot/d2h_ms").set(
            (time.perf_counter() - t0) * 1e3
        )
        return host

    @property
    def last_published(self) -> int:
        """Highest version ever handed to the fanout (the rollback
        audit's published-floor evidence — train/learner.py; rollback
        keeps the version counter monotone, so the floor never needs a
        rewind)."""
        # lint-ok: thread-ownership(rollback reads this only after drain()
        # returned — the engine thread is provably idle at that point)
        return self._last_published

    def _do_publish(self, params: Any, version: int) -> None:
        if self._health is not None and self._health.unhealthy is not None:
            # contain: a flagged step's params never reach the wire; the
            # fanout keeps serving the last good version until rollback
            self._tel.counter("health/publish_blocked_total").inc()
            logger.warning(
                "snapshot: publish of version %d BLOCKED — training "
                "health latched unhealthy (%s at step %d); actors keep "
                "the last good weights",
                version, self._health.unhealthy.reason,
                self._health.unhealthy.step,
            )
            return
        if version <= self._last_published:
            return  # stale re-submit (drain/tail overlap): monotonic wins
        from dotaclient_tpu.transport.serialize import encode_weights

        host = self._fetch(params)
        trace_blob = None
        if self._tracer is not None:
            # publish-side trace record (ISSUE 12): stamped AFTER the
            # fetch so the hop dates the moment the version hits the
            # fanout, which is what actor-apply lag is measured against
            rec = tracing.weights_record(version)
            trace_blob = tracing.record_to_blob(rec, pad=False)
            self._tracer.emit("publish", version=version)
        msg = encode_weights(
            host, version, wire_dtype=self._wire_dtype, trace=trace_blob
        )
        with self._tel.span("transport/publish_weights"):
            self._transport.publish_weights(msg)
        self._last_published = version

    def _do_checkpoint(self, state: Any, config: Any) -> None:
        healthy = True
        if self._health is not None:
            if self._health.unhealthy is not None:
                # contain: a poisoned TrainState must not enter the rolling
                # retention (it would eventually GC the last healthy save —
                # the exact failure mode ISSUE 6 exists to close)
                self._tel.counter("health/checkpoints_blocked_total").inc()
                logger.warning(
                    "snapshot: periodic checkpoint BLOCKED — training "
                    "health latched unhealthy; awaiting rollback",
                )
                return
            healthy = self._health.cfg.enabled
        host = self._fetch(
            {
                "step": state.step,
                "version": state.version,
                "params": state.params,
                "opt_state": state.opt_state,
            }
        )
        # periodic cadence (force=False): I/O failures degrade to the
        # checkpoint/save_failures_total counter inside save_host — exactly
        # the policy a sync periodic save follows. With the guardian on,
        # every verdict <= this state's step has already been folded (the
        # stats backlog precedes this job), so a save that reaches here is
        # health-verified: mirror it into the last_good slot.
        self._ckpt.save_host(host, config, force=False, mark_good=healthy)

    def _do_metrics(
        self, device_tree: Any, finish: Callable[[Any], None]
    ) -> None:
        with self._tel.span("learner/metrics_fetch"):
            host = self._fetch(device_tree)
        finish(host)
