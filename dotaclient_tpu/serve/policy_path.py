"""Inference-only policy path: the slim param tree the serving plane runs.

Training carries state serving never needs: the value head (PPO's critic),
the optimizer moments, the step/version counters. The serve plane runs
``models.policy.Policy`` with ``value_head=False`` — the SAME trunk, core,
and action-head modules, so logits are bit-identical to the training policy
by construction — over a param tree that is exactly the training tree minus
``head_value``.

Two sources restore into that slim tree, and must agree bit-for-bit
(pinned by tests/test_serve.py's round-trip test):

* a **training checkpoint** (``load_inference_params``): the orbax
  weights-only restore (integrity-manifest verified, walk-back on
  corruption — utils/checkpoint.py) followed by the slice;
* a **published weights frame** (``weights_frame_to_params``): the
  ``ModelWeights`` proto the snapshot engine fans out to actors, decoded
  (bf16 wire leaves upcast exactly) and sliced — the path a live serve
  server's weight-swap subscription takes on every refresh.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

from dotaclient_tpu.config import RunConfig
from dotaclient_tpu.models.policy import Policy

# Top-level param-tree entries that exist only for training. The slice is
# name-based (not shape-based) so a future training-only head lands here
# once instead of silently riding into every serve tree.
TRAIN_ONLY_PARAM_KEYS = ("head_value",)


def make_inference_policy(config: RunConfig) -> Policy:
    """The serving-plane policy module: identical architecture, no value
    head (``value_head=False``), so it applies the sliced tree directly."""
    if config.model.moe_experts > 0 and config.model.core != "transformer":
        raise ValueError(
            f"moe_experts={config.model.moe_experts} requires "
            f"core='transformer' (got core={config.model.core!r})"
        )
    return Policy(
        model=config.model,
        obs_spec=config.obs,
        action_spec=config.actions,
        value_head=False,
    )


def slice_train_params(params: Any) -> Dict[str, Any]:
    """Training param tree → inference-only tree (drop the value head).

    Accepts the variables dict (``{"params": {...}}``) or a bare params
    level and returns the same nesting it was given; unknown layouts fail
    loudly rather than serving a tree the slim module would reject."""
    if not isinstance(params, dict):
        raise TypeError(
            f"expected a param dict, got {type(params).__name__}"
        )
    if "params" in params:
        out = dict(params)
        out["params"] = slice_train_params(params["params"])
        return out
    return {
        k: v for k, v in params.items() if k not in TRAIN_ONLY_PARAM_KEYS
    }


def load_inference_params(checkpoint_dir: str) -> Tuple[RunConfig, Dict[str, Any], int]:
    """Restore a training checkpoint into the slim tree.

    Returns ``(config, sliced params, step)`` — the checkpoint's OWN config
    is authoritative for the model tree (guessing one risks a template
    mismatch), and the step doubles as the serve plane's starting weights
    version (the snapshot engine publishes version=step-aligned counters,
    so a later fanout frame with a higher version supersedes it)."""
    from dotaclient_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(checkpoint_dir)
    try:
        config = mgr.restore_config()
        params, step = mgr.restore_weights()
    finally:
        mgr.close()
    return config, slice_train_params(params), int(step)


def weights_frame_to_params(msg: Any) -> Tuple[int, Dict[str, Any]]:
    """A published ``ModelWeights`` frame → ``(version, sliced params)``.

    ``decode_weights`` upcasts bf16 wire leaves to f32 exactly (the
    lossless inverse of the fanout's ``wire_dtype`` cast), so the result is
    bit-identical to slicing the learner-side host params the frame was
    encoded from."""
    from dotaclient_tpu.transport.serialize import decode_weights

    version, tree = decode_weights(msg)
    return version, slice_train_params(tree)
