"""Socket front door of the serving plane: request/reply on the wire lane.

Requests and replies ride the SAME wire discipline as the training
transports (transport/socket_transport.py): length-prefixed frames with a
header CRC (framing loss is fatal — TCP cannot resync) and a payload CRC32
trailer, corrupt frames dropped and counted
(``transport/frames_corrupt_total``), and a peer that ships
``transport.poison_frame_limit`` CONSECUTIVE bad frames quarantined
(``transport/peers_quarantined``) — its connection cut and its carry slot
reclaimed. Two new frame kinds extend the shared kind space:
``KIND_SERVE_REQUEST`` (3) and ``KIND_SERVE_REPLY`` (4).

Payloads reuse the rollout codec end-to-end: a request is
``encode_rollout_bytes({"obs": ..., "reset": ...})`` — so
``serve.request_wire_dtype="bfloat16"`` narrows observation leaves through
the exact ``__wire_cast__`` cast-plan machinery of ISSUE 7 — with
``env_id`` carrying the client's slot and ``rollout_id`` the request id the
reply echoes. A reply carries the packed per-head actions, the joint
log-prob, and the weights version that sampled it (``model_version``).

Slot lifecycle: the server allocates the lowest free carry slot at accept
and sends an attach frame (a reply-kind frame whose ``env_id`` names the
slot) through the connection's writer; disconnect, idle timeout, and
quarantine all release the slot — the engine zeroes its carry row between
dispatches, so the next game to claim it starts fresh even if its client
forgets the first-step ``reset`` flag.

Weight refresh: ``attach_weights_source`` subscribes the server to a
weights fanout — any object with the transports' ``latest_weights()``
surface, i.e. a ``SocketTransport`` connected to the learner's socket
fanout or a ``ShmTransport`` attached to the same-host shm slab — and a
dedicated thread polls it, slices each new frame into the inference-only
tree, and submits it to the engine (hot-swapped between dispatches,
monotonic version).
"""

from __future__ import annotations

import heapq
import socket
import threading
from collections import deque
from typing import Any, Deque, List, Optional, Tuple

import numpy as np

from dotaclient_tpu.models.distributions import HEADS
from dotaclient_tpu.serve.engine import ServeEngine
from dotaclient_tpu.serve.policy_path import weights_frame_to_params
from dotaclient_tpu.transport.socket_transport import (
    FrameCorrupt,
    FramingLost,
    _recv_frame,
    _send_frame,
)
from dotaclient_tpu.transport.serialize import (
    decode_rollout_bytes,
    encode_rollout_bytes,
)
from dotaclient_tpu.utils import telemetry, tracing

# Wire frame kinds 0-2 belong to the training transport (rollout, weights,
# heartbeat); the serve lane extends the shared kind space.
KIND_SERVE_REQUEST = 3
KIND_SERVE_REPLY = 4

# the attach frame's request id: replies echo real request ids, which the
# clients start at 1, so 0 is unambiguous
ATTACH_REQUEST_ID = 0


def encode_reply(
    actions: np.ndarray, logp: float, version: int, slot: int,
    request_id: int, trace: "bytes | None" = None,
    dispatch_idx: int = 0, carry: "dict | None" = None,
) -> Any:
    """One reply's wire bytes: packed head indices + joint logp, version
    in ``model_version``, slot in ``env_id``, echoed request id. A traced
    request's record (recv/reply hops appended server-side) rides back
    in-band (ISSUE 12) so the client can close the round trip.

    ``dispatch_idx`` names the dispatch (and hence the sampling rng
    ``fold_in`` index) that produced this reply — the re-home parity
    digest (ISSUE 19) replays exactly these indices. ``carry`` is the
    carry-shadow row dict (``ServeEngine.carry_row_to_wire``), present
    only on shadow-mode engines."""
    arrays = {
        "actions": np.asarray(actions, np.int32),
        "logp": np.asarray(logp, np.float32),
        "dispatch_idx": np.asarray(dispatch_idx, np.int32),
    }
    if carry is not None:
        arrays["carry"] = carry
    return encode_rollout_bytes(
        arrays,
        model_version=version,
        env_id=slot,
        rollout_id=request_id,
        length=1,
        total_reward=0.0,
        trace=trace,
    )


class _ServeConn:
    """One attached game: socket + slot + the reply queue its writer
    drains. Only the writer thread ever writes the socket."""

    __slots__ = (
        "sock", "slot", "cond", "replies", "dead", "bad_streak", "traces",
    )

    def __init__(self, sock: socket.socket, slot: int) -> None:
        self.sock = sock
        self.slot = slot
        self.cond = threading.Condition()
        # (actions, logp, version, request_id, dispatch_idx, carry)
        # tuples; encode happens on the writer thread so the batcher's
        # reply callback stays O(1)
        self.replies: Deque[Tuple] = deque()
        self.dead = False
        self.bad_streak = 0
        # request_id → trace record for TRACED requests only (ISSUE 12):
        # written by the reader, popped by the writer, both under `cond`;
        # dropped with the connection
        self.traces: dict = {}


class PolicyServer:
    """Listener + per-connection reader/writer threads over a ServeEngine."""

    def __init__(
        self,
        engine: ServeEngine,
        config: Any,
        host: str = "127.0.0.1",
        port: int = 0,
        registry: Optional[telemetry.Registry] = None,
    ) -> None:
        self._engine = engine
        self._config = config
        self._poison_frame_limit = max(
            1, config.transport.poison_frame_limit
        )
        self._tel = (
            registry if registry is not None else telemetry.get_registry()
        )
        self._listener = socket.create_server((host, port))
        self.address = self._listener.getsockname()
        self._conns: List[_ServeConn] = []
        self._conns_lock = threading.Lock()
        # lowest-slot-first reuse keeps the slot set compact (and makes
        # reclamation observable: a reconnect lands on the freed slot)
        self._free_slots: List[int] = list(range(engine.max_slots))
        heapq.heapify(self._free_slots)
        self._closed = threading.Event()
        self._weights_thread: Optional[threading.Thread] = None
        # eager-create (the --require-serve tier pins presence at zero)
        self._tel.counter("serve/conns_rejected_total")
        self._tel.gauge("serve/clients_connected")
        self._tel.gauge("serve/slots_in_use")
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="serve-accept", daemon=True
        )
        self._accept_thread.start()

    # -- threads -------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                if not self._free_slots:
                    # every carry slot is owned by a live game: shed the
                    # joiner instead of degrading everyone (counted)
                    self._tel.counter("serve/conns_rejected_total").inc()
                    try:
                        sock.close()
                    except OSError:
                        pass
                    continue
                slot = heapq.heappop(self._free_slots)
                conn = _ServeConn(sock, slot)
                self._conns.append(conn)
            self._publish_conn_gauges()
            # attach frame rides the writer queue: a joiner that never
            # reads can only wedge its own writer, never this loop
            with conn.cond:
                conn.replies.append(
                    (np.zeros((len(HEADS),), np.int32), 0.0,
                     self._engine.version, ATTACH_REQUEST_ID, 0, None)
                )
                conn.cond.notify()
            threading.Thread(
                target=self._reader_loop, args=(conn,),
                name="serve-reader", daemon=True,
            ).start()
            threading.Thread(
                target=self._writer_loop, args=(conn,),
                name="serve-writer", daemon=True,
            ).start()

    def _poison(self, conn: _ServeConn, fatal: bool = False) -> None:
        """One corrupt/undecodable frame: count, advance the streak, and
        quarantine (raise → connection drop → slot reclaim) at the limit —
        the transport lane's exact discipline."""
        self._tel.counter("transport/frames_corrupt_total").inc()
        conn.bad_streak += 1
        if fatal or conn.bad_streak >= self._poison_frame_limit:
            self._tel.counter("transport/peers_quarantined").inc()
            raise FrameCorrupt(
                f"serve client quarantined after {conn.bad_streak} "
                f"consecutive corrupt frames"
            )

    def _reader_loop(self, conn: _ServeConn) -> None:
        try:
            while not self._closed.is_set():
                try:
                    frame = _recv_frame(conn.sock)
                except FramingLost:
                    # length word untrustworthy: nothing to resync to
                    self._poison(conn, fatal=True)   # always raises
                except FrameCorrupt:
                    self._poison(conn)
                    continue
                if frame is None:
                    return  # clean disconnect
                kind, payload = frame
                if kind != KIND_SERVE_REQUEST:
                    continue  # future control kinds: ignore, stay in sync
                try:
                    meta, arrays = decode_rollout_bytes(payload, upcast=True)
                    tracer = tracing.get()
                    if tracer is not None and "trace_blob" in meta:
                        # serve request hop (ISSUE 12): receive + CRC
                        # verify happened in _recv_frame just above; the
                        # record rides to the writer for the reply stamp
                        rec = tracing.stamp_serve_recv(meta)
                        if rec is not None:
                            tracer.emit(
                                "serve_request",
                                tid=rec["tid"],
                                slot=conn.slot,
                            )
                            with conn.cond:
                                conn.traces[meta["rollout_id"]] = rec
                    obs = arrays["obs"]
                    reset = bool(
                        np.asarray(arrays["reset"]).reshape(-1)[0]
                    )
                    # submit validates the obs tree (and any re-homed
                    # session's shadow carry row) against the engine on
                    # THIS thread — a decodable request from a
                    # config-skewed client (wrong max_units, missing
                    # leaf, alien carry) rides the poison path below,
                    # and the batcher never sees an undispatable row
                    self._engine.submit(
                        conn.slot, obs, reset,
                        reply=self._make_reply(conn),
                        request_id=meta["rollout_id"],
                        carry=arrays.get("carry"),
                    )
                except Exception:
                    # undecodable or lane-incompatible request
                    # (version-skewed client): the poison discipline
                    # covers semantic garbage too
                    self._poison(conn)
                    continue
                conn.bad_streak = 0
        except (OSError, ValueError):
            pass  # dead/quarantined client: disposable (SURVEY.md §5.3)
        finally:
            self._drop(conn)

    def _make_reply(self, conn: _ServeConn):
        def reply(actions, logp, version, request_id, dispatch_idx,
                  carry=None):
            with conn.cond:
                if conn.dead:
                    raise ConnectionError("serve client gone")
                conn.replies.append(
                    (actions, logp, version, request_id, dispatch_idx,
                     carry)
                )
                conn.cond.notify()

        return reply

    def _writer_loop(self, conn: _ServeConn) -> None:
        while True:
            with conn.cond:
                while not conn.replies and not conn.dead and not self._closed.is_set():
                    conn.cond.wait(0.5)
                if conn.dead or self._closed.is_set():
                    return
                batch = list(conn.replies)
                conn.replies.clear()
                reply_traces = {
                    rid: conn.traces.pop(rid)
                    for _a, _l, _v, rid, _d, _c in batch
                    if rid in conn.traces
                } if conn.traces else {}
            try:
                for actions, logp, version, request_id, didx, carry in batch:
                    blob = None
                    rec = reply_traces.get(request_id)
                    if rec is not None:
                        tracing.append_hop(rec, "reply")
                        blob = tracing.record_to_blob(rec, pad=False)
                    _send_frame(
                        conn.sock, KIND_SERVE_REPLY,
                        encode_reply(
                            actions, logp, version, conn.slot, request_id,
                            trace=blob, dispatch_idx=didx, carry=carry,
                        ),
                    )
            except (OSError, ValueError):
                self._drop(conn)
                return

    def _drop(self, conn: _ServeConn) -> None:
        with self._conns_lock:
            if conn in self._conns:
                self._conns.remove(conn)
                # slot back in the pool; the engine zeroes its carry row
                # between dispatches (never mid-batch)
                heapq.heappush(self._free_slots, conn.slot)
                self._engine.release_slot(conn.slot)
        with conn.cond:
            conn.dead = True
            conn.cond.notify_all()
        for fn in (lambda: conn.sock.shutdown(socket.SHUT_RDWR), conn.sock.close):
            try:
                fn()
            except OSError:
                pass
        self._publish_conn_gauges()

    def _publish_conn_gauges(self) -> None:
        with self._conns_lock:
            n = len(self._conns)
            in_use = self._engine.max_slots - len(self._free_slots)
        self._tel.gauge("serve/clients_connected").set(float(n))   # host-sync-ok: host ints
        self._tel.gauge("serve/slots_in_use").set(float(in_use))   # host-sync-ok: host ints

    # -- weights subscription ------------------------------------------------

    def attach_weights_source(self, source: Any) -> None:
        """Subscribe to a weights fanout: ``source`` is any object with the
        transports' ``latest_weights()`` surface (a ``SocketTransport``
        connected to the learner, a ``ShmTransport`` on the same-host slab,
        or a test stub). A dedicated thread polls at
        ``serve.weights_poll_s``, slices each NEW version into the
        inference tree, and hands it to the engine's between-dispatch
        swap."""
        if self._weights_thread is not None:
            raise RuntimeError("weights source already attached")
        poll_s = max(0.01, self._config.serve.weights_poll_s)

        def loop() -> None:
            last_seen = self._engine.version
            while not self._closed.wait(poll_s):
                try:
                    msg = source.latest_weights()
                except ConnectionError:
                    return  # fanout gone: keep serving the last version
                if msg is None or msg.version <= last_seen:
                    continue
                last_seen, params = weights_frame_to_params(msg)
                self._engine.submit_weights(last_seen, params)

        self._weights_thread = threading.Thread(
            target=loop, name="serve-weights", daemon=True
        )
        self._weights_thread.start()

    # -- lifecycle -----------------------------------------------------------

    @property
    def n_connected(self) -> int:
        with self._conns_lock:
            return len(self._conns)

    def close(self) -> None:
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            self._drop(conn)
        if self._weights_thread is not None:
            self._weights_thread.join(timeout=5)
