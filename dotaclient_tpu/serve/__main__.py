"""Standalone policy-serving server.

    python -m dotaclient_tpu.serve --checkpoint runs/ckpt
    python -m dotaclient_tpu.serve --checkpoint runs/ckpt \
        --serve batch_window_ms=4,max_batch=128 --serve-listen 0.0.0.0:7788
    python -m dotaclient_tpu.serve --checkpoint runs/ckpt \
        --subscribe 10.0.0.5:7777          # hot weight refresh from a learner
    python -m dotaclient_tpu.serve --checkpoint runs/ckpt \
        --subscribe shm://tpu-dota-1234    # same-host shm weights slab

Loads a training checkpoint into the inference-only tree (no value head, no
optimizer state), serves actions over the continuous-batching socket lane,
and optionally subscribes to a learner's weights fanout so refreshes are
hot-swapped between dispatches. Clients are ``serve.ServeClient`` (one per
game); ``scripts/serve_loadgen.py`` drives synthetic fleets.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--checkpoint", type=str, required=True,
                   help="training checkpoint directory (orbax run dir); "
                   "its stored config governs the model tree")
    p.add_argument("--serve-listen", type=str, default="127.0.0.1:0",
                   help="host:port for the serve request/reply lane "
                   "(port 0 = ephemeral, printed at startup)")
    p.add_argument(
        "--serve", type=str, default=None, metavar="K=V,...",
        help="comma-separated ServeConfig overrides, e.g. "
        "'batch_window_ms=4,max_batch=128' (knob table in "
        "docs/OPERATIONS.md)",
    )
    p.add_argument(
        "--subscribe", type=str, default=None, metavar="ADDR",
        help="weights fanout to subscribe to: 'host:port' (a learner's "
        "--transport socket lane) or 'shm://NAME' (its same-host shm "
        "slab); new versions hot-swap between dispatches",
    )
    p.add_argument(
        "--serve-metrics-jsonl", type=str, default=None, metavar="PATH",
        help="append a serve-telemetry snapshot (one {ts, step, scalars} "
        "object per interval; step = dispatch count) to PATH — validate "
        "with scripts/check_telemetry_schema.py --path PATH --require-serve",
    )
    p.add_argument("--trace-jsonl", type=str, default=None, metavar="PATH",
                   help="pipeline tracing (ISSUE 12): append sampled "
                   "lifecycle events (request/reply trace records, "
                   "per-compile cost analysis) as JSON lines to PATH; "
                   "merge with a learner/actor run's logs via "
                   "scripts/trace_report.py")
    p.add_argument("--trace-sample", type=int, default=None, metavar="N",
                   help="with --trace-jsonl: trace every Nth request "
                   "(default telemetry.trace_sample_n = 16)")
    p.add_argument("--fleet-interval", type=float, default=None, metavar="S",
                   help="fleet health plane (ISSUE 13): with --subscribe, "
                   "push one compact metric snapshot (serve counters + "
                   "gauges) back to the learner every S seconds over the "
                   "subscription lane (default telemetry.fleet_interval_s "
                   "= 5; 0 disables) — the fleet console then shows serve "
                   "p99 next to the actors")
    p.add_argument("--duration", type=float, default=0.0,
                   help="serve for this many seconds then exit (0 = forever)")
    args = p.parse_args(argv)

    from dotaclient_tpu.serve import (
        PolicyServer,
        ServeEngine,
        load_inference_params,
        make_inference_policy,
    )
    from dotaclient_tpu.utils import telemetry
    from dotaclient_tpu.utils.overrides import parse_dataclass_overrides

    config, params, version = load_inference_params(args.checkpoint)
    if args.serve:
        from dotaclient_tpu.config import ServeConfig

        try:
            over = parse_dataclass_overrides(ServeConfig, args.serve, "--serve")
        except ValueError as e:
            p.error(str(e))
        config = dataclasses.replace(
            config, serve=dataclasses.replace(config.serve, **over)
        )

    if args.trace_jsonl:
        from dotaclient_tpu.utils import tracing

        # before the engine/server exist — they capture tracing.get()
        tracing.configure(args.trace_jsonl, sample_n=args.trace_sample)

    policy = make_inference_policy(config)
    engine = ServeEngine(config, policy, params, version=version)
    host, port = args.serve_listen.rsplit(":", 1)
    server = PolicyServer(engine, config, host=host, port=int(port))
    print(
        f"serve: listening on {server.address} "
        f"(window {config.serve.batch_window_ms} ms, "
        f"max_batch {config.serve.max_batch}, "
        f"{config.serve.max_slots} carry slots, weights v{version})",
        flush=True,
    )
    # machine-readable address line: the chaos harness and fleet tooling
    # spawn ephemeral-port backends and parse this (ISSUE 19)
    print(
        "SERVE_LISTENING "
        + json.dumps({
            "host": server.address[0], "port": int(server.address[1]),
        }),
        flush=True,
    )

    if args.subscribe:
        if args.subscribe.startswith("shm://"):
            from dotaclient_tpu.transport.shm_transport import ShmTransport

            source = ShmTransport(args.subscribe[len("shm://"):])
        else:
            from dotaclient_tpu.transport.socket_transport import (
                SocketTransport,
            )

            sub_host, sub_port = args.subscribe.rsplit(":", 1)
            source = SocketTransport(sub_host, int(sub_port))
        server.attach_weights_source(source)
        print(f"serve: subscribed to weights fanout {args.subscribe}", flush=True)

    publisher = None
    if args.subscribe:
        # fleet health plane (ISSUE 13): the weights-subscription lane is
        # the serve process's channel back to the learner — ride metric
        # snapshots on it so the fleet console shows this server's p99
        from dotaclient_tpu.utils.fleet import FleetPublisher

        interval = (
            telemetry.fleet_interval_s
            if args.fleet_interval is None
            else args.fleet_interval
        )
        if interval > 0:
            # peer id = the bound listen port, NOT the pid: a restarted
            # serve process must reuse its fleet row so the
            # fleet_peer_stale page resolves on its first fresh snapshot
            # (a pid-keyed row would stay stale — and paging — until the
            # aggregator's forget window). Ephemeral-port servers
            # (--serve-listen :0) get a fresh row per boot by nature.
            publisher = FleetPublisher(
                peer_id=int(server.address[1]) & 0xFFFF, kind="serve",
                interval_s=interval,
            )

    sink = None
    if args.serve_metrics_jsonl:
        sink = telemetry.JsonlSink(args.serve_metrics_jsonl)
    tel = telemetry.get_registry()
    t_end = time.time() + args.duration if args.duration else None
    # the wake interval follows the fleet cadence so snapshots publish on
    # time, but the JSONL sink keeps its OWN historical 5 s cadence —
    # --fleet-interval must not silently multiply the metrics log volume
    wake = min(5.0, publisher.interval_s) if publisher is not None else 5.0
    sink_every = 5.0
    last_sink = time.monotonic()
    try:
        while t_end is None or time.time() < t_end:
            time.sleep(min(wake, t_end - time.time()) if t_end else wake)
            if publisher is not None:
                try:
                    publisher.maybe_publish(source)
                except (ConnectionError, OSError):
                    pass   # learner gone: serving continues on last weights
            if (
                sink is not None
                and time.monotonic() - last_sink >= sink_every
            ):
                last_sink = time.monotonic()
                snap = tel.snapshot()
                sink.emit(int(snap.get("serve/dispatches_total", 0)), snap)
    except KeyboardInterrupt:
        pass
    finally:
        if sink is not None:
            snap = tel.snapshot()
            sink.emit(int(snap.get("serve/dispatches_total", 0)), snap)
            sink.close()
        server.close()
        engine.stop()
        if args.trace_jsonl:
            from dotaclient_tpu.utils import tracing

            tracing.shutdown()
        snap = tel.snapshot()
        print(json.dumps({
            "serve_requests_total": snap.get("serve/requests_total", 0.0),
            "serve_dispatches_total": snap.get("serve/dispatches_total", 0.0),
            "serve_p99_latency_ms": snap.get("serve/p99_latency_ms", 0.0),
            "serve_weights_version": snap.get("serve/weights_version", 0.0),
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
